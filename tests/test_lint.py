"""ocvf-lint framework tests: per-rule fixture snippets (positive, negative,
suppressed), suppression hygiene, CLI exit-code contract, and the tier-1
gate that the real tree is clean.

The fixture tests assert exact (rule, line) pairs — the acceptance bar is
that a deliberately seeded violation of every rule is detected at the
correct file:line, not merely that "something" fires."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.ocvf_lint import core  # noqa: E402


def lint_tree(tmp_path, files, rules=None):
    """Write {relpath: source} under tmp_path and lint the tree."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return core.run([str(tmp_path)], rules=rules).findings


def lint_source(tmp_path, source, rules=None):
    return lint_tree(tmp_path, {"mod.py": source}, rules=rules)


def rules_and_lines(findings):
    return [(f.rule, f.line) for f in findings]


# ---------------- blocking-under-lock ----------------


def test_blocking_under_lock_positive(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def bad(self):
                with self._lock:
                    time.sleep(0.1)
        """, rules=["blocking-under-lock"])
    assert rules_and_lines(findings) == [("blocking-under-lock", 6)]


def test_blocking_under_lock_negatives(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def sleep_outside(self):
                with self._lock:
                    x = 1
                time.sleep(0.1)

            def nested_def_resets(self):
                with self._lock:
                    def later():
                        time.sleep(0.1)  # runs outside the lock
                    self.hook = later

            def str_join_is_not_io(self):
                with self._lock:
                    return ", ".join(["a"])
        """, rules=["blocking-under-lock"])
    assert findings == []


def test_blocking_under_lock_io_and_suppression(tmp_path):
    findings = lint_source(tmp_path, """\
        import os

        class S:
            def fsyncs(self, fh):
                with self._lock:
                    os.fsync(fh.fileno())

            def justified(self, fh):
                with self._lock:  # ocvf-lint: disable-block=blocking-under-lock -- this lock exists to serialize these writes
                    fh.write(b"x")
                    fh.flush()
        """, rules=["blocking-under-lock"])
    assert rules_and_lines(findings) == [("blocking-under-lock", 6)]


# ---------------- lock-order ----------------


def test_lock_order_inversion_detected(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def ab(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """, rules=["lock-order"])
    assert len(findings) == 1
    assert findings[0].rule == "lock-order"
    assert findings[0].line == 4  # the first edge site
    assert "inversion" in findings[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
        """, rules=["lock-order"])
    assert findings == []


def test_lock_order_re_entry_detected(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def re_enter(self):
                with self._lock:
                    with self._lock:
                        pass
        """, rules=["lock-order"])
    assert rules_and_lines(findings) == [("lock-order", 4)]
    assert "re-acquired" in findings[0].message


def test_lock_order_call_propagation(tmp_path):
    """An inversion only visible through a method call: ab() nests
    lexically, ba() holds b and CALLS a helper that takes a."""
    findings = lint_source(tmp_path, """\
        class S:
            def ab(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def take_a(self):
                with self._a_lock:
                    pass

            def ba(self):
                with self._b_lock:
                    self.take_a()
        """, rules=["lock-order"])
    assert len(findings) == 1
    assert "inversion" in findings[0].message


def test_lock_order_suppression_at_any_edge(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def ab(self):
                with self._a_lock:
                    with self._b_lock:  # ocvf-lint: disable=lock-order -- ordered handoff proven safe by construction here
                        pass

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """, rules=["lock-order"])
    assert findings == []


# ---------------- non-atomic-write ----------------


def test_non_atomic_write_positive(tmp_path):
    findings = lint_source(tmp_path, """\
        import json

        def save(path, obj):
            with open(path, "w") as fh:
                json.dump(obj, fh)
        """, rules=["non-atomic-write"])
    assert rules_and_lines(findings) == [("non-atomic-write", 4)]


def test_non_atomic_write_negatives(tmp_path):
    findings = lint_source(tmp_path, """\
        def fine(path):
            with open(path) as fh:
                data = fh.read()
            with open(path, "rb") as fh:
                blob = fh.read()
            with open(path, "a") as fh:  # append = journal-style, exempt
                fh.write("x")
            return data, blob
        """, rules=["non-atomic-write"])
    assert findings == []


def test_non_atomic_write_exempt_layers_and_suppression(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/serialization.py": """\
            def atomic_write_bytes(path, blob):
                with open(path + ".tmp", "wb") as fh:  # the helper itself
                    fh.write(blob)
            """,
        "app.py": """\
            def dump(path, text):
                # ocvf-lint: disable=non-atomic-write -- throwaway debug artifact, torn file is harmless
                with open(path, "w") as fh:
                    fh.write(text)
            """,
        "pathlib_user.py": """\
            def bad(p):
                p.write_text("hello")
            """,
    }, rules=["non-atomic-write"])
    assert [(f.rule, os.path.basename(f.path), f.line) for f in findings] == [
        ("non-atomic-write", "pathlib_user.py", 2)]


# ---------------- metrics-registry ----------------

METRIC_FIXTURE_REGISTRY = """\
    GOOD = "good_metric"
    OTHER = "other_metric"
    FAMILY_PREFIX = "fam_"
    """


def test_metrics_registry_literals(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            def f(metrics, reason):
                metrics.incr("good_metric")
                metrics.incr("bad_typo_metric")
                metrics.observe("other_metric", 1.0)
                metrics.incr(f"fam_{reason}")
                metrics.incr(f"unregistered_{reason}")
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 3),
                                         ("metrics-registry", 6)]


def test_metrics_registry_constants_and_prefix_concat(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            import utils.metric_names as mn
            from utils.metric_names import GOOD

            def f(metrics, reason, name):
                metrics.incr(mn.GOOD)
                metrics.incr(GOOD)
                metrics.incr(mn.FAMILY_PREFIX + reason)
                metrics.incr(mn.DOES_NOT_EXIST)
                metrics.incr(name)
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 8),
                                         ("metrics-registry", 9)]


def test_metrics_registry_prefix_strictness(tmp_path):
    """Prefix/name pools stay disjoint: a bare prefix is not a counter
    name, a full name is not a prefix, and concatenation requires a
    *_PREFIX constant (or its literal value) on the left."""
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            import utils.metric_names as mn

            def f(metrics, reason):
                metrics.incr("fam_" + reason)          # literal prefix: ok
                metrics.incr(mn.FAMILY_PREFIX + reason)
                metrics.incr(mn.GOOD + reason)          # full name + x: drift
                metrics.incr("fam_")                    # bare prefix as name
                metrics.counters_with_prefix("good_metric")  # name as prefix
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 6),
                                         ("metrics-registry", 7),
                                         ("metrics-registry", 8)]


def test_metrics_registry_checks_count_shim_sites(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            def f(conn):
                conn._count("good_metric")
                conn._count("conector_reconects")  # the typo class
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 3)]


def test_metrics_registry_read_sites_and_np_percentile(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            import numpy as np

            def f(metrics, ts):
                metrics.counter("good_metric")
                metrics.counter("typo_metric")
                metrics.counters_with_prefix("fam_")
                return np.percentile(ts, 50)  # not a Metrics read
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 5)]


# ---------------- swallowed-exception ----------------


def test_swallowed_exception_positive(tmp_path):
    findings = lint_source(tmp_path, """\
        def f():
            try:
                work()
            except Exception:
                pass
            try:
                work()
            except:
                return None
        """, rules=["swallowed-exception"])
    assert rules_and_lines(findings) == [("swallowed-exception", 4),
                                         ("swallowed-exception", 8)]


def test_swallowed_exception_accounted_forms_pass(tmp_path):
    findings = lint_source(tmp_path, """\
        def f(metrics, log, q):
            try:
                work()
            except Exception:
                metrics.incr("errors")
            try:
                work()
            except Exception:
                raise RuntimeError("wrapped")
            try:
                work()
            except Exception as e:
                q["error"] = repr(e)  # exception is read -> recorded
            try:
                work()
            except ValueError:
                pass  # narrow except is out of scope for this rule
        """, rules=["swallowed-exception"])
    assert findings == []


def test_swallowed_exception_suppression(tmp_path):
    findings = lint_source(tmp_path, """\
        def f():
            try:
                work()
            except Exception:  # ocvf-lint: disable=swallowed-exception -- teardown is best-effort by contract
                pass
        """, rules=["swallowed-exception"])
    assert findings == []


# ---------------- suppression hygiene ----------------


def test_bare_suppression_is_inert_and_flagged(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def bad(self):
                with self._lock:
                    time.sleep(0.1)  # ocvf-lint: disable=blocking-under-lock
        """, rules=["blocking-under-lock"])
    got = rules_and_lines(findings)
    assert ("suppression", 6) in got          # the bare disable is a finding
    assert ("blocking-under-lock", 6) in got  # and it suppressed NOTHING


def test_short_justification_counts_as_bare(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def bad(self):
                with self._lock:
                    time.sleep(0.1)  # ocvf-lint: disable=blocking-under-lock -- ok
        """, rules=["blocking-under-lock"])
    assert ("suppression", 6) in rules_and_lines(findings)


def test_unknown_rule_in_suppression_flagged(tmp_path):
    findings = lint_source(tmp_path, """\
        x = 1  # ocvf-lint: disable=no-such-rule -- justification text here
        """)
    assert [(f.rule, f.line) for f in findings] == [("suppression", 1)]
    assert "unknown rule" in findings[0].message


def test_disable_file_covers_everything(tmp_path):
    findings = lint_source(tmp_path, """\
        # ocvf-lint: disable-file=non-atomic-write -- scratch artifact writer, torn output is harmless
        def a(p):
            open(p, "w").write("x")

        def b(p):
            open(p, "w").write("y")
        """, rules=["non-atomic-write"])
    assert findings == []


def test_disable_block_covers_whole_statement(tmp_path):
    findings = lint_source(tmp_path, """\
        import os

        class S:
            def f(self, fh):
                with self._lock:  # ocvf-lint: disable-block=blocking-under-lock -- serializing these writes is the purpose of this lock
                    fh.write(b"a")
                    fh.flush()
                    os.fsync(fh.fileno())
                with self._lock:
                    fh.write(b"b")
        """, rules=["blocking-under-lock"])
    assert rules_and_lines(findings) == [("blocking-under-lock", 10)]


def test_suppression_meta_rule_cannot_be_suppressed(tmp_path):
    findings = lint_source(tmp_path, """\
        x = 1  # ocvf-lint: disable=unknown-thing -- long enough justification ; ocvf-lint: disable=suppression -- nice try
        """)
    assert any(f.rule == "suppression" for f in findings)


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    findings = lint_source(tmp_path, "def broken(:\n")
    assert [f.rule for f in findings] == ["parse-error"]


# ---------------- CLI contract ----------------


def _cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.ocvf_lint", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO_ROOT
             + os.pathsep + os.environ.get("PYTHONPATH", "")})


def test_cli_exit_0_on_clean(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = _cli(str(clean))
    assert proc.returncode == 0, proc.stderr


def test_cli_exit_1_on_findings_and_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text('def f(p):\n    open(p, "w").write("x")\n')
    proc = _cli("--json", str(bad))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["findings"][0]["rule"] == "non-atomic-write"
    assert doc["findings"][0]["line"] == 2


def test_cli_exit_2_on_internal_error(tmp_path):
    proc = _cli(str(tmp_path / "does-not-exist"))
    assert proc.returncode == 2


def test_cli_list_rules_names_all_five(tmp_path):
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule in ("lock-order", "blocking-under-lock", "non-atomic-write",
                 "metrics-registry", "swallowed-exception"):
        assert rule in proc.stdout


# ---------------- the tier-1 gate: the real tree is clean ----------------


def test_real_tree_has_zero_findings():
    """The acceptance bar: ``python -m tools.ocvf_lint
    opencv_facerecognizer_tpu scripts`` exits 0 at head, with all five
    rules active and every suppression justified."""
    proc = _cli("opencv_facerecognizer_tpu", "scripts", "--json")
    assert proc.returncode == 0, f"lint found issues:\n{proc.stdout}\n{proc.stderr}"
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert set(doc["rules"]) >= {"lock-order", "blocking-under-lock",
                                 "non-atomic-write", "metrics-registry",
                                 "swallowed-exception"}
    assert doc["files_scanned"] > 40


def test_real_lock_graph_is_nonempty_and_acyclic():
    """The static inter-module lock graph over the real runtime must keep
    seeing the known edges (StateLifecycle -> WAL/journal/gallery/metrics)
    — if this goes empty the lock-order rule has silently gone blind."""
    from tools.ocvf_lint.checkers.lock_order import build_lock_graph

    edges = set(build_lock_graph(
        [os.path.join(REPO_ROOT, "opencv_facerecognizer_tpu")]))
    assert any(a.endswith("StateLifecycle._enroll_lock") for a, _ in edges)
    assert any(b.endswith("Metrics._lock") for _, b in edges)
    inverted = [(a, b) for (a, b) in edges if a != b and (b, a) in edges]
    assert not inverted


# ---------------- metric_names registry sanity ----------------


def test_metric_names_registry_no_duplicates():
    from opencv_facerecognizer_tpu.utils import metric_names as mn

    names = mn.all_names()
    assert len(names) == len(set(names)), "duplicate metric name values"
    assert len(names) > 50
    prefixes = mn.all_prefixes()
    assert all(p.endswith("_") for p in prefixes)
    # no full name may collide into a prefix family ambiguously with itself
    assert len(prefixes) == len(set(prefixes))


# ---------------- DebugLock dynamic backstop unit tests ----------------


def test_debug_lock_records_edges_and_detects_inversion():
    from opencv_facerecognizer_tpu.utils.debug_lock import (
        DebugLock, LockOrderError, LockOrderMonitor)

    monitor = LockOrderMonitor()
    a = monitor.debug_lock("A")
    b = monitor.debug_lock("B")
    with a:
        with b:
            pass
    assert monitor.edges() == {("A", "B")}
    monitor.check()  # consistent so far
    with b:
        with a:
            pass
    assert monitor.inversions() == [("A", "B")]
    with pytest.raises(LockOrderError):
        monitor.check()


def test_debug_lock_re_entry_raises_immediately():
    from opencv_facerecognizer_tpu.utils.debug_lock import (
        LockOrderError, LockOrderMonitor)

    monitor = LockOrderMonitor()
    a = monitor.debug_lock("A")
    with a:
        with pytest.raises(LockOrderError):
            a.acquire()


def test_debug_lock_backs_a_condition_variable():
    from opencv_facerecognizer_tpu.utils.debug_lock import LockOrderMonitor

    monitor = LockOrderMonitor()
    inner = monitor.debug_lock("CV")
    cv = threading.Condition(inner)
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    with cv:
        hits.append(1)
        cv.notify()
    t.join(timeout=5.0)
    assert not t.is_alive()
    monitor.check()
