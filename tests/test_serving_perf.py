"""Tier-1 serving-loop perf smoke (fast, deterministic, no hardware).

Drives ``bench_serving.run_smoke`` over the fake instant backend
(``runtime.fakes.InstantPipeline``), which emulates the tunneled backend's
~100 ms ``is_ready`` sync-poll floor on CPU. The overlapped pipeline
(readback worker + continuous batching) must sustain the offered load with
**zero drops** and keep ``ready_wait`` p50 far below that poll floor — the
regression tripwire for the event-driven readback design: if anything on
the serving path starts polling readbacks again, ready_wait snaps to the
floor and this fails. The legacy-vs-overlapped comparison artifact is
written by ``python bench_serving.py --smoke`` (BENCH_SERVING_smoke.json);
this test runs only the overlapped mode to stay fast.
"""

import importlib.util
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_serving", os.path.join(REPO_ROOT, "bench_serving.py"))
bench_serving = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_serving)

#: the emulated sync-poll readback floor (ms) and the smoke's offered load.
POLL_FLOOR_MS = 100.0
FRAMES = 160
BATCH = 8


def test_perf_smoke_overlapped_readback_off_the_poll_floor():
    artifact = bench_serving.run_smoke(
        frames_n=FRAMES, rate_hz=200.0, batch_size=BATCH,
        sync_poll_floor_s=POLL_FLOOR_MS / 1e3, compute_s=0.002,
        modes=("overlapped",), write=False,
    )
    row = artifact["modes"]["overlapped"]
    # Sustained: every offered frame completed, none dropped, and the loop
    # actually pipelined whole batches (>= ceil(FRAMES / BATCH)).
    assert row["dropped_frames"] == 0
    assert row["completed_frames"] == FRAMES
    assert row["batches"] >= FRAMES // BATCH
    # The decomposition's readback term sits far below the poll floor: the
    # worker blocks on the array (event-driven) instead of polling is_ready
    # on the hot path. Generous margin (half the floor) keeps this
    # deterministic on a loaded CI host while still catching any
    # reintroduced poll (which would read >= ~100 ms).
    ready_wait_p50 = row["decomposition_ms"]["ready_wait_p50_ms"]
    assert ready_wait_p50 < POLL_FLOOR_MS / 2, row["decomposition_ms"]
