"""Spatial histogram vs per-cell np.histogram oracle (SURVEY.md §4)."""

import numpy as np

from opencv_facerecognizer_tpu.ops import histogram as H

RNG = np.random.default_rng(4)


def numpy_spatial_histogram(codes, grid, num_bins, normalize):
    gy, gx = grid
    h, w = codes.shape
    ch, cw = h // gy, w // gx
    y0, x0 = (h - gy * ch) // 2, (w - gx * cw) // 2
    codes = codes[y0 : y0 + gy * ch, x0 : x0 + gx * cw]
    out = []
    for iy in range(gy):
        for ix in range(gx):
            cell = codes[iy * ch : (iy + 1) * ch, ix * cw : (ix + 1) * cw]
            hist, _ = np.histogram(cell, bins=num_bins, range=(0, num_bins))
            hist = hist.astype(np.float64)
            if normalize:
                hist /= max(hist.sum(), 1e-12)
            out.append(hist)
    return np.concatenate(out)


def test_matches_numpy_oracle_with_remainder_crop():
    codes = RNG.integers(0, 16, size=(13, 11)).astype(np.int32)
    got = np.asarray(H.spatial_histogram(codes, grid=(3, 2), num_bins=16, normalize=False))
    want = numpy_spatial_histogram(codes, (3, 2), 16, False)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_normalized_cells_sum_to_one():
    codes = RNG.integers(0, 256, size=(2, 32, 32)).astype(np.int32)
    got = np.asarray(H.spatial_histogram(codes, grid=(4, 4), num_bins=256))
    assert got.shape == (2, 4 * 4 * 256)
    sums = got.reshape(2, 16, 256).sum(-1)
    np.testing.assert_allclose(sums, 1.0, atol=1e-5)


def test_batched_equals_per_image():
    codes = RNG.integers(0, 8, size=(3, 16, 16)).astype(np.int32)
    batched = np.asarray(H.spatial_histogram(codes, grid=(2, 2), num_bins=8))
    singles = np.stack(
        [np.asarray(H.spatial_histogram(c, grid=(2, 2), num_bins=8)) for c in codes]
    )
    np.testing.assert_allclose(batched, singles, atol=1e-6)
