"""Visualization helpers (utils/visual.py) — file-rendering smoke + content
checks for the reference's eigenface-grid / mean-face / overlay surface."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")

from opencv_facerecognizer_tpu.models import PCA
from opencv_facerecognizer_tpu.utils import visual
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

X, Y, NAMES = make_synthetic_faces(num_subjects=4, per_subject=5,
                                   size=(24, 24), seed=9)


def _is_png(path):
    with open(path, "rb") as f:
        return f.read(8) == b"\x89PNG\r\n\x1a\n"


def test_subplot_grid_writes_png(tmp_path):
    out = str(tmp_path / "grid.png")
    path = visual.subplot_grid([X[0], X[1], X[2]], ["a", "b", "c"],
                               suptitle="faces", filename=out)
    assert path == out and _is_png(out)


def test_plot_eigenfaces_and_mean_face(tmp_path):
    feat = PCA(6)
    feat.compute(X, Y)
    e = visual.plot_eigenfaces(feat, (24, 24), num=4,
                               filename=str(tmp_path / "eig.png"))
    m = visual.plot_mean_face(feat, (24, 24),
                              filename=str(tmp_path / "mean.png"))
    assert _is_png(e) and _is_png(m)


def test_plot_eigenfaces_clamps_num(tmp_path):
    feat = PCA(3)
    feat.compute(X, Y)
    out = visual.plot_eigenfaces(feat, (24, 24), num=99,
                                 filename=str(tmp_path / "few.png"))
    assert _is_png(out)


def test_draw_detections_overlay(tmp_path):
    frame = np.zeros((64, 80), np.float32)
    faces = [
        {"box": (10, 12, 30, 40), "name": "alice", "similarity": 0.93},
        {"box": (50, 5, 75, 35)},  # name/similarity optional
    ]
    out = visual.draw_detections(frame, faces,
                                 filename=str(tmp_path / "det.png"))
    assert _is_png(out)


def test_normalize_for_display_constant_image():
    flat = visual._normalize_for_display(np.full((8, 8), 3.0))
    assert flat.min() == flat.max() == 0.0
