"""CNN embedder: shapes, ArcFace training signal, plugin integration,
verification protocol (SURVEY.md §7.5)."""

import numpy as np
import pytest

from opencv_facerecognizer_tpu.models import NearestNeighbor, PredictableModel
from opencv_facerecognizer_tpu.models.embedder import (
    CNNEmbedding,
    FaceEmbedNet,
    arcface_loss,
    init_embedder,
    train_embedder,
)
from opencv_facerecognizer_tpu.ops.distance import CosineDistance
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces
from opencv_facerecognizer_tpu.utils.verification import (
    make_verification_pairs,
    verification_accuracy,
)

import jax.numpy as jnp

TINY = dict(embed_dim=32, stem_features=8, stage_features=(8, 16), stage_blocks=(1, 1))


def _tiny_net():
    return FaceEmbedNet(embed_dim=32, stem_features=8, stage_features=(8, 16),
                        stage_blocks=(1, 1))


def test_embeddings_are_unit_norm():
    net = _tiny_net()
    params = init_embedder(net, num_classes=4, input_shape=(32, 32))
    emb = np.asarray(net.apply({"params": params["net"]}, jnp.zeros((3, 32, 32)) + 1.0))
    assert emb.shape == (3, 32)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, atol=1e-5)


def test_arcface_margin_increases_loss():
    rng = np.random.default_rng(0)
    emb = rng.normal(size=(8, 16)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    w = rng.normal(size=(4, 16)).astype(np.float32)
    y = jnp.asarray(rng.integers(0, 4, size=8))
    base = float(arcface_loss(jnp.asarray(emb), y, jnp.asarray(w), margin=0.0))
    with_margin = float(arcface_loss(jnp.asarray(emb), y, jnp.asarray(w), margin=0.5))
    assert with_margin > base


def test_training_reduces_loss_and_separates_classes():
    X, y, _ = make_synthetic_faces(4, 8, (32, 32), seed=21, noise=8.0)
    net = _tiny_net()
    params = init_embedder(net, num_classes=4, input_shape=(32, 32), seed=0)
    from opencv_facerecognizer_tpu.models.embedder import normalize_faces

    xn = normalize_faces(X, (32, 32))
    emb0 = np.asarray(net.apply({"params": params["net"]}, xn))
    params = train_embedder(net, params, np.asarray(xn), y, steps=60, batch_size=16,
                            learning_rate=3e-3)
    emb1 = np.asarray(net.apply({"params": params["net"]}, xn))

    def genuine_vs_impostor_gap(emb):
        sims = emb @ emb.T
        same = y[:, None] == y[None, :]
        off_diag = ~np.eye(len(y), dtype=bool)
        return sims[same & off_diag].mean() - sims[~same].mean()

    assert genuine_vs_impostor_gap(emb1) > genuine_vs_impostor_gap(emb0) + 0.1


def test_cnn_embedding_plugin_in_predictable_model():
    X, y, _ = make_synthetic_faces(4, 6, (32, 32), seed=2, noise=8.0)
    feat = CNNEmbedding(input_size=(32, 32), train_steps=80, batch_size=24,
                        learning_rate=3e-3, **{k: v for k, v in TINY.items() if k != "embed_dim"},
                        embed_dim=32)
    model = PredictableModel(feat, NearestNeighbor(CosineDistance(), k=1))
    model.compute(X, y)
    pred, _ = model.predict(X)
    assert (np.asarray(pred) == y).mean() >= 0.9
    single, _ = model.predict(X[0])
    assert np.ndim(single) == 0


def test_cnn_embedding_checkpoint_roundtrip(tmp_path):
    from opencv_facerecognizer_tpu.utils import serialization

    serialization.register(CNNEmbedding)
    X, y, _ = make_synthetic_faces(3, 4, (32, 32), seed=4)
    feat = CNNEmbedding(input_size=(32, 32), train_steps=5, batch_size=12,
                        **{k: v for k, v in TINY.items() if k != "embed_dim"}, embed_dim=32)
    model = PredictableModel(feat, NearestNeighbor(CosineDistance(), k=1))
    model.compute(X, y)
    before = np.asarray(model.feature.extract(X))
    path = str(tmp_path / "cnn.ckpt")
    serialization.save_model(path, model)
    restored = serialization.load_model(path)
    after = np.asarray(restored.feature.extract(X))
    np.testing.assert_allclose(before, after, atol=1e-5)


def test_verification_pairs_balanced_no_self():
    _, y, _ = make_synthetic_faces(6, 5, (8, 8), seed=0)
    a, b, same = make_verification_pairs(y, num_pairs=200, seed=1)
    assert len(a) == 200
    assert same.sum() == 100
    assert np.all(a != b) or np.all(y[a[same]] == y[b[same]])
    assert np.all(y[a[same]] == y[b[same]])
    assert np.all(y[a[~same]] != y[b[~same]])


def test_verification_accuracy_separable_embeddings():
    rng = np.random.default_rng(3)
    centers = rng.normal(size=(5, 16))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    y = np.repeat(np.arange(5), 20)
    emb = centers[y] + 0.05 * rng.normal(size=(100, 16))
    a, b, same = make_verification_pairs(y, num_pairs=400, seed=2)
    acc, std, thr = verification_accuracy(emb[a], emb[b], same)
    assert acc > 0.97
    assert -1.0 <= thr <= 1.0


def test_verification_accuracy_random_embeddings_near_chance():
    rng = np.random.default_rng(4)
    y = np.repeat(np.arange(5), 20)
    emb = rng.normal(size=(100, 16))
    a, b, same = make_verification_pairs(y, num_pairs=400, seed=5)
    acc, _, _ = verification_accuracy(emb[a], emb[b], same)
    assert acc < 0.65


def test_verification_pairs_requires_multi_sample_classes():
    with pytest.raises(ValueError):
        make_verification_pairs(np.arange(10), num_pairs=10)


def test_augment_batch_shapes_and_determinism():
    """In-graph augmentation: shape-preserving, deterministic per key,
    different across keys, and the cutout fills with the (standardized)
    mean rather than wrapping values."""
    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.embedder import augment_batch

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(6, 32, 32)).astype(np.float32))
    k = jax.random.PRNGKey(1)
    a1 = np.asarray(augment_batch(k, x))
    a2 = np.asarray(augment_batch(k, x))
    a3 = np.asarray(augment_batch(jax.random.PRNGKey(2), x))
    assert a1.shape == (6, 32, 32)
    np.testing.assert_array_equal(a1, a2)  # same key -> same augmentation
    assert np.abs(a1 - a3).max() > 1e-3  # different key -> different
    assert np.isfinite(a1).all()


def test_tta_extract_matches_flip_average():
    """tta=True must return the re-normalized average of the plain and
    mirrored embeddings — and stay unit-norm."""
    from opencv_facerecognizer_tpu.models.embedder import CNNEmbedding
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

    X, y, _ = make_synthetic_faces(num_subjects=4, per_subject=4, size=(32, 32),
                                   seed=5)
    emb = CNNEmbedding(embed_dim=16, input_size=(32, 32), stem_features=8,
                       stage_features=(8, 16), stage_blocks=(1, 1),
                       train_steps=0, tta=True)
    emb.compute(X, y)
    e_tta = np.asarray(emb._extract_batch(np.asarray(X[:4], np.float32)))
    np.testing.assert_allclose(np.linalg.norm(e_tta, axis=-1), 1.0, atol=1e-5)
    emb.tta = False
    e_plain = np.asarray(emb._extract_batch(np.asarray(X[:4], np.float32)))
    e_flip = np.asarray(emb._extract_batch(
        np.asarray(X[:4], np.float32)[:, :, ::-1]))
    want = e_plain + e_flip
    want /= np.linalg.norm(want, axis=-1, keepdims=True)
    np.testing.assert_allclose(e_tta, want, atol=1e-4)


def test_augmented_training_runs_and_improves_separation():
    """augment=True + cosine schedule must train end-to-end (the jitted
    step now consumes a PRNG key) and still separate classes."""
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder, normalize_faces, train_embedder)
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

    X, y, _ = make_synthetic_faces(num_subjects=4, per_subject=6, size=(32, 32),
                                   seed=7)
    net = FaceEmbedNet(embed_dim=16, stem_features=8, stage_features=(8, 16),
                       stage_blocks=(1, 1))
    params = init_embedder(net, num_classes=4, input_shape=(32, 32), seed=0)
    xn = np.asarray(normalize_faces(np.asarray(X, np.float32), (32, 32)))
    params = train_embedder(net, params, xn, y, steps=60, batch_size=16,
                            augment=True, lr_schedule="cosine", seed=0)
    e = np.asarray(net.apply({"params": params["net"]}, jnp.asarray(xn)))
    sims = e @ e.T
    same = y[:, None] == y[None, :]
    off_diag = ~np.eye(len(y), dtype=bool)
    assert sims[same & off_diag].mean() > sims[~same].mean() + 0.1


def test_serving_default_constants_construct_and_run():
    """SERVING_EMBEDDER_KWARGS/SERVING_FACE_SIZE (the accuracy-gated
    serving default) must construct a net whose forward works at the
    gated input size and L2-normalizes its embeddings."""
    from opencv_facerecognizer_tpu.models.embedder import (
        SERVING_EMBEDDER_KWARGS, SERVING_FACE_SIZE, FaceEmbedNet,
        init_embedder, normalize_faces,
    )

    assert SERVING_FACE_SIZE == (64, 64)  # the gate protocol's resolution
    net = FaceEmbedNet(**SERVING_EMBEDDER_KWARGS)
    params = init_embedder(net, num_classes=4, input_shape=SERVING_FACE_SIZE,
                           seed=0)["net"]
    x = np.random.default_rng(0).uniform(0, 255, (2, *SERVING_FACE_SIZE))
    emb = net.apply({"params": params},
                    normalize_faces(jnp.asarray(x, jnp.float32),
                                    SERVING_FACE_SIZE))
    assert emb.shape == (2, SERVING_EMBEDDER_KWARGS["embed_dim"])
    np.testing.assert_allclose(np.linalg.norm(np.asarray(emb), axis=-1),
                               1.0, atol=1e-3)
