"""Dataset reading: folder-per-subject layout, label/name alignment."""

import os

import numpy as np
import pytest

from opencv_facerecognizer_tpu.models import NearestNeighbor
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces, read_images, shuffle


def _write_png(path, img):
    import cv2

    cv2.imwrite(path, img.astype(np.uint8))


def test_read_images_folder_per_subject(tmp_path):
    X, _, _ = make_synthetic_faces(3, 2, (20, 20), seed=1)
    for i, name in enumerate(["alice", "bob", "carol"]):
        os.makedirs(tmp_path / name)
        for j in range(2):
            _write_png(str(tmp_path / name / f"{j}.png"), X[i * 2 + j])
    imgs, labels, names = read_images(str(tmp_path), image_size=(16, 16))
    assert imgs.shape == (6, 16, 16)
    assert names == ["alice", "bob", "carol"]
    np.testing.assert_array_equal(labels, [0, 0, 1, 1, 2, 2])


def test_read_images_skips_unreadable_subject_keeps_alignment(tmp_path):
    # regression: a subject dir with no readable images must not shift
    # later subjects onto wrong labels/names
    X, _, _ = make_synthetic_faces(2, 2, (20, 20), seed=2)
    os.makedirs(tmp_path / "alice")
    _write_png(str(tmp_path / "alice" / "0.png"), X[0])
    os.makedirs(tmp_path / "bob")
    (tmp_path / "bob" / "junk.png").write_bytes(b"not an image")
    os.makedirs(tmp_path / "carol")
    _write_png(str(tmp_path / "carol" / "0.png"), X[2])
    imgs, labels, names = read_images(str(tmp_path))
    assert names == ["alice", "carol"]
    np.testing.assert_array_equal(labels, [0, 1])
    assert labels.max() == len(names) - 1


def test_read_images_empty_dir_raises(tmp_path):
    with pytest.raises(ValueError):
        read_images(str(tmp_path))


def test_shuffle_is_joint_and_deterministic():
    X, y, _ = make_synthetic_faces(3, 3, (8, 8), seed=0)
    X1, y1 = shuffle(X, y, seed=5)
    X2, y2 = shuffle(X, y, seed=5)
    np.testing.assert_array_equal(y1, y2)
    np.testing.assert_allclose(X1, X2)
    # pairs stay aligned: each shuffled image equals the original at its label position
    for i in range(len(y1)):
        orig_idx = np.flatnonzero([np.allclose(X[j], X1[i]) for j in range(len(y))])[0]
        assert y[orig_idx] == y1[i]


def test_string_labels_rejected_with_clear_error():
    X, y, _ = make_synthetic_faces(2, 2, (8, 8), seed=0)
    clf = NearestNeighbor()
    with pytest.raises(TypeError, match="subject_names"):
        clf.compute(X.reshape(4, -1), np.array(["a", "a", "b", "b"]))
