"""Numerical equivalence of the fused separable-block pallas kernel (and
the fused serving forward built on it) against the flax graph — the
transform re-schedules inference; it must not change the math beyond bf16
rounding (ops/pallas_sepblock.py module docstring)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opencv_facerecognizer_tpu.models import embedder as emb_mod
from opencv_facerecognizer_tpu.models.embedder import (
    FaceEmbedNet, fused_forward, init_embedder,
)
from opencv_facerecognizer_tpu.ops.pallas_sepblock import fused_sep_block

RNG = np.random.default_rng(11)


def _flax_block(features, stride, x, seed=0):
    blk = emb_mod._SepBlock(features=features, stride=stride)
    params = blk.init(jax.random.PRNGKey(seed), x)["params"]
    return blk, params


@pytest.mark.parametrize("stride,cin,cout,hw", [
    (1, 32, 32, 16),   # residual block
    (1, 32, 64, 16),   # channel change, no residual
    (2, 64, 128, 16),  # downsampling stage head
    (2, 32, 32, 8),    # stride without channel change
])
def test_fused_block_matches_flax(stride, cin, cout, hw):
    x = jnp.asarray(RNG.normal(size=(4, hw, hw, cin)).astype(np.float32),
                    jnp.bfloat16)
    blk, params = _flax_block(cout, stride, x)
    want = np.asarray(blk.apply({"params": params}, x), np.float32)
    got = np.asarray(fused_sep_block(
        x, params["Conv_0"]["kernel"], params["GroupNorm_0"]["scale"],
        params["GroupNorm_0"]["bias"], params["Conv_1"]["kernel"],
        params["GroupNorm_1"]["scale"], params["GroupNorm_1"]["bias"],
        stride=stride, residual=(stride == 1 and cin == cout),
        interpret=True, block_b=2,
    ), np.float32)
    assert got.shape == want.shape
    # bf16 activations: elementwise agreement within bf16 ulp-scale noise
    scale = max(1.0, float(np.abs(want).max()))
    np.testing.assert_allclose(got, want, atol=0.03 * scale, rtol=0.05)
    # and tight agreement in aggregate (the rounding noise is unbiased)
    corr = np.corrcoef(got.ravel(), want.ravel())[0, 1]
    assert corr > 0.9995, corr


def test_fused_block_batch_padding():
    """Batch not divisible by block_b: padded lanes must not leak."""
    x = jnp.asarray(RNG.normal(size=(5, 8, 8, 16)).astype(np.float32),
                    jnp.bfloat16)
    blk, params = _flax_block(16, 1, x)
    want = np.asarray(blk.apply({"params": params}, x), np.float32)
    got = np.asarray(fused_sep_block(
        x, params["Conv_0"]["kernel"], params["GroupNorm_0"]["scale"],
        params["GroupNorm_0"]["bias"], params["Conv_1"]["kernel"],
        params["GroupNorm_1"]["scale"], params["GroupNorm_1"]["bias"],
        stride=1, residual=True, interpret=True, block_b=4,
    ), np.float32)
    assert got.shape == want.shape
    scale = max(1.0, float(np.abs(want).max()))
    np.testing.assert_allclose(got, want, atol=0.03 * scale, rtol=0.05)


def test_fused_forward_matches_net_apply():
    """End-to-end: fused serving forward vs net.apply on a small separable
    net — final L2-normalized embeddings nearly identical."""
    net = FaceEmbedNet(embed_dim=32, stem_features=8, stage_features=(8, 16),
                       stage_blocks=(2, 1))
    params = init_embedder(net, 4, (32, 32), seed=0)["net"]
    x = RNG.normal(size=(4, 32, 32)).astype(np.float32)
    want = np.asarray(net.apply({"params": params}, x))
    got = np.asarray(fused_forward(net, params, jnp.asarray(x),
                                   interpret=True, block_b=2))
    assert got.shape == want.shape
    cos = np.sum(got * want, axis=-1)  # both L2-normalized
    assert np.all(cos > 0.9999), cos
    np.testing.assert_allclose(got, want, atol=2e-2)


def test_fused_forward_serving_config_shapes():
    """The SERVING default config itself traces through the fused path
    (structure coverage, small batch to keep CPU time sane)."""
    from opencv_facerecognizer_tpu.models.embedder import (
        SERVING_EMBEDDER_KWARGS, SERVING_FACE_SIZE,
    )

    net = FaceEmbedNet(**SERVING_EMBEDDER_KWARGS)
    params = init_embedder(net, 4, SERVING_FACE_SIZE, seed=0)["net"]
    x = RNG.normal(size=(2, *SERVING_FACE_SIZE)).astype(np.float32)
    want = np.asarray(net.apply({"params": params}, x))
    got = np.asarray(fused_forward(net, params, jnp.asarray(x),
                                   interpret=True, block_b=2))
    cos = np.sum(got * want, axis=-1)
    assert np.all(cos > 0.9999), cos


def test_fused_forward_space_to_depth_variant():
    """The s2d mirror branch (stem-stride folding) must track the flax
    graph too — it's not the serving default but the config surface covers
    it, and an untested branch could silently diverge."""
    net = FaceEmbedNet(embed_dim=16, stem_features=8, stage_features=(8, 16),
                       stage_blocks=(1, 1), space_to_depth=2)
    params = init_embedder(net, 4, (32, 32), seed=0)["net"]
    x = RNG.normal(size=(2, 32, 32)).astype(np.float32)
    want = np.asarray(net.apply({"params": params}, x))
    got = np.asarray(fused_forward(net, params, jnp.asarray(x),
                                   interpret=True, block_b=2))
    cos = np.sum(got * want, axis=-1)
    assert np.all(cos > 0.9999), cos


def test_fused_forward_rejects_uncovered_configs():
    net = FaceEmbedNet(embed_dim=16, stem_features=8, stage_features=(8,),
                       stage_blocks=(1,), block="dense")
    with pytest.raises(ValueError, match="separable"):
        fused_forward(net, {}, jnp.zeros((1, 32, 32)))
    net = FaceEmbedNet(embed_dim=16, stem_features=8, stage_features=(8,),
                       stage_blocks=(1,), norm="light")
    with pytest.raises(ValueError, match="norm"):
        fused_forward(net, {}, jnp.zeros((1, 32, 32)))
