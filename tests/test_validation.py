"""Validation harness: k-fold / LOO / simple on the synthetic ORL stand-in
(SURVEY.md §3.5, §6 measurement plan step 1 — the real ORL is unreachable in
this zero-egress environment, so the accuracy band is established on the
deterministic synthetic set)."""

import numpy as np

from opencv_facerecognizer_tpu.models import (
    Fisherfaces,
    NearestNeighbor,
    PCA,
    PredictableModel,
)
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces
from opencv_facerecognizer_tpu.utils.validation import (
    KFoldCrossValidation,
    LeaveOneOutCrossValidation,
    SimpleValidation,
    stratified_kfold_indices,
)

X, Y, _ = make_synthetic_faces(num_subjects=8, per_subject=6, size=(24, 24), seed=5)
# Milder illumination variation for the raw-PCA band: Eigenfaces is
# illumination-sensitive by design (that is why Fisherfaces exists), and the
# default synthetic set varies illumination far harder than ORL does.
X_MILD, Y_MILD, _ = make_synthetic_faces(
    num_subjects=8, per_subject=6, size=(24, 24), seed=5, noise=8.0, illumination=0.1
)


def test_stratified_folds_cover_and_balance():
    folds = stratified_kfold_indices(Y, k=3, seed=0)
    all_idx = np.concatenate(folds)
    assert sorted(all_idx.tolist()) == list(range(len(Y)))
    for f in folds:
        counts = np.bincount(Y[f], minlength=8)
        assert counts.max() - counts.min() <= 1


def test_kfold_eigenfaces_band():
    model = PredictableModel(PCA(num_components=20), NearestNeighbor(k=1))
    cv = KFoldCrossValidation(k=3).validate(model, X_MILD, Y_MILD)
    assert len(cv.results) == 3
    assert cv.mean_accuracy >= 0.90, cv.results


def test_kfold_fisherfaces_band():
    model = PredictableModel(Fisherfaces(), NearestNeighbor(k=1))
    cv = KFoldCrossValidation(k=3).validate(model, X, Y)
    assert cv.mean_accuracy >= 0.90, cv.results


def test_leave_one_out_on_tiny_subset():
    Xs, Ys, _ = make_synthetic_faces(num_subjects=3, per_subject=4, size=(16, 16), seed=9)
    model = PredictableModel(PCA(num_components=6), NearestNeighbor(k=1))
    cv = LeaveOneOutCrossValidation().validate(model, Xs, Ys)
    assert len(cv.results) == len(Ys)
    assert cv.mean_accuracy >= 0.8


def test_simple_validation_result_fields():
    model = PredictableModel(PCA(num_components=10), NearestNeighbor(k=1))
    cv = SimpleValidation().validate(model, X, Y)
    r = cv.results[0]
    assert r.total == len(Y)
    assert 0.0 <= r.accuracy <= 1.0
    assert "ValidationResult" in repr(r)
