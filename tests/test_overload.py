"""Overload-protection suite (ISSUE 3): admission control at
connector-receive, priority-aware + stale shedding in the batcher, the
brownout controller's hysteresis, the durable dead-letter journal, and the
admission-ledger invariant ``admitted == completed + Σ drops_by_reason``.

Everything here runs over ``runtime.fakes.InstantPipeline`` (deterministic,
no hardware) — the overload layer is pure host-side control flow.
"""

import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime import (
    PRIORITY_BULK,
    PRIORITY_INTERACTIVE,
    AdmissionController,
    BrownoutPolicy,
    DeadLetterJournal,
    FakeConnector,
    FrameBatcher,
    RecognizerService,
    ResiliencePolicy,
    TokenBucket,
    parse_priority,
)
from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    RESULT_TOPIC,
    STATUS_TOPIC,
)
from opencv_facerecognizer_tpu.utils.metrics import Metrics

FRAME_HW = (16, 16)


def _wait(cond, timeout=10.0, interval=0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _frame():
    return np.zeros(FRAME_HW, np.float32)


def _service(pipeline=None, **kwargs):
    pipeline = pipeline or InstantPipeline(FRAME_HW)
    connector = FakeConnector()
    kwargs.setdefault("batch_size", 4)
    kwargs.setdefault("resilience", ResiliencePolicy(readback_deadline_s=2.0))
    service = RecognizerService(
        pipeline, connector, frame_shape=FRAME_HW,
        flush_timeout=0.02, similarity_threshold=0.0, **kwargs,
    )
    return pipeline, service, connector


# ---------- priority parsing + token bucket ----------


def test_parse_priority_wire_forms():
    assert parse_priority(None) == PRIORITY_INTERACTIVE
    assert parse_priority("interactive") == PRIORITY_INTERACTIVE
    assert parse_priority("Bulk") == PRIORITY_BULK
    assert parse_priority("enroll") == PRIORITY_BULK
    assert parse_priority(3) == 3
    assert parse_priority(-2) == 0  # clamped
    assert parse_priority("garbage") == PRIORITY_INTERACTIVE  # safe default
    assert parse_priority(object()) == PRIORITY_INTERACTIVE


def test_token_bucket_rate_and_burst():
    tb = TokenBucket(rate=1000.0, burst=3)
    assert tb.try_acquire() and tb.try_acquire() and tb.try_acquire()
    assert not tb.try_acquire()  # burst spent, no time passed
    time.sleep(0.01)  # ~10 tokens refill at 1000/s
    assert tb.try_acquire()


def test_admission_controller_reasons_and_reserve():
    inflight = {"n": 0}
    a = AdmissionController(max_inflight_frames=100,
                            rate_limit_fps=None,
                            interactive_reserve=0.25,
                            inflight_fn=lambda: inflight["n"])
    assert a.admit("t", PRIORITY_INTERACTIVE) is None
    # Bulk loses admission at 75% of the bound; interactive keeps headroom.
    inflight["n"] = 80
    assert a.admit("t", PRIORITY_BULK) == "overload"
    assert a.admit("t", PRIORITY_INTERACTIVE) is None
    inflight["n"] = 100
    assert a.admit("t", PRIORITY_INTERACTIVE) == "overload"
    # Rate limit: burst of 1s x 50fps, then rejections.
    r = AdmissionController(rate_limit_fps=50.0, burst_seconds=1.0)
    admitted = sum(r.admit("t") is None for _ in range(200))
    assert 45 <= admitted <= 60  # the burst, ± refill during the loop
    assert r.admit("t") == "rate_limit"


# ---------- batcher: priority-aware + stale shedding ----------


def test_batcher_overflow_evicts_lowest_priority_first():
    m = Metrics()
    drops = []
    b = FrameBatcher(2, FRAME_HW, flush_timeout=10.0, max_pending=3,
                     metrics=m, drop_log=lambda r, e: drops.append((r, e)))
    assert b.put(_frame(), meta="bulk0", priority=PRIORITY_BULK)
    assert b.put(_frame(), meta="inter0", priority=PRIORITY_INTERACTIVE)
    assert b.put(_frame(), meta="bulk1", priority=PRIORITY_BULK)
    # Full; an interactive arrival evicts the OLDEST bulk, not the oldest
    # frame overall.
    assert b.put(_frame(), meta="inter1", priority=PRIORITY_INTERACTIVE)
    assert m.counter("batcher_dropped_overflow") == 1
    assert drops == [("overflow", [{"meta": "bulk0", "enqueue_ts": drops[0][1][0]["enqueue_ts"],
                                    "priority": PRIORITY_BULK,
                                    "trace_id": None,
                                    "stage": "batcher.overflow"}])]
    batch = b.get_batch(block=False)
    assert batch.metas[:2] == ["inter0", "bulk1"]  # FIFO among survivors


def test_batcher_overflow_rejects_incoming_bulk_when_queue_outranks_it():
    m = Metrics()
    b = FrameBatcher(2, FRAME_HW, flush_timeout=10.0, max_pending=2, metrics=m)
    assert b.put(_frame(), meta="i0", priority=PRIORITY_INTERACTIVE)
    assert b.put(_frame(), meta="i1", priority=PRIORITY_INTERACTIVE)
    # Everything queued outranks the incoming bulk frame: IT is the victim.
    assert not b.put(_frame(), meta="b", priority=PRIORITY_BULK)
    assert m.counter("batcher_dropped_overflow") == 1
    assert b.stats["dropped_overflow"] == 1
    batch = b.get_batch(block=False)
    assert batch.metas[:2] == ["i0", "i1"]  # untouched


def test_batcher_overflow_without_priorities_keeps_drop_oldest():
    # Backward compatibility: all-default priorities degrade to the old
    # freshness-over-backlog rule (oldest evicted).
    b = FrameBatcher(2, FRAME_HW, flush_timeout=10.0, max_pending=3)
    for i in range(5):
        b.put(_frame(), meta=i)
    batch = b.get_batch(block=False)
    assert b.stats["dropped_overflow"] == 2
    assert batch.metas[:2] == [2, 3]


def test_batcher_stale_frames_never_reach_a_dispatch_slot():
    m = Metrics()
    drops = []
    b = FrameBatcher(4, FRAME_HW, flush_timeout=0.01, stale_after_s=0.05,
                     metrics=m, drop_log=lambda r, e: drops.append((r, e)))
    b.put(_frame(), meta="doomed")
    time.sleep(0.08)  # past the freshness bound
    b.put(_frame(), meta="fresh")
    batch = b.get_batch()
    assert batch.count == 1 and batch.metas[0] == "fresh"
    assert m.counter("batcher_dropped_stale") == 1
    assert b.stats["dropped_stale"] == 1
    assert drops[0][0] == "stale" and drops[0][1][0]["meta"] == "doomed"


def test_batcher_stale_eviction_preferred_at_overflow():
    b = FrameBatcher(2, FRAME_HW, flush_timeout=10.0, max_pending=2,
                     stale_after_s=0.05)
    b.put(_frame(), meta="stale-soon", priority=PRIORITY_INTERACTIVE)
    time.sleep(0.08)
    b.put(_frame(), meta="fresh-bulk", priority=PRIORITY_BULK)
    # Queue full; the stale interactive frame is the victim even though a
    # bulk frame is queued (dead weight goes first, whatever its class).
    assert b.put(_frame(), meta="new", priority=PRIORITY_BULK)
    assert b.stats["dropped_stale"] == 1
    assert b.stats["dropped_overflow"] == 0
    batch = b.get_batch(block=False)
    assert batch.metas[:2] == ["fresh-bulk", "new"]


# ---------- dead-letter journal ----------


def test_journal_append_records_and_replay(tmp_path):
    m = Metrics()
    j = DeadLetterJournal(str(tmp_path / "dl.jsonl"), metrics=m)
    j.append("dead_letter", [DeadLetterJournal.frame_entry({"seq": 1}, 2.5, 0),
                             DeadLetterJournal.frame_entry({"seq": 2}, 2.6, 1)])
    j.append("brownout", [DeadLetterJournal.frame_entry({"seq": 3})], level=2)
    records = list(j.records())
    assert [r["reason"] for r in records] == ["dead_letter", "brownout"]
    assert records[0]["frames"][0] == {"meta": {"seq": 1}, "enqueue_ts": 2.5,
                                       "priority": 0, "trace_id": None,
                                       "stage": None}
    assert records[1]["level"] == 2
    assert m.counter("journal_records") == 2
    assert m.counter("journal_frames") == 3
    replayed = []
    n = j.replay(lambda e: replayed.append((e["reason"], e["meta"]["seq"])))
    assert n == 3
    assert replayed == [("dead_letter", 1), ("dead_letter", 2), ("brownout", 3)]
    assert j.replay(lambda e: None, reasons=("brownout",)) == 1
    j.close()


def test_journal_rotation_bounded(tmp_path):
    path = tmp_path / "dl.jsonl"
    j = DeadLetterJournal(str(path), max_bytes=300, backups=1)
    for i in range(50):
        j.append("stale", [DeadLetterJournal.frame_entry({"seq": i})])
    j.close()
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ["dl.jsonl", "dl.jsonl.1"]  # bounded: exactly 1 backup
    assert path.stat().st_size <= 300 + 120  # one record of slack
    # Oldest-first replay across the rotation boundary.
    seqs = [r["frames"][0]["meta"]["seq"] for r in
            DeadLetterJournal(str(path), backups=1).records()]
    assert seqs == sorted(seqs) and seqs[-1] == 49


def test_journal_failures_never_raise(tmp_path):
    m = Metrics()
    j = DeadLetterJournal(str(tmp_path / "dl.jsonl"), metrics=m)
    j.append("failed", [DeadLetterJournal.frame_entry(object())])  # unserializable meta
    assert list(j.records())  # repr-encoded, not lost
    j.close()


# ---------- service: admission + rejection statuses ----------


def test_service_rejects_explicitly_with_aggregated_status():
    _, service, connector = _service(
        admission=AdmissionController(max_inflight_frames=200,
                                      rate_limit_fps=25.0, burst_seconds=0.2))
    service._reject_note_interval_s = 0.0  # publish every rejection window
    service.start(warmup=False)
    try:
        for i in range(60):
            connector.inject(FRAME_TOPIC, {"frame": _frame(),
                                           "meta": {"seq": i}})
        assert _wait(lambda: service.metrics.counter(
            "frames_rejected_rate_limit") > 0)
        assert service.drain(10.0)
    finally:
        service.stop()
    c = service.metrics.counters()
    assert c["frames_rejected_rate_limit"] > 0
    # Explicit backpressure: 'rejected' statuses with the reason, counts
    # aggregated (sum over statuses == rejected counter).
    rejected = [m for m in connector.messages(STATUS_TOPIC)
                if m.get("status") == "rejected"]
    assert rejected and all(m["reason"] == "rate_limit" for m in rejected)
    assert sum(m["count"] for m in rejected) == c["frames_rejected_rate_limit"]
    # Ledger: rejections live OUTSIDE (never admitted); what was admitted
    # reconciles exactly.
    ledger = service.ledger()
    assert ledger["admitted"] == 60 - c["frames_rejected_rate_limit"]
    assert ledger["in_system"] == 0


def test_service_admission_bound_sheds_bulk_before_interactive():
    pipeline = InstantPipeline(FRAME_HW, dispatch_s=0.02)  # 200 fps capacity
    _, service, connector = _service(
        pipeline=pipeline,
        admission=AdmissionController(max_inflight_frames=8),
        inflight_depth=2)
    service.start(warmup=False)
    try:
        # Burst far beyond the bound: mixed priorities.
        for i in range(120):
            pri = "interactive" if i % 2 == 0 else "bulk"
            connector.inject(FRAME_TOPIC, {"frame": _frame(), "priority": pri,
                                           "meta": {"seq": i, "pri": pri}})
        assert service.drain(20.0)
    finally:
        service.stop()
    c = service.metrics.counters()
    assert c.get("frames_rejected_overload", 0) > 0
    done = [m["meta"]["pri"] for m in connector.messages(RESULT_TOPIC)]
    # The 25% interactive reserve must have bought interactive more
    # completions than bulk under the same offered load.
    assert done.count("interactive") > done.count("bulk")
    assert service.ledger()["in_system"] == 0


# ---------- service: brownout controller ----------


def test_brownout_enters_sheds_bulk_and_recovers_with_hysteresis():
    # Deliberately NOT started: the brownout controller is pure host-side
    # logic (connector handlers dispatch synchronously on the fake), so
    # driving the load signal directly keeps every assertion deterministic
    # — a running loop's idle ticks would decay the EWMA under us.
    _, service, connector = _service(
        batch_size=64,  # nothing flushes; frames just queue
        brownout=BrownoutPolicy(queue_wait_s=0.05, exit_ratio=0.5,
                                dwell_s=10.0, bulk_skip=2, max_level=2))
    service._note_queue_wait(0.2)  # EWMA seeds above the threshold
    assert service.brownout_level == 1  # dwell now blocks level 2
    assert service.metrics.gauge("brownout_level") == 1
    # Level 1: bulk is skip-2 shed at intake, interactive untouched.
    for i in range(8):
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "priority": "bulk",
                                       "meta": {"seq": i}})
    for i in range(4):
        connector.inject(FRAME_TOPIC, {"frame": _frame(),
                                       "priority": "interactive",
                                       "meta": {"seq": 100 + i}})
    assert service.metrics.counter("frames_dropped_brownout") == 4
    # Hysteresis: an EWMA below the entry threshold but above the exit
    # band (exit_ratio * threshold) must NOT recover.
    service._brownout_changed_at = 0.0  # dwell elapsed
    service._queue_wait_ewma = 0.04
    service._update_brownout()
    assert service.brownout_level == 1
    # Below the exit band -> recovery.
    service._queue_wait_ewma = 0.01
    service._update_brownout()
    assert service.brownout_level == 0
    msgs = [m for m in connector.messages(STATUS_TOPIC)
            if m.get("status", "").startswith("brownout")]
    assert [m["status"] for m in msgs] == ["brownout", "brownout_recovered"]
    assert msgs[0]["level"] == 1
    assert service.metrics.gauge("brownout_level") == 0
    # Live ledger: 12 admitted, 4 brownout-shed, 8 still queued (in
    # system) — the remainder tracks un-quiesced frames exactly.
    ledger = service.ledger()
    assert ledger["admitted"] == 12
    assert ledger["drops_by_reason"]["frames_dropped_brownout"] == 4
    assert ledger["in_system"] == 8


def test_brownout_max_level_sheds_all_bulk_and_caps_ladder():
    pipeline, service, connector = _service(
        brownout=BrownoutPolicy(queue_wait_s=0.05, dwell_s=0.01, max_level=2),
        batch_size=8, bucket_sizes=(2, 8))
    service.start(warmup=False)
    try:
        # Drive straight to max level.
        for _ in range(3):
            service._note_queue_wait(0.5)
            time.sleep(0.02)
        assert service.brownout_level == 2
        # All bulk shed at intake now.
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "priority": "bulk",
                                       "meta": {"b": 1}})
        assert _wait(lambda: service.metrics.counter(
            "frames_dropped_brownout") >= 1)
        # An oversized interactive batch is trimmed to the smallest bucket
        # (2): 5 admitted -> 2 served per batch, the excess shed with the
        # explicit brownout reason — never silently truncated.
        for i in range(5):
            connector.inject(FRAME_TOPIC, {"frame": _frame(),
                                           "priority": "interactive",
                                           "meta": {"seq": i}})
        assert service.drain(10.0)
    finally:
        service.stop()
    assert all(b <= 2 for b in pipeline.batch_sizes_seen), \
        pipeline.batch_sizes_seen
    ledger = service.ledger()
    assert ledger["in_system"] == 0
    completed = len(connector.messages(RESULT_TOPIC))
    assert completed == ledger["completed"]
    assert (ledger["completed"]
            + ledger["drops_by_reason"]["frames_dropped_brownout"]
            == ledger["admitted"])


def test_brownout_recovers_on_idle_queue():
    """Traffic stopping dead must still recover the brownout level — the
    idle tick feeds the EWMA zeros."""
    _, service, connector = _service(
        brownout=BrownoutPolicy(queue_wait_s=0.05, dwell_s=0.02,
                                max_level=1, ewma_alpha=0.9))
    service.start(warmup=False)
    try:
        service._note_queue_wait(0.5)
        time.sleep(0.03)
        service._note_queue_wait(0.5)
        assert service.brownout_level == 1
        # No traffic at all: the serving loop's idle ticks decay the EWMA.
        assert _wait(lambda: service.brownout_level == 0, timeout=5.0)
    finally:
        service.stop()
    assert service.metrics.counter("brownout_recoveries") == 1


# ---------- dead-letter metadata + journal end to end ----------


def test_dead_letter_status_carries_frame_ids_and_feeds_journal(tmp_path):
    from opencv_facerecognizer_tpu.runtime import FaultInjector

    injector = FaultInjector(seed=3)
    journal = DeadLetterJournal(str(tmp_path / "dl.jsonl"))
    _, service, connector = _service(
        fault_injector=injector, dead_letter_journal=journal,
        batch_size=2, resilience=ResiliencePolicy(readback_deadline_s=0.3))
    service.start(warmup=False)
    try:
        injector.script("readback", "stuck")
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "meta": {"seq": 7}})
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "meta": {"seq": 8}})
        assert _wait(lambda: service.metrics.counter(
            "batches_dead_lettered") >= 1)
    finally:
        service.stop()
        journal.close()
    # The status message names the dead frames (producers can retry).
    dl = next(m for m in connector.messages(STATUS_TOPIC)
              if m["status"] == "dead_letter")
    assert dl["frames"] == 2
    assert dl["frame_ids"] == [{"seq": 7}, {"seq": 8}]
    assert len(dl["enqueued_at"]) == 2
    assert all(ts is not None for ts in dl["enqueued_at"])
    # And the same frames landed in the durable journal.
    records = list(journal.records())
    assert [r["reason"] for r in records] == ["dead_letter"]
    assert [f["meta"] for f in records[0]["frames"]] == [{"seq": 7}, {"seq": 8}]
    # Ledger: both frames accounted as dead-lettered.
    ledger = service.ledger()
    assert ledger["drops_by_reason"]["frames_dead_lettered"] == 2
    assert ledger["in_system"] == 0


def test_abandoned_batch_frames_land_in_ledger_and_journal(tmp_path):
    from opencv_facerecognizer_tpu.runtime import FaultInjector

    injector = FaultInjector(seed=4)
    journal = DeadLetterJournal(str(tmp_path / "dl.jsonl"))
    _, service, connector = _service(
        fault_injector=injector, dead_letter_journal=journal, batch_size=2,
        resilience=ResiliencePolicy(dispatch_retries=0, backoff_base_s=0.01,
                                    readback_deadline_s=2.0, degraded_after=99))
    service.start(warmup=False)
    try:
        injector.script("dispatch", "unavailable")
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "meta": {"seq": 1}})
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "meta": {"seq": 2}})
        assert _wait(lambda: service.metrics.counter("batches_failed") >= 1)
        assert service.drain(10.0)
    finally:
        service.stop()
        journal.close()
    ledger = service.ledger()
    assert ledger["drops_by_reason"]["frames_failed"] == 2
    assert ledger["in_system"] == 0
    assert [r["reason"] for r in journal.records()] == ["failed"]


# ---------- the ledger under a mixed storm ----------


def test_ledger_reconciles_exactly_under_mixed_faults_and_overload(tmp_path):
    from opencv_facerecognizer_tpu.runtime import FaultInjector

    injector = FaultInjector(
        seed=5, rates={"receive": {"flood": 0.3, "drop": 0.1},
                       "dispatch": {"unavailable": 0.05}},
        flood_factor=4)
    journal = DeadLetterJournal(str(tmp_path / "dl.jsonl"))
    pipeline = InstantPipeline(FRAME_HW, dispatch_s=0.01)
    _, service, connector = _service(
        pipeline=pipeline, fault_injector=injector,
        dead_letter_journal=journal,
        admission=AdmissionController(max_inflight_frames=16),
        brownout=BrownoutPolicy(queue_wait_s=0.04, dwell_s=0.1),
        shed_stale_after_s=0.2,
        resilience=ResiliencePolicy(dispatch_retries=1, backoff_base_s=0.005,
                                    backoff_max_s=0.01,
                                    readback_deadline_s=2.0,
                                    degraded_after=999))
    service.start(warmup=False)
    try:
        for i in range(300):
            pri = "interactive" if i % 4 == 0 else "bulk"
            connector.inject(FRAME_TOPIC, {"frame": _frame(), "priority": pri,
                                           "meta": {"seq": i}})
            if i % 25 == 0:
                time.sleep(0.01)
        injector.disarm()
        assert service.drain(30.0)
    finally:
        service.stop()
        journal.close()
    ledger = service.ledger()
    # THE invariant: every admitted frame is completed or in exactly one
    # named drop bucket — nothing vanished, nothing double-counted.
    assert ledger["in_system"] == 0, ledger
    assert ledger["admitted"] > 0 and ledger["completed"] > 0
    # Results on the wire match the completed count exactly.
    assert len(connector.messages(RESULT_TOPIC)) == ledger["completed"]


# ---------- stats surface ----------


def test_stats_command_exposes_ledger_and_brownout():
    _, service, connector = _service(
        brownout=BrownoutPolicy(queue_wait_s=0.5))
    from opencv_facerecognizer_tpu.runtime.recognizer import CONTROL_TOPIC

    connector.inject(CONTROL_TOPIC, {"cmd": "stats"})
    stats = next(m for m in connector.messages(STATUS_TOPIC)
                 if m.get("status") == "stats")
    assert stats["brownout_level"] == 0
    assert "ledger" in stats and stats["ledger"]["in_system"] == 0
