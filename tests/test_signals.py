"""Signals-layer suite (ISSUE 9): streaming log-bucket histograms and the
rolling windows under ``Metrics``, the SLO burn-rate monitor + health
state machine, Prometheus exposition (render + format lint + live
``/prom`` / ``/health`` endpoints), the recompile watchdog, the
``bench_compare`` perf-regression gate, and the journal ``--stage``
filter.

Everything runs over ``runtime.fakes.InstantPipeline`` and fake clocks —
fast, deterministic, no hardware. The one property the whole layer hangs
on — "a rolling-histogram quantile matches the exact sample quantile
within one bucket width" — is tested as a randomized property over
several distributions, not a point check.
"""

import importlib.util
import json
import math
import os
import random
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
from opencv_facerecognizer_tpu.runtime.expo import ExpoServer
from opencv_facerecognizer_tpu.runtime.fakes import (
    InstantPipeline,
    build_overload_stack,
)
from opencv_facerecognizer_tpu.runtime.journal import DeadLetterJournal
from opencv_facerecognizer_tpu.runtime.promtext import (
    lint_prometheus_text,
    render,
)
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    STATUS_TOPIC,
    RecognizerService,
)
from opencv_facerecognizer_tpu.runtime.resilience import ServiceSupervisor
from opencv_facerecognizer_tpu.runtime.slo import (
    SLO,
    SLOMonitor,
    STATE_CRITICAL,
    STATE_OK,
    STATE_WARN,
    default_objectives,
    loop_liveness_objective,
)
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.histogram import (
    BUCKET_BOUNDS,
    BUCKET_GROWTH,
    BUCKET_HI,
    BUCKET_LO,
    LogBucketHistogram,
    RollingHistogram,
    bucket_index,
)
from opencv_facerecognizer_tpu.utils.metrics import Metrics
from opencv_facerecognizer_tpu.utils.tracing import LIFECYCLE_TOPIC, Tracer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_compare", os.path.join(REPO_ROOT, "scripts", "bench_compare.py"))
bench_compare = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_compare)

FRAME_HW = (16, 16)


class FakeClock:
    """A settable monotonic clock for the rolling rings and the monitor."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ---------- log-bucket histogram: schema + property test ----------


def test_bucket_index_total_and_consistent_with_bounds():
    # Totality: clock hiccups (NaN, negative, zero) land in the underflow
    # bucket instead of raising on the serving path.
    assert bucket_index(float("nan")) == 0
    assert bucket_index(-1.0) == 0
    assert bucket_index(0.0) == 0
    assert bucket_index(BUCKET_LO) == 0
    assert bucket_index(BUCKET_HI * 10) == len(BUCKET_BOUNDS) - 1
    # Containment invariant on a dense sweep including exact boundaries:
    # BUCKET_BOUNDS[idx-1] < value <= BUCKET_BOUNDS[idx].
    probes = list(BUCKET_BOUNDS[:-1])
    probes += [b * 1.0000001 for b in BUCKET_BOUNDS[:-1]]
    probes += [10 ** e for e in np.linspace(-4.9, 2.0, 200)]
    last_idx = 0
    for value in sorted(probes):
        idx = bucket_index(value)
        assert value <= BUCKET_BOUNDS[idx]
        if idx > 0:
            assert value > BUCKET_BOUNDS[idx - 1]
        assert idx >= last_idx  # monotone in the value
        last_idx = idx


def test_quantiles_match_exact_within_one_bucket_property():
    """The acceptance property: for randomized data across distributions,
    every reported quantile lies within one bucket width (a factor of
    ``BUCKET_GROWTH`` in log space) of the exact nearest-rank sample
    quantile."""
    distributions = {
        "uniform": lambda rng: rng.uniform(1e-4, 10.0),
        "lognormal": lambda rng: min(100.0, max(2e-5,
                                                math.exp(rng.gauss(-3, 2)))),
        "bimodal": lambda rng: (rng.uniform(0.8e-3, 1.2e-3) if rng.random()
                                < 0.7 else rng.uniform(0.3, 0.8)),
    }
    for seed in (0, 7, 1234):
        for name, draw in distributions.items():
            rng = random.Random(seed)
            values = [draw(rng) for _ in range(2000)]
            hist = LogBucketHistogram()
            for v in values:
                hist.observe(v)
            exact = sorted(values)
            for q in (1, 25, 50, 90, 95, 99):
                rank = min(len(exact) - 1, int(q / 100.0 * len(exact)))
                e = exact[rank]
                r = hist.quantile(q)
                assert e / BUCKET_GROWTH * (1 - 1e-9) <= r \
                    <= e * BUCKET_GROWTH * (1 + 1e-9), \
                    (name, seed, q, e, r)


def test_histogram_merge_equals_union_and_snapshot_shape():
    rng = random.Random(3)
    a, b, union = (LogBucketHistogram(), LogBucketHistogram(),
                   LogBucketHistogram())
    for _ in range(500):
        v = math.exp(rng.uniform(math.log(2e-5), math.log(50.0)))
        target = a if rng.random() < 0.5 else b
        target.observe(v)
        union.observe(v)
    merged = LogBucketHistogram().merge(a).merge(b)
    assert merged.counts == union.counts
    assert merged.count == union.count == 500
    assert merged.sum == pytest.approx(union.sum)
    for q in (50, 95, 99):
        assert merged.quantile(q) == union.quantile(q)
    snap = merged.snapshot()
    assert len(snap["bounds"]) == len(BUCKET_BOUNDS) - 1  # +Inf implied
    assert sum(snap["counts"]) == snap["count"] == 500


def test_empty_histogram_reads():
    hist = LogBucketHistogram()
    assert math.isnan(hist.quantile(50))
    assert hist.fraction_above(0.1) == 0.0


def test_fraction_above_is_bucket_conservative():
    hist = LogBucketHistogram()
    for _ in range(50):
        hist.observe(0.001)
    for _ in range(50):
        hist.observe(1.0)
    # A clean split reads exactly; observations in the threshold's OWN
    # bucket count as not-above (a breach must be provable from counts).
    assert hist.fraction_above(0.01) == pytest.approx(0.5)
    assert hist.fraction_above(1.0) == 0.0
    assert hist.fraction_above(2.0) == 0.0


def test_rolling_window_expiry_and_horizons():
    clock = FakeClock()
    ring = RollingHistogram(window_s=80.0, slices=8, clock=clock)  # 10 s/slice
    ring.observe(0.001)
    clock.t = 25.0
    ring.observe(1.0)
    # Full window sees both; a short horizon reads only the recent slices
    # (the current partial slice always counts).
    assert ring.count() == 2
    assert ring.count(horizon_s=10.0) == 1
    assert ring.fraction_above(0.1) == pytest.approx(0.5)
    assert ring.fraction_above(0.1, horizon_s=10.0) == pytest.approx(1.0)
    # Lazy expiry: once the window rotates past an epoch, reads skip it.
    clock.t = 84.0  # first observation's slice (epoch 0) is now expired
    assert ring.count() == 1
    clock.t = 200.0
    assert ring.count() == 0
    ring.observe(0.5)
    assert ring.count() == 1


def test_metrics_memory_flat_under_100k_observation_soak():
    """The unbounded-window fix: 100k observations into one Metrics
    window hold exactly as many bucket cells as one observation does."""
    rng = random.Random(11)
    metrics = Metrics()
    metrics.observe(mn.QUEUE_WAIT, 0.001)
    window = metrics._latencies[mn.QUEUE_WAIT]
    cells_after_one = window.memory_cells()
    for _ in range(100_000):
        metrics.observe(mn.QUEUE_WAIT, math.exp(rng.uniform(-10, 4)))
    assert window.memory_cells() == cells_after_one
    assert len(window._hists[0].counts) == len(BUCKET_BOUNDS)
    assert metrics.window_count(mn.QUEUE_WAIT) == 100_001
    summary = metrics.summary()
    assert summary[f"{mn.QUEUE_WAIT}_p99_ms"] is not None


# ---------- Metrics surface over the rolling windows ----------


def test_metrics_percentiles_fractions_and_export_state():
    metrics = Metrics()
    for _ in range(90):
        metrics.observe("w", 0.010)
    for _ in range(10):
        metrics.observe("w", 1.0)
    assert metrics.percentile("w", 50) == pytest.approx(0.010, rel=0.1)
    assert metrics.percentile("w", 99) == pytest.approx(1.0, rel=0.1)
    assert metrics.fraction_above("w", 0.1) == pytest.approx(0.10)
    assert metrics.window_count("w") == 100
    # Unknown windows: NaN / 0.0 / 0 — never a raise, never a fake zero
    # percentile.
    assert math.isnan(metrics.percentile("nope", 50))
    assert metrics.fraction_above("nope", 0.1) == 0.0
    assert metrics.window_count("nope") == 0
    metrics.incr(mn.FRAMES_COMPLETED, 3)
    metrics.set_gauge(mn.HEALTH_STATE, 1)
    counters, gauges, hists = metrics.export_state()
    assert counters[mn.FRAMES_COMPLETED] == 3
    assert gauges[mn.HEALTH_STATE] == 1
    assert hists["w"]["count"] == 100
    # A known-but-reset window still exports (count 0) and summaries as
    # explicit nulls — the PR-8 contract preserved over histograms.
    metrics.reset_window("w")
    assert metrics.export_state()[2]["w"]["count"] == 0
    assert metrics.summary()["w_p50_ms"] is None


# ---------- SLO monitor: burn rates + health state machine ----------


def _ratio_slo(**kw):
    defaults = dict(name="completion", kind="ratio", target=0.9,
                    bad_counters=("frames_dropped_brownout",),
                    total_counters=(mn.FRAMES_ADMITTED,),
                    short_s=5.0, long_s=5.0, warn_burn=1.0,
                    critical_burn=2.0)
    defaults.update(kw)
    return SLO(**defaults)


def test_slo_validation():
    with pytest.raises(ValueError):
        SLO(name="x", kind="nope")
    with pytest.raises(ValueError):
        SLO(name="x", kind="latency")  # no window
    with pytest.raises(ValueError):
        SLO(name="x", kind="gauge")  # no value_fn
    with pytest.raises(ValueError):
        SLO(name="x", kind="latency", window="w", target=1.5)


def test_slo_monitor_rejects_windows_beyond_metrics_horizon():
    # A latency horizon longer than the metrics rolling window would read
    # only window_s of data — the monitor must refuse it loudly at
    # construction, not evaluate a quietly-weaker long window.
    metrics = Metrics(window_s=60.0)
    over = SLO(name="p99", kind="latency", window="w", threshold_s=0.1,
               short_s=30.0, long_s=120.0)
    with pytest.raises(ValueError, match="rolling horizon"):
        SLOMonitor(metrics, [over])
    # ...and a window below one ring slice would silently aggregate a
    # full slice anyway — reaction ~slice_s/short_s slower than asked.
    with pytest.raises(ValueError, match="ring resolution"):
        SLOMonitor(Metrics(window_s=600.0, window_slices=20),  # 30 s/slice
                   [SLO(name="p99", kind="latency", window="w",
                        threshold_s=0.1, short_s=5.0, long_s=60.0)])
    # At-or-under the horizon constructs fine; so does a metrics object
    # without a readable window_s (duck-typed fakes) or no metrics at all.
    SLOMonitor(metrics, [SLO(name="p99", kind="latency", window="w",
                             threshold_s=0.1, short_s=30.0, long_s=60.0)])
    class NoWindow:
        def counters(self):
            return {}
    SLOMonitor(NoWindow(), [over])
    SLOMonitor(None, [over])


def test_slo_swapped_windows_rejected():
    # A swapped pair is symmetric for burn severity so it would never
    # surface as a runtime error — but the reported horizons invert and
    # the watchdog-event hold window inflates. Loud constructor instead.
    with pytest.raises(ValueError, match="short-first"):
        SLO(name="x", kind="latency", window="w", threshold_s=0.1,
            short_s=600.0, long_s=60.0)


def test_add_objective_validates_and_rederives():
    metrics = Metrics(window_s=600.0)
    monitor = SLOMonitor(metrics, [SLO(
        name="p99", kind="latency", window="w", threshold_s=0.1,
        short_s=60.0, long_s=300.0)], interval_s=5.0)
    # Post-construction registration runs the same loud validation as
    # __init__ — and a refused objective must not be half-added.
    with pytest.raises(ValueError, match="rolling horizon"):
        monitor.add_objective(SLO(name="over", kind="latency", window="w",
                                  threshold_s=0.1, short_s=60.0,
                                  long_s=1200.0))
    assert len(monitor.objectives) == 1
    ring_before = monitor._counter_ring.maxlen
    assert monitor.event_window_s == 60.0
    monitor.add_objective(SLO(name="g", kind="gauge",
                              value_fn=lambda: 0.0, bound=1.0,
                              short_s=30.0, long_s=600.0))
    assert len(monitor.objectives) == 2
    # The counter ring re-derives to cover the new longest long window,
    # and the watchdog-event hold window follows the new min short_s.
    assert monitor._counter_ring.maxlen > ring_before
    assert monitor.event_window_s == 30.0


def test_loop_liveness_objective_flags_wedged_loop():
    # Empty latency windows read as burn 0 and the ratio objective sees
    # no counter growth, so a wedged serving loop scores a clean /health
    # forever — only the loop_liveness gauge (evaluated by whichever
    # ticker still runs, i.e. the expo backstop) can escalate it.
    metrics = Metrics()
    # The monitor is deliberately NOT wired into the service: this test
    # plays the expo-backstop ticker itself, and a live serving loop both
    # contends the non-blocking evaluation claim and keeps refreshing the
    # stamp the wedge simulation rewinds.
    monitor = SLOMonitor(metrics, [], interval_s=0.01, recovery_evals=1)
    _pipeline, service, connector = build_overload_stack(
        frame_shape=FRAME_HW, batch_size=4, dispatch_s=0.0,
        metrics=metrics)
    monitor.add_objective(loop_liveness_objective(
        service, stale_s=30.0, short_s=5.0, long_s=5.0))
    assert service.loop_staleness_s == 0.0  # stopped: no signal
    service.start(warmup=False)
    try:
        frame = np.zeros(FRAME_HW, np.float32)
        connector.inject(FRAME_TOPIC, {"frame": frame, "meta": {"seq": 0}})
        assert service.drain(timeout=10.0)
        obj = monitor.evaluate()["objectives"]["loop_liveness"]
        assert obj["state"] == "ok" and obj["burn"] < 1.0
    finally:
        service.stop()
    # Simulate a wedged-but-running loop by setting the flags on the
    # stopped service directly: staleness is all the gauge reads, and a
    # real deadlocked thread could not be un-wedged for teardown.
    service._running = True
    try:
        service._loop_progress_t = time.monotonic() - 31.0
        assert (monitor.evaluate()["objectives"]["loop_liveness"]["state"]
                == "warn")
        service._loop_progress_t = time.monotonic() - 200.0
        assert (monitor.evaluate()["objectives"]["loop_liveness"]
                ["state_code"] == STATE_CRITICAL)
    finally:
        service._running = False
    assert service.loop_staleness_s == 0.0  # stopped again: no signal


def test_slo_min_events_floor_suppresses_low_volume_severity():
    # One dropped frame on an idle replica is a huge burn against a tight
    # budget but not an outage: severity needs min_events in BOTH windows;
    # the burn is still reported, flagged low_volume.
    metrics = Metrics()
    clock = FakeClock()
    monitor = SLOMonitor(metrics, [_ratio_slo(target=0.999)],
                         interval_s=5.0, clock=clock)
    monitor.evaluate()
    clock.t = 10.0
    metrics.incr(mn.FRAMES_ADMITTED, 2)
    metrics.incr("frames_dropped_brownout", 1)
    verdict = monitor.evaluate()
    obj = verdict["objectives"]["completion"]
    assert obj["burn_short"] > 100 and obj["low_volume"] is True
    assert monitor.state == "ok"
    # The same rate at volume escalates: the floor gates volume, not rate.
    clock.t = 20.0
    metrics.incr(mn.FRAMES_ADMITTED, 100)
    metrics.incr("frames_dropped_brownout", 50)
    verdict = monitor.evaluate()
    assert "low_volume" not in verdict["objectives"]["completion"]
    assert monitor.state_code == STATE_CRITICAL
    # Gauge objectives are point-in-time reads — exempt from the floor.
    gauge_mon = SLOMonitor(Metrics(), [SLO(
        name="lag", kind="gauge", value_fn=lambda: 2048.0, bound=1024.0)],
        clock=FakeClock())
    gauge_mon.evaluate()
    assert gauge_mon.state_code == STATE_WARN


def test_slo_latency_breach_detected_within_one_interval():
    metrics = Metrics()
    clock = FakeClock()
    monitor = SLOMonitor(metrics, [SLO(
        name="p99", kind="latency", window="w", threshold_s=0.1,
        target=0.99, short_s=30.0, long_s=60.0)],
        interval_s=5.0, clock=clock)
    assert monitor.tick() is not None  # first tick evaluates
    assert monitor.state == "ok"
    # The tick cadence: nothing happens inside the interval.
    clock.t = 2.0
    assert monitor.tick() is None
    # Inject a p99 breach (every observation over threshold -> the whole
    # budget and then some); the NEXT evaluation must see it.
    for _ in range(200):
        metrics.observe("w", 1.0)
    clock.t = 5.1
    verdict = monitor.tick()
    assert verdict is not None and monitor.state_code == STATE_CRITICAL
    obj = verdict["objectives"]["p99"]
    assert obj["burn_short"] >= 6.0 and obj["burn_long"] >= 6.0
    assert metrics.counter(mn.SLO_EVALUATIONS) == 2
    assert metrics.summary()[mn.HEALTH_STATE] == STATE_CRITICAL


def test_slo_severity_requires_both_windows():
    class SplitWindows:
        """Short window burning, long window calm — the flap filter."""

        def counters(self):
            return {}

        def set_gauge(self, name, value):
            pass

        def incr(self, name, value=1.0):
            pass

        def window_count(self, name, horizon_s=None):
            return 100

        def fraction_above(self, name, threshold_s, horizon_s=None):
            return 1.0 if horizon_s <= 30.0 else 0.0

    monitor = SLOMonitor(SplitWindows(), [SLO(
        name="p99", kind="latency", window="w", threshold_s=0.1,
        target=0.99, short_s=30.0, long_s=600.0)], clock=FakeClock())
    verdict = monitor.evaluate()
    assert monitor.state_code == STATE_OK
    assert verdict["objectives"]["p99"]["burn_short"] >= 6.0
    assert verdict["objectives"]["p99"]["burn_long"] == 0.0


def test_slo_ratio_objective_and_hysteresis_recovery():
    metrics = Metrics()
    clock = FakeClock()
    monitor = SLOMonitor(metrics, [_ratio_slo()], interval_s=5.0,
                         recovery_evals=2, clock=clock)
    metrics.incr(mn.FRAMES_ADMITTED, 100)
    monitor.evaluate()
    assert monitor.state == "ok"
    # A drop storm: half the admitted frames die -> frac 0.5 against a
    # 0.1 budget -> burn 5 on both windows -> critical, immediately.
    clock.t = 10.0
    metrics.incr(mn.FRAMES_ADMITTED, 50)
    metrics.incr("frames_dropped_brownout", 25)
    monitor.evaluate()
    assert monitor.state_code == STATE_CRITICAL
    # Recovery de-escalates ONE level per recovery_evals calm evaluations
    # — critical -> warn -> ok takes four calm evals, never a flap.
    states = []
    for i in range(4):
        clock.t = 20.0 + 10.0 * i  # each eval's 5 s windows see no drops
        monitor.evaluate()
        states.append(monitor.state)
    assert states == ["critical", "warn", "warn", "ok"]
    assert metrics.counter(mn.SLO_TRANSITIONS) == 3  # up, down, down


def test_slo_gauge_objective_and_probe_failure_counted():
    metrics = Metrics()
    lag = {"rows": 2048.0}
    monitor = SLOMonitor(metrics, [SLO(
        name="durability_lag", kind="gauge",
        value_fn=lambda: lag["rows"], bound=1024.0,
        warn_burn=1.0, critical_burn=6.0)], clock=FakeClock())
    verdict = monitor.evaluate()
    assert verdict["objectives"]["durability_lag"]["burn"] == 2.0
    assert monitor.state_code == STATE_WARN
    # A dead probe reads burn 0 (no data is not a breach) but is counted.
    lag["rows"] = 0.0

    def boom():
        raise RuntimeError("probe died")

    monitor.objectives[0].value_fn = boom
    monitor.evaluate()
    assert metrics.counter(mn.SLO_PROBE_FAILURES) == 1


def test_slo_watchdog_events_hold_warn_then_expire():
    metrics = Metrics()
    clock = FakeClock()
    monitor = SLOMonitor(metrics, [], interval_s=1.0, recovery_evals=1,
                         event_window_s=10.0, clock=clock)
    monitor.evaluate()
    assert monitor.state == "ok"
    monitor.note_event("recompile_post_warmup")
    assert metrics.counter(
        mn.SLO_EVENTS_PREFIX + "recompile_post_warmup") == 1
    clock.t = 1.0
    verdict = monitor.evaluate()
    assert monitor.state == "warn"
    assert verdict["events"] == {"recompile_post_warmup": 1}
    # Outside the event window the hold releases (one calm eval at
    # recovery_evals=1).
    clock.t = 12.0
    monitor.evaluate()
    assert monitor.state == "ok"


def test_slo_critical_transition_emits_span_and_flight_dump(tmp_path):
    metrics = Metrics()
    tracer = Tracer(sample=1.0, dump_dir=str(tmp_path),
                    min_dump_interval_s=0.0)
    clock = FakeClock()
    monitor = SLOMonitor(metrics, [_ratio_slo()], tracer=tracer,
                         clock=clock)
    metrics.incr(mn.FRAMES_ADMITTED, 100)
    monitor.evaluate()
    clock.t = 10.0
    metrics.incr(mn.FRAMES_ADMITTED, 50)
    metrics.incr("frames_dropped_brownout", 50)
    monitor.evaluate()
    assert monitor.state_code == STATE_CRITICAL
    spans = [s for s in tracer.snapshot(topic=LIFECYCLE_TOPIC)
             if s["stage"] == "health"]
    assert spans and spans[-1]["to_state"] == "critical"
    dumps = [f for f in os.listdir(tmp_path) if "slo_critical" in f]
    assert len(dumps) == 1
    with open(tmp_path / dumps[0]) as fh:
        rec = json.load(fh)
    assert rec["extra"]["verdict"]["objectives"]["completion"]["burn"] > 2.0


def test_default_objectives_composition():
    objectives = default_objectives(drop_counters=("a",), state=None)
    assert [o.name for o in objectives] == ["interactive_p99",
                                            "queue_wait_p99", "completion"]

    class StateStub:
        rows_since_checkpoint = 7

    objectives = default_objectives(drop_counters=("a",), state=StateStub())
    assert objectives[-1].name == "durability_lag"
    assert objectives[-1].value_fn() == 7.0


# ---------- recompile watchdog over the serving loop ----------


def test_recompile_watchdog_silent_when_prewarmed_then_flags_injection():
    metrics = Metrics()
    tracer = Tracer(sample=1.0)
    monitor = SLOMonitor(metrics, [], interval_s=0.05, tracer=tracer)
    pipeline, service, connector = build_overload_stack(
        frame_shape=FRAME_HW, batch_size=4, dispatch_s=0.0,
        metrics=metrics, slo_monitor=monitor, tracer=tracer)
    # The warmup contract, minus the jax graphs: every ladder bucket
    # compiled, then the watchdog armed (exactly what warmup() does).
    pipeline.prewarm_batch_shapes(service._bucket_ladder, FRAME_HW,
                                  np.float32)
    service._warmed = True
    service.start(warmup=False)
    try:
        frame = np.zeros(FRAME_HW, np.float32)
        for i in range(8):
            connector.inject(FRAME_TOPIC, {"frame": frame,
                                           "meta": {"seq": i}})
        assert service.drain(timeout=10.0)
        # The whole prewarmed ladder served cache hits: silence.
        assert set(pipeline.batch_sizes_seen) <= set(service._bucket_ladder)
        assert metrics.counter(mn.RECOMPILES_POST_WARMUP) == 0
        # Injected post-warmup compile: losing the jit cache makes the
        # next dispatch a miss — counted, spanned, and a warn-level SLO
        # event visible on the next evaluation.
        pipeline.compiled_batch_sizes.clear()
        for i in range(8, 12):
            connector.inject(FRAME_TOPIC, {"frame": frame,
                                           "meta": {"seq": i}})
        assert service.drain(timeout=10.0)
        assert metrics.counter(mn.RECOMPILES_POST_WARMUP) >= 1
        assert metrics.counter(
            mn.SLO_EVENTS_PREFIX + "recompile_post_warmup") >= 1
        # The serving loop is ticking the monitor concurrently and
        # evaluate() yields to an in-flight evaluation (returns None) —
        # either way the event lands in the verdict within an interval.
        deadline = time.monotonic() + 5.0
        while ("recompile_post_warmup" not in monitor.verdict()["events"]
               and time.monotonic() < deadline):
            monitor.evaluate()
            time.sleep(0.01)
        assert "recompile_post_warmup" in monitor.verdict()["events"]
        assert monitor.state_code >= STATE_WARN
        spans = [s for s in tracer.snapshot(topic=LIFECYCLE_TOPIC)
                 if s["stage"] == "recompile"]
        assert spans and spans[0]["bucket"] in service._bucket_ladder
    finally:
        service.stop()


# ---------- supervisor publishes health transitions ----------


def test_supervisor_announces_health_transitions_edge_triggered():
    metrics = Metrics()
    monitor = SLOMonitor(metrics, [], interval_s=0.01, recovery_evals=1,
                         event_window_s=0.05)
    _pipeline, service, connector = build_overload_stack(
        frame_shape=FRAME_HW, batch_size=4, dispatch_s=0.0,
        metrics=metrics, slo_monitor=monitor)
    supervisor = ServiceSupervisor(service, poll_interval_s=10.0)
    monitor.evaluate()
    supervisor._check_health(service, STATUS_TOPIC)
    # The boring initial "ok" is not announced; unchanged state neither.
    supervisor._check_health(service, STATUS_TOPIC)
    assert not [m for m in connector.messages(STATUS_TOPIC)
                if m.get("status") == "health"]
    monitor.note_event("recompile_post_warmup")
    monitor.evaluate()
    supervisor._check_health(service, STATUS_TOPIC)
    supervisor._check_health(service, STATUS_TOPIC)  # no re-announce
    announcements = [m for m in connector.messages(STATUS_TOPIC)
                     if m.get("status") == "health"]
    assert len(announcements) == 1
    assert announcements[0]["state"] == "warn"
    assert announcements[0]["events"] == {"recompile_post_warmup": 1}


def test_supervisor_check_health_ticks_the_monitor_itself():
    # The supervisor is the always-on backstop ticker: without expo, a
    # wedged serving loop (the primary ticker) would otherwise freeze the
    # verdict at its last state and loop_liveness could never escalate.
    metrics = Metrics()
    monitor = SLOMonitor(metrics, [], interval_s=0.01)
    _pipeline, service, _connector = build_overload_stack(
        frame_shape=FRAME_HW, batch_size=4, dispatch_s=0.0,
        metrics=metrics, slo_monitor=monitor)
    supervisor = ServiceSupervisor(service, poll_interval_s=10.0)
    assert monitor.verdict()["evaluations"] == 0
    supervisor._check_health(service, STATUS_TOPIC)
    # The service was never started: only the supervisor's own tick can
    # have driven this evaluation.
    assert monitor.verdict()["evaluations"] >= 1


# ---------- Prometheus exposition: render + format lint ----------


def test_prom_render_families_labels_and_lint_clean():
    metrics = Metrics()
    metrics.incr(mn.FRAMES_COMPLETED, 5)
    metrics.set_gauge(mn.BROWNOUT_LEVEL, 1)
    metrics.set_gauge(mn.SLO_BURN_PREFIX + "completion", 1.5)
    metrics.incr(mn.FRAMES_REJECTED_PREFIX + "overload", 2)
    metrics.incr(mn.SLO_EVENTS_PREFIX + "recompile_post_warmup")
    for v in (0.001, 0.01, 0.1):
        metrics.observe(mn.QUEUE_WAIT, v)
    text = render(metrics)
    assert lint_prometheus_text(text) == []
    assert "# TYPE ocvf_frames_completed_total counter" in text
    assert "ocvf_frames_completed_total 5" in text
    assert "# TYPE ocvf_brownout_level gauge" in text
    # Dynamic prefix families fold into labels, one family each.
    assert 'ocvf_frames_rejected_total{reason="overload"} 2' in text
    assert 'ocvf_slo_burn{objective="completion"} 1.5' in text
    assert 'ocvf_slo_events_total{reason="recompile_post_warmup"} 1' in text
    # Histograms: cumulative buckets, +Inf == _count, sum present.
    assert "# TYPE ocvf_queue_wait_seconds histogram" in text
    assert 'ocvf_queue_wait_seconds_bucket{le="+Inf"} 3' in text
    assert "ocvf_queue_wait_seconds_count 3" in text


def test_prom_label_value_escaping():
    metrics = Metrics()
    weird = 'bad"reason\\with\nnewline'
    metrics.incr(mn.FRAMES_REJECTED_PREFIX + weird)
    text = render(metrics)
    assert lint_prometheus_text(text) == []
    assert r'reason="bad\"reason\\with\nnewline"' in text


def test_prom_format_lint_catches_malformations():
    cases = {
        "no TYPE": "ocvf_x_total 1\n",
        "TYPE after samples": ("ocvf_x_total 1\n"
                               "# TYPE ocvf_x_total counter\n"),
        "duplicate TYPE": ("# TYPE ocvf_x counter\n"
                           "# TYPE ocvf_x counter\nocvf_x 1\n"),
        "bogus kind": "# TYPE ocvf_x bogus\nocvf_x 1\n",
        "unparseable value": "# TYPE ocvf_x gauge\nocvf_x twelve\n",
        "illegal escape": ('# TYPE ocvf_h histogram\n'
                           'ocvf_h_bucket{le="a\\q"} 1\n'
                           'ocvf_h_bucket{le="+Inf"} 1\n'
                           'ocvf_h_sum 1\nocvf_h_count 1\n'),
        "missing +Inf": ('# TYPE ocvf_h histogram\n'
                         'ocvf_h_bucket{le="0.1"} 1\n'
                         'ocvf_h_sum 1\nocvf_h_count 1\n'),
        "non-cumulative": ('# TYPE ocvf_h histogram\n'
                           'ocvf_h_bucket{le="0.1"} 5\n'
                           'ocvf_h_bucket{le="+Inf"} 3\n'
                           'ocvf_h_sum 1\nocvf_h_count 3\n'),
        "+Inf != count": ('# TYPE ocvf_h histogram\n'
                          'ocvf_h_bucket{le="0.1"} 1\n'
                          'ocvf_h_bucket{le="+Inf"} 2\n'
                          'ocvf_h_sum 1\nocvf_h_count 3\n'),
    }
    for label, text in cases.items():
        assert lint_prometheus_text(text), f"lint missed: {label}"


# ---------- live expo endpoints: /prom, /health, /spans bounds ----------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.status, json.loads(resp.read().decode())


def _get_raw(url):
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return (resp.status, resp.headers.get("Content-Type"),
                resp.read().decode())


def _service_with_expo(slo_interval_s=0.05, refresh_s=10.0):
    metrics = Metrics()
    tracer = Tracer(sample=1.0)
    monitor = SLOMonitor(metrics, [SLO(
        name="queue_wait_p99", kind="latency", window=mn.QUEUE_WAIT,
        threshold_s=0.5, target=0.9, short_s=30.0, long_s=60.0)],
        interval_s=slo_interval_s, tracer=tracer)
    pipeline, service, connector = build_overload_stack(
        frame_shape=FRAME_HW, batch_size=4, dispatch_s=0.0,
        metrics=metrics, slo_monitor=monitor, tracer=tracer)
    expo = ExpoServer(service, port=0, refresh_s=refresh_s,
                      bench_path=os.path.join(REPO_ROOT,
                                              "BENCH_DETAIL.json"))
    return pipeline, service, connector, expo, monitor, metrics


def test_expo_prom_and_health_endpoints_live():
    _pipeline, service, connector, expo, monitor, metrics = \
        _service_with_expo()
    service.start(warmup=False)
    expo.start()
    base = f"http://{expo.host}:{expo.port}"
    try:
        frame = np.zeros(FRAME_HW, np.float32)
        for i in range(8):
            connector.inject(FRAME_TOPIC, {"frame": frame,
                                           "meta": {"seq": i}})
        assert service.drain(timeout=10.0)

        status, index = _get_json(base + "/")
        assert "/prom" in index["endpoints"] and "/health" in index["endpoints"]
        # /prom: Prometheus content type, lints clean, carries the live
        # counters and the e2e histogram family.
        status, ctype, text = _get_raw(base + "/prom")
        assert status == 200 and ctype.startswith("text/plain")
        assert lint_prometheus_text(text) == []
        assert "ocvf_frames_completed_total 8" in text
        assert "# TYPE ocvf_e2e_latency_seconds histogram" in text
        # /health: ok after the serving loop's tick evaluated.
        status, health = _get_json(base + "/health")
        assert status == 200 and health["state"] == "ok"
        assert "queue_wait_p99" in health["objectives"]
        # An injected p99 breach flips the verdict within one evaluation
        # interval — and critical answers 503 for probes/load balancers.
        for _ in range(200):
            metrics.observe(mn.QUEUE_WAIT, 5.0)
        monitor.evaluate()
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(base + "/health")
        assert err.value.code == 503
        body = json.loads(err.value.read().decode())
        assert body["state"] == "critical"
        assert body["objectives"]["queue_wait_p99"]["burn_short"] >= 6.0
    finally:
        expo.stop()
        service.stop()


def test_expo_health_without_monitor():
    expo = ExpoServer(metrics=Metrics(), port=0, refresh_s=10.0)
    expo.start()
    try:
        status, health = _get_json(
            f"http://{expo.host}:{expo.port}/health")
        assert status == 200 and health["state"] is None
    finally:
        expo.stop()


def test_expo_spans_limit_bounds_checking():
    metrics = Metrics()
    tracer = Tracer(sample=1.0)
    for _ in range(20):
        tracer.emit(tracer.new_trace(), "receive", topic="t")
    expo = ExpoServer(tracer=tracer, metrics=metrics, port=0,
                      refresh_s=10.0)
    expo.start()
    base = f"http://{expo.host}:{expo.port}"
    try:
        status, spans = _get_json(base + "/spans?topic=t&limit=5")
        assert status == 200 and len(spans["spans"]) == 5
        status, spans = _get_json(base + "/spans?n=7")  # legacy alias
        assert status == 200 and len(spans["spans"]) == 7
        status, spans = _get_json(base + "/spans?limit=999999")  # clamped
        assert status == 200 and len(spans["spans"]) == 20
        for bad in ("abc", "0", "-3", "1.5"):
            with pytest.raises(urllib.error.HTTPError) as err:
                _get_json(base + f"/spans?limit={bad}")
            assert err.value.code == 400, bad
            assert "limit" in json.loads(err.value.read().decode())["error"]
    finally:
        expo.stop()


def test_expo_concurrent_get_hammer_no_500s_counters_consistent():
    _pipeline, service, connector, expo, _monitor, metrics = \
        _service_with_expo()
    service.start(warmup=False)
    expo.start()
    base = f"http://{expo.host}:{expo.port}"
    paths = ("/metrics", "/prom", "/health", "/ledger", "/brownout",
             "/spans?limit=50")
    statuses = []
    lock = threading.Lock()

    def hammer(worker):
        got = []
        for i in range(24):
            url = base + paths[(worker + i) % len(paths)]
            try:
                with urllib.request.urlopen(url, timeout=10.0) as resp:
                    resp.read()
                    got.append(resp.status)
            except urllib.error.HTTPError as err:
                got.append(err.code)
        with lock:
            statuses.extend(got)

    try:
        frame = np.zeros(FRAME_HW, np.float32)
        for i in range(8):
            connector.inject(FRAME_TOPIC, {"frame": frame,
                                           "meta": {"seq": i}})
        threads = [threading.Thread(target=hammer, args=(w,))
                   for w in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(statuses) == 8 * 24
        assert set(statuses) == {200}  # no 500s, no flapping health
        assert service.drain(timeout=10.0)
        assert metrics.counter(mn.EXPO_ERRORS) == 0
        assert metrics.counter(mn.EXPO_REQUESTS) >= len(statuses)
    finally:
        expo.stop()
        service.stop()


# ---------- bench_compare: the perf-regression gate ----------


def _smoke_doc(e2e=10.0, ready=3.0, dropped=0, p99=80.0, done=120,
               offered=120, ratio=1.0, scaleout_x2=2.0, parity=1.0,
               cutover_ratio=0.95, ingest_p99=0.6, ingest_uplift=2.5,
               cascade_uplift=4.0, video_uplift=2.8, failover_s=0.25,
               registry_parity=1.0, registry_ratio=0.93):
    return {
        "modes": {"overlapped": {
            "e2e_p50_ms": e2e, "dropped_frames": dropped,
            "decomposition_ms": {"ready_wait_p50_ms": ready}}},
        "overload_sweep": {"rows": [
            {"offered_multiplier": 4.0, "interactive_e2e_p99_ms": p99,
             "interactive_offered": offered,
             "interactive_completed": done}]},
        "tracing_overhead": {"p50_ratio": ratio},
        "replica_scaleout": {"scaling": {"x2": scaleout_x2}},
        "rollout": {"parity_agreement": parity,
                    "cutover_window_completed_ratio": cutover_ratio},
        "registry": {"parity_agreement": registry_parity,
                     "swap_window_completed_ratio": registry_ratio},
        "ingest": {"h2d": {"32": {"uint8_ring": {"p99_ms": ingest_p99}}},
                   "uplift": {"b32": {"uplift": ingest_uplift}}},
        "cascade": {"uplift": {"d0": {"uplift": cascade_uplift}}},
        "video": {"cells": {"c90": {"uplift": video_uplift}}},
        "partition": {"failover_s": failover_s},
    }


def _write(tmp_path, name, doc):
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return str(path)


def test_bench_compare_self_compare_is_clean(tmp_path):
    base = _write(tmp_path, "base.json", _smoke_doc())
    assert bench_compare.main([base, base]) == 0
    report = bench_compare.compare(_smoke_doc(), _smoke_doc())
    assert report["ok"] and not report["regressions"]
    assert all(r["verdict"] == "ok" for r in report["metrics"])


def test_bench_compare_flags_each_regression_direction(tmp_path):
    base = _write(tmp_path, "base.json", _smoke_doc())
    # e2e p50 doubled: above 1.10x + 0.5 ms.
    assert bench_compare.main(
        [base, _write(tmp_path, "a.json", _smoke_doc(e2e=25.0))]) == 1
    # completion ratio collapsed: below 0.98x (a higher-is-better
    # metric). The ratio — not the raw completed count — is what gates:
    # the offer loop is time-based, so counts drift between clean runs.
    assert bench_compare.main(
        [base, _write(tmp_path, "b.json", _smoke_doc(done=50))]) == 1
    # A clean run that simply OFFERED fewer frames (run-to-run drift at
    # 100% completion) stays green — the absolute-count false positive.
    assert bench_compare.main(
        [base, _write(tmp_path, "b2.json",
                      _smoke_doc(done=100, offered=100))]) == 0
    # tracing overhead ratio drifted past the absolute threshold.
    assert bench_compare.main(
        [base, _write(tmp_path, "c.json", _smoke_doc(ratio=1.05))]) == 1
    # Replica scale-out collapsed: below 0.90x of the baseline's 2.0x
    # (a candidate may not quietly lose the router's scaling win).
    assert bench_compare.main(
        [base, _write(tmp_path, "e.json", _smoke_doc(scaleout_x2=1.2))]) == 1
    # Small jitter inside thresholds stays green.
    assert bench_compare.main(
        [base, _write(tmp_path, "d.json",
                      _smoke_doc(e2e=10.6, p99=85.0, done=118,
                                 scaleout_x2=1.9))]) == 0


def test_bench_compare_missing_metric_and_overrides(tmp_path):
    base = _write(tmp_path, "base.json", _smoke_doc())
    gone = _smoke_doc()
    del gone["tracing_overhead"]
    candidate = _write(tmp_path, "gone.json", gone)
    # The candidate stopped measuring something: structural regression...
    assert bench_compare.main([base, candidate]) == 1
    # ...unless explicitly allowed.
    assert bench_compare.main([base, candidate, "--allow-missing"]) == 0
    # Absent from BOTH artifacts: skipped, not failed.
    both = _write(tmp_path, "both.json", gone)
    assert bench_compare.main([both, both]) == 0
    # Asymmetry: a BASELINE predating the metric (older artifact) has
    # nothing to regress from — skipped, the gate stays green.
    assert bench_compare.main([candidate, base]) == 0
    report = bench_compare.compare(gone, _smoke_doc())
    (row,) = [r for r in report["metrics"]
              if r["metric"] == "tracing_p50_ratio"]
    assert row["verdict"] == "skipped" and "predates" in row["note"]
    # Threshold override loosens one metric's gate.
    slow = _write(tmp_path, "slow.json", _smoke_doc(e2e=25.0))
    assert bench_compare.main(
        [base, slow, "--threshold", "overlapped_e2e_p50_ms=3.0"]) == 0
    # Unusable input: unknown threshold, bad number, garbage file -> rc 2.
    assert bench_compare.main([base, slow, "--threshold", "nope=1"]) == 2
    assert bench_compare.main(
        [base, slow, "--threshold", "overlapped_e2e_p50_ms=x"]) == 2
    garbage = tmp_path / "garbage.json"
    garbage.write_text("not json")
    assert bench_compare.main([base, str(garbage)]) == 2
    assert bench_compare.main([base, str(tmp_path / "missing.json")]) == 2


def test_bench_compare_json_report_shape(tmp_path, capsys):
    base = _write(tmp_path, "base.json", _smoke_doc())
    cand = _write(tmp_path, "cand.json", _smoke_doc(e2e=25.0))
    assert bench_compare.main([base, cand, "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] is False
    by_name = {r["metric"]: r for r in report["metrics"]}
    assert by_name["overlapped_e2e_p50_ms"]["verdict"] == "regression"
    assert by_name["overlapped_e2e_p50_ms"]["limit"] == pytest.approx(11.5)


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REPO_ROOT, "BENCH_SERVING_smoke.json")),
    reason="no committed smoke artifact")
def test_bench_compare_real_artifact_self_compare():
    artifact = os.path.join(REPO_ROOT, "BENCH_SERVING_smoke.json")
    assert bench_compare.main([artifact, artifact]) == 0


# ---------- journal --stage filter ----------


def test_journal_cli_stage_filter_and_composition(tmp_path, capsys):
    from opencv_facerecognizer_tpu.runtime import journal as journal_mod

    path = str(tmp_path / "dead.jsonl")
    journal = DeadLetterJournal(path)
    journal.append("stale", [journal.frame_entry(
        meta={"seq": 1}, trace_id=11, stage="batcher.stale")])
    journal.append("dead_letter", [journal.frame_entry(
        meta={"seq": 2}, trace_id=22, stage="readback.dead_letter")])
    journal.append("stale", [journal.frame_entry(
        meta={"seq": 3}, trace_id=33, stage="batcher.stale")])
    journal.close()

    assert journal_mod.main([path, "--stage", "batcher.stale"]) == 0
    rows = [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]
    assert [r["frames"][0]["meta"]["seq"] for r in rows] == [1, 3]
    # Filters compose (AND): stage + trace narrows to one frame.
    assert journal_mod.main(
        [path, "--stage", "batcher.stale", "--trace", "33"]) == 0
    rows = [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]
    assert [r["frames"][0]["meta"]["seq"] for r in rows] == [3]
    # An unmatched stage prints nothing and still exits 0.
    assert journal_mod.main([path, "--stage", "nope"]) == 0
    assert capsys.readouterr().out.strip() == ""
