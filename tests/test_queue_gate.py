"""The measurement queue's fused-schedule re-run gate
(scripts/check_sepblock_win.py): pure decision logic, pinned here so the
queue's one branch can't silently rot."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from check_sepblock_win import sepblock_won  # noqa: E402


def _write(tmp_path, doc):
    p = tmp_path / "BENCH_DETAIL.json"
    p.write_text(json.dumps(doc))
    return str(p)


def test_win_at_any_batch_triggers(tmp_path):
    doc = {"sepblock_fused": {"batches": {
        "64": {"speedup": 0.98}, "256": {"speedup": 1.31}}}}
    assert sepblock_won(_write(tmp_path, doc))


def test_below_threshold_does_not_trigger(tmp_path):
    doc = {"sepblock_fused": {"batches": {
        "64": {"speedup": 1.01}, "256": {"speedup": 1.04}}}}
    assert not sepblock_won(_write(tmp_path, doc))


def test_failed_ab_rows_do_not_trigger(tmp_path):
    # bench_sepblock records {"error": ...} rows (no speedup key) when a
    # side fails — those must read as no-win, not crash
    doc = {"sepblock_fused": {"batches": {
        "64": {"flax": {"error": "Mosaic"}},
        "256": {"speedup": None}}}}
    assert not sepblock_won(_write(tmp_path, doc))


def test_missing_file_or_section_does_not_trigger(tmp_path):
    assert not sepblock_won(str(tmp_path / "nope.json"))
    assert not sepblock_won(_write(tmp_path, {}))
    (tmp_path / "bad.json").write_text("{not json")
    assert not sepblock_won(str(tmp_path / "bad.json"))
