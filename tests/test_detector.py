"""CNN detector: target building, loss, end-to-end training on synthetic
scenes, CascadedDetector-compatible API (SURVEY.md §7.6)."""

import numpy as np
import pytest

import jax.numpy as jnp

from opencv_facerecognizer_tpu.models.detector import (
    STRIDE,
    CNNFaceDetector,
    DetectorNet,
    decode_detections,
    detector_loss,
    gaussian_heatmap_targets,
)
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes


def test_gaussian_targets_peak_at_centers():
    boxes = np.array([[[16, 24, 48, 56], [0, 0, 0, 0]]], dtype=np.float32)
    heat, size, offset, mask = gaussian_heatmap_targets(boxes, np.array([1]), (96, 96), 2)
    assert heat.shape == (1, 12, 12)
    cy, cx = (16 + 48) / 2 / STRIDE, (24 + 56) / 2 / STRIDE
    iy, ix = int(cy), int(cx)
    assert heat[0].argmax() == iy * 12 + ix
    assert mask[0].sum() == 1.0
    np.testing.assert_allclose(size[0, iy, ix], [4.0, 4.0])
    np.testing.assert_allclose(offset[0, iy, ix], [cy - iy, cx - ix], atol=1e-6)


def test_detector_loss_prefers_correct_heatmap():
    boxes = np.array([[[16, 16, 40, 40]]], dtype=np.float32)
    heat, size, offset, mask = gaussian_heatmap_targets(boxes, np.array([1]), (64, 64), 1)
    targets = {"heatmap": jnp.asarray(heat), "size": jnp.asarray(size),
               "offset": jnp.asarray(offset), "mask": jnp.asarray(mask)}
    logit_good = np.full((1, 8, 8), -6.0, dtype=np.float32)
    iy, ix = np.unravel_index(heat[0].argmax(), heat[0].shape)
    logit_good[0, iy, ix] = 6.0
    good = {"heatmap": jnp.asarray(logit_good), "size": targets["size"],
            "offset": targets["offset"]}
    bad = {"heatmap": jnp.asarray(-logit_good), "size": targets["size"],
           "offset": targets["offset"]}
    assert float(detector_loss(good, targets)) < float(detector_loss(bad, targets))


def test_decode_static_shapes():
    net = DetectorNet(features=(8, 8, 16), head_features=16)
    import jax

    params = net.init(jax.random.PRNGKey(0), jnp.zeros((2, 64, 64)))["params"]
    out = net.apply({"params": params}, jnp.zeros((2, 64, 64)))
    boxes, scores, valid = decode_detections(out, max_faces=5)
    assert boxes.shape == (2, 5, 4)
    assert scores.shape == (2, 5)
    assert valid.shape == (2, 5)


@pytest.fixture(scope="module")
def trained_detector():
    scenes, boxes, counts = make_synthetic_scenes(48, (96, 96), max_faces=2, seed=3)
    det = CNNFaceDetector(features=(8, 16, 32), head_features=32, max_faces=4,
                          score_threshold=0.25)
    det.train(scenes, boxes, counts, steps=250, batch_size=16, learning_rate=2e-3)
    return det


def test_detector_quality_bands(trained_detector):
    """Recall/precision@IoU=0.5 on held-out scenes (VERDICT round-1 #4:
    the cascade replacement must be measurably good — 50% recall passing
    was far too low a bar). Measured headroom: this recipe reaches ~0.98
    recall / ~1.0 precision; the bands leave margin for seed jitter."""
    from opencv_facerecognizer_tpu.models.detector import evaluate_detector

    scenes, boxes, counts = make_synthetic_scenes(32, (96, 96), max_faces=2, seed=99)
    m = evaluate_detector(trained_detector, scenes, boxes, counts,
                          iou_threshold=0.5)
    assert m["recall"] >= 0.9, m
    assert m["precision"] >= 0.9, m
    assert m["mean_matched_iou"] >= 0.7, m


def test_detect_single_image_reference_api(trained_detector):
    scenes, boxes, counts = make_synthetic_scenes(4, (96, 96), max_faces=1, seed=7)
    i = int(np.flatnonzero(counts > 0)[0])
    rects = trained_detector.detect(scenes[i])
    assert isinstance(rects, list)
    assert all(len(r) == 4 for r in rects)
    # x-first tuples, ints
    if rects:
        x0, y0, x1, y1 = rects[0]
        assert x1 > x0 and y1 > y0


def test_detect_before_train_raises():
    det = CNNFaceDetector()
    with pytest.raises(RuntimeError):
        det.detect(np.zeros((64, 64), dtype=np.float32))


def test_detect_batch_clips_boxes_to_unpadded_extent():
    """Non-multiple-of-8 inputs are edge-padded before decode; the returned
    boxes must still live inside the CALLER's (h, w), not the padded canvas
    (a border face could otherwise report coords up to STRIDE-1 px out)."""
    import jax

    det = CNNFaceDetector(features=(8, 8), max_faces=4, space_to_depth=2,
                          score_threshold=0.0)
    params = det.net.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64)))["params"]
    det.load_params(params)
    h, w = 67, 70  # pads to 72x72
    boxes, scores, valid = det.detect_batch(
        jnp.asarray(np.random.default_rng(0).uniform(0, 255, (2, h, w)),
                    jnp.float32))
    b = np.asarray(boxes)
    assert b.shape == (2, 4, 4)
    # exclusive yxyx bounds: y1 == h / x1 == w are legal edge boxes
    assert (b[..., [0, 2]] <= h).all() and (b[..., [1, 3]] <= w).all()
    assert (b >= 0).all()
