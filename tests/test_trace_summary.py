"""Self-time accounting for the profiler-trace summary (ADVICE r4: raw
duration sums double-count nested events, inflating top-op totals relative
to the interval-union busy fraction)."""

import os
import sys
from collections import namedtuple

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from trace_summary import _line_self_times  # noqa: E402

Ev = namedtuple("Ev", "name start_ns end_ns duration_ns")


def _ev(name, start, end):
    return Ev(name, start, end, end - start)


def test_nested_child_charged_to_parent_once():
    # parent [0,100] encloses child [10,30] and grandchild [12,20]
    events = [_ev("parent", 0, 100), _ev("child", 10, 30), _ev("grand", 12, 20)]
    self_ns = _line_self_times(events)
    assert self_ns["grand"] == 8
    assert self_ns["child"] == 20 - 8  # child minus grandchild
    assert self_ns["parent"] == 100 - 20  # parent minus DIRECT child only
    # invariant: self times sum to the union of intervals (== busy time)
    assert sum(self_ns.values()) == 100


def test_siblings_do_not_interfere():
    events = [_ev("p", 0, 50), _ev("a", 5, 15), _ev("b", 20, 40)]
    self_ns = _line_self_times(events)
    assert self_ns["a"] == 10 and self_ns["b"] == 20
    assert self_ns["p"] == 50 - 10 - 20
    assert sum(self_ns.values()) == 50


def test_sequential_top_level_events_unchanged():
    events = [_ev("x", 0, 10), _ev("y", 10, 25), _ev("x", 30, 35)]
    self_ns = _line_self_times(events)
    assert self_ns["x"] == 15  # same-name events aggregate
    assert self_ns["y"] == 15


def test_empty_line():
    assert _line_self_times([]) == {}
