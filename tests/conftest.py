"""Test harness config (SURVEY.md §4 prescription).

Tests run on the CPU backend with 8 virtual devices so N-way sharding is
exercised without a TPU pod; the real-chip paths are covered by bench.py and
__graft_entry__.py which the driver runs on hardware.

Gotcha: this environment's sitecustomize force-registers the axon TPU
backend and overrides the JAX_PLATFORMS env var, so merely setting the env
is NOT enough — ``jax.config.update('jax_platforms', 'cpu')`` after import
is what actually wins. XLA_FLAGS still must be set before the first backend
initialization to get the 8 virtual CPU devices.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_report_header(config):
    return f"jax backend: {jax.devices()[0].platform}, devices: {len(jax.devices())}"
