"""Test harness config (SURVEY.md §4 prescription).

Tests run on the CPU backend with 8 virtual devices so N-way sharding is
exercised without a TPU pod; the real-chip paths are covered by bench.py and
__graft_entry__.py which the driver runs on hardware. Env vars must be set
before jax initializes its backend, hence this conftest does it at import
time (pytest imports conftest before any test module).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
