"""Feature plugin boundary tests (SURVEY.md §4, §7.2)."""

import numpy as np
import pytest

from opencv_facerecognizer_tpu.models import (
    ChainOperator,
    CombineOperator,
    CombineOperatorND,
    Fisherfaces,
    HistogramEqualization,
    Identity,
    LDA,
    MinMaxNormalize,
    PCA,
    Resize,
    SpatialHistogram,
    TanTriggsPreprocessing,
)
from opencv_facerecognizer_tpu.ops import lbp as lbp_ops
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

X, Y, NAMES = make_synthetic_faces(num_subjects=6, per_subject=6, size=(24, 24), seed=7)


def test_identity_flattens():
    feat = Identity()
    out = np.asarray(feat.compute(X, Y))
    assert out.shape == (36, 24 * 24)
    one = np.asarray(feat.extract(X[0]))
    np.testing.assert_allclose(one, X[0].ravel(), rtol=1e-6)


def test_pca_compute_extract_consistency():
    feat = PCA(num_components=10)
    proj = np.asarray(feat.compute(X, Y))
    assert proj.shape == (36, 10)
    again = np.asarray(feat.extract(X))
    np.testing.assert_allclose(proj, again, atol=1e-3)
    single = np.asarray(feat.extract(X[3]))
    np.testing.assert_allclose(single, proj[3], atol=1e-3)


def test_pca_extract_before_compute_raises():
    with pytest.raises(RuntimeError):
        PCA(5).extract(X[0])


def test_lda_projects_to_c_minus_1():
    feat = LDA()
    proj = np.asarray(feat.compute(X, Y))
    assert proj.shape == (36, 5)


def test_fisherfaces_class_separation():
    feat = Fisherfaces()
    proj = np.asarray(feat.compute(X, Y))
    assert proj.shape == (36, 5)
    # class centroids should be far apart relative to within-class spread
    means = np.stack([proj[Y == c].mean(0) for c in range(6)])
    within = np.mean([np.linalg.norm(proj[Y == c] - means[c], axis=1).mean() for c in range(6)])
    between = np.linalg.norm(means[:, None] - means[None], axis=-1)
    between = between[~np.eye(6, dtype=bool)].mean()
    assert between > 2.0 * within


def test_spatial_histogram_shapes_and_lbph_defaults():
    feat = SpatialHistogram(sz=(4, 4))
    out = np.asarray(feat.compute(X, Y))
    assert out.shape == (36, 4 * 4 * 256)
    single = np.asarray(feat.extract(X[0]))
    np.testing.assert_allclose(single, out[0], atol=1e-6)


def test_spatial_histogram_with_var_lbp():
    feat = SpatialHistogram(lbp_operator=lbp_ops.VarLBP(bins=32), sz=(2, 2))
    out = np.asarray(feat.compute(X, Y))
    assert out.shape == (36, 2 * 2 * 32)


def test_chain_operator_preprocess_then_subspace():
    chain = ChainOperator(TanTriggsPreprocessing(), Fisherfaces())
    proj = np.asarray(chain.compute(X, Y))
    assert proj.shape == (36, 5)
    single = np.asarray(chain.extract(X[5]))
    np.testing.assert_allclose(single, proj[5], atol=1e-2)


def test_chain_operator_resize_first():
    chain = ChainOperator(Resize((16, 16)), PCA(8))
    proj = np.asarray(chain.compute(X, Y))
    assert proj.shape == (36, 8)


def test_combine_operator_concatenates():
    comb = CombineOperator(PCA(4), SpatialHistogram(sz=(2, 2)))
    out = np.asarray(comb.compute(X, Y))
    assert out.shape == (36, 4 + 2 * 2 * 256)
    single = np.asarray(comb.extract(X[1]))
    np.testing.assert_allclose(single, out[1], atol=1e-3)


def test_combine_operator_nd_preserves_structure():
    # Two image-shaped features concatenated without flattening: widths add.
    comb = CombineOperatorND(TanTriggsPreprocessing(), HistogramEqualization())
    out = np.asarray(comb.compute(X, Y))
    assert out.shape == (36, 24, 48)
    a = np.asarray(TanTriggsPreprocessing().compute(X, Y))
    b = np.asarray(HistogramEqualization().compute(X, Y))
    np.testing.assert_allclose(out, np.concatenate([a, b], axis=-1), atol=1e-5)
    single = np.asarray(comb.extract(X[3]))
    np.testing.assert_allclose(single, out[3], atol=1e-5)
    # Non-negative axes address per-sample dims, so batched and single calls
    # concatenate along the same semantic axis (heights add with axis 0).
    comb0 = CombineOperatorND(TanTriggsPreprocessing(), HistogramEqualization(),
                              hstack_axis=0)
    out0 = np.asarray(comb0.compute(X, Y))
    assert out0.shape == (36, 48, 24)
    single0 = np.asarray(comb0.extract(X[3]))
    assert single0.shape == (48, 24)
    np.testing.assert_allclose(single0, out0[3], atol=1e-5)


def test_combine_operator_nd_roundtrips(tmp_path):
    from opencv_facerecognizer_tpu.models import NearestNeighbor, PredictableModel
    from opencv_facerecognizer_tpu.utils import serialization

    feat = ChainOperator(
        CombineOperatorND(TanTriggsPreprocessing(), HistogramEqualization()),
        PCA(6),
    )
    model = PredictableModel(feat, NearestNeighbor())
    model.compute(X, Y)
    path = str(tmp_path / "nd.msgpack")
    serialization.save_model(path, model)
    restored = serialization.load_model(path)
    assert restored.feature.model1.hstack_axis == -1
    pred0 = model.predict(X[0])[0]
    assert restored.predict(X[0])[0] == pred0


def test_chain_pca_lda_single_sample():
    # regression: 1-D intermediate features must not be misread as batches
    chain = ChainOperator(PCA(8), LDA())
    proj = np.asarray(chain.compute(X, Y))
    single = np.asarray(chain.extract(X[2]))
    assert single.shape == proj[2].shape
    np.testing.assert_allclose(single, proj[2], atol=1e-3)


def test_preprocessing_plugins_keep_image_shape():
    for feat in (TanTriggsPreprocessing(), HistogramEqualization(), MinMaxNormalize()):
        out = np.asarray(feat.compute(X, Y))
        assert out.shape == X.shape, type(feat).__name__
