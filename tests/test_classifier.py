"""Classifier boundary tests: k-NN vs sklearn oracle, SVM separability."""

import numpy as np

from opencv_facerecognizer_tpu.models import NearestNeighbor, SVM
from opencv_facerecognizer_tpu.ops import distance as D

RNG = np.random.default_rng(11)


def _blobs(num_classes=4, per_class=15, d=8, sep=5.0, seed=11):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=sep, size=(num_classes, d)).astype(np.float32)
    x = np.concatenate([c + rng.normal(size=(per_class, d)).astype(np.float32) for c in centers])
    y = np.repeat(np.arange(num_classes), per_class)
    return x, y


def test_knn_matches_sklearn_k1():
    from sklearn.neighbors import KNeighborsClassifier

    x, y = _blobs()
    q = x + RNG.normal(scale=0.3, size=x.shape).astype(np.float32)
    clf = NearestNeighbor(D.EuclideanDistance(), k=1)
    clf.compute(x, y)
    pred, info = clf.predict(q)
    sk = KNeighborsClassifier(n_neighbors=1).fit(x, y)
    np.testing.assert_array_equal(np.asarray(pred), sk.predict(q))
    assert info["distances"].shape == (len(q), 1)


def test_knn_matches_sklearn_k5_majority():
    from sklearn.neighbors import KNeighborsClassifier

    x, y = _blobs(sep=3.0)
    q = RNG.normal(scale=4.0, size=(40, 8)).astype(np.float32)
    clf = NearestNeighbor(D.EuclideanDistance(), k=5)
    clf.compute(x, y)
    pred, _ = clf.predict(q)
    sk = KNeighborsClassifier(n_neighbors=5).fit(x, y)
    agree = (np.asarray(pred) == sk.predict(q)).mean()
    # sklearn breaks vote ties differently; require near-total agreement
    assert agree > 0.9


def test_knn_single_query_reference_contract():
    x, y = _blobs()
    clf = NearestNeighbor(k=3)
    clf.compute(x, y)
    out = clf.predict(x[0])
    assert isinstance(out, list) and len(out) == 2
    label, info = out
    assert int(label) == int(y[0])
    assert info["labels"].shape == (3,)
    assert info["distances"][0] <= info["distances"][1]


def test_knn_preserves_original_label_values():
    x, y = _blobs(num_classes=3)
    y_shifted = (y * 7 + 100).astype(np.int64)  # non-contiguous labels
    clf = NearestNeighbor(k=1)
    clf.compute(x, y_shifted)
    pred, _ = clf.predict(x[:10])
    np.testing.assert_array_equal(np.asarray(pred), y_shifted[:10])


def test_knn_cosine_metric():
    x, y = _blobs()
    clf = NearestNeighbor(D.CosineDistance(), k=1)
    clf.compute(x, y)
    pred, _ = clf.predict(x)
    assert (np.asarray(pred) == y).mean() == 1.0


def test_svm_separable_blobs():
    x, y = _blobs(sep=6.0)
    clf = SVM(epochs=200)
    clf.compute(x, y)
    pred, info = clf.predict(x)
    assert (np.asarray(pred) == y).mean() > 0.97
    assert info["logits"].shape == (len(y), 4)
    single = clf.predict(x[0])
    assert int(single[0]) == int(y[0])
