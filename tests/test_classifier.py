"""Classifier boundary tests: k-NN vs sklearn oracle, SVM separability."""

import numpy as np

from opencv_facerecognizer_tpu.models import NearestNeighbor, SVM
from opencv_facerecognizer_tpu.ops import distance as D

RNG = np.random.default_rng(11)


def _blobs(num_classes=4, per_class=15, d=8, sep=5.0, seed=11):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=sep, size=(num_classes, d)).astype(np.float32)
    x = np.concatenate([c + rng.normal(size=(per_class, d)).astype(np.float32) for c in centers])
    y = np.repeat(np.arange(num_classes), per_class)
    return x, y


def test_knn_matches_sklearn_k1():
    from sklearn.neighbors import KNeighborsClassifier

    x, y = _blobs()
    q = x + RNG.normal(scale=0.3, size=x.shape).astype(np.float32)
    clf = NearestNeighbor(D.EuclideanDistance(), k=1)
    clf.compute(x, y)
    pred, info = clf.predict(q)
    sk = KNeighborsClassifier(n_neighbors=1).fit(x, y)
    np.testing.assert_array_equal(np.asarray(pred), sk.predict(q))
    assert info["distances"].shape == (len(q), 1)


def test_knn_matches_sklearn_k5_majority():
    from sklearn.neighbors import KNeighborsClassifier

    x, y = _blobs(sep=3.0)
    q = RNG.normal(scale=4.0, size=(40, 8)).astype(np.float32)
    clf = NearestNeighbor(D.EuclideanDistance(), k=5)
    clf.compute(x, y)
    pred, _ = clf.predict(q)
    sk = KNeighborsClassifier(n_neighbors=5).fit(x, y)
    agree = (np.asarray(pred) == sk.predict(q)).mean()
    # sklearn breaks vote ties differently; require near-total agreement
    assert agree > 0.9


def test_knn_single_query_reference_contract():
    x, y = _blobs()
    clf = NearestNeighbor(k=3)
    clf.compute(x, y)
    out = clf.predict(x[0])
    assert isinstance(out, list) and len(out) == 2
    label, info = out
    assert int(label) == int(y[0])
    assert info["labels"].shape == (3,)
    assert info["distances"][0] <= info["distances"][1]


def test_knn_preserves_original_label_values():
    x, y = _blobs(num_classes=3)
    y_shifted = (y * 7 + 100).astype(np.int64)  # non-contiguous labels
    clf = NearestNeighbor(k=1)
    clf.compute(x, y_shifted)
    pred, _ = clf.predict(x[:10])
    np.testing.assert_array_equal(np.asarray(pred), y_shifted[:10])


def test_knn_cosine_metric():
    x, y = _blobs()
    clf = NearestNeighbor(D.CosineDistance(), k=1)
    clf.compute(x, y)
    pred, _ = clf.predict(x)
    assert (np.asarray(pred) == y).mean() == 1.0


def test_svm_separable_blobs():
    x, y = _blobs(sep=6.0)
    clf = SVM(epochs=200)
    clf.compute(x, y)
    pred, info = clf.predict(x)
    assert (np.asarray(pred) == y).mean() > 0.97
    assert info["logits"].shape == (len(y), 4)
    single = clf.predict(x[0])
    assert int(single[0]) == int(y[0])


def _rings(n_per=80, seed=4):
    """Concentric rings — linearly inseparable; the kernel-SVM acid test."""
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for cls, radius in enumerate((1.0, 3.0, 5.0)):
        theta = rng.uniform(0, 2 * np.pi, n_per)
        r = radius + rng.normal(scale=0.2, size=n_per)
        xs.append(np.stack([r * np.cos(theta), r * np.sin(theta)], -1))
        ys.append(np.full(n_per, cls))
    return (np.concatenate(xs).astype(np.float32),
            np.concatenate(ys).astype(np.int64))


def test_kernel_svm_rbf_separates_rings():
    from opencv_facerecognizer_tpu.models import KernelSVM

    x, y = _rings()
    x_te, y_te = _rings(n_per=40, seed=9)
    clf = KernelSVM(kernel="rbf")
    clf.compute(x, y)
    pred, info = clf.predict(x_te)
    acc = (np.asarray(pred) == y_te).mean()
    assert acc >= 0.95, f"rbf accuracy {acc:.3f}"
    assert info["logits"].shape == (len(x_te), 3)
    # Linear SVM cannot separate rings — confirms the kernel is doing the work.
    lin = SVM(epochs=400)
    lin.compute(x, y)
    lin_pred, _ = lin.predict(x_te)
    assert (np.asarray(lin_pred) == y_te).mean() < 0.7


def test_kernel_svm_agrees_with_sklearn_svc():
    from sklearn.svm import SVC

    from opencv_facerecognizer_tpu.models import KernelSVM

    x, y = _rings(n_per=60)
    q, _ = _rings(n_per=30, seed=21)
    ours = KernelSVM(kernel="rbf")
    ours.compute(x, y)
    pred, _ = ours.predict(q)
    sk = SVC(kernel="rbf", gamma="scale").fit(x, y)
    agree = (np.asarray(pred) == sk.predict(q)).mean()
    assert agree >= 0.9, f"rbf: agreement with sklearn {agree:.2f}"


def test_kernel_svm_poly_quadratic_boundary():
    """Degree-2 poly kernel on an inside/outside-circle problem (the
    textbook quadratically-separable case; sklearn's deg-3 poly does badly
    on rings, so oracle agreement is only meaningful for rbf above)."""
    from opencv_facerecognizer_tpu.models import KernelSVM

    rng = np.random.default_rng(8)
    x = rng.uniform(-3, 3, size=(240, 2)).astype(np.float32)
    y = (np.sum(x**2, axis=1) > 4.0).astype(np.int64)
    q = rng.uniform(-3, 3, size=(80, 2)).astype(np.float32)
    qy = (np.sum(q**2, axis=1) > 4.0).astype(np.int64)
    clf = KernelSVM(kernel="poly", degree=2)
    clf.compute(x, y)
    pred, _ = clf.predict(q)
    acc = (np.asarray(pred) == qy).mean()
    assert acc >= 0.9, f"poly-2 accuracy {acc:.3f}"


def test_kernel_svm_single_sample_and_roundtrip(tmp_path):
    from opencv_facerecognizer_tpu.models import Identity, KernelSVM, PredictableModel
    from opencv_facerecognizer_tpu.utils import serialization

    x, y = _rings(n_per=30)
    model = PredictableModel(Identity(), KernelSVM(kernel="rbf"))
    model.compute(x.reshape(-1, 1, 2), y)  # image-shaped samples flatten via Identity
    single = model.predict(x[0].reshape(1, 2))
    assert single[0] == y[0]
    path = str(tmp_path / "ksvm.msgpack")
    serialization.save_model(path, model)
    restored = serialization.load_model(path)
    assert restored.classifier.kernel == "rbf"
    p0, _ = model.predict(x.reshape(-1, 1, 2)[:20])
    p1, _ = restored.predict(x.reshape(-1, 1, 2)[:20])
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
