"""End-to-end fused detect->align->embed->match pipeline on the 8-device
CPU mesh (SURVEY.md §3.3 rebuild contract, §7.7)."""

import numpy as np
import pytest

from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
from opencv_facerecognizer_tpu.models.embedder import (
    FaceEmbedNet,
    init_embedder,
    normalize_faces,
    train_embedder,
)
from opencv_facerecognizer_tpu.ops import image as image_ops
from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes


FACE = (32, 32)


@pytest.fixture(scope="module")
def pipeline_setup():
    # Train a tiny detector on synthetic scenes.
    scenes, boxes, counts = make_synthetic_scenes(48, (96, 96), max_faces=2, seed=31)
    det = CNNFaceDetector(features=(8, 16, 32), head_features=32, max_faces=4,
                          score_threshold=0.25)
    det.train(scenes, boxes, counts, steps=250, batch_size=16, learning_rate=2e-3)

    # "Subjects": crops of distinct synthetic faces; embedder trained on them.
    net = FaceEmbedNet(embed_dim=32, stem_features=8, stage_features=(8, 16),
                       stage_blocks=(1, 1))
    crops, labels = [], []
    for i in range(len(scenes)):
        for b in range(counts[i]):
            y0, x0, y1, x1 = boxes[i, b].astype(int)
            crop = np.asarray(image_ops.resize(scenes[i][y0:y1, x0:x1], FACE))
            crops.append(crop)
            labels.append(i % 5)  # 5 pseudo-identities
    crops = np.stack(crops)
    labels = np.asarray(labels, np.int32)
    params = init_embedder(net, num_classes=5, input_shape=FACE, seed=0)
    xn = np.asarray(normalize_faces(crops, FACE))
    params = train_embedder(net, params, xn, labels, steps=40, batch_size=16)
    return det, net, params, scenes, boxes, counts, crops, labels


@pytest.mark.parametrize("dp,tp", [(2, 4), (1, 8)])
def test_fused_pipeline_runs_sharded(pipeline_setup, dp, tp):
    det, net, params, scenes, boxes, counts, crops, labels = pipeline_setup
    mesh = make_mesh(dp=dp, tp=tp)
    gallery = ShardedGallery(capacity=64, dim=32, mesh=mesh)
    emb = np.asarray(net.apply({"params": params["net"]},
                               normalize_faces(crops, FACE)))
    gallery.add(emb, labels)

    pipe = RecognitionPipeline(det, net, params["net"], gallery, face_size=FACE, top_k=2)
    batch = scenes[:8]
    result = pipe.recognize_batch(batch)
    assert result.boxes.shape == (8, 4, 4)
    assert result.valid.shape == (8, 4)
    assert result.labels.shape == (8, 4, 2)
    assert result.similarities.shape == (8, 4, 2)
    # detection quality bar (raised from gt//2 per VERDICT round-1 #4):
    # >=90% of ground-truth faces must come out of the fused graph valid.
    det_count = int(np.asarray(result.valid).sum())
    gt_count = int(counts[:8].sum())
    assert det_count >= int(np.ceil(0.9 * gt_count)), (det_count, gt_count)
    # matched labels for valid faces must be real gallery labels
    valid = np.asarray(result.valid)
    lbl = np.asarray(result.labels)[..., 0]
    assert set(np.unique(lbl[valid]).tolist()) <= set(range(5))
    # similarities are cosine-bounded
    sims = np.asarray(result.similarities)[valid]
    assert np.all(sims <= 1.0 + 1e-3)


def test_pipeline_uint8_transfer_matches_f32(pipeline_setup):
    """The uint8 fast-transfer path (frames ride H2D as uint8, cast to f32
    in-graph) must produce the same result as sending the same pixel
    values as f32 — it is a transfer-format choice, not a model change."""
    det, net, params, scenes, boxes, counts, crops, labels = pipeline_setup
    mesh = make_mesh(tp=8)
    gallery = ShardedGallery(capacity=64, dim=32, mesh=mesh)
    emb = np.asarray(net.apply({"params": params["net"]},
                               normalize_faces(crops, FACE)))
    gallery.add(emb, labels)
    pipe = RecognitionPipeline(det, net, params["net"], gallery,
                               face_size=FACE, top_k=1)
    u8 = np.clip(scenes[:8], 0, 255).astype(np.uint8)
    r_u8 = pipe.recognize_batch(u8)
    r_f32 = pipe.recognize_batch(u8.astype(np.float32))
    np.testing.assert_array_equal(np.asarray(r_u8.valid),
                                  np.asarray(r_f32.valid))
    np.testing.assert_array_equal(np.asarray(r_u8.labels),
                                  np.asarray(r_f32.labels))
    np.testing.assert_allclose(np.asarray(r_u8.boxes),
                               np.asarray(r_f32.boxes), atol=1e-4)
    np.testing.assert_allclose(np.asarray(r_u8.similarities),
                               np.asarray(r_f32.similarities), atol=1e-5)
    # distinct trace per dtype, cached independently
    assert len(pipe._step_cache) == 2


def test_pipeline_batch_caching(pipeline_setup):
    det, net, params, scenes, *_ = pipeline_setup
    mesh = make_mesh(tp=8)
    gallery = ShardedGallery(capacity=16, dim=32, mesh=mesh)
    gallery.add(np.eye(16, 32, dtype=np.float32), np.arange(16, dtype=np.int32))
    pipe = RecognitionPipeline(det, net, params["net"], gallery, face_size=FACE)
    r1 = pipe.recognize_batch(scenes[:8])
    assert len(pipe._step_cache) == 1
    r2 = pipe.recognize_batch(scenes[8:16])
    assert len(pipe._step_cache) == 1  # same shape -> no recompile
    pipe.recognize_batch(scenes[:16])
    assert len(pipe._step_cache) == 2


def test_pipeline_fused_embedder_matches_flax(pipeline_setup):
    """fused_embedder=True swaps the embed stage onto the pallas schedule
    (interpret mode off-TPU) without changing results — the one-flag flip
    the on-chip A/B (scripts/bench_sepblock.py) decides."""
    import jax
    from jax.sharding import Mesh

    from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS

    det, net, params, scenes, boxes, counts, crops, labels = pipeline_setup
    # single-device mesh: pallas custom calls don't partition over tp
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                (DP_AXIS, TP_AXIS))
    gallery = ShardedGallery(capacity=64, dim=32, mesh=mesh)
    emb = np.asarray(net.apply({"params": params["net"]},
                               normalize_faces(crops, FACE)))
    gallery.add(emb, labels)
    outs = {}
    for fused in (False, True):
        pipe = RecognitionPipeline(det, net, params["net"], gallery,
                                   face_size=FACE, top_k=1,
                                   fused_embedder=fused)
        outs[fused] = pipe.recognize_batch(scenes[:4])
    np.testing.assert_array_equal(np.asarray(outs[False].valid),
                                  np.asarray(outs[True].valid))
    np.testing.assert_allclose(np.asarray(outs[False].boxes),
                               np.asarray(outs[True].boxes), atol=1e-4)
    # embeddings differ only by bf16 rounding -> near-identical sims; label
    # flips are possible only at exact ties, which the synthetic gallery
    # doesn't produce
    np.testing.assert_array_equal(np.asarray(outs[False].labels),
                                  np.asarray(outs[True].labels))
    np.testing.assert_allclose(np.asarray(outs[False].similarities),
                               np.asarray(outs[True].similarities), atol=2e-2)
