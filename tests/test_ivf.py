"""IVF quantizer subsystem: two-stage match correctness, the recall
acceptance gate, and the derived-state lifecycle (rebuild bit-equivalence,
WAL-replay assignment reproducibility, swap invalidation, retrain chaos).

The two-stage path is single-device (like the pallas matcher), so every
test here builds the 1x1 mesh explicitly; the pallas rerank runs in
interpret mode on CPU, same as test_pallas_match.py.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from opencv_facerecognizer_tpu.ops.ivf_match import (
    ivf_match_topk,
    tie_aware_agreement,
    tie_aware_mismatch,
)
from opencv_facerecognizer_tpu.parallel import ShardedGallery
from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS
from opencv_facerecognizer_tpu.parallel.quantizer import (
    CoarseQuantizer,
    SidecarError,
    decode_sidecar,
    encode_sidecar,
)
from opencv_facerecognizer_tpu.utils.metrics import Metrics
from opencv_facerecognizer_tpu.utils import metric_names as mn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mesh1():
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                (DP_AXIS, TP_AXIS))


def _unit(x):
    x = np.asarray(x, np.float32)
    return x / np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-12)


def build_gallery(rows=2048, dim=32, nlist=32, nprobe=8, seed=0,
                  mode="ivf", metrics=None, build=True):
    rng = np.random.default_rng(seed)
    emb = _unit(rng.normal(size=(rows, dim)))
    labels = np.arange(rows, dtype=np.int32)
    g = ShardedGallery(capacity=rows, dim=dim, mesh=mesh1())
    g.add(emb, labels)
    q = CoarseQuantizer(nlist=nlist, nprobe=nprobe, seed=seed,
                        kmeans_iters=5, train_sample=min(rows, 4096),
                        metrics=metrics)
    # Attach AFTER the bulk add so no background build races the tests'
    # explicit, deterministic rebuild_now().
    g.attach_quantizer(q, mode=mode)
    if build:
        assert q.rebuild_now()
    return g, q, emb, rng


# ---------------------------------------------------------------- matching

def test_two_stage_matches_exact_on_perturbed_queries():
    g, q, emb, rng = build_gallery()
    queries = _unit(emb[:16] + 0.05 * rng.normal(size=(16, emb.shape[1])))
    assert g._ivf_enabled()
    li, si, ii = (np.asarray(v) for v in g.match(queries, k=1))
    g.match_mode = "exact"
    lx, sx, ix = (np.asarray(v) for v in g.match(queries, k=1))
    assert tie_aware_agreement(si, ii, sx, ix) == 1.0
    # labels of agreeing rows agree too
    agree = (ii == ix).reshape(-1)
    assert np.array_equal(li.reshape(-1)[agree], lx.reshape(-1)[agree])


def test_ivf_tie_break_prefers_lowest_gallery_index():
    """Duplicate rows spread across different list positions must resolve
    to the LOWEST gallery index, exactly like the exact kernel (PR-2) —
    the bucket is re-sorted by global id before the rerank."""
    rng = np.random.default_rng(3)
    base = _unit(rng.normal(size=(8, 16)))
    emb = np.tile(base, (16, 1))  # 128 rows, each base row appears 16x
    g = ShardedGallery(capacity=len(emb), dim=16, mesh=mesh1())
    g.add(emb, np.arange(len(emb), dtype=np.int32))
    q = CoarseQuantizer(nlist=8, nprobe=8, seed=1, kmeans_iters=4,
                        train_sample=128)
    g.attach_quantizer(q, mode="ivf")
    assert q.rebuild_now()
    _l, sims, idx = (np.asarray(v) for v in g.match(base, k=4))
    sims_full = base @ emb.T
    oidx = np.argsort(-sims_full, axis=1, kind="stable")[:, :4]
    assert np.array_equal(idx, oidx), (idx, oidx)


def test_ivf_masks_invalid_rows_and_emits_sentinels():
    """Rows the gallery marks invalid never surface; with fewer valid
    rows than k the empty slots carry the -1 sentinel."""
    g, q, emb, rng = build_gallery(rows=256, dim=16, nlist=8)
    data = g.data
    ivf = q.data
    valid = np.zeros(data.capacity, bool)
    valid[:5] = True
    import jax.numpy as jnp

    vals, idx = (np.asarray(v) for v in ivf_match_topk(
        jnp.asarray(emb[:8]), jnp.asarray(valid), ivf, k=8, nprobe=8,
        interpret=True))
    real = idx >= 0
    assert np.all(idx[real] < 5)
    assert np.all(vals[~real] < -1e29)
    assert real.sum(axis=1).max() <= 5


def test_incremental_add_exceeding_assign_chunk_is_chunked():
    """One add() larger than ASSIGN_CHUNK must be sliced through the
    batched insert (a single padded scatter would need a negative pad and
    crash under the gallery write lock, leaving host counters claiming
    placements the device arrays never got)."""
    from opencv_facerecognizer_tpu.parallel.quantizer import ASSIGN_CHUNK

    g, q, emb, rng = build_gallery(rows=16384, dim=16, nlist=8, seed=1)
    n = ASSIGN_CHUNK + 64
    new = _unit(rng.normal(size=(n, 16)))
    start = g.size
    g.add(new, np.arange(start, start + n, dtype=np.int32))
    assert q.ready  # the cells absorbed the rows; no overflow-invalidate
    assert q._assigned_rows == start + n
    probe = np.concatenate([new[:1], new[-1:]])
    pad = np.tile(probe[-1], (6, 1))
    _l, _s, idx = (np.asarray(v) for v in g.match(
        np.concatenate([probe, pad]), k=1))
    assert idx[0, 0] == start and idx[1, 0] == start + n - 1


def test_incremental_add_immediately_matchable():
    g, q, emb, rng = build_gallery()
    new = _unit(rng.normal(size=(6, emb.shape[1])))
    start = g.size
    g.add(new, np.arange(start, start + 6, dtype=np.int32))
    _l, _s, idx = (np.asarray(v) for v in g.match(new, k=1))
    assert np.array_equal(idx[:, 0], np.arange(start, start + 6))


def test_auto_mode_threshold_selects_path():
    g, q, emb, rng = build_gallery(mode="auto")
    # auto below the capacity threshold: exact path despite a ready quantizer
    assert g.capacity < ShardedGallery.IVF_MIN_CAPACITY
    assert q.ready and not g._ivf_enabled()
    assert g._ivf_data(g.data) is None
    # lowering the threshold flips it to the two-stage path
    g.IVF_MIN_CAPACITY = g.capacity
    assert g._ivf_enabled()
    assert g._ivf_data(g.data) is not None
    # pinned-arity match_fn: 5-arg when ivf, 4-arg when exact
    fn = g.match_fn(1, use_ivf=True)
    assert fn.__code__.co_argcount == 5
    fn = g.match_fn(1, use_ivf=False)


def test_multi_device_mesh_never_selects_ivf():
    from opencv_facerecognizer_tpu.parallel import make_mesh

    mesh = make_mesh()
    if mesh.size == 1:
        pytest.skip("needs the 8-virtual-device suite mesh")
    g = ShardedGallery(capacity=256, dim=16, mesh=mesh)
    q = CoarseQuantizer(nlist=8, nprobe=4, seed=0, kmeans_iters=2,
                        train_sample=256)
    g.attach_quantizer(q, mode="ivf")
    assert not g._ivf_wanted()


# ----------------------------------------------------------- recall gate

@pytest.mark.parametrize("rows,nlist", [(262_144, 512)])
def test_recall_gate_262k(rows, nlist):
    """THE acceptance gate (ISSUE 6): two-stage top-1 recall >= 0.99 vs
    tie-aware brute force on a seeded >=262k-row synthetic gallery, at
    serving-distribution queries (perturbed enrolled rows)."""
    # per_batch=4 keeps the batch-level cell union SMALL (<=128 of 512
    # cells per call): the gate tests per-query shortlist quality, not
    # the whole-table union that larger batches degenerate into.
    dim, nprobe, n_q, per_batch = 64, 32, 64, 4
    rng = np.random.default_rng(42)
    emb = _unit(rng.normal(size=(rows, dim)).astype(np.float32))
    g = ShardedGallery(capacity=rows, dim=dim, mesh=mesh1(),
                       store_dtype="bfloat16")
    g.add(emb, np.arange(rows, dtype=np.int32))
    q = CoarseQuantizer(nlist=nlist, nprobe=nprobe, seed=7, kmeans_iters=6,
                        train_sample=32768)
    g.attach_quantizer(q, mode="ivf")
    assert q.rebuild_now()
    pick = rng.choice(rows, n_q, replace=False)
    queries = _unit(emb[pick] + 0.05 * rng.normal(size=(n_q, dim)))
    sims_i = np.empty((n_q,), np.float32)
    idx_i = np.empty((n_q,), np.int64)
    # Small per-call batches keep the cell union (and the interpret-mode
    # rerank bucket) small — the union is Q*nprobe cells.
    for off in range(0, n_q, per_batch):
        _l, s, i = (np.asarray(v) for v in
                    g.match(queries[off:off + per_batch], k=1))
        sims_i[off:off + per_batch] = s[:, 0]
        idx_i[off:off + per_batch] = i[:, 0]
    # Brute-force oracle (f32, stable lowest-index ties).
    sims = queries @ emb.T
    idx_x = np.argmax(sims, axis=1)
    vals_x = sims[np.arange(n_q), idx_x]
    recall = tie_aware_agreement(sims_i, idx_i, vals_x, idx_x)
    assert recall >= 0.99, (recall, int(tie_aware_mismatch(
        sims_i, idx_i, vals_x, idx_x).sum()))


# ------------------------------------------------- lifecycle: determinism

def test_rebuild_on_snapshot_load_bit_equivalence():
    """Same rows + same seed -> the rebuild after load_snapshot
    reproduces centroids, assignments and packed lists bit-for-bit."""
    g1, q1, emb, rng = build_gallery(rows=1024, dim=16, nlist=16, seed=9)
    snap = g1.snapshot()
    g2 = ShardedGallery(capacity=1024, dim=16, mesh=mesh1())
    q2 = CoarseQuantizer(nlist=16, nprobe=8, seed=9, kmeans_iters=5,
                         train_sample=4096)
    g2.attach_quantizer(q2, mode="ivf")
    g2.load_snapshot(*snap)
    assert not q2.ready  # load_snapshot invalidates
    assert q2.rebuild_now()
    np.testing.assert_array_equal(q1._h_centroids, q2._h_centroids)
    np.testing.assert_array_equal(q1._h_assign, q2._h_assign)
    for field in ("cell_rows", "cell_q8", "cell_scale", "spill_rows",
                  "spill_q8", "spill_scale"):
        np.testing.assert_array_equal(
            np.asarray(getattr(q1.data, field)),
            np.asarray(getattr(q2.data, field)), err_msg=field)


def test_wal_replay_reproduces_incremental_assignments(tmp_path):
    """The PR-4 contract extended to derived state: recovery = sidecar
    (keyed by checkpoint wal_seq) + WAL replay re-driving gallery.add,
    which re-runs the same incremental assignment path — the recovered
    quantizer state equals the live one bit-for-bit."""
    from opencv_facerecognizer_tpu.runtime.state_store import StateLifecycle

    metrics = Metrics()
    g1, q1, emb, rng = build_gallery(rows=512, dim=16, nlist=8, seed=4,
                                     metrics=metrics)
    state1 = StateLifecycle(str(tmp_path), metrics=metrics)
    state1.bind(g1, [])
    assert state1.checkpoint_now(wait=True)  # checkpoint + sidecar
    assert metrics.counter(mn.IVF_SIDECAR_WRITES) == 1
    # acknowledged enrollments AFTER the checkpoint -> WAL only
    live_rows = []
    for i in range(3):
        new = _unit(rng.normal(size=(2 + i, 16)))
        live_rows.append(new)
        state1.append_enrollment(
            new, np.arange(g1.size, g1.size + len(new), dtype=np.int32),
            apply_fn=lambda new=new: g1.add(
                new, np.arange(g1.size, g1.size + len(new), dtype=np.int32)))
    assign_live = q1._h_assign.copy()
    v_live = {f: np.asarray(getattr(q1.data, f))
              for f in ("cell_rows", "cell_q8", "cell_scale", "spill_rows")}

    # crash + recover into a FRESH gallery/quantizer/lifecycle
    metrics2 = Metrics()
    g2 = ShardedGallery(capacity=512, dim=16, mesh=mesh1())
    q2 = CoarseQuantizer(nlist=8, nprobe=8, seed=4, kmeans_iters=5,
                         train_sample=512, metrics=metrics2)
    g2.attach_quantizer(q2, mode="ivf")
    state2 = StateLifecycle(str(tmp_path), metrics=metrics2)
    report = state2.recover(g2, [])
    assert report["quantizer_sidecar"] == "loaded"
    assert report["replayed_records"] == 3
    assert metrics2.counter(mn.IVF_SIDECAR_LOADS) == 1
    assert q2.ready
    np.testing.assert_array_equal(q1._h_centroids, q2._h_centroids)
    n = min(len(assign_live), len(q2._h_assign))
    np.testing.assert_array_equal(assign_live[:n], q2._h_assign[:n])
    for f, want in v_live.items():
        np.testing.assert_array_equal(want, np.asarray(getattr(q2.data, f)),
                                      err_msg=f)
    # and the recovered two-stage matcher finds the replayed rows at the
    # exact gallery positions the live process enrolled them at
    assert g2.size == g1.size
    probe = live_rows[-1]  # the last enrollment's rows
    start = g2.size - len(probe)
    pad = np.tile(probe[-1], (8 - len(probe), 1))
    _l, _s, idx = (np.asarray(v) for v in g2.match(
        np.concatenate([probe, pad]), k=1))
    assert np.array_equal(idx[:len(probe), 0],
                          np.arange(start, start + len(probe)))


def test_stale_sidecar_is_ignored(tmp_path):
    """A sidecar whose wal_seq does not match the recovered checkpoint is
    never trusted — recovery proceeds quantizer-less (retrain path)."""
    from opencv_facerecognizer_tpu.runtime.state_store import StateLifecycle

    metrics = Metrics()
    g1, q1, emb, rng = build_gallery(rows=256, dim=16, nlist=8, seed=2,
                                     metrics=metrics)
    state1 = StateLifecycle(str(tmp_path), metrics=metrics)
    state1.bind(g1, [])
    assert state1.checkpoint_now(wait=True)
    # a LATER enrollment + checkpoint WITHOUT a quantizer would bump
    # wal_seq; simulate staleness by rewriting the sidecar with a bogus seq
    payload = g1.snapshot_quantizer()
    with open(state1.sidecar_path, "wb") as fh:
        fh.write(encode_sidecar(payload, wal_seq=999))
    metrics2 = Metrics()
    g2 = ShardedGallery(capacity=256, dim=16, mesh=mesh1())
    q2 = CoarseQuantizer(nlist=8, nprobe=8, seed=2, kmeans_iters=5,
                         train_sample=256, metrics=metrics2)
    g2.attach_quantizer(q2, mode="ivf")
    state2 = StateLifecycle(str(tmp_path), metrics=metrics2)
    report = state2.recover(g2, [])
    assert "quantizer_sidecar" not in report
    assert metrics2.counter(mn.IVF_SIDECAR_STALE) == 1
    assert not q2.ready
    # serving still works (exact fallback) while the retrain is pending
    _l, _s, idx = (np.asarray(v) for v in g2.match(emb[:8], k=1))
    assert np.array_equal(idx[:, 0], np.arange(8))


def test_corrupt_sidecar_fails_closed(tmp_path):
    from opencv_facerecognizer_tpu.runtime.state_store import StateLifecycle

    metrics = Metrics()
    g1, q1, emb, rng = build_gallery(rows=256, dim=16, nlist=8, seed=6,
                                     metrics=metrics)
    state1 = StateLifecycle(str(tmp_path), metrics=metrics)
    state1.bind(g1, [])
    assert state1.checkpoint_now(wait=True)
    blob = open(state1.sidecar_path, "rb").read()
    with open(state1.sidecar_path, "wb") as fh:
        fh.write(blob[:len(blob) // 2])  # torn write
    with pytest.raises(SidecarError):
        decode_sidecar(blob[:len(blob) // 2])
    metrics2 = Metrics()
    g2 = ShardedGallery(capacity=256, dim=16, mesh=mesh1())
    q2 = CoarseQuantizer(nlist=8, nprobe=8, seed=6, kmeans_iters=5,
                         train_sample=256, metrics=metrics2)
    g2.attach_quantizer(q2, mode="ivf")
    state2 = StateLifecycle(str(tmp_path), metrics=metrics2)
    state2.recover(g2, [])
    assert not q2.ready
    assert metrics2.counter(mn.IVF_SIDECAR_ERRORS) == 1


# -------------------------------------------- lifecycle: invalidation

def test_swap_from_invalidates_and_falls_back_exact():
    g, q, emb, rng = build_gallery(rows=512, dim=16, nlist=8)
    other = ShardedGallery(capacity=512, dim=16, mesh=mesh1())
    emb2 = _unit(rng.normal(size=(64, 16)))
    other.add(emb2, np.arange(64, dtype=np.int32))
    pre_swap_data = g.data
    g.swap_from(other)
    assert not q.ready
    assert not g._ivf_enabled()
    _l, _s, idx = (np.asarray(v) for v in g.match(emb2[:8], k=1))
    assert np.array_equal(idx[:, 0], np.arange(8))  # exact path serves
    # a retrain over the swapped-in rows restores the two-stage path
    assert q.rebuild_now()
    assert g._ivf_enabled()
    _l, _s, idx = (np.asarray(v) for v in g.match(emb2[:8], k=1))
    assert np.array_equal(idx[:, 0], np.arange(8))
    # epoch cross-check: the POST-swap quantizer snapshot must never pair
    # with a PRE-swap gallery snapshot a slow reader may still hold —
    # scoring the old rows against the new lists would be a silent
    # misrecognition, so _ivf_data rejects the cross-epoch pair.
    assert q.data.gallery_epoch == g.data.epoch != pre_swap_data.epoch
    assert g._ivf_data(pre_swap_data) is None
    assert g._ivf_data(g.data) is not None


def test_reset_invalidates():
    g, q, emb, rng = build_gallery(rows=256, dim=16, nlist=8)
    g.reset()
    assert not q.ready


def test_spill_overflow_invalidates_never_drops(monkeypatch):
    """When a cell AND the spill are full, the quantizer refuses to
    silently miss the row: it invalidates (exact serving) instead."""
    metrics = Metrics()
    g, q, emb, rng = build_gallery(rows=256, dim=16, nlist=8,
                                   metrics=metrics)
    # exhaust the spill artificially, then force a full cell
    q._spill_count = q.data.spill_cap
    full_cell = int(np.argmax(q._h_counts))
    q._h_counts[full_cell] = q.data.max_cell
    row = np.asarray(q._h_centroids[full_cell], np.float32)
    row = _unit(row[None, :])[0]
    start = g.size
    g.add(row[None, :], np.asarray([start], np.int32))
    assert not q.ready  # invalidated, not silently dropped
    assert metrics.counter(mn.IVF_INVALIDATIONS) == 1
    _l, _s, idx = (np.asarray(v) for v in g.match(
        np.tile(row, (8, 1)), k=1))
    assert idx[0, 0] == start  # exact fallback still finds the row


# ----------------------------------------------------- retrain chaos

def test_failed_retrain_leaves_serving_intact(monkeypatch):
    """Kill the k-means mid-retrain: the previous published quantizer (or
    the exact path) keeps serving, the failure is counted, and the
    single-flight guard is released for the next attempt."""
    metrics = Metrics()
    g, q, emb, rng = build_gallery(rows=512, dim=16, nlist=8,
                                   metrics=metrics)
    v_before = q.version

    def boom(*a, **k):
        raise RuntimeError("injected kmeans crash")

    import opencv_facerecognizer_tpu.parallel.quantizer as quantizer_mod

    monkeypatch.setattr(quantizer_mod, "_kmeans", boom)
    assert q.rebuild_now() is False
    assert metrics.counter(mn.IVF_BUILD_FAILURES) == 1
    assert q.ready and q.version == v_before  # old state intact
    queries = _unit(emb[:8] + 0.02 * rng.normal(size=(8, 16)))
    _l, _s, idx = (np.asarray(v) for v in g.match(queries, k=1))
    assert idx.shape == (8, 1)
    assert not q._train_lock.locked()  # single-flight guard released
    monkeypatch.undo()
    assert q.rebuild_now()  # next attempt succeeds
    assert q.version == v_before + 1


def test_failed_retrain_before_first_build_serves_exact(monkeypatch):
    metrics = Metrics()
    g, q, emb, rng = build_gallery(rows=256, dim=16, nlist=8,
                                   metrics=metrics, build=False)

    import opencv_facerecognizer_tpu.parallel.quantizer as quantizer_mod

    monkeypatch.setattr(quantizer_mod, "_kmeans",
                        lambda *a, **k: (_ for _ in ()).throw(
                            RuntimeError("boom")))
    assert q.rebuild_now() is False
    assert not q.ready
    _l, _s, idx = (np.asarray(v) for v in g.match(emb[:8], k=1))
    assert np.array_equal(idx[:, 0], np.arange(8))


def test_fenced_rebuild_refires_async(monkeypatch):
    """An epoch bump landing mid-train (swap/load/reset whose own poke
    was skipped as in-flight) must not leave the quantizer unbuilt
    forever: the fenced-out attempt re-fires one async build against the
    new row set."""
    import time as time_mod

    import opencv_facerecognizer_tpu.parallel.quantizer as quantizer_mod

    g, q, emb, rng = build_gallery(rows=256, dim=16, nlist=8, build=False)
    real_kmeans = quantizer_mod._kmeans
    fenced = []

    def fence_once(*a, **k):
        out = real_kmeans(*a, **k)
        if not fenced:
            fenced.append(True)
            g.run_locked(lambda: setattr(g, "_epoch", g._epoch + 1))
        return out

    monkeypatch.setattr(quantizer_mod, "_kmeans", fence_once)
    assert q.rebuild_now() is False  # this attempt was fenced out
    deadline = time_mod.monotonic() + 60
    while not q.ready and time_mod.monotonic() < deadline:
        time_mod.sleep(0.05)
    assert q.ready  # the re-fired attempt published against the new epoch
    assert q.data.gallery_epoch == g._epoch


def test_single_flight_retrain_guard():
    g, q, emb, rng = build_gallery(rows=256, dim=16, nlist=8,
                                   metrics=Metrics())
    assert q._train_lock.acquire(blocking=False)
    try:
        assert q.rebuild_now(wait=False) is False
        assert q.maybe_rebuild_async() is False
        assert q.metrics.counter(mn.IVF_RETRAINS_SKIPPED_INFLIGHT) == 2
    finally:
        q._train_lock.release()


# -------------------------------------------------------- pipeline wiring

def test_pipeline_threads_ivf_through_fused_step():
    """The fused serving step must produce identical labels in ivf and
    exact modes (perturbed-row queries, no near-ties) — proving the
    IVFDeviceData pytree rides the jitted step as an argument."""
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder, normalize_faces,
    )
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    face = (16, 16)
    scenes, boxes, counts = make_synthetic_scenes(8, (64, 64), max_faces=2,
                                                  seed=13)
    det = CNNFaceDetector(features=(4, 8), head_features=8, max_faces=2,
                          score_threshold=0.0)
    det.train(scenes, boxes, counts, steps=30, batch_size=8)
    net = FaceEmbedNet(embed_dim=16, stem_features=4, stage_features=(4, 8),
                       stage_blocks=(1, 1))
    params = init_embedder(net, num_classes=4, input_shape=face, seed=0)

    rng = np.random.default_rng(5)
    emb = _unit(rng.normal(size=(256, 16)))
    gallery = ShardedGallery(capacity=256, dim=16, mesh=mesh1())
    gallery.add(emb, np.arange(256, dtype=np.int32))
    q = CoarseQuantizer(nlist=8, nprobe=8, seed=1, kmeans_iters=4,
                        train_sample=256)
    gallery.attach_quantizer(q, mode="ivf")
    assert q.rebuild_now()

    pipe = RecognitionPipeline(det, net, params["net"], gallery,
                               face_size=face, top_k=1)
    batch = scenes[:2]
    res_ivf = pipe.recognize_batch(batch)
    assert gallery._ivf_data(gallery.data) is not None
    gallery.match_mode = "exact"
    res_exact = pipe.recognize_batch(batch)
    # two cache entries: the ivf and exact steps are distinct executables
    assert len(pipe._step_cache) == 2
    si = np.asarray(res_ivf.similarities).reshape(-1)
    se = np.asarray(res_exact.similarities).reshape(-1)
    ii = np.asarray(res_ivf.labels).reshape(-1)
    ie = np.asarray(res_exact.labels).reshape(-1)
    assert tie_aware_agreement(si, ii, se, ie) == 1.0


# --------------------------------------------------------- tier-1 smoke

def test_bench_ivf_smoke_gate():
    """The committed recall gate: ``bench.py --ivf-smoke`` must exit 0 —
    tier-1 runs this on every commit so a recall regression in the
    two-stage path fails loud."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--ivf-smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    import json

    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["ok"] and doc["ivf_enabled"]
    assert doc["tie_aware_recall_at_1"] >= 0.99


# -------------------------------------------------------------- comparator

def test_tie_aware_comparator_semantics():
    vals_a = np.asarray([0.9, 0.8, 0.7])
    vals_b = np.asarray([0.9, 0.5, 0.7])
    idx_a = np.asarray([1, 2, 3])
    idx_b = np.asarray([5, 9, 3])
    mism = tie_aware_mismatch(vals_a, idx_a, vals_b, idx_b)
    # row 0: different idx, equal vals -> tie, accepted
    # row 1: different idx, different vals -> REAL disagreement
    # row 2: same idx -> agreement
    assert mism.tolist() == [False, True, False]
    assert tie_aware_agreement(vals_a, idx_a, vals_b, idx_b) == pytest.approx(2 / 3)


def test_sidecar_roundtrip_and_default_nlist():
    g, q, emb, rng = build_gallery(rows=256, dim=16, nlist=8)
    payload = g.snapshot_quantizer()
    blob = encode_sidecar(payload, wal_seq=17)
    header, cent, assign = decode_sidecar(blob)
    assert header["wal_seq"] == 17
    np.testing.assert_array_equal(cent, payload["centroids"])
    np.testing.assert_array_equal(assign, payload["assign"])
    with pytest.raises(SidecarError):
        decode_sidecar(b"garbage" + blob)
    assert CoarseQuantizer.default_nlist(262_144) == 2048
    assert CoarseQuantizer.default_nlist(10_000_000) == 16384
    assert CoarseQuantizer.default_nlist(1) == 64
