"""Serving runtime tests: batcher semantics/concurrency (SURVEY.md §5.2),
connectors, the service loop over a fake transport (§5.8), enrolment
protocol, double-buffered reload (§5.3), trainer flows."""

import io
import json
import threading
import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime import (
    FakeConnector,
    FrameBatcher,
    JSONLConnector,
    RecognizerService,
    TheTrainer,
)
from opencv_facerecognizer_tpu.runtime.connector import decode_frame, encode_frame
from opencv_facerecognizer_tpu.runtime.recognizer import (
    CONTROL_TOPIC,
    FRAME_TOPIC,
    RESULT_TOPIC,
    STATUS_TOPIC,
)
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces, make_synthetic_scenes

RNG = np.random.default_rng(23)


# ---------- FrameBatcher ----------


def test_batcher_full_batch():
    b = FrameBatcher(batch_size=4, frame_shape=(8, 8), flush_timeout=10.0)
    for i in range(4):
        assert b.put(np.full((8, 8), i, np.float32), meta=i)
    frames, metas, count, _ts, _tids, _pris = b.get_batch()
    assert count == 4 and frames.shape == (4, 8, 8)
    assert metas == [0, 1, 2, 3]
    np.testing.assert_allclose(frames[2], 2.0)


def test_batcher_timeout_flush_pads():
    b = FrameBatcher(batch_size=4, frame_shape=(8, 8), flush_timeout=0.05)
    b.put(np.ones((8, 8), np.float32), meta="only")
    t0 = time.monotonic()
    frames, metas, count, _ts, _tids, _pris = b.get_batch()
    assert time.monotonic() - t0 < 1.0
    assert count == 1
    assert metas[0] == "only" and metas[1] is None
    np.testing.assert_allclose(frames[1], 0.0)


def test_batcher_rejects_malformed():
    b = FrameBatcher(batch_size=2, frame_shape=(8, 8))
    assert not b.put(np.ones((9, 9), np.float32))
    assert not b.put(np.array([["a", "b"]]))
    assert b.stats["dropped_malformed"] == 2


def test_batcher_overflow_drops_oldest():
    b = FrameBatcher(batch_size=2, frame_shape=(4, 4), max_pending=3)
    for i in range(5):
        b.put(np.full((4, 4), i, np.float32), meta=i)
    frames, metas, count, _ts, _tids, _pris = b.get_batch()
    assert b.stats["dropped_overflow"] == 2
    assert metas[:2] == [2, 3]  # oldest (0, 1) dropped


def test_batcher_concurrent_producers_consumer():
    b = FrameBatcher(batch_size=8, frame_shape=(4, 4), flush_timeout=0.02)
    total = 64
    seen = []

    def producer(start):
        for i in range(total // 2):
            b.put(np.zeros((4, 4), np.float32), meta=start + i)
            time.sleep(0.0005)

    threads = [threading.Thread(target=producer, args=(0,)),
               threading.Thread(target=producer, args=(1000,))]
    for t in threads:
        t.start()

    def consumer():
        while len(seen) < total:
            out = b.get_batch(block=True)
            if out is None:
                break
            _, metas, count, _ts, _tids, _pris = out
            seen.extend(metas[:count])

    c = threading.Thread(target=consumer)
    c.start()
    for t in threads:
        t.join()
    c.join(timeout=5.0)
    assert sorted(seen) == sorted(list(range(32)) + list(range(1000, 1032)))


def test_batcher_close_unblocks():
    b = FrameBatcher(batch_size=2, frame_shape=(4, 4))
    done = []

    def consumer():
        done.append(b.get_batch(block=True))

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    b.close()
    t.join(timeout=2.0)
    assert done == [None]


# ---------- continuous batching ----------


def test_batcher_flushes_early_at_size_threshold():
    """A full batch forms the moment batch_size frames are buffered — no
    flush-window wait even with a huge deadline cap."""
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    b = FrameBatcher(batch_size=4, frame_shape=(8, 8), flush_timeout=10.0,
                     metrics=m, target_latency_s=5.0)
    for i in range(4):
        b.put(np.full((8, 8), i, np.float32), meta=i)
    t0 = time.monotonic()
    batch = b.get_batch()
    assert time.monotonic() - t0 < 1.0
    assert batch.count == 4
    assert m.counter("batcher_batches_size") == 1
    assert m.counter("batcher_batches_deadline") == 0
    assert b.stats["batches_size"] == 1


def test_batcher_adaptive_deadline_under_trickle():
    """Under trickle load (fewer than batch_size frames) a batch waits up
    to the ADAPTIVE deadline: target latency minus the reported downstream
    service time, clamped to [min_deadline, flush_timeout] — never the full
    fixed flush window."""
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    b = FrameBatcher(batch_size=8, frame_shape=(4, 4), flush_timeout=5.0,
                     metrics=m, target_latency_s=0.2)
    # No service estimate yet: full budget, capped by flush_timeout.
    assert abs(b.current_flush_deadline() - 0.2) < 1e-9
    b.report_service_time(0.15)  # EWMA seeds at the first report
    assert abs(b.current_flush_deadline() - 0.05) < 1e-6
    # Budget exhausted -> the floor, not zero (back-to-back frames still
    # coalesce) and never a negative wait.
    b.report_service_time(0.5)
    for _ in range(40):
        b.report_service_time(0.5)
    assert b.current_flush_deadline() == b.min_deadline_s
    # The gauge mirrors the current deadline on the shared surface.
    assert m.gauge("batcher_flush_deadline_ms") == b.min_deadline_s * 1e3
    # A trickle frame flushes at ~the deadline, not at flush_timeout.
    b.put(np.zeros((4, 4), np.float32), meta="lone")
    t0 = time.monotonic()
    batch = b.get_batch()
    waited = time.monotonic() - t0
    assert batch.count == 1 and batch.metas[0] == "lone"
    assert waited < 1.0  # far below the 5 s fixed window
    assert m.counter("batcher_batches_deadline") == 1


def test_batcher_coalescing_stats_match_frames_offered():
    """Every offered frame is accounted for on the shared Metrics surface:
    offered == batched + malformed + overflow + closed + still pending."""
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    b = FrameBatcher(batch_size=2, frame_shape=(4, 4), flush_timeout=0.01,
                     max_pending=4, metrics=m)
    b.put(np.ones((9, 9), np.float32))  # malformed
    for i in range(6):  # 4 fit, 2 force overflow drops of the oldest
        b.put(np.full((4, 4), i, np.float32), meta=i)
    batches = []
    while True:
        out = b.get_batch(block=False)
        if out is None:
            break
        batches.append(out)
    b.close()
    b.put(np.zeros((4, 4), np.float32))  # dropped: closed
    batched = sum(bt.count for bt in batches)
    c = m.counters()
    assert c["batcher_frames_offered"] == 8
    assert c["batcher_frames_batched"] == batched == 4
    assert c["batcher_dropped_malformed"] == 1
    assert c["batcher_dropped_overflow"] == 2
    assert c["batcher_dropped_closed"] == 1
    assert b.pending == 0
    assert (c["batcher_frames_batched"] + c["batcher_dropped_malformed"]
            + c["batcher_dropped_overflow"] + c["batcher_dropped_closed"]
            == c["batcher_frames_offered"])


def test_batcher_buffer_pool_recycles_staging_arrays():
    """A recycled staging array is reused by a later batch (zero per-batch
    allocations in steady state) with its padding lanes re-zeroed; wrong
    shapes are silently refused."""
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    b = FrameBatcher(batch_size=4, frame_shape=(4, 4), flush_timeout=0.01,
                     metrics=m)
    for i in range(4):
        b.put(np.full((4, 4), 7.0, np.float32), meta=i)
    first = b.get_batch()
    b.recycle(first.frames)
    b.recycle(np.zeros((2, 4, 4), np.float32))  # wrong shape: ignored
    b.put(np.full((4, 4), 1.0, np.float32), meta="x")
    second = b.get_batch()  # partial: deadline flush
    assert second.frames is first.frames  # the pooled buffer came back
    assert second.count == 1
    np.testing.assert_allclose(second.frames[1:], 0.0)  # padding re-zeroed
    assert m.counter("batcher_buffer_reuse") == 1


# ---------- overlapped serving pipeline (fake instant backend) ----------


def _instant_service(batch_size=8, frame_hw=(16, 16), **kwargs):
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline

    pipeline = InstantPipeline(frame_hw)
    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=batch_size, frame_shape=frame_hw,
        flush_timeout=0.05, similarity_threshold=0.0, **kwargs,
    )
    return pipeline, service, connector


def test_service_bucketed_dispatch_slices_partial_batches():
    """A partial batch dispatches at the smallest bucket >= its real frame
    count — never the full padded batch_size — and the slice is a view of
    the pooled staging array (no per-batch copy)."""
    pipeline, service, connector = _instant_service(
        batch_size=32, bucket_sizes=(8, 32))
    service.start(warmup=False)
    try:
        for i in range(3):
            connector.inject(FRAME_TOPIC,
                             {"frame": np.zeros((16, 16), np.float32),
                              "meta": {"i": i}})
        deadline = time.monotonic() + 10
        while (len(connector.messages(RESULT_TOPIC)) < 3
               and time.monotonic() < deadline):
            time.sleep(0.01)
    finally:
        assert service.drain(timeout=10.0)
        service.stop()
    assert len(connector.messages(RESULT_TOPIC)) == 3
    assert pipeline.batch_sizes_seen == [8]  # 3 frames -> bucket 8, once
    assert service.metrics.counter("batches_bucketed") == 1


def test_service_continuous_batching_stats_and_zero_drops():
    """Full-rate traffic forms size-triggered batches; the trailing partial
    flushes at the adaptive deadline; nothing drops and every offered frame
    reconciles on the metrics surface."""
    _, service, connector = _instant_service(
        batch_size=4, target_latency_s=0.05)
    service.start(warmup=False)
    n = 10  # 2 full batches + a partial of 2
    try:
        for i in range(n):
            connector.inject(FRAME_TOPIC,
                             {"frame": np.zeros((16, 16), np.float32),
                              "meta": {"i": i}})
        deadline = time.monotonic() + 10
        while (len(connector.messages(RESULT_TOPIC)) < n
               and time.monotonic() < deadline):
            time.sleep(0.01)
    finally:
        assert service.drain(timeout=10.0)
        service.stop()
    assert len(connector.messages(RESULT_TOPIC)) == n
    c = service.metrics.counters()
    assert c["batcher_frames_offered"] == n
    assert c["batcher_frames_batched"] == n
    assert c.get("batcher_dropped_overflow", 0) == 0
    assert c["batcher_batches_size"] >= 2
    assert c["batcher_batches_deadline"] >= 1
    assert c["frames_processed"] == n


def test_service_fallback_inline_drain_still_serves():
    """readback_worker=False selects the pre-worker inline poll path (the
    named fallback knobs) — it must still serve end to end."""
    _, service, connector = _instant_service(
        batch_size=4, readback_worker=False, readback_poll_s=0.001,
        drain_poll_s=0.01)
    service.start(warmup=False)
    try:
        for i in range(8):
            connector.inject(FRAME_TOPIC,
                             {"frame": np.zeros((16, 16), np.float32),
                              "meta": {"i": i}})
        deadline = time.monotonic() + 10
        while (len(connector.messages(RESULT_TOPIC)) < 8
               and time.monotonic() < deadline):
            time.sleep(0.01)
    finally:
        assert service.drain(timeout=10.0)
        service.stop()
    assert len(connector.messages(RESULT_TOPIC)) == 8
    assert service._worker is None  # no readback worker thread was spawned


# ---------- connectors ----------


def test_frame_codec_roundtrip():
    frame = RNG.uniform(0, 255, (12, 10)).astype(np.float32)
    decoded = decode_frame(encode_frame(frame))
    np.testing.assert_array_equal(decoded, frame)
    assert decoded.dtype == frame.dtype


def test_fake_connector_pubsub_and_record():
    c = FakeConnector()
    got = []
    c.subscribe("t1", lambda topic, m: got.append(m))
    c.publish("t1", {"a": 1})
    c.publish("t2", {"b": 2})
    assert got == [{"a": 1}]
    assert c.messages("t2") == [{"b": 2}]


def test_jsonl_connector_roundtrip_and_malformed():
    frames_in = io.StringIO(
        json.dumps({"topic": "x", "data": {"v": 1}}) + "\n"
        + "this is not json\n"
        + json.dumps({"no_topic": True}) + "\n"
        + json.dumps({"topic": "x", "data": {"v": 2}}) + "\n"
    )
    out = io.StringIO()
    c = JSONLConnector(frames_in, out)
    got = []
    c.subscribe("x", lambda t, m: got.append(m["v"]))
    c.start()
    for _ in range(100):
        if len(got) == 2:
            break
        time.sleep(0.01)
    c.stop()
    assert got == [1, 2]
    assert c.malformed_lines == 2
    c.publish("y", {"ok": True})
    assert json.loads(out.getvalue().strip()) == {"topic": "y", "data": {"ok": True}}


def test_ros_connector_clear_error_without_rospy():
    from opencv_facerecognizer_tpu.runtime.connector import ROSConnector

    with pytest.raises(ImportError, match="JSONLConnector"):
        ROSConnector()


# ---------- recognizer service over fake transport ----------


@pytest.fixture(scope="module")
def serving_stack():
    """Tiny trained detector+embedder+gallery on the 8-device CPU mesh."""
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder, normalize_faces, train_embedder,
    )
    from opencv_facerecognizer_tpu.ops import image as image_ops
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline

    FACE = (32, 32)
    scenes, boxes, counts = make_synthetic_scenes(48, (96, 96), max_faces=2, seed=31)
    det = CNNFaceDetector(features=(8, 16, 32), head_features=32, max_faces=4,
                          score_threshold=0.25)
    det.train(scenes, boxes, counts, steps=250, batch_size=16, learning_rate=2e-3)
    net = FaceEmbedNet(embed_dim=32, stem_features=8, stage_features=(8, 16),
                       stage_blocks=(1, 1))
    crops, labels = [], []
    for i in range(len(scenes)):
        for b in range(counts[i]):
            y0, x0, y1, x1 = boxes[i, b].astype(int)
            crops.append(np.asarray(image_ops.resize(scenes[i][y0:y1, x0:x1], FACE)))
            labels.append(i % 5)
    crops, labels = np.stack(crops), np.asarray(labels, np.int32)
    params = init_embedder(net, 5, FACE, seed=0)
    params = train_embedder(net, params, np.asarray(normalize_faces(crops, FACE)),
                            labels, steps=40, batch_size=16)
    mesh = make_mesh(tp=8)
    gallery = ShardedGallery(capacity=512, dim=32, mesh=mesh)
    emb = np.asarray(net.apply({"params": params["net"]}, normalize_faces(crops, FACE)))
    gallery.add(emb, labels)
    pipe = RecognitionPipeline(det, net, params["net"], gallery, face_size=FACE)
    return pipe, mesh


def _make_service(pipe, batch_size=4):
    connector = FakeConnector()
    service = RecognizerService(
        pipe, connector, batch_size=batch_size, frame_shape=(96, 96),
        flush_timeout=0.02, similarity_threshold=0.2,
        subject_names=[f"person_{i}" for i in range(5)],
    )
    return service, connector


def test_service_end_to_end_results(serving_stack):
    pipe, _ = serving_stack
    service, connector = _make_service(pipe)
    service.start()
    try:
        scenes, boxes, counts = make_synthetic_scenes(8, (96, 96), max_faces=2, seed=91)
        for i, scene in enumerate(scenes):
            connector.inject(FRAME_TOPIC, {**encode_frame(scene), "meta": {"frame_id": i}})
        deadline = time.monotonic() + 20
        while len(connector.messages(RESULT_TOPIC)) < 8 and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        service.stop()
    results = connector.messages(RESULT_TOPIC)
    assert len(results) == 8
    frame_ids = sorted(r["meta"]["frame_id"] for r in results)
    assert frame_ids == list(range(8))
    found = sum(len(r["faces"]) for r in results)
    assert found >= int(counts.sum()) // 2
    for r in results:
        for f in r["faces"]:
            assert set(f) == {"box", "detection_score", "label", "name", "similarity"}
            assert f["name"].startswith(("person_", "unknown"))


def test_service_skips_malformed_frames(serving_stack):
    pipe, _ = serving_stack
    service, connector = _make_service(pipe, batch_size=2)
    service.start()
    try:
        connector.inject(FRAME_TOPIC, {"garbage": True})
        connector.inject(FRAME_TOPIC, {**encode_frame(np.zeros((10, 10), np.float32))})
        scene = make_synthetic_scenes(1, (96, 96), seed=5)[0][0]
        connector.inject(FRAME_TOPIC, {**encode_frame(scene), "meta": {"frame_id": 0}})
        deadline = time.monotonic() + 10
        while not connector.messages(RESULT_TOPIC) and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        service.stop()
    assert len(connector.messages(RESULT_TOPIC)) == 1
    assert service.metrics.counter("frames_malformed") == 1
    assert service.metrics.counter("frames_dropped") == 1


def test_service_enrolment_protocol(serving_stack):
    pipe, mesh = serving_stack
    service, connector = _make_service(pipe, batch_size=2)
    size_before = pipe.gallery.size
    service.start()
    try:
        connector.inject(CONTROL_TOPIC, {"cmd": "enroll", "subject": "newcomer", "count": 2})
        scenes, _, counts = make_synthetic_scenes(12, (96, 96), max_faces=1, seed=13)
        scenes = scenes[counts > 0]
        for i, scene in enumerate(scenes):
            connector.inject(FRAME_TOPIC, {**encode_frame(scene), "meta": i})
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            status = [m for m in connector.messages(STATUS_TOPIC) if m.get("status") == "enrolled"]
            if status:
                break
            time.sleep(0.05)
    finally:
        service.stop()
    enrolled = [m for m in connector.messages(STATUS_TOPIC) if m.get("status") == "enrolled"]
    assert enrolled and enrolled[0]["subject"] == "newcomer"
    assert pipe.gallery.size == size_before + 2
    assert "newcomer" in service.subject_names


def test_service_reload_without_drop(serving_stack):
    pipe, mesh = serving_stack
    from opencv_facerecognizer_tpu.parallel import ShardedGallery

    service, connector = _make_service(pipe)
    staged = ShardedGallery(capacity=512, dim=32, mesh=mesh)
    staged.add(RNG.normal(size=(3, 32)).astype(np.float32), np.array([9, 9, 9], np.int32))
    service.reload_gallery(staged)
    assert pipe.gallery.size == 3
    status = connector.messages(STATUS_TOPIC)
    assert status and status[-1]["status"] == "reloaded"


def test_service_stats_command(serving_stack):
    pipe, _ = serving_stack
    service, connector = _make_service(pipe)
    connector.inject(CONTROL_TOPIC, {"cmd": "stats"})
    stats = [m for m in connector.messages(STATUS_TOPIC) if m.get("status") == "stats"]
    assert stats and "gallery_size" in stats[0]


# ---------- trainer ----------


def test_trainer_classic_flow_and_checkpoint(tmp_path):
    from opencv_facerecognizer_tpu.utils import serialization

    X, y, names = make_synthetic_faces(5, 6, (24, 24), seed=41)
    trainer = TheTrainer(model="fisherfaces", image_size=(24, 24), kfold=3)
    path = str(tmp_path / "model.ckpt")
    model = trainer.train(X, y, names, model_path=path)
    assert trainer.mean_accuracy > 0.8
    restored = serialization.load_model(path)
    pred, _ = restored.predict(X[:4])
    assert (np.asarray(pred) == y[:4]).mean() == 1.0
    assert restored.subject_names == names


def test_trainer_model_zoo():
    # 40x40 keeps LBPH's 8x8 grid cells at a usable 4-5 px (the reference
    # default is 70x70; tiny cells starve the histograms)
    X, y, names = make_synthetic_faces(4, 5, (40, 40), seed=43)
    for model_type in ("eigenfaces", "lbph"):
        trainer = TheTrainer(model=model_type, image_size=(40, 40), kfold=2)
        trainer.train(X, y, names)
        assert trainer.mean_accuracy > 0.7, model_type


def test_trainer_lbp_fisherfaces_checkpoint(tmp_path):
    """The r5 robustness config (raw r=3 LBP 6x6 -> Fisherfaces -> cosine
    NN) trains, validates, and roundtrips through the msgpack checkpoint —
    the composite (ChainOperator + SpatialHistogram(ExtendedLBP r=3) +
    Fisherfaces + cosine NearestNeighbor) must all re-serialize."""
    from opencv_facerecognizer_tpu.utils import serialization

    # 48x48 keeps the 6x6 grid cells at ~7 px (r=3 LBP crops 3 px/side)
    X, y, names = make_synthetic_faces(5, 6, (48, 48), seed=41)
    trainer = TheTrainer(model="lbp_fisherfaces", image_size=(48, 48),
                         kfold=3)
    path = str(tmp_path / "model.ckpt")
    trainer.train(X, y, names, model_path=path)
    assert trainer.mean_accuracy > 0.8
    restored = serialization.load_model(path)
    pred, _ = restored.predict(X[:4])
    assert (np.asarray(pred) == y[:4]).mean() == 1.0
    assert restored.subject_names == names


def test_trainer_cnn_gallery_handoff():
    from opencv_facerecognizer_tpu.parallel import make_mesh

    X, y, names = make_synthetic_faces(4, 6, (32, 32), seed=47, noise=8.0)
    trainer = TheTrainer(
        model="cnn", image_size=(32, 32), kfold=0, embed_dim=32, train_steps=40,
        cnn_kwargs=dict(stem_features=8, stage_features=(8, 16), stage_blocks=(1, 1),
                        batch_size=16, learning_rate=3e-3),
    )
    trainer.train(X, y, names, validate=False)
    gallery = trainer.build_gallery(X, y, make_mesh(tp=8))
    assert gallery.size == len(y)
    emb = np.array(trainer.model.feature.extract(X[:8]))
    labels, sims, _ = (np.asarray(v) for v in gallery.match(emb, k=1))
    assert (labels[:, 0] == y[:8]).mean() >= 0.9
    # store_dtype handoff: build_gallery defaults to f32 while the
    # ocvf-recognize serving default is bf16 — swap_from casts the staged
    # snapshot to the serving width at install (round-5 advisor), so the
    # documented retrain -> reload_gallery handoff works without the
    # trainer knowing serving's dtype.
    import jax.numpy as jnp

    serving = trainer.build_gallery(X, y, make_mesh(tp=8),
                                    store_dtype=jnp.bfloat16)
    assert serving.data.embeddings.dtype == jnp.bfloat16
    staged = trainer.build_gallery(X, y, make_mesh(tp=8),
                                   capacity=serving.capacity,
                                   store_dtype=jnp.bfloat16)
    serving.swap_from(staged)  # dtype + capacity match: plain ref swap
    assert serving.data.embeddings.dtype == jnp.bfloat16
    serving.swap_from(gallery)  # f32 default into bf16 serving: cast
    assert serving.data.embeddings.dtype == jnp.bfloat16
    assert serving.size == gallery.size
    labels2, _, _ = (np.asarray(v) for v in serving.match(emb, k=1))
    assert (labels2[:, 0] == y[:8]).mean() >= 0.9


def test_trainer_rejects_unknown_model_and_field():
    with pytest.raises(TypeError):
        TheTrainer(bogus_field=1)
    trainer = TheTrainer(model="nope")
    with pytest.raises(ValueError):
        trainer.train(*make_synthetic_faces(2, 2, (16, 16)))


def test_trainer_classifier_swap(tmp_path):
    """The reference let any classifier pair with any feature; the trainer
    exposes nn | svm | kernel_svm over every model family."""
    from opencv_facerecognizer_tpu.models import KernelSVM, SVM
    from opencv_facerecognizer_tpu.utils import serialization

    X, y, names = make_synthetic_faces(5, 6, (24, 24), seed=41)
    for clf_kind, clf_type in (("svm", SVM), ("kernel_svm", KernelSVM)):
        trainer = TheTrainer(model="eigenfaces", image_size=(24, 24),
                             kfold=0, classifier=clf_kind)
        path = str(tmp_path / f"{clf_kind}.ckpt")
        trainer.train(X, y, names, validate=False, model_path=path)
        assert isinstance(trainer.model.classifier, clf_type)
        restored = serialization.load_model(path)
        pred, _ = restored.predict(X[:6])
        assert (np.asarray(pred) == y[:6]).mean() >= 0.8, clf_kind
    with pytest.raises(ValueError):
        TheTrainer(classifier="nope").train(X, y, names, validate=False)


def test_select_model_picks_measured_winner(tmp_path):
    """select_model k-folds every candidate on the same data, fits ONLY
    the winner on the full set, and checkpoints it (the 'which model?'
    question answered by measurement — SURVEY §2.1 Validation extension)."""
    from opencv_facerecognizer_tpu.runtime.trainer import select_model
    from opencv_facerecognizer_tpu.utils import serialization

    X, y, names = make_synthetic_faces(5, 6, (48, 48), seed=41)
    path = str(tmp_path / "auto.ckpt")
    winner, scores = select_model(
        X, y, names, candidates=("eigenfaces", "lbp_fisherfaces"),
        model_path=path, image_size=(48, 48), kfold=3)
    assert set(scores) == {"eigenfaces", "lbp_fisherfaces"}
    best = max(scores, key=scores.get)
    assert winner.config.model == best
    assert winner.mean_accuracy == scores[best]
    restored = serialization.load_model(path)
    pred, _ = restored.predict(X[:4])
    assert (np.asarray(pred) == y[:4]).mean() >= 0.75
