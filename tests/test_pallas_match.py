"""Streaming pallas top-k matcher vs the lax.top_k oracle.

Runs in interpret mode on the CPU suite (SURVEY.md §4 prescription: every
kernel gets an oracle test); the compiled-TPU path is exercised by bench.py
and the gallery fast path on the real chip.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from opencv_facerecognizer_tpu.ops.pallas_match import streaming_match_topk

RNG = np.random.default_rng(3)


def _oracle(q, g, valid, k):
    sims = q.astype(np.float32) @ g.astype(np.float32).T
    sims = np.where(np.asarray(valid)[None, :], sims, -1e30)
    idx = np.argsort(-sims, axis=1)[:, :k]
    return np.take_along_axis(sims, idx, axis=1), idx


def _normed(shape):
    x = RNG.normal(size=shape).astype(np.float32)
    return x / np.linalg.norm(x, axis=-1, keepdims=True)


@pytest.mark.parametrize("qn,n,k", [(8, 256, 1), (16, 512, 4), (32, 1024, 8)])
def test_streaming_topk_matches_oracle(qn, n, k):
    q = _normed((qn, 64))
    g = _normed((n, 64))
    valid = np.ones(n, bool)
    vals, idx = streaming_match_topk(jnp.asarray(q), jnp.asarray(g),
                                     jnp.asarray(valid), k=k,
                                     block_q=8, block_n=128, interpret=True)
    ovals, _ = _oracle(q, g, valid, k)
    # bf16 matmul: compare values loosely, and exact given re-scored indices
    np.testing.assert_allclose(np.asarray(vals), ovals, atol=2e-2)
    rescored = np.take_along_axis(q @ g.T, np.asarray(idx), axis=1)
    np.testing.assert_allclose(np.sort(rescored), np.sort(ovals), atol=2e-2)


def test_streaming_topk_masks_invalid_rows():
    q = _normed((8, 32))
    g = _normed((256, 32))
    valid = np.zeros(256, bool)
    valid[:7] = True  # fewer valid rows than would fill k on some tiles
    vals, idx = streaming_match_topk(jnp.asarray(q), jnp.asarray(g),
                                     jnp.asarray(valid), k=4,
                                     block_q=8, block_n=64, interpret=True)
    idx = np.asarray(idx)
    vals = np.asarray(vals)
    real = vals > -1e29
    assert np.all(idx[real] < 7), "an invalid gallery row surfaced"
    ovals, oidx = _oracle(q, g, valid, 4)
    np.testing.assert_allclose(vals[real], ovals[real.nonzero()[0],
                                                 real.nonzero()[1]], atol=2e-2)


def test_streaming_topk_unaligned_sizes():
    # Q and N not multiples of the blocks: padding path.
    q = _normed((13, 48))
    g = _normed((300, 48))
    valid = np.ones(300, bool)
    valid[250:] = False
    vals, idx = streaming_match_topk(jnp.asarray(q), jnp.asarray(g),
                                     jnp.asarray(valid), k=3,
                                     block_q=8, block_n=128, interpret=True)
    assert vals.shape == (13, 3) and idx.shape == (13, 3)
    ovals, _ = _oracle(q, g, valid, 3)
    np.testing.assert_allclose(np.asarray(vals), ovals, atol=2e-2)
    assert np.all(np.asarray(idx) < 250)


def test_streaming_topk_tie_break_prefers_lowest_index():
    """Deterministic tie-breaking parity (BENCH_r05: pallas-vs-XLA idx
    match 0.6914 with |sim diff| exactly 0 — pure tie-order divergence):
    on a tie-heavy gallery (every row duplicated many times, ties spanning
    multiple gallery tiles) the kernel must agree with a stable
    lowest-index-first oracle on EVERY index — idx match == 1.0."""
    base = _normed((4, 32))
    g = np.tile(base, (32, 1))  # 128 rows; each base row appears 32x,
    q = base                    # copies 4 apart -> ties cross block_n=32 tiles
    valid = np.ones(len(g), bool)
    vals, idx = streaming_match_topk(jnp.asarray(q), jnp.asarray(g),
                                     jnp.asarray(valid), k=4,
                                     block_q=8, block_n=32, interpret=True)
    sims = q @ g.T
    # Stable argsort == lax.top_k's documented tie order: lowest index
    # first among equal similarities.
    oidx = np.argsort(-sims, axis=1, kind="stable")[:, :4]
    idx = np.asarray(idx)
    assert (idx == oidx).mean() == 1.0, (idx, oidx)
    # And the tied values themselves survive exactly.
    ovals = np.take_along_axis(sims, oidx, axis=1)
    np.testing.assert_allclose(np.asarray(vals), ovals, atol=2e-2)


def test_streaming_topk_duplicate_scores_unique_indices():
    # Identical gallery rows: the k winners must be k distinct indices.
    g = np.tile(_normed((1, 16)), (64, 1)).astype(np.float32)
    q = g[:4]
    vals, idx = streaming_match_topk(jnp.asarray(q), jnp.asarray(g),
                                     jnp.ones(64, bool), k=4,
                                     block_q=8, block_n=32, interpret=True)
    idx = np.asarray(idx)
    for row in idx:
        assert len(set(row.tolist())) == 4, row
