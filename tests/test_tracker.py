"""Temporal identity cache (ISSUE 17): the ``IdentityTracker`` unit
contract (confirmation, re-verify window + brownout stretch, median-
signature drift, embedder-version fence, ambiguity sweep, miss aging,
teleport re-acquisition), the synthetic video generator + oracle, the
serving gate's ``completed_cached`` ledger settlement, the fast seed-7
chaos-video variant, and the registry/bench plumbing."""

import importlib.util
import json
import os

import numpy as np

from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
from opencv_facerecognizer_tpu.runtime.fakes import (
    InstantPipeline,
    synthetic_video_stream,
)
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    RESULT_TOPIC,
    RecognizerService,
)
from opencv_facerecognizer_tpu.runtime.tracker import (
    IdentityTracker,
    TrackerConfig,
)
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.metrics import Metrics

HW = (64, 64)
CAM = "cam0"


def _frame(box=(10, 8, 26, 24), value=160.0, seed=0):
    """Noise background + one identity blob (the oracle encoding:
    ``160 + 24 * label``)."""
    frame = np.random.default_rng(seed).integers(
        20, 90, size=HW).astype(np.uint8).astype(np.float32)
    if box is not None:
        y0, x0, y1, x1 = box
        frame[y0:y1, x0:x1] = value
    return frame


def _face(box=(10, 8, 26, 24), label=0, name="id0", sim=0.9, det=0.9):
    """Publish-path face dict (x-first box), as ``update`` consumes."""
    y0, x0, y1, x1 = box
    return {"box": [x0, y0, x1, y1], "label": label, "name": name,
            "similarity": sim, "detection_score": det}


def _tracker(metrics=None, **cfg):
    cfg.setdefault("reverify_frames", 4)
    return IdentityTracker(TrackerConfig(**cfg),
                           metrics=metrics or Metrics())


def _confirm(tracker, box=(10, 8, 26, 24), label=0, value=160.0,
             version=None):
    """Two full frames: seed + confirm one track (confirm_hits=2)."""
    frame = _frame(box, value)
    for _ in range(2):
        tracker.update(CAM, [_face(box, label)], frame,
                       embedder_version=version)
    return frame


# ---- unit: lifecycle, window, drift, fences --------------------------------


def test_lookup_requires_confirmation_then_hits():
    tracker = _tracker()
    frame = _frame()
    assert tracker.lookup(CAM, frame) is None          # no tracks yet
    tracker.update(CAM, [_face()], frame)
    assert tracker.lookup(CAM, frame) is None          # tentative
    tracker.update(CAM, [_face()], frame)
    hit = tracker.lookup(CAM, frame)
    assert hit is not None
    face = hit["faces"][0]
    # Payload shaped exactly like the publish path's, plus track_id.
    assert face["box"] == [8.0, 10.0, 24.0, 26.0]      # x-first
    assert face["label"] == 0 and face["name"] == "id0"
    assert face["track_id"] == hit["track_id"]
    assert tracker.stats()["tracks_live"] == 1


def test_reverify_window_and_brownout_stretch():
    tracker = _tracker(reverify_frames=4)
    frame = _confirm(tracker)
    hits = sum(tracker.lookup(CAM, frame) is not None for _ in range(6))
    assert hits == 3                                   # interval 4: 3 cached
    assert tracker.metrics.counter(mn.TRACK_REVERIFIES) == 1
    # The window edge parks the track until the next FULL frame...
    assert tracker.lookup(CAM, frame) is None
    tracker.update(CAM, [_face()], frame)
    # ...and a brownout stretch of 2.0 doubles the cached run.
    hits = sum(tracker.lookup(CAM, frame, reverify_stretch=2.0) is not None
               for _ in range(10))
    assert hits == 7


def test_drift_flags_identity_swap_but_tolerates_motion():
    tracker = _tracker(reverify_frames=100)
    frame = _confirm(tracker)
    # Ordinary 1px motion: only edge cells of the pooled signature move,
    # the MEDIAN stays ~0 — still a hit.
    assert tracker.lookup(CAM, _frame((10, 9, 26, 25))) is not None
    # In-place identity swap (same box, new fill): every cell moves by
    # the full label delta — forced verify on this very frame.
    assert tracker.lookup(CAM, _frame(value=232.0)) is None
    assert tracker.metrics.counter(mn.TRACK_REVERIFIES) >= 1
    # Parked (never served stale) until a full frame re-verifies; the
    # verify flushes the old identity and seeds the new one, which must
    # confirm (two full frames) before it serves.
    assert tracker.lookup(CAM, _frame(value=232.0)) is None
    tracker.update(CAM, [_face(label=3, name="id3")], _frame(value=232.0))
    assert tracker.metrics.counter(
        mn.TRACK_FLUSHES_PREFIX + "identity") == 1
    tracker.update(CAM, [_face(label=3, name="id3")], _frame(value=232.0))
    hit = tracker.lookup(CAM, _frame(value=232.0))
    assert hit is not None and hit["faces"][0]["label"] == 3


def test_embedder_version_fence_flushes():
    tracker = _tracker(reverify_frames=100)
    frame = _confirm(tracker, version=1)
    assert tracker.lookup(CAM, frame, embedder_version=1) is not None
    # Cutover: entries stamped v1 are dead on arrival under v2.
    assert tracker.lookup(CAM, frame, embedder_version=2) is None
    assert tracker.metrics.counter(
        mn.TRACK_FLUSHES_PREFIX + "version") == 1
    assert tracker.stats()["tracks_live"] == 0


def test_ambiguity_flushes_both_tracks():
    tracker = _tracker()
    a, b = (10, 4, 34, 28), (10, 36, 30, 56)
    frame = _frame(a)
    frame[10:30, 36:56] = 184.0
    for _ in range(2):
        tracker.update(CAM, [_face(a, 0), _face(b, 1, "id1")], frame)
    assert tracker.lookup(CAM, frame) is not None
    # The small face moves inside the big one (IoU ~0.69 > ceiling):
    # neither fails the identity check, only the sweep catches it —
    # BOTH flush, before either can capture the other's identity.
    nested = (12, 6, 32, 26)
    tracker.update(CAM, [_face(a, 0), _face(nested, 1, "id1")], frame)
    assert tracker.metrics.counter(
        mn.TRACK_FLUSHES_PREFIX + "ambiguity") == 2
    assert tracker.stats()["tracks_live"] == 0


def test_note_miss_parks_then_ttl_flushes_lost():
    tracker = _tracker(reverify_frames=100)
    frame = _confirm(tracker)
    tracker.note_miss(CAM)
    # Occlusion parks the track out of the cache without burning it...
    assert tracker.lookup(CAM, frame) is None
    tracker.update(CAM, [_face()], frame)
    assert tracker.lookup(CAM, frame) is not None
    # ...but past the TTL (miss_ttl=2) the subject is gone: flush lost.
    for _ in range(3):
        tracker.note_miss(CAM)
    assert tracker.metrics.counter(mn.TRACK_FLUSHES_PREFIX + "lost") == 1
    assert tracker.stats()["tracks_live"] == 0


def test_reacquisition_after_teleport_keeps_confirmed_state():
    tracker = _tracker(reverify_frames=100)
    _confirm(tracker)
    # The subject teleports (admission drop gap, scene cut): no IoU, no
    # centroid reach — but the FULL pipeline just verified this label at
    # the new box, so the unique unmatched track re-seeds there instead
    # of orphaning + cold-starting.
    far = (40, 40, 56, 56)
    tracker.update(CAM, [_face(far, 0)], _frame(far))
    reg = tracker.registry()
    assert len(reg) == 1 and reg[0]["confirmed"]
    assert reg[0]["box"] == [40.0, 40.0, 56.0, 56.0]
    assert tracker.lookup(CAM, _frame(far)) is not None


def test_flush_all_cold_starts():
    tracker = _tracker()
    frame = _confirm(tracker)
    assert tracker.flush_all() == 1
    assert tracker.lookup(CAM, frame) is None
    assert tracker.metrics.counter(mn.TRACK_FLUSHES_PREFIX + "reset") == 1


# ---- video generator + oracle ----------------------------------------------


def test_synthetic_video_stream_deterministic_and_coherent():
    a = synthetic_video_stream(30, HW, streams=2, coherence=0.9, seed=3)
    b = synthetic_video_stream(30, HW, streams=2, coherence=0.9, seed=3)
    assert len(a) == 30
    for (fa, ka, na), (fb, kb, nb) in zip(a, b):
        assert ka == kb and na == nb
        np.testing.assert_array_equal(fa, fb)
    assert {k for _f, k, _n in a} == {"cam0", "cam1"}
    # Identity blobs use the oracle encoding (160 + 24 * label).
    for frame, _k, n in a:
        if n:
            vals = set(np.unique(frame[frame >= 150]).tolist())
            assert vals <= {160, 184, 208, 232}


def test_synthetic_video_stream_identity_swap_in_place():
    rows = synthetic_video_stream(12, HW, coherence=1.0, seed=5,
                                  identity_swap_at=6)
    def blob_val(frame):
        return int(frame[frame >= 150].max())
    before, after = blob_val(rows[5][0]), blob_val(rows[6][0])
    assert before != after                             # identity changed


def test_instant_pipeline_video_oracle_decodes_labels():
    pipeline = InstantPipeline(HW, cascade_stub=True, video_oracle=True)
    # The oracle is what lets tests assert identity CORRECTNESS, not
    # just settlement: label = (fill - 160) / 24 at the blob's bbox.
    batch = np.stack([_frame(value=160.0), _frame(value=208.0)])
    packed = np.asarray(pipeline.recognize_batch_packed(batch))
    from opencv_facerecognizer_tpu.parallel.pipeline import unpack_result
    result = unpack_result(packed, pipeline.top_k)
    assert bool(result.valid[0, 0]) and bool(result.valid[1, 0])
    assert int(result.labels[0, 0, 0]) == 0
    assert int(result.labels[1, 0, 0]) == 2


# ---- serving gate: completed_cached settlement -----------------------------


def _service(tracker):
    metrics = tracker.metrics
    pipeline = InstantPipeline(HW, cascade_stub=True, video_oracle=True)
    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=4, frame_shape=HW,
        flush_timeout=0.01, inflight_depth=2, similarity_threshold=0.0,
        metrics=metrics, bucket_sizes=(1, 2, 4), cascade=True,
        subject_names=["id0", "id1", "id2", "id3"], tracker=tracker)
    pipeline.prewarm_batch_shapes(service._bucket_ladder, HW,
                                  service.batcher.dtype)
    service._warmed = True
    return service, connector


def test_service_settles_cache_hits_as_completed_cached():
    tracker = _tracker(reverify_frames=6)
    service, connector = _service(tracker)
    results = []
    connector.subscribe(RESULT_TOPIC, lambda t, m: results.append(m))
    service.start(warmup=False)
    rows = synthetic_video_stream(24, HW, coherence=1.0, seed=1)
    for i, (frame, key, _n) in enumerate(rows):
        connector.inject(FRAME_TOPIC, {"frame": frame,
                                       "meta": {"seq": i, "stream": key}})
        assert service.drain(timeout=20.0)
    service.stop()
    ledger = service.ledger()
    assert ledger["completed_cached"] > 0 and ledger["completed"] > 0
    drops = sum(ledger["drops_by_reason"].values())
    # The extended invariant: every admitted frame lands in exactly one
    # terminal bucket, cached included.
    assert ledger["admitted"] == (ledger["completed"]
                                  + ledger["completed_empty"]
                                  + ledger["completed_cached"] + drops)
    assert ledger["in_system"] == 0
    assert len(results) == 24
    cached = [m for m in results if m.get("exit") == "track_cache"]
    assert len(cached) == ledger["completed_cached"]
    full_label = next(m for m in results
                      if m.get("exit") is None)["faces"][0]["label"]
    for m in cached:
        assert "track_id" in m
        assert m["faces"][0]["label"] == full_label  # never a wrong identity
    assert tracker.metrics.counter(mn.TRACK_BATCH_EXITS) >= 0


def test_service_without_stream_key_takes_full_path():
    tracker = _tracker()
    service, connector = _service(tracker)
    service.start(warmup=False)
    for i in range(8):
        connector.inject(FRAME_TOPIC, {"frame": _frame(seed=i),
                                       "meta": {"seq": i}})
        assert service.drain(timeout=20.0)
    service.stop()
    ledger = service.ledger()
    # No stream identity -> no temporal coherence to exploit: the cache
    # must stand aside, not guess.
    assert ledger["completed_cached"] == 0
    assert ledger["completed"] == 8


# ---- chaos: the fast seed-7 video variant ----------------------------------

REPO_ROOT = os.path.join(os.path.dirname(__file__), "..")
_spec = importlib.util.spec_from_file_location(
    "chaos_soak_video", os.path.join(REPO_ROOT, "scripts", "chaos_soak.py"))
chaos_soak = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos_soak)


def test_chaos_video_fast_deterministic():
    """Seed-7 tier-1 variant of ``--scenario video``: identity swap with
    the drift check armed (zero stale) and disabled (stale bounded by
    the re-verify window), ambiguity flushing both, failover cold-start
    + version fence, exact extended ledgers and span accounting."""
    report = chaos_soak.run_video(seconds=1.0, seed=7)
    assert report["ok"], report["failures"]
    assert report["swap_drift"]["stale_after_swap"] == 0
    assert report["swap_drift"]["cached_total"] > 0
    assert report["ambiguity"]["flushes"] >= 2
    assert report["ambiguity"]["cached_past_window"] == 0
    assert report["failover"]["version_flushes"] >= 1
    acct = report["span_accounting"]
    assert acct["completed_cached"] > 0
    assert acct["traced"] == (acct["completed"] + acct["completed_empty"]
                              + acct["completed_cached"]
                              + sum(acct["drops"].values()))


# ---- registry / plumbing ---------------------------------------------------


def test_track_metric_names_registered():
    names = set(mn.all_names())
    for name in (mn.TRACK_LOOKUPS, mn.TRACK_CACHE_HITS,
                 mn.TRACK_CACHE_HIT_RATE, mn.TRACK_REVERIFIES,
                 mn.TRACK_BATCH_EXITS, mn.TRACK_ERRORS,
                 mn.FRAMES_COMPLETED_CACHED):
        assert name in names
    assert mn.TRACK_FLUSHES_PREFIX in set(mn.all_prefixes())
    from tools.ocvf_lint.wiring import ATTR_HINTS, HOT_PATH_SUFFIXES

    assert ATTR_HINTS["tracker"] == "IdentityTracker"
    assert any(s.endswith("runtime/tracker.py") for s in HOT_PATH_SUFFIXES)


def test_expo_tracks_endpoint_and_null_shape():
    import urllib.request

    from opencv_facerecognizer_tpu.runtime.expo import ExpoServer

    tracker = _tracker()
    _confirm(tracker)

    class _Svc:  # the expo surface only reads .tracker
        pass

    svc = _Svc()
    svc.tracker = tracker
    expo = ExpoServer(metrics=Metrics(), service=svc, port=0)
    expo.start()
    try:
        with urllib.request.urlopen(
                f"http://{expo.host}:{expo.port}/tracks", timeout=5) as r:
            body = json.loads(r.read())
        assert len(body["tracks"]) == 1
        assert body["tracks"][0]["confirmed"]
        assert body["stats"]["tracks_live"] == 1
    finally:
        expo.stop()
    # Unwired tracker answers the null shape, not a 404.
    bare = ExpoServer(metrics=Metrics(), port=0)
    bare.start()
    try:
        with urllib.request.urlopen(
                f"http://{bare.host}:{bare.port}/tracks", timeout=5) as r:
            assert json.loads(r.read())["tracks"] is None
    finally:
        bare.stop()


def test_bench_compare_tracks_video_uplift():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(REPO_ROOT, "scripts",
                                      "bench_compare.py"))
    bench_compare = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_compare)
    assert "video_cache_uplift" in bench_compare.METRICS
    doc = {"video": {"cells": {"c90": {"uplift": 2.5}}}}
    extract = bench_compare.METRICS["video_cache_uplift"][0]
    assert extract(doc) == 2.5
    # Regression direction: candidate losing the uplift fails.
    report = bench_compare.compare(doc, {"video": {"cells": {
        "c90": {"uplift": 1.0}}}})
    assert any(r["metric"] == "video_cache_uplift"
               and r["verdict"] == "regression" for r in report["metrics"])
