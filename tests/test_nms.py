"""On-device NMS vs brute-force oracle (SURVEY.md §4 prescription)."""

import numpy as np

from opencv_facerecognizer_tpu.ops import nms as N

RNG = np.random.default_rng(13)


def brute_force_nms(boxes, scores, iou_t, score_t):
    def iou(a, b):
        y0, x0 = max(a[0], b[0]), max(a[1], b[1])
        y1, x1 = min(a[2], b[2]), min(a[3], b[3])
        inter = max(y1 - y0, 0) * max(x1 - x0, 0)
        area = lambda z: max(z[2] - z[0], 0) * max(z[3] - z[1], 0)
        return inter / max(area(a) + area(b) - inter, 1e-12)

    order = np.argsort(-scores)
    kept = []
    for i in order:
        if scores[i] <= score_t:
            continue
        if all(iou(boxes[i], boxes[j]) <= iou_t for j in kept):
            kept.append(i)
    return sorted(kept)


def _random_boxes(k=40):
    y0 = RNG.uniform(0, 60, k)
    x0 = RNG.uniform(0, 60, k)
    h = RNG.uniform(5, 30, k)
    w = RNG.uniform(5, 30, k)
    boxes = np.stack([y0, x0, y0 + h, x0 + w], axis=1).astype(np.float32)
    scores = RNG.uniform(0, 1, k).astype(np.float32)
    return boxes, scores


def test_pairwise_iou_oracle():
    a, _ = _random_boxes(10)
    b, _ = _random_boxes(7)
    got = np.asarray(N.pairwise_iou(a, b))
    for i in range(10):
        for j in range(7):
            yi0, xi0 = max(a[i, 0], b[j, 0]), max(a[i, 1], b[j, 1])
            yi1, xi1 = min(a[i, 2], b[j, 2]), min(a[i, 3], b[j, 3])
            inter = max(yi1 - yi0, 0) * max(xi1 - xi0, 0)
            area_a = (a[i, 2] - a[i, 0]) * (a[i, 3] - a[i, 1])
            area_b = (b[j, 2] - b[j, 0]) * (b[j, 3] - b[j, 1])
            want = inter / (area_a + area_b - inter)
            np.testing.assert_allclose(got[i, j], want, rtol=1e-4, atol=1e-5)


def test_nms_mask_matches_bruteforce():
    for trial in range(5):
        boxes, scores = _random_boxes(40)
        keep = np.asarray(N.nms_mask(boxes, scores, 0.4, 0.1))
        want = brute_force_nms(boxes, scores, 0.4, 0.1)
        assert sorted(np.flatnonzero(keep).tolist()) == want, f"trial {trial}"


def test_nms_fixed_output_shapes_and_order():
    boxes, scores = _random_boxes(30)
    out_boxes, out_scores, valid = (np.asarray(v) for v in N.nms_fixed(boxes, scores, 8, 0.4, 0.1))
    assert out_boxes.shape == (8, 4) and out_scores.shape == (8,) and valid.shape == (8,)
    vs = out_scores[valid]
    assert np.all(np.diff(vs) <= 1e-6)  # descending
    assert np.all(out_boxes[~valid] == 0.0)


def test_nms_all_below_threshold():
    boxes, scores = _random_boxes(10)
    _, out_scores, valid = N.nms_fixed(boxes, scores * 0.01, 4, 0.4, 0.5)
    assert not np.any(np.asarray(valid))


def test_nms_identical_boxes_keep_one():
    box = np.array([[10, 10, 30, 30]] * 5, dtype=np.float32)
    scores = np.array([0.9, 0.8, 0.7, 0.6, 0.5], dtype=np.float32)
    keep = np.asarray(N.nms_mask(box, scores, 0.5, 0.0))
    assert keep.sum() == 1 and keep[0]
