"""Transport-layer tests (SURVEY.md §5.8, §2.1 ROS/RSB rows): EOF/shutdown
semantics, the TCP socket transport (two-process round-trip), the real
ROSConnector body against a mocked rospy, and gallery auto-grow."""

import io
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime.connector import (
    JSONLConnector,
    ROSConnector,
    SocketConnector,
    decode_frame,
    decode_ros_image,
    encode_frame,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------- JSONL shutdown semantics ----------


def test_jsonl_eof_event_set_when_stream_ends():
    c = JSONLConnector(io.StringIO('{"topic": "t", "data": {}}\n'), io.StringIO())
    c.start()
    assert c.eof.wait(timeout=5.0)
    c.stop()


def test_jsonl_stop_unblocks_reader_without_input():
    # A pipe with no writer activity: the reader thread blocks in readline.
    r_fd, w_fd = os.pipe()
    reader = os.fdopen(r_fd, "r")
    c = JSONLConnector(reader, io.StringIO())
    c.start()
    time.sleep(0.1)
    assert c._thread.is_alive()
    t0 = time.monotonic()
    c.stop()  # closes the stream -> reader unblocks
    assert time.monotonic() - t0 < 2.5
    assert c._thread is None
    assert c.eof.is_set()
    os.close(w_fd)


# ---------- socket transport ----------


def test_socket_connector_roundtrip_in_process():
    server = SocketConnector(listen=True)
    received = []
    server.subscribe("frames", lambda t, m: received.append(m))
    server.start()

    client = SocketConnector(port=server.port)
    results = []
    client.subscribe("results", lambda t, m: results.append(m))
    client.start()

    frame = np.arange(12, dtype=np.float32).reshape(3, 4)
    client.publish("frames", {**encode_frame(frame), "meta": {"seq": 1}})
    deadline = time.monotonic() + 5
    while not received and time.monotonic() < deadline:
        time.sleep(0.01)
    assert received, "server never received the client frame"
    np.testing.assert_array_equal(decode_frame(received[0]), frame)

    server.publish("results", {"name": "alice", "seq": 1})
    deadline = time.monotonic() + 5
    while not results and time.monotonic() < deadline:
        time.sleep(0.01)
    assert results == [{"name": "alice", "seq": 1}]

    client.stop()
    server.stop()


def test_socket_connector_stalled_client_dropped_not_wedging():
    """One client that never reads (full TCP buffer) must neither wedge
    publishes to healthy clients nor block the publishing thread forever:
    the bounded send drops it like a dead client (round-2 advisor #1)."""
    import socket as socket_mod

    server = SocketConnector(listen=True)
    server._send_deadline_s = 0.25  # keep the test fast
    server.start()

    # Healthy client: a real SocketConnector that reads.
    healthy = SocketConnector(port=server.port)
    got = []
    healthy.subscribe("results", lambda t, m: got.append(m))
    healthy.start()

    # Stalled client: raw socket with a tiny receive buffer that never reads.
    stalled = socket_mod.create_connection(("127.0.0.1", server.port))
    stalled.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVBUF, 1024)

    deadline = time.monotonic() + 5
    while len(server._client_socks) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(server._client_socks) == 2
    # Shrink the server-side send buffers so the stalled client's pipe
    # actually fills (default buffers could swallow the whole test load).
    with server._lock:
        for sock in server._client_socks:
            sock.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_SNDBUF, 4096)

    # Publish payloads big enough to overrun the stalled client's buffers.
    blob = "x" * 65536
    t0 = time.monotonic()
    for i in range(8):
        server.publish("results", {"seq": i, "blob": blob})
    elapsed = time.monotonic() - t0
    # Bounded: the stalled client costs at most ~one deadline before it is
    # dropped; an unbounded sendall would hang here forever.
    assert elapsed < 5.0, f"publish loop took {elapsed:.1f}s — send not bounded"

    deadline = time.monotonic() + 5
    while len(got) < 8 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(got) == 8, f"healthy client got {len(got)}/8 messages"
    # The stalled client was evicted; the healthy one remains.
    with server._lock:
        assert len(server._client_socks) == 1

    stalled.close()
    healthy.stop()
    server.stop()


def test_socket_stalled_client_drop_counted_on_metrics():
    """The deadline-bounded send path (`_send_deadline_s`) counts each
    evicted stalled client as ``connector_stalled_clients_dropped`` on the
    shared Metrics surface — the ledger a stats consumer reads."""
    import socket as socket_mod

    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    server = SocketConnector(listen=True, metrics=m)
    server._send_deadline_s = 0.25
    server.start()
    try:
        stalled = socket_mod.create_connection(("127.0.0.1", server.port))
        stalled.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVBUF, 1024)
        deadline = time.monotonic() + 5
        while not server._client_socks and time.monotonic() < deadline:
            time.sleep(0.01)
        with server._lock:
            for sock in server._client_socks:
                sock.setsockopt(socket_mod.SOL_SOCKET,
                                socket_mod.SO_SNDBUF, 4096)
        blob = "x" * 65536
        for i in range(8):
            server.publish("results", {"seq": i, "blob": blob})
            if m.counter("connector_stalled_clients_dropped"):
                break
        assert m.counter("connector_stalled_clients_dropped") == 1
        with server._lock:
            assert server._client_socks == []  # evicted
        stalled.close()
    finally:
        server.stop()


def test_socket_client_reconnects_after_server_blip():
    """Satellite: ``SocketConnector(listen=False)`` used to die permanently
    when the server dropped the connection. Now it redials with bounded
    exponential backoff, counts ``connector_reconnects``, and keeps
    round-tripping on the new connection; ``eof`` stays unset."""
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    server = SocketConnector(listen=True)
    received = []
    server.subscribe("frames", lambda t, msg: received.append(msg))
    server.start()
    port = server.port

    m = Metrics()
    client = SocketConnector(port=port, metrics=m,
                             reconnect_backoff_base_s=0.02)
    client.start()
    server2 = None
    try:
        client.publish("frames", {"seq": 1})
        deadline = time.monotonic() + 5
        while not received and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received == [{"seq": 1}]

        # Server blip: tear it down, then resurrect on the SAME port.
        server.stop()
        server2 = SocketConnector(host="127.0.0.1", port=port, listen=True)
        server2.subscribe("frames", lambda t, msg: received.append(msg))
        server2.start()

        deadline = time.monotonic() + 10
        while (m.counter("connector_reconnects") < 1
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert m.counter("connector_reconnects") == 1
        assert m.counter("connector_peer_disconnects") == 1
        assert not client.eof.is_set()

        # The reconnected session round-trips.
        deadline = time.monotonic() + 5
        while len(received) < 2 and time.monotonic() < deadline:
            client.publish("frames", {"seq": 2})
            time.sleep(0.05)
        assert received[-1] == {"seq": 2}
    finally:
        client.stop()
        if server2 is not None:
            server2.stop()


def test_socket_client_reconnect_budget_bounded_then_eof():
    """With the server gone for good, the client retries exactly its
    bounded budget (counting failures), then sets ``eof`` — no infinite
    redial loop, no permanent zombie."""
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    server = SocketConnector(listen=True)
    server.start()
    m = Metrics()
    client = SocketConnector(port=server.port, metrics=m,
                             reconnect_attempts=2,
                             reconnect_backoff_base_s=0.02,
                             reconnect_backoff_max_s=0.05)
    client.start()
    try:
        server.stop()  # and never comes back
        assert client.eof.wait(timeout=10.0), "client never gave up"
        assert m.counter("connector_reconnect_failures") == 2
        assert m.counter("connector_reconnects") == 0
    finally:
        client.stop()


_CHILD_ECHO = """
import sys
sys.path.insert(0, {root!r})
from opencv_facerecognizer_tpu.runtime.connector import SocketConnector, \\
    decode_frame, encode_frame

# Child = the "service": accepts a frame, answers with a result message.
server = SocketConnector(listen=True)

def on_frame(topic, message):
    frame = decode_frame(message)
    server.publish("results", {{"mean": float(frame.mean()),
                                "meta": message.get("meta")}})

server.subscribe("frames", on_frame)
server.start()
print(server.port, flush=True)
server.eof.wait(timeout=30)
server.stop()
"""


def test_socket_connector_two_process_roundtrip():
    """Frames -> results across a real process boundary over TCP."""
    child = subprocess.Popen(
        [sys.executable, "-c", _CHILD_ECHO.format(root=REPO_ROOT)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        port = int(child.stdout.readline())
        client = SocketConnector(port=port)
        results = []
        client.subscribe("results", lambda t, m: results.append(m))
        client.start()
        frame = np.full((4, 4), 7.0, dtype=np.float32)
        client.publish("frames", {**encode_frame(frame), "meta": {"n": 42}})
        deadline = time.monotonic() + 10
        while not results and time.monotonic() < deadline:
            time.sleep(0.02)
        client.stop()
        assert results and results[0]["mean"] == 7.0
        assert results[0]["meta"] == {"n": 42}
    finally:
        child.terminate()
        child.wait(timeout=10)


# ---------- ROS image decoding ----------


class _ImageMsg:
    def __init__(self, height, width, encoding, data, step=None, is_bigendian=0):
        self.height = height
        self.width = width
        self.encoding = encoding
        self.data = data
        bpp = {"mono8": 1, "mono16": 2, "rgb8": 3, "bgr8": 3,
               "rgba8": 4, "bgra8": 4}[encoding]
        self.step = step if step is not None else width * bpp
        self.is_bigendian = is_bigendian
        self.header = type("H", (), {"stamp": "12.5"})()


def test_decode_ros_image_mono8_with_row_padding():
    img = np.arange(6, dtype=np.uint8).reshape(2, 3)
    padded = np.concatenate([img, np.zeros((2, 2), np.uint8)], axis=1)  # step=5
    msg = _ImageMsg(2, 3, "mono8", padded.tobytes(), step=5)
    np.testing.assert_array_equal(decode_ros_image(msg), img.astype(np.float32))


def test_decode_ros_image_bgr8_luma():
    rgb = np.zeros((1, 2, 3), np.uint8)
    rgb[0, 0] = (255, 0, 0)  # pure red
    rgb[0, 1] = (0, 255, 0)  # pure green
    bgr = rgb[..., ::-1]
    msg = _ImageMsg(1, 2, "bgr8", bgr.tobytes())
    gray = decode_ros_image(msg)
    np.testing.assert_allclose(gray[0], [255 * 0.299, 255 * 0.587], rtol=1e-5)


def test_decode_ros_image_rejects_unknown_encoding():
    msg = _ImageMsg(1, 1, "mono8", b"\x00")
    msg.encoding = "yuv422"
    with pytest.raises(ValueError, match="encoding"):
        decode_ros_image(msg)


# ---------- ROSConnector against a mocked rospy ----------


class _FakePublisher:
    def __init__(self, topic, msg_cls, queue_size=0):
        self.topic = topic
        self.published = []

    def publish(self, msg):
        self.published.append(msg)


class _FakeSubscriber:
    def __init__(self, topic, msg_cls, callback):
        self.topic = topic
        self.callback = callback
        self.unregistered = False

    def unregister(self):
        self.unregistered = True


class _FakeRospy:
    def __init__(self):
        self.node = None
        self.publishers = []
        self.subscribers = []

    def init_node(self, name, **kwargs):
        self.node = (name, kwargs)

    def Subscriber(self, topic, msg_cls, callback):
        sub = _FakeSubscriber(topic, msg_cls, callback)
        self.subscribers.append(sub)
        return sub

    def Publisher(self, topic, msg_cls, queue_size=0):
        pub = _FakePublisher(topic, msg_cls, queue_size)
        self.publishers.append(pub)
        return pub


@pytest.fixture
def ros_stack():
    rospy = _FakeRospy()
    conn = ROSConnector(rospy_module=rospy)
    conn.start()
    return rospy, conn


def test_ros_connector_image_to_frame_topic(ros_stack):
    from opencv_facerecognizer_tpu.runtime.recognizer import FRAME_TOPIC

    rospy, conn = ros_stack
    assert rospy.node[0] == "ocvf_recognizer"
    got = []
    conn.subscribe(FRAME_TOPIC, lambda t, m: got.append(m))

    img = np.arange(20, dtype=np.uint8).reshape(4, 5)
    image_sub = next(s for s in rospy.subscribers if s.topic == conn.image_topic)
    image_sub.callback(_ImageMsg(4, 5, "mono8", img.tobytes()))
    assert len(got) == 1
    np.testing.assert_array_equal(decode_frame(got[0]), img.astype(np.float32))
    assert got[0]["meta"]["stamp"] == "12.5"

    # malformed image: counted, not fatal
    bad = _ImageMsg(4, 5, "mono8", b"\x00\x01")  # too short
    image_sub.callback(bad)
    assert conn.frames_malformed == 1
    assert len(got) == 1


def test_ros_connector_control_and_result_paths(ros_stack):
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        CONTROL_TOPIC, RESULT_TOPIC,
    )

    rospy, conn = ros_stack
    commands = []
    conn.subscribe(CONTROL_TOPIC, lambda t, m: commands.append(m))
    control_sub = next(s for s in rospy.subscribers if s.topic == conn.control_topic)

    # Bare command payload (what a human types into rostopic pub).
    control_sub.callback(type("S", (), {"data": '{"cmd": "stats"}'})())
    # Full wire form too.
    control_sub.callback(type("S", (), {
        "data": json.dumps({"topic": CONTROL_TOPIC,
                            "data": {"cmd": "enroll", "subject": "bob"}})})())
    assert commands == [{"cmd": "stats"}, {"cmd": "enroll", "subject": "bob"}]

    conn.publish(RESULT_TOPIC, {"faces": [], "meta": None})
    pub = next(p for p in rospy.publishers if p.topic == conn.result_topic)
    assert json.loads(pub.published[0].data) == {"faces": [], "meta": None}


def test_ros_connector_stop_unregisters(ros_stack):
    rospy, conn = ros_stack
    conn.stop()
    assert all(s.unregistered for s in rospy.subscribers)


# ---------- gallery auto-grow ----------


def test_gallery_auto_grows_and_preserves_rows():
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh

    mesh = make_mesh()
    # capacity rounds up to a tp multiple (8 devices -> min 8 rows)
    gal = ShardedGallery(capacity=4, dim=8, mesh=mesh)
    base_capacity = gal.capacity
    rng = np.random.default_rng(0)
    e1 = rng.normal(size=(base_capacity, 8)).astype(np.float32)
    gal.add(e1, np.arange(base_capacity, dtype=np.int32))
    assert gal.grow_count == 0

    e2 = rng.normal(size=(3, 8)).astype(np.float32)
    gal.add(e2, np.asarray([10, 11, 12], np.int32))  # overflows -> grows
    assert gal.grow_count == 1
    assert gal.size == base_capacity + 3
    assert gal.capacity >= base_capacity + 3
    assert gal.capacity % mesh.shape["tp"] == 0

    # All rows still match to their own labels after the grow.
    all_e = np.concatenate([e1, e2])
    all_e /= np.linalg.norm(all_e, axis=-1, keepdims=True)
    want = list(range(base_capacity)) + [10, 11, 12]
    dp = mesh.shape["dp"]
    q = len(all_e) // dp * dp
    labels, sims, _ = gal.match(np.asarray(all_e[:q]), k=1)
    assert np.asarray(labels).flatten().tolist() == want[:q]


def test_gallery_swap_from_adopts_capacity():
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh

    mesh = make_mesh()
    small = ShardedGallery(capacity=4, dim=8, mesh=mesh)
    big = ShardedGallery(capacity=32, dim=8, mesh=mesh)
    e = np.eye(8, dtype=np.float32)
    big.add(e, np.arange(8, dtype=np.int32))
    small.swap_from(big)
    assert small.capacity == big.capacity
    assert small.size == 8
    # And further adds land in the adopted (bigger) arrays.
    small.add(np.ones((1, 8), np.float32), np.asarray([99], np.int32))
    assert small.size == 9

    tiny = ShardedGallery(capacity=4, dim=5, mesh=mesh)
    with pytest.raises(ValueError, match="dim"):
        small.swap_from(tiny)


def test_gallery_concurrent_adds_lose_no_rows():
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh

    mesh = make_mesh()
    gal = ShardedGallery(capacity=8, dim=4, mesh=mesh)
    rng = np.random.default_rng(1)
    chunks = [rng.normal(size=(2, 4)).astype(np.float32) for _ in range(8)]

    def add_chunk(i):
        gal.add(chunks[i], np.full(2, i, np.int32))

    threads = [threading.Thread(target=add_chunk, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert gal.size == 16  # every concurrent add claimed distinct rows
    labels = gal.data.labels
    counts = {i: int((np.asarray(labels) == i).sum()) for i in range(8)}
    assert all(v == 2 for v in counts.values()), counts


# ---------- transport failure paths on the shared metrics surface ----------
# (ISSUE 1: failure-path coverage asserted via utils.metrics.Metrics — the
# one ledger the serving stats consumer reads — not per-transport attrs.)


def test_jsonl_garbage_and_truncated_lines_counted_on_metrics():
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    lines = (
        "not json at all\n"
        '{"topic": "t", "data": {"trunc":\n'  # truncated mid-object
        '{"no_topic_key": 1}\n'               # parses, wrong schema
        '{"topic": "t", "data": {"k": 1}}\n'  # the one healthy line
    )
    c = JSONLConnector(io.StringIO(lines), io.StringIO(), metrics=m)
    got = []
    c.subscribe("t", lambda t, msg: got.append(msg))
    c.start()
    assert c.eof.wait(timeout=5.0)
    c.stop()
    assert got == [{"k": 1}]
    assert c.malformed_lines == 3
    assert m.counter("connector_malformed_lines") == 3


def test_socket_peer_disconnect_mid_message_counted():
    """A peer that dies mid-message: the unterminated final line counts
    malformed (truncated JSON never parses) and the disconnect itself is
    counted — two counters, two distinct faults — while the server keeps
    serving other clients."""
    import socket as socket_mod

    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    server = SocketConnector(listen=True, metrics=m)
    received = []
    server.subscribe("frames", lambda t, msg: received.append(msg))
    server.start()
    healthy = None
    try:
        flaky = socket_mod.create_connection(("127.0.0.1", server.port))
        flaky.sendall(b'{"topic": "frames", "data": {"seq": 1}}\n')
        # Mid-message death: half a JSON object, no newline, then gone.
        flaky.sendall(b'{"topic": "frames", "data": {"seq":')
        flaky.close()

        deadline = time.monotonic() + 5
        while (m.counter("connector_peer_disconnects") < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert received == [{"seq": 1}]
        assert m.counter("connector_malformed_lines") == 1
        assert m.counter("connector_peer_disconnects") == 1

        # Still serving: a healthy client round-trips after the flake.
        healthy = SocketConnector(port=server.port)
        healthy.start()
        healthy.publish("frames", {"seq": 2})
        deadline = time.monotonic() + 5
        while len(received) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received[-1] == {"seq": 2}
    finally:
        # Server first: its own stop() clears _running before closing
        # sockets, so tearing down the healthy client afterwards must not
        # read as another peer flake.
        server.stop()
        if healthy is not None:
            healthy.stop()
    assert m.counter("connector_peer_disconnects") == 1


def test_batcher_drop_counters_on_metrics():
    """FrameBatcher.put drops land on the shared Metrics: malformed frames
    (wrong shape / non-numeric dtype) and freshness-overflow evictions."""
    from opencv_facerecognizer_tpu.runtime.batcher import FrameBatcher
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    m = Metrics()
    b = FrameBatcher(batch_size=4, frame_shape=(8, 8), flush_timeout=0.01,
                     max_pending=2, metrics=m)
    assert b.put(np.zeros((8, 8), np.float32))
    assert not b.put(np.zeros((4, 4), np.float32))        # wrong shape
    assert not b.put(np.zeros((8, 8, 3), np.float32))     # wrong rank
    assert not b.put(np.array([["x"] * 8] * 8))           # non-numeric
    assert m.counter("batcher_dropped_malformed") == 3
    assert b.put(np.ones((8, 8), np.float32))
    assert b.put(np.full((8, 8), 2.0, np.float32))        # evicts oldest
    assert m.counter("batcher_dropped_overflow") == 1
    time.sleep(0.02)  # past flush_timeout: the partial batch is flushable
    batch = b.get_batch(block=False)
    assert batch is not None and batch.count == 2
    np.testing.assert_array_equal(batch.frames[0], np.ones((8, 8)))
    b.close()
