"""Sharded gallery + mesh tests on the 8-virtual-device CPU backend
(SURVEY.md §7.7: N-way CPU-simulated device tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS

RNG = np.random.default_rng(17)


def _unit(v):
    return v / np.maximum(np.linalg.norm(v, axis=-1, keepdims=True), 1e-12)


def _brute_force_topk(queries, gallery, labels, k):
    sims = _unit(queries) @ _unit(gallery).T
    idx = np.argsort(-sims, axis=1)[:, :k]
    return labels[idx], np.take_along_axis(sims, idx, axis=1)


def test_make_mesh_factorizations():
    assert make_mesh().shape == {DP_AXIS: 1, TP_AXIS: 8}
    assert make_mesh(dp=2).shape == {DP_AXIS: 2, TP_AXIS: 4}
    assert make_mesh(tp=2).shape == {DP_AXIS: 4, TP_AXIS: 2}
    assert make_mesh(dp=8, tp=1).shape == {DP_AXIS: 8, TP_AXIS: 1}
    with pytest.raises(ValueError):
        make_mesh(dp=3)
    with pytest.raises(ValueError):
        make_mesh(dp=2, tp=2)


@pytest.mark.parametrize("dp,tp", [(1, 8), (2, 4), (8, 1)])
def test_sharded_match_equals_bruteforce(dp, tp):
    mesh = make_mesh(dp=dp, tp=tp)
    gal_emb = RNG.normal(size=(64, 16)).astype(np.float32)
    gal_labels = RNG.integers(0, 10, size=64).astype(np.int32)
    g = ShardedGallery(capacity=64, dim=16, mesh=mesh)
    g.add(gal_emb, gal_labels)
    queries = _unit(RNG.normal(size=(8, 16)).astype(np.float32))
    for k in (1, 3):
        labels, sims, idx = (np.asarray(v) for v in g.match(queries, k=k))
        want_labels, want_sims = _brute_force_topk(queries, gal_emb, gal_labels, k)
        np.testing.assert_allclose(sims, want_sims, atol=2e-2)  # bf16 matmul
        # labels can differ at near-ties under bf16; require match on clear wins
        clear = (want_sims[:, :1] - want_sims[:, -1:]) > 0.05 if k > 1 else np.ones((8, 1), bool)
        np.testing.assert_array_equal(labels[:, 0][clear[:, 0]], want_labels[:, 0][clear[:, 0]])


def test_gallery_partial_fill_and_masking():
    mesh = make_mesh(tp=8)
    g = ShardedGallery(capacity=30, dim=8, mesh=mesh)  # rounds up to 32
    assert g.capacity == 32
    emb = RNG.normal(size=(5, 8)).astype(np.float32)
    labels = np.arange(5, dtype=np.int32)
    g.add(emb, labels)
    q = _unit(emb)
    got_labels, sims, idx = (np.asarray(v) for v in g.match(q, k=1))
    np.testing.assert_array_equal(got_labels[:, 0], labels)
    assert np.all(idx < 5)  # never matches an invalid padded row


def test_gallery_overflow_auto_grows():
    # Overflow no longer raises: capacity doubles (tp-aligned) and the
    # enrolment lands (see test_connectors.py for the full growth suite).
    mesh = make_mesh(tp=8)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh)
    g.add(RNG.normal(size=(8, 4)).astype(np.float32), np.arange(8, dtype=np.int32))
    g.add(RNG.normal(size=(1, 4)).astype(np.float32), np.array([9], dtype=np.int32))
    assert g.grow_count == 1
    assert g.size == 9
    assert g.capacity == 16 and g.capacity % 8 == 0


def test_gallery_incremental_enrolment():
    mesh = make_mesh(tp=4, dp=2)
    g = ShardedGallery(capacity=16, dim=8, mesh=mesh)
    e1 = RNG.normal(size=(4, 8)).astype(np.float32)
    e2 = RNG.normal(size=(4, 8)).astype(np.float32)
    g.add(e1, np.zeros(4, dtype=np.int32))
    g.add(e2, np.ones(4, dtype=np.int32))
    assert g.size == 8
    labels, _, _ = (np.asarray(v) for v in g.match(_unit(e2)[:2], k=1))
    np.testing.assert_array_equal(labels[:, 0], [1, 1])


def test_double_buffered_swap():
    mesh = make_mesh(tp=8)
    live = ShardedGallery(capacity=8, dim=4, mesh=mesh)
    live.add(_unit(RNG.normal(size=(4, 4)).astype(np.float32)), np.zeros(4, np.int32))
    staged = ShardedGallery(capacity=8, dim=4, mesh=mesh)
    new_emb = _unit(RNG.normal(size=(6, 4)).astype(np.float32))
    staged.add(new_emb, np.full(6, 7, np.int32))
    live.swap_from(staged)
    assert live.size == 6
    labels, _, _ = (np.asarray(v) for v in live.match(new_emb[:1], k=1))
    assert labels[0, 0] == 7


def test_query_count_must_divide_dp():
    mesh = make_mesh(dp=4, tp=2)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh)
    g.add(RNG.normal(size=(4, 4)).astype(np.float32), np.arange(4, dtype=np.int32))
    with pytest.raises(ValueError, match="divisible"):
        g.match(np.zeros((3, 4), dtype=np.float32), k=1)


def test_gallery_pallas_path_matches_gspmd():
    """use_pallas=True (interpret mode off-TPU) must agree with the GSPMD
    matcher — the auto fast path may silently switch between them on
    hardware, so they have to be interchangeable."""
    rng = np.random.default_rng(17)
    emb = rng.normal(size=(96, 16)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    labels = rng.integers(0, 12, size=96)
    q = rng.normal(size=(8, 16)).astype(np.float32)
    q /= np.linalg.norm(q, axis=-1, keepdims=True)

    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                (DP_AXIS, TP_AXIS))
    outs = {}
    for use_pallas in (False, True):
        g = ShardedGallery(capacity=128, dim=16, mesh=mesh,
                           use_pallas=use_pallas)
        g.add(emb, labels)
        lab, sims, idx = (np.asarray(v) for v in g.match(q, k=3))
        outs[use_pallas] = (lab, sims, idx)
    np.testing.assert_array_equal(outs[False][0], outs[True][0])
    np.testing.assert_array_equal(outs[False][2], outs[True][2])
    np.testing.assert_allclose(outs[False][1], outs[True][1], atol=1e-2)


def test_gallery_pallas_autodetect_off_on_cpu():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                (DP_AXIS, TP_AXIS))
    g = ShardedGallery(capacity=1 << 17, dim=8, mesh=mesh)
    assert not g._pallas_enabled()  # CPU backend: stays on GSPMD


@pytest.mark.parametrize("dp,tp", [(4, 2), (2, 4), (1, 8)])
def test_pod_pallas_matcher_matches_gspmd(dp, tp):
    """shard_map + per-shard pallas streaming kernel + collective merge
    (the multi-chip pallas formulation) must agree with match_global."""
    from opencv_facerecognizer_tpu.parallel.gallery import (
        match_global, match_pod_pallas)

    mesh = make_mesh(dp=dp, tp=tp)
    rng = np.random.default_rng(23)
    cap = 128
    emb = rng.normal(size=(cap, 16)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    valid = np.ones(cap, bool)
    valid[100:] = False
    labels = rng.integers(0, 20, size=cap).astype(np.int32)
    q = rng.normal(size=(8, 16)).astype(np.float32)
    q /= np.linalg.norm(q, axis=-1, keepdims=True)

    args = (jnp.asarray(q), jnp.asarray(emb), jnp.asarray(valid),
            jnp.asarray(labels))
    with mesh:
        pod = match_pod_pallas(*args, k=3, mesh=mesh, interpret=True)
    ref = match_global(*args, k=3, mesh=mesh)
    for a, b in zip(pod, ref):
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype.kind == "f":
            np.testing.assert_allclose(a, b, atol=1e-2)
        else:
            np.testing.assert_array_equal(a, b)


def test_pod_pallas_matcher_sparse_shards():
    """Startup regime: fewer valid rows than k on most shards — sentinel
    indices must stay -1 (masked), not alias a neighbor shard's rows."""
    from opencv_facerecognizer_tpu.parallel.gallery import match_pod_pallas

    mesh = make_mesh(dp=1, tp=8)
    rng = np.random.default_rng(5)
    cap = 64  # 8 rows/shard
    emb = np.zeros((cap, 8), np.float32)
    valid = np.zeros(cap, bool)
    labels = np.full(cap, -1, np.int32)
    emb[0] = rng.normal(size=8)
    emb[0] /= np.linalg.norm(emb[0])
    valid[0] = True
    labels[0] = 7
    q = np.tile(emb[0], (8, 1))
    with mesh:
        lab, sims, idx = (np.asarray(v) for v in match_pod_pallas(
            jnp.asarray(q), jnp.asarray(emb), jnp.asarray(valid),
            jnp.asarray(labels), k=3, mesh=mesh, interpret=True))
    # best hit is the one real row
    assert (idx[:, 0] == 0).all() and (lab[:, 0] == 7).all()
    # everything else is masked: sentinel index, -inf-ish score
    assert (idx[:, 1:] == -1).all(), idx
    assert (sims[:, 1:] < -1e29).all()


def test_sentinel_slots_carry_pad_label():
    """Sentinel -1 indices must surface the PAD label even when rows 0 and
    capacity-1 hold real subjects — a clamped/wrapped gather would pair a
    real subject's label with the -1e30 sentinel sim (round-2 advisor
    finding: direct gallery.match() callers got a plausible wrong label)."""
    from opencv_facerecognizer_tpu.parallel.gallery import match_pod_pallas

    rng = np.random.default_rng(5)
    cap = 64
    emb = np.zeros((cap, 8), np.float32)
    valid = np.zeros(cap, bool)
    labels = np.full(cap, -1, np.int32)
    # real subjects at the exact rows a clamp (0) or wrap (-1 -> last row)
    # would alias onto
    for row, lab in ((0, 3), (cap - 1, 9)):
        v = rng.normal(size=8).astype(np.float32)
        emb[row] = v / np.linalg.norm(v)
        valid[row] = True
        labels[row] = lab
    q = np.tile(emb[0], (8, 1))

    # pod shard_map form (interpret mode on the CPU mesh)
    mesh = make_mesh(dp=1, tp=8)
    with mesh:
        lab, sims, idx = (np.asarray(v) for v in match_pod_pallas(
            jnp.asarray(q), jnp.asarray(emb), jnp.asarray(valid),
            jnp.asarray(labels), k=4, mesh=mesh, interpret=True))
    sentinel = idx == -1
    assert sentinel.any()
    assert (lab[sentinel] == -1).all(), lab
    assert set(lab[~sentinel].ravel()) <= {3, 9}

    # single-device pallas fast path via gallery.match_fn
    from jax.sharding import Mesh

    mesh1 = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                 (DP_AXIS, TP_AXIS))
    g = ShardedGallery(capacity=cap, dim=8, mesh=mesh1, use_pallas=True)
    g.add(emb[valid], labels[valid])
    lab, sims, idx = (np.asarray(v) for v in g.match(np.asarray(q), k=4))
    sentinel = idx == -1
    assert sentinel.any()
    assert (lab[sentinel] == g.labels_pad).all(), lab


def test_initialize_multihost_single_process_noop(monkeypatch):
    from opencv_facerecognizer_tpu.parallel.mesh import initialize_multihost

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    # no coordinator configured -> graceful single-process no-op
    assert initialize_multihost() is False
    # devices still visible, meshes still build
    assert make_mesh().devices.size == len(jax.devices())


def test_initialize_multihost_env_and_args(monkeypatch):
    from opencv_facerecognizer_tpu.parallel import mesh as mesh_mod

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    # raising=False: jax < 0.5 has no is_initialized — the attr is created
    # here and mesh._distributed_is_initialized picks it up via getattr.
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False,
                        raising=False)
    # env-var path
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    assert mesh_mod.initialize_multihost() is True
    assert calls[-1] == {"coordinator_address": "10.0.0.1:1234",
                         "num_processes": 4, "process_id": 2}
    # explicit args trigger initialization even without env
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var)
    assert mesh_mod.initialize_multihost(num_processes=8, process_id=3) is True
    assert calls[-1] == {"coordinator_address": None,
                         "num_processes": 8, "process_id": 3}
    # already-initialized short circuit
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True,
                        raising=False)
    n = len(calls)
    assert mesh_mod.initialize_multihost() is True
    assert len(calls) == n


def test_gallery_async_grow_never_blocks_and_lands_rows():
    """async_grow: an overflowing add() returns immediately (rows staged),
    the background worker compiles the next tier via prewarm_hooks BEFORE
    installing, and the rows become matchable after wait_ready()."""
    import threading

    mesh = make_mesh(tp=4)
    g = ShardedGallery(capacity=16, dim=8, mesh=mesh, async_grow=True)
    warmed = []
    hook_thread = []

    def hook(capacity):
        warmed.append(capacity)
        hook_thread.append(threading.current_thread().name)

    g.prewarm_hooks.append(hook)
    e = RNG.normal(size=(16, 8)).astype(np.float32)
    g.add(e, np.arange(16, dtype=np.int32))
    assert g.size == 16 and g.pending_rows == 0  # fits: synchronous path
    e2 = RNG.normal(size=(8, 8)).astype(np.float32)
    g.add(e2, np.arange(16, 24, dtype=np.int32))  # overflows -> staged
    assert g.wait_ready(timeout=30)
    assert g.pending_rows == 0
    assert g.size == 24
    assert g.capacity == 32
    assert g.grow_count == 1
    assert warmed == [32]
    assert hook_thread and hook_thread[0] != threading.main_thread().name
    # staged rows are matchable post-install
    q = e2 / np.linalg.norm(e2, axis=-1, keepdims=True)
    labels, _, _ = (np.asarray(v) for v in g.match(q, k=1))
    np.testing.assert_array_equal(labels[:, 0], np.arange(16, 24))


def test_gallery_async_grow_absorbs_adds_during_grow():
    """Adds arriving while a grow is in flight are staged and spliced into
    the same (or a follow-up) install — none are lost, order preserved."""
    import threading

    mesh = make_mesh(tp=2)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh, async_grow=True)
    slow = threading.Event()

    def slow_hook(capacity):
        slow.wait(5)  # hold the grow so follow-up adds land in pending

    g.prewarm_hooks.append(slow_hook)
    g.add(RNG.normal(size=(8, 4)).astype(np.float32),
          np.arange(8, dtype=np.int32))
    g.add(RNG.normal(size=(4, 4)).astype(np.float32),
          np.arange(8, 12, dtype=np.int32))  # overflow -> worker starts
    g.add(RNG.normal(size=(4, 4)).astype(np.float32),
          np.arange(12, 16, dtype=np.int32))  # lands mid-grow
    assert g.pending_rows == 8
    slow.set()
    assert g.wait_ready(timeout=30)
    assert g.size == 16
    assert np.array_equal(np.asarray(g.labels)[:16], np.arange(16))


def test_gallery_reset_cancels_inflight_grow():
    mesh = make_mesh(tp=2)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh, async_grow=True)
    import threading

    hold = threading.Event()
    g.prewarm_hooks.append(lambda cap: hold.wait(5))
    g.add(RNG.normal(size=(8, 4)).astype(np.float32),
          np.arange(8, dtype=np.int32))
    g.add(RNG.normal(size=(4, 4)).astype(np.float32),
          np.arange(8, 12, dtype=np.int32))
    g.reset()  # bump epoch: the in-flight grow must not resurrect rows
    hold.set()
    assert g.wait_ready(timeout=30)
    assert g.size == 0
    assert g.pending_rows == 0


def test_gallery_async_grow_normalizes_on_worker_and_waits_residency():
    """add() stages RAW rows — the enrolling thread pays no normalization
    (measured 16 s for 920k rows on a 1-core host); the worker normalizes
    before splicing, waits for device residency BEFORE the atomic publish
    (so the first new-tier serving call doesn't absorb the gallery H2D),
    and records the phase decomposition in last_grow_info."""
    mesh = make_mesh(tp=2)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh, async_grow=True)
    g.add(np.full((8, 4), 7.0, np.float32), np.arange(8, dtype=np.int32))
    raw = np.full((8, 4), 5.0, np.float32)  # deliberately unnormalized
    g.add(raw, np.arange(8, 16, dtype=np.int32))  # overflow -> staged raw
    raw[:] = -3.0  # caller reuses its buffer: staging must have copied
    assert g.wait_ready(timeout=30)
    assert g.size == 16 and g.pending_rows == 0
    # every landed row is unit-norm even though the add staged raw rows
    norms = np.linalg.norm(g._host_emb[:16], axis=-1)
    np.testing.assert_allclose(norms, 1.0, rtol=1e-5)
    # ...and holds the values STAGED, not the caller's later mutation
    np.testing.assert_allclose(g._host_emb[8:16], 0.5, rtol=1e-5)
    info = g.last_grow_info
    assert "normalize_s" in info and "upload_wait_s" in info
    assert "install_s" in info and not info.get("residency_timeout")
    # the published device snapshot is the residency-checked one
    np.testing.assert_allclose(np.asarray(g.data.embeddings)[:16],
                               g._host_emb[:16], rtol=1e-6)


def test_gallery_async_grow_chunked_upload_path():
    """Grow uploads above 2x CHUNK_UPLOAD_BYTES go through the paced
    chunked device-put (device-side zeros + donated dynamic_update_slice
    per chunk) — forced here via an instance-level chunk-size override on
    a SINGLE-device mesh (chunking is scoped to 1-device meshes: with
    tp>1 the dynamic-offset update replicates each chunk to every device,
    see _build_snapshot) — and the published snapshot is identical to the
    host mirror."""
    import jax

    mesh = make_mesh(dp=1, tp=1, devices=jax.devices()[:1])
    g = ShardedGallery(capacity=32, dim=16, mesh=mesh, async_grow=True)
    g.CHUNK_UPLOAD_BYTES = 1024  # 16 rows/chunk: several chunks at 96 rows
    g.add(RNG.normal(size=(32, 16)).astype(np.float32),
          np.arange(32, dtype=np.int32))
    g.add(RNG.normal(size=(64, 16)).astype(np.float32) * 11.0,
          np.arange(32, 96, dtype=np.int32))  # overflow -> chunked upload
    assert g.wait_ready(timeout=60)
    assert g.size == 96 and g.capacity == 128
    np.testing.assert_allclose(np.asarray(g.data.embeddings)[:96],
                               g._host_emb[:96], rtol=1e-6)
    assert np.array_equal(np.asarray(g.data.labels)[:96], np.arange(96))
    assert not g.last_grow_info.get("error")
    # all rows matchable through the sharded matcher
    q = g._host_emb[40:44]
    labels, _, _ = (np.asarray(v) for v in g.match(q, k=1))
    np.testing.assert_array_equal(labels[:, 0], np.arange(40, 44))


def test_gallery_bf16_store_matches_f32():
    """store_dtype=bfloat16 halves gallery HBM/upload bytes and must be
    numerically interchangeable on the match path: both matchers already
    compute the similarity matmul as bf16 x bf16 -> f32, so a bf16-stored
    gallery changes only WHERE the cast happens (enrolment vs per call)."""
    import jax.numpy as jnp

    mesh = make_mesh(tp=4)
    emb = RNG.normal(size=(64, 16)).astype(np.float32)
    lab = np.arange(64, dtype=np.int32)
    q = emb[10:20] / np.linalg.norm(emb[10:20], axis=-1, keepdims=True)
    results = {}
    for dtype in (jnp.float32, jnp.bfloat16):
        g = ShardedGallery(capacity=64, dim=16, mesh=mesh, store_dtype=dtype)
        g.add(emb, lab)
        assert g.data.embeddings.dtype == dtype
        labels, sims, idx = (np.asarray(v) for v in g.match(q, k=3))
        results[str(dtype)] = (labels, sims)
    (l32, s32), (l16, s16) = results.values()
    np.testing.assert_array_equal(l32, l16)
    np.testing.assert_allclose(s32, s16, atol=2e-3)
    # grow path keeps the dtype (incl. the chunked branch on 1-device)
    import jax

    g1 = ShardedGallery(capacity=16, dim=16,
                        mesh=make_mesh(dp=1, tp=1, devices=jax.devices()[:1]),
                        store_dtype=jnp.bfloat16, async_grow=True)
    g1.CHUNK_UPLOAD_BYTES = 512
    g1.add(emb[:16], lab[:16])
    g1.add(emb[16:], lab[16:])  # overflow -> chunked bf16 upload
    assert g1.wait_ready(timeout=30)
    assert g1.size == 64 and g1.data.embeddings.dtype == jnp.bfloat16
    labels, _, _ = (np.asarray(v) for v in g1.match(q, k=1))
    np.testing.assert_array_equal(labels[:, 0], np.arange(10, 20))


def test_gallery_async_grow_failed_upload_restores_rows_and_retries():
    """If the upload dies AFTER the splice popped entries off pending, the
    worker must restore them (pending_rows stays truthful, enrolment order
    kept) and the next add() retries the grow successfully."""
    mesh = make_mesh(tp=2)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh, async_grow=True)
    g.add(RNG.normal(size=(8, 4)).astype(np.float32),
          np.arange(8, dtype=np.int32))

    real_build = g._build_snapshot
    calls = {"n": 0}

    def dying_build(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("tunnel RPC died mid-upload")
        return real_build(*a, **k)

    g._build_snapshot = dying_build
    g.add(RNG.normal(size=(4, 4)).astype(np.float32),
          np.arange(8, 12, dtype=np.int32))  # overflow -> worker dies
    assert g.wait_ready(timeout=30)
    assert "error" in g.last_grow_info
    assert g.pending_rows == 4  # restored, not lost
    assert g.size == 8  # nothing published from the failed round
    # next add restarts the worker; BOTH batches land, in order
    g.add(RNG.normal(size=(4, 4)).astype(np.float32),
          np.arange(12, 16, dtype=np.int32))
    assert g.wait_ready(timeout=30)
    assert g.pending_rows == 0 and g.size == 16
    assert np.array_equal(np.asarray(g.labels)[:16], np.arange(16))


@pytest.mark.parametrize("store_dtype", ["float32", "bfloat16"])
def test_pipeline_prewarm_registers_and_compiles_future_tier(store_dtype):
    """RecognitionPipeline registers a prewarm hook; after an async grow
    the serving-path cache already holds the new tier's packed step (keyed
    exactly as the post-grow lookup) and serving output stays correct.
    Parametrized over the gallery store dtype: the prewarm scratch arrays
    must match it — an f32 scratch on a bf16 gallery warms an executable
    serving never hits (aval mismatch -> post-grow serving retrace)."""
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    import jax
    import jax.numpy as jnp

    mesh = make_mesh(dp=2, tp=4)
    g = ShardedGallery(capacity=32, dim=16, mesh=mesh, async_grow=True,
                       store_dtype=getattr(jnp, store_dtype))
    emb = RNG.normal(size=(32, 16)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    g.add(emb, np.arange(32, dtype=np.int32))

    det = CNNFaceDetector(features=(8, 8), head_features=8, max_faces=2,
                          score_threshold=0.0, space_to_depth=2)
    det.load_params(det.net.init(jax.random.PRNGKey(0),
                                 np.zeros((1, 64, 64)))["params"])
    net = FaceEmbedNet(embed_dim=16, stem_features=8, stage_features=(8,),
                       stage_blocks=(1,))
    emb_params = init_embedder(net, num_classes=4, input_shape=(32, 32),
                               seed=0)["net"]
    pipe = RecognitionPipeline(det, net, emb_params, g, face_size=(32, 32),
                               top_k=1)
    assert pipe.prewarm_capacity in g.prewarm_hooks
    frames = make_synthetic_scenes(4, (64, 64), max_faces=2, seed=5)[0]
    out0 = np.asarray(pipe.recognize_batch_packed(frames))

    g.add(RNG.normal(size=(40, 16)).astype(np.float32),
          np.arange(32, 72, dtype=np.int32))  # overflow -> async grow
    assert g.wait_ready(timeout=60)
    assert g.capacity == 128
    key = pipe._step_key(pipe._as_device_frames(frames), g.data)
    assert key[4] == 128  # capacity baked into the serving cache key
    assert key in pipe._packed_cache  # prewarmed BEFORE the swap published
    # BOTH executables are warm: recognize_batch (unpacked) must not pay a
    # first-call compile after the grow either (ADVICE r4).
    assert key in pipe._step_cache
    warmed = pipe._packed_cache[key]
    before = warmed._cache_size() if hasattr(warmed, "_cache_size") else None
    out1 = np.asarray(pipe.recognize_batch_packed(frames))
    assert out1.shape == out0.shape
    if before is not None:
        # The serving call must HIT the prewarmed executable, not trace a
        # second one (e.g. scratch-vs-gallery dtype aval mismatch).
        assert warmed._cache_size() == before, (
            "post-grow serving call retraced the prewarmed step")


def test_step_key_derives_from_snapshot_not_live_gallery():
    """The serving cache key must come from the SAME GalleryData snapshot
    the call feeds: a grow installing between the snapshot read and a
    separate gallery.capacity read would otherwise pair a stale key with
    new-tier arrays (ADVICE r4 pipeline._step_key)."""
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline

    import jax

    mesh = make_mesh(dp=2, tp=4)
    g = ShardedGallery(capacity=16, dim=16, mesh=mesh)
    det = CNNFaceDetector(features=(8, 8), head_features=8, max_faces=2,
                          score_threshold=0.0, space_to_depth=2)
    det.load_params(det.net.init(jax.random.PRNGKey(0),
                                 np.zeros((1, 64, 64)))["params"])
    net = FaceEmbedNet(embed_dim=16, stem_features=8, stage_features=(8,),
                       stage_blocks=(1,))
    emb_params = init_embedder(net, num_classes=4, input_shape=(32, 32),
                               seed=0)["net"]
    pipe = RecognitionPipeline(det, net, emb_params, g, face_size=(32, 32))
    old_data = g.data  # reader's snapshot, taken pre-grow
    emb = RNG.normal(size=(40, 16)).astype(np.float32)
    g.add(emb, np.arange(40, dtype=np.int32))  # sync grow: 16 -> 64
    assert g.capacity == 64
    frames = jnp.zeros((2, 64, 64), jnp.float32)
    # Key from the OLD snapshot names the OLD tier even though the live
    # gallery has moved on — snapshot and key can never mix tiers.
    assert pipe._step_key(frames, old_data)[4] == 16
    assert pipe._step_key(frames, g.data)[4] == 64


def test_grow_evicts_tiers_older_than_previous():
    """Growing A->B->C drops tier-A compiled entries from the gallery match
    cache and registered pipelines (B survives for in-flight readers):
    without eviction, crossing many tiers retains every executable forever
    (ADVICE r4 gallery._match_cache)."""
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline

    import jax

    mesh = make_mesh(dp=2, tp=4)
    g = ShardedGallery(capacity=16, dim=16, mesh=mesh)
    det = CNNFaceDetector(features=(8, 8), head_features=8, max_faces=2,
                          score_threshold=0.0, space_to_depth=2)
    det.load_params(det.net.init(jax.random.PRNGKey(0),
                                 np.zeros((1, 64, 64)))["params"])
    net = FaceEmbedNet(embed_dim=16, stem_features=8, stage_features=(8,),
                       stage_blocks=(1,))
    emb_params = init_embedder(net, num_classes=4, input_shape=(32, 32),
                               seed=0)["net"]
    pipe = RecognitionPipeline(det, net, emb_params, g, face_size=(32, 32))
    assert pipe.evict_below in g.evict_hooks

    emb = RNG.normal(size=(8, 16)).astype(np.float32)
    g.add(emb, np.arange(8, dtype=np.int32))
    frames = np.zeros((2, 64, 64), np.float32)
    pipe.recognize_batch(frames)  # compile at tier 16
    g.match(jnp.asarray(emb[:4]), k=1)  # matcher cache entry at tier 16
    assert any(k[4] == 16 for k in pipe._step_cache)
    assert any(k[1] == 16 for k in g._match_cache)

    g.add(RNG.normal(size=(16, 16)).astype(np.float32),
          np.arange(8, 24, dtype=np.int32))  # grow 16 -> 32 (B)
    # previous tier (16) must SURVIVE the first grow (in-flight readers)
    assert any(k[4] == 16 for k in pipe._step_cache)
    pipe.recognize_batch(frames)  # compile at tier 32
    g.add(RNG.normal(size=(24, 16)).astype(np.float32),
          np.arange(24, 48, dtype=np.int32))  # grow 32 -> 64 (C)
    # tier 16 evicted everywhere; tier 32 (previous) survives
    assert not any(k[4] == 16 for k in pipe._step_cache)
    assert not any(k[4] == 16 for k in pipe._packed_cache)
    assert not any(k[1] == 16 for k in g._match_cache)
    assert any(k[4] == 32 for k in pipe._step_cache)
    # serving still correct at the new tier
    out = pipe.recognize_batch(frames)
    assert np.asarray(out.labels).shape == (2, 2, 1)


def test_gallery_async_grow_copies_staged_labels():
    """The staged path must copy LABELS too, not just embeddings: asarray
    of an int32 input is a no-copy view, and the worker splices seconds
    after add() returns — a caller reusing its label buffer would enroll
    wrong identities (round-5 advisor)."""
    import threading

    mesh = make_mesh(tp=2)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh, async_grow=True)
    hold = threading.Event()
    g.prewarm_hooks.append(lambda cap: hold.wait(5))
    g.add(RNG.normal(size=(8, 4)).astype(np.float32),
          np.arange(8, dtype=np.int32))
    label_buf = np.arange(8, 12, dtype=np.int32)  # int32: asarray is a view
    g.add(RNG.normal(size=(4, 4)).astype(np.float32), label_buf)
    label_buf[:] = 99  # caller reuses its buffer while the grow is held
    hold.set()
    assert g.wait_ready(timeout=30)
    assert g.size == 12
    np.testing.assert_array_equal(np.asarray(g.labels)[8:12],
                                  np.arange(8, 12))


def test_pace_chunk_per_chunk_deadline_and_timeout_flag():
    """_pace_chunk (the chunked-upload pacer): a chunk that never lands
    gives up at ITS deadline and records info['chunk_pacing_timeout'] so
    grow artifacts surface the degraded (unpaced) window; a ready chunk
    paces clean; a backend without is_ready stops pacing silently."""
    import time as _time

    class _Never:
        def is_ready(self):
            return False

    class _Ready:
        def is_ready(self):
            return True

    info = {}
    t0 = _time.monotonic()
    assert not ShardedGallery._pace_chunk(_Never(), _time.monotonic() + 0.1,
                                          info=info)
    assert info.get("chunk_pacing_timeout") is True
    assert _time.monotonic() - t0 < 5.0  # per-chunk deadline, not residency's
    info = {}
    assert ShardedGallery._pace_chunk(_Ready(), _time.monotonic() + 0.1,
                                      info=info)
    assert "chunk_pacing_timeout" not in info
    # cancelled wait: returns immediately (doomed snapshot), no flag
    assert ShardedGallery._pace_chunk(_Never(), _time.monotonic() + 10.0,
                                      cancel=lambda: True, info=info)
    assert "chunk_pacing_timeout" not in info
    # no is_ready: pacing impossible, not degraded — no flag
    assert not ShardedGallery._pace_chunk(object(), _time.monotonic() + 10.0,
                                          info=info)
    assert "chunk_pacing_timeout" not in info


def test_gallery_swap_from_casts_store_dtype():
    """A store_dtype mismatch on swap_from is CAST at install, not
    rejected: the documented retrain -> reload_gallery handoff stages at
    the trainer's f32 default while serving defaults to bf16 (round-5
    advisor). The installed snapshot carries the SERVING gallery's dtype,
    so compiled cache keys (capacity-keyed) never alias."""
    mesh = make_mesh(tp=4)
    serving = ShardedGallery(capacity=16, dim=8, mesh=mesh,
                             store_dtype=jnp.bfloat16)
    staged = ShardedGallery(capacity=16, dim=8, mesh=mesh)  # f32 default
    emb = _unit(RNG.normal(size=(6, 8)).astype(np.float32))
    staged.add(emb, np.full(6, 3, np.int32))
    serving.swap_from(staged)
    assert serving.size == 6
    assert serving.data.embeddings.dtype == jnp.bfloat16
    labels, sims, _ = (np.asarray(v) for v in serving.match(emb[:2], k=1))
    np.testing.assert_array_equal(labels[:, 0], [3, 3])
    assert (sims[:, 0] > 0.99).all()


def test_gallery_snapshot_roundtrip_bf16_from_f32_checkpoint():
    """Satellite (state-lifecycle PR): snapshot()/load_snapshot()
    round-trip across a store_dtype boundary — an f32 trainer gallery's
    host-mirror snapshot (what a durable checkpoint persists) installs
    into a bf16 serving gallery at the SERVING width (the swap_from cast
    path, via the restore route this time), with match parity."""
    mesh = make_mesh(tp=4)
    trainer = ShardedGallery(capacity=16, dim=8, mesh=mesh)  # f32 default
    emb = _unit(RNG.normal(size=(6, 8)).astype(np.float32))
    trainer.add(emb, np.arange(6, dtype=np.int32))
    snap = trainer.snapshot()
    serving = ShardedGallery(capacity=16, dim=8, mesh=mesh,
                             store_dtype=jnp.bfloat16)
    serving.load_snapshot(*snap)
    assert serving.size == 6
    assert serving.data.embeddings.dtype == jnp.bfloat16  # serving width
    assert serving._host_emb.dtype == np.float32  # host truth stays f32
    l32, s32, i32 = (np.asarray(v) for v in trainer.match(emb, k=1))
    l16, s16, i16 = (np.asarray(v) for v in serving.match(emb, k=1))
    np.testing.assert_array_equal(l32, l16)
    np.testing.assert_array_equal(i32, i16)
    np.testing.assert_allclose(s32, s16, atol=2e-2)  # bf16 matmul on both


def test_gallery_load_snapshot_restores_last_known_good():
    """load_snapshot (the supervisor's restore path): rows added after the
    snapshot are rolled back, the host mirrors are private copies of the
    snapshot arrays, and any in-flight async grow is invalidated."""
    mesh = make_mesh(tp=8)
    g = ShardedGallery(capacity=8, dim=4, mesh=mesh)
    emb = _unit(RNG.normal(size=(4, 4)).astype(np.float32))
    g.add(emb, np.arange(4, dtype=np.int32))
    snap = g.snapshot()
    g.add(_unit(RNG.normal(size=(3, 4)).astype(np.float32)),
          np.full(3, 9, np.int32))
    assert g.size == 7
    g.load_snapshot(*snap)
    assert g.size == 4
    labels, _, _ = (np.asarray(v) for v in g.match(emb[:2], k=1))
    np.testing.assert_array_equal(labels[:, 0], [0, 1])
    # restored mirrors are private: mutating the snapshot can't reach them
    snap[0][:] = 0.0
    assert np.linalg.norm(g._host_emb[:4]) > 0


def test_chunked_upload_stops_pacing_after_first_timeout():
    """Hang-mode bound: once one chunk's pacing deadline expires, the
    remaining chunks are NOT paced — the total stall is one chunk
    deadline, not chunks * deadline (the final residency wait still gates
    the publish)."""
    import jax

    mesh = make_mesh(dp=1, tp=1, devices=jax.devices()[:1])
    g = ShardedGallery(capacity=32, dim=16, mesh=mesh, async_grow=True)
    g.CHUNK_UPLOAD_BYTES = 1024  # several chunks at 96 rows
    calls = []

    def never_ready_pacer(buf, deadline, cancel=None, info=None):
        calls.append(deadline)
        if info is not None:
            info["chunk_pacing_timeout"] = True
        return False  # every paced chunk "times out"

    g._pace_chunk = never_ready_pacer  # instance attr shadows the static
    info = {}
    emb = RNG.normal(size=(96, 16)).astype(np.float32)  # 6 chunks of 16 rows
    g._chunked_emb_put(emb, info=info)
    assert len(calls) == 1  # paced once, then gave up for the remainder
    assert info["chunk_pacing_timeout"] is True
