"""Partition-tolerance tests (ISSUE 16): the ``transport`` fault
boundary (partition / half-open / slow link / drop / duplicate /
reorder), link supervision over application heartbeats, idempotent
frame-id routing (intake dedup + fan-in dedup), interactive hedged
dispatch, router probe-error streaks, reconnect-backoff jitter, the
``link_health`` SLO objective, the half-open writer's
``lease_unreachable`` degraded flip, and the fast deterministic tier-1
variant of the partition chaos scenario
(``scripts/chaos_soak.py --scenario partition``)."""

import importlib.util
import logging
import os
import random
import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime.admission import AdmissionController
from opencv_facerecognizer_tpu.runtime.connector import (
    SocketConnector,
    WILDCARD_TOPIC,
    encode_frame,
)
from opencv_facerecognizer_tpu.runtime.fakes import (
    TrafficRecorder,
    build_replica_fleet,
)
from opencv_facerecognizer_tpu.runtime.faults import FaultInjector
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    LINK_PING_TOPIC,
    LINK_PONG_TOPIC,
    RESULT_TOPIC,
)
from opencv_facerecognizer_tpu.runtime.replication import (
    ReplicaHandle,
    TopicRouter,
)
from opencv_facerecognizer_tpu.runtime.slo import link_health_objective
from opencv_facerecognizer_tpu.utils.metrics import Metrics
from opencv_facerecognizer_tpu.utils import metric_names as mn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------- the transport fault boundary ----------


def test_transport_passthrough_without_faults():
    fi = FaultInjector(seed=7)
    assert fi.on_transport("peer", "send", {"m": 1}) == [{"m": 1}]
    assert not fi.injected


def test_transport_partition_cuts_both_directions_until_healed():
    fi = FaultInjector(seed=7)
    fi.set_partition("peer")
    assert fi.on_transport("peer", "send", {"m": 1}) == []
    assert fi.on_transport("peer", "recv", {"m": 1}) == []
    # Other links are untouched — the partition is per peer.
    assert fi.on_transport("other", "send", {"m": 1}) == [{"m": 1}]
    fi.heal_partition("peer")
    assert fi.on_transport("peer", "send", {"m": 1}) == [{"m": 1}]
    assert fi.injected["transport:partition"] == 2


def test_transport_half_open_is_directional():
    # Half-open: our sends vanish (the peer's stack ACKs, the app never
    # sees them) while the peer's traffic still reaches us.
    fi = FaultInjector(seed=7)
    fi.set_half_open("peer")
    assert fi.on_transport("peer", "send", {"m": 1}) == []
    assert fi.on_transport("peer", "recv", {"m": 1}) == [{"m": 1}]
    fi.heal_half_open("peer")
    assert fi.on_transport("peer", "send", {"m": 1}) == [{"m": 1}]


def test_transport_slow_link_sleeps_then_delivers():
    fi = FaultInjector(seed=7)
    fi.set_slow_link("peer", latency_s=0.05)
    t0 = time.monotonic()
    out = fi.on_transport("peer", "send", {"m": 1})
    assert out == [{"m": 1}]
    assert time.monotonic() - t0 >= 0.045
    fi.heal_slow_link("peer")
    t0 = time.monotonic()
    fi.on_transport("peer", "send", {"m": 1})
    assert time.monotonic() - t0 < 0.04


def test_transport_scripted_drop_duplicate_reorder():
    fi = FaultInjector(seed=7)
    fi.script("transport", "duplicate", "drop", "reorder")
    assert fi.on_transport("p", "send", {"m": 1}) == [{"m": 1}, {"m": 1}]
    assert fi.on_transport("p", "send", {"m": 2}) == []
    # Reorder: message 3 is held back, delivered AFTER message 4.
    assert fi.on_transport("p", "send", {"m": 3}) == []
    assert fi.on_transport("p", "send", {"m": 4}) == [{"m": 4}, {"m": 3}]


def test_transport_scripted_refuses_stateful_kinds():
    fi = FaultInjector(seed=7)
    with pytest.raises(ValueError):
        fi.script("transport", "partition")


def test_transport_holdback_flush_and_sink():
    fi = FaultInjector(seed=7)
    fired = []
    fi.script("transport", "reorder")
    assert fi.on_transport("p", "send", {"m": 1}, sink=fired.append) == []
    # Teardown accounting: a link that never crosses again can flush its
    # parked message explicitly.
    assert fi.flush_holdback("p") == [{"m": 1}]
    assert fi.flush_holdback("p") == []
    assert fired == ["reorder"]


def test_transport_disarm_is_passthrough():
    fi = FaultInjector(seed=7)
    fi.set_partition("peer")
    fi.disarm()
    assert fi.on_transport("peer", "send", {"m": 1}) == [{"m": 1}]
    fi.arm()
    assert fi.on_transport("peer", "send", {"m": 1}) == []


# ---------- socket transport threading ----------


def test_socket_connector_partition_and_heal():
    """The transport boundary sits on the REAL socket send path: a
    partitioned peer's publishes never hit the wire, a healed one's do."""
    fi = FaultInjector(seed=7)
    server = SocketConnector(listen=True)
    received = []
    server.subscribe("frames", lambda t, m: received.append(m))
    server.start()
    client = SocketConnector(port=server.port, fault_injector=fi,
                             peer_name="server")
    client.start()
    try:
        fi.set_partition("server")
        client.publish("frames", {"seq": 0})
        fi.heal_partition("server")
        client.publish("frames", {"seq": 1})
        deadline = time.monotonic() + 5.0
        while not received and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)  # the partitioned message must NOT trickle in
        assert [m["seq"] for m in received] == [1]
    finally:
        client.stop()
        server.stop()


def test_socket_connector_duplicate_frames_one_payload():
    """A send-side duplicate is framed as two JSONL lines in one payload
    — the wire shape of a retransmit-happy link."""
    fi = FaultInjector(seed=7)
    server = SocketConnector(listen=True)
    received = []
    server.subscribe("frames", lambda t, m: received.append(m))
    server.start()
    client = SocketConnector(port=server.port, fault_injector=fi,
                             peer_name="server")
    client.start()
    try:
        fi.script("transport", "duplicate")
        client.publish("frames", {"seq": 0})
        deadline = time.monotonic() + 5.0
        while len(received) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert [m["seq"] for m in received] == [0, 0]
    finally:
        client.stop()
        server.stop()


def test_reconnect_jitter_clamped_and_applied():
    c = SocketConnector(listen=True, reconnect_jitter=3.0)
    assert c.reconnect_jitter == 1.0
    c = SocketConnector(listen=True, reconnect_jitter=-1.0)
    assert c.reconnect_jitter == 0.0
    # The jitter multiplies each backoff delay by a uniform draw from
    # [1 - j, 1 + j]: with a pinned RNG the total redial wait is exactly
    # predictable, and jitter=0 restores the deterministic schedule.
    c = SocketConnector(port=1, reconnect_attempts=2,
                        reconnect_backoff_base_s=0.05,
                        reconnect_jitter=0.5)
    c._backoff_rng = random.Random(42)
    draws = random.Random(42)
    expect = sum(d * draws.uniform(0.5, 1.5) for d in (0.05, 0.1))
    c._running = True  # redials without start(): port 1 never answers
    t0 = time.monotonic()
    assert c._reconnect_with_backoff() is None
    elapsed = time.monotonic() - t0
    assert elapsed >= expect * 0.9
    c._running = False


# ---------- idempotent intake (frame-id dedup) ----------


def _fleet(n=2, **kw):
    kw.setdefault("health_interval_s", 0.05)
    router_metrics = kw.pop("router_metrics", Metrics())
    router, stacks = build_replica_fleet(n, dispatch_s=0.005,
                                         router_metrics=router_metrics,
                                         **kw)
    for _p, svc, _c, _m in stacks:
        svc.start(warmup=False)
    return router, stacks, router_metrics


def _stop_fleet(router, stacks):
    router.stop()
    for _p, svc, _c, _m in stacks:
        svc.stop()


def test_intake_dedup_refuses_duplicate_fid():
    """A duplicated delivery of an admitted fid is refused BEFORE
    admission — counted ``frames_deduped``, never double-counted in the
    ledger, and exactly one result is published."""
    router, stacks, _rm = _fleet(1)
    try:
        _p, svc, connector, metrics = stacks[0]
        msg = {**encode_frame(np.zeros((32, 32), np.float32)),
               "priority": "interactive",
               "meta": {"seq": 0, "_fid": "f1"}}
        results = []
        connector.subscribe(RESULT_TOPIC, lambda t, m: results.append(m))
        connector.inject(FRAME_TOPIC, msg)
        connector.inject(FRAME_TOPIC, dict(msg))
        svc.drain(timeout=10.0)
        counters = metrics.counters()
        assert counters.get(mn.FRAMES_DEDUPED) == 1
        assert counters.get(mn.FRAMES_ADMITTED) == 1
        assert len(results) == 1
        ledger = svc.ledger()
        assert ledger["admitted"] == 1 and ledger["in_system"] == 0
    finally:
        _stop_fleet(router, stacks)


def test_dedup_records_only_after_admission():
    """A frame REJECTED at the front door stays re-admittable: its fid
    is recorded only once admission succeeds, so a retry after a
    rejection is a fresh frame, not a duplicate."""
    router, stacks, _rm = _fleet(1)
    try:
        _p, svc, connector, metrics = stacks[0]
        # Force a rejection: zero staging headroom for one admit call.
        svc.admission.staging_free_fn = lambda: 0
        msg = {**encode_frame(np.zeros((32, 32), np.float32)),
               "priority": "interactive", "meta": {"_fid": "f9"}}
        connector.inject(FRAME_TOPIC, msg)
        assert metrics.counters().get(mn.FRAMES_ADMITTED, 0) == 0
        svc.admission.staging_free_fn = None
        connector.inject(FRAME_TOPIC, dict(msg))  # the retry
        svc.drain(timeout=10.0)
        counters = metrics.counters()
        assert counters.get(mn.FRAMES_ADMITTED) == 1
        assert counters.get(mn.FRAMES_DEDUPED, 0) == 0
    finally:
        _stop_fleet(router, stacks)


def test_dedup_window_evicts_fifo():
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService

    svc = RecognizerService(InstantPipeline((8, 8)), FakeConnector(),
                            batch_size=2, frame_shape=(8, 8),
                            similarity_threshold=0.0, dedup_window=2)
    svc._dedup_record("a")
    svc._dedup_record("b")
    svc._dedup_record("c")  # evicts "a"
    assert not svc._dedup_hit("a")
    assert svc._dedup_hit("b") and svc._dedup_hit("c")


def test_router_stamps_monotonic_fid_and_resend_keeps_identity():
    handles = [ReplicaHandle("r0", __import__(
        "opencv_facerecognizer_tpu.runtime.connector",
        fromlist=["FakeConnector"]).FakeConnector())]
    router = TopicRouter(handles, health_interval_s=1e9)
    m1 = router._stamp_fid({"meta": {"seq": 0}})
    m2 = router._stamp_fid({"meta": {"seq": 1}})
    assert m1["meta"]["_fid"] != m2["meta"]["_fid"]
    # A re-send (hedge, retry) keeps its original identity.
    assert router._stamp_fid(m1)["meta"]["_fid"] == m1["meta"]["_fid"]


def test_fan_in_dedups_duplicate_results_first_wins():
    """A result duplicated on the replica->router link is dispatched
    upstream exactly once (``router_results_deduped``)."""
    netfi = FaultInjector(seed=7)
    router, stacks, rm = _fleet(1, router_fault_injector=netfi)
    try:
        deliveries = []
        router.subscribe(RESULT_TOPIC, lambda t, m: deliveries.append(m))
        netfi.rates["transport"] = {"duplicate": 1.0}
        router.publish("camera/0",
                       {**encode_frame(np.zeros((32, 32), np.float32)),
                        "priority": "interactive", "meta": {"seq": 0}})
        deadline = time.monotonic() + 10.0
        while not deliveries and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # any duplicate would land right behind
        seqs = [(m.get("meta") or {}).get("seq") for m in deliveries]
        assert seqs == [0]
        assert rm.counters().get(mn.ROUTER_RESULTS_DEDUPED, 0) >= 1
    finally:
        _stop_fleet(router, stacks)


# ---------- hedged interactive dispatch ----------


def test_hedge_fires_after_deadline_and_winner_accounted():
    netfi = FaultInjector(seed=7)
    router, stacks, rm = _fleet(2, router_fault_injector=netfi,
                                hedge_deadline_s=0.05)
    try:
        # Find a topic whose rendezvous preference is replica 0, then
        # blackhole replica 0 so only the hedge can complete the frame.
        victim = None
        topic = None
        for t in range(64):
            handle = router.route(f"camera/{t}")
            if handle is not None:
                victim, topic = handle.name, f"camera/{t}"
                break
        assert topic is not None
        netfi.set_partition(victim)
        recorder = TrafficRecorder(router)
        recorder.offer(router, encode_frame(np.zeros((32, 32), np.float32)),
                       0, "interactive")
        # offer() publishes on FRAME_TOPIC; hedge needs the routed topic:
        router.publish(topic,
                       {**encode_frame(np.zeros((32, 32), np.float32)),
                        "priority": "interactive", "meta": {"seq": 1}})
        time.sleep(0.1)
        fired = router.check_hedges()
        assert fired >= 1
        deadline = time.monotonic() + 10.0
        while not recorder.completed([1]) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert recorder.completed([1]) == 1
        counters = rm.counters()
        assert counters.get(mn.ROUTER_HEDGES, 0) >= 1
        assert counters.get(mn.ROUTER_HEDGE_WINS, 0) >= 1
        # One hedge per frame, ever: a second pass re-sends nothing.
        assert router.check_hedges() == 0
    finally:
        netfi.heal_all_links()
        _stop_fleet(router, stacks)


def test_hedge_duplicate_result_counted_wasted():
    """When the first replica answers AFTER the hedge already won, the
    late result is deduped and accounted ``router_hedge_wasted``."""
    netfi = FaultInjector(seed=7)
    router, stacks, rm = _fleet(2, router_fault_injector=netfi,
                                hedge_deadline_s=0.05)
    try:
        victim = router.route("camera/0").name
        # Half-open TOWARD the victim: our frames vanish, but anything it
        # sends still arrives — so after healing, its late result lands.
        netfi.set_half_open(victim, direction="send")
        router.publish("camera/0",
                       {**encode_frame(np.zeros((32, 32), np.float32)),
                        "priority": "interactive", "meta": {"seq": 0}})
        time.sleep(0.1)
        assert router.check_hedges() >= 1
        deadline = time.monotonic() + 10.0
        while (not rm.counters().get(mn.ROUTER_HEDGE_WINS)
               and time.monotonic() < deadline):
            time.sleep(0.01)
        # Heal and re-send the SAME fid straight to the victim: its
        # result is the losing twin the fan-in must dedup.
        netfi.heal_half_open(victim)
        fid_msg = None
        with router._hedge_lock:
            pass  # (ordering only: the hedge bookkeeping is settled)
        # Re-deliver by replaying through the victim's own intake:
        victim_stack = next(s for s in stacks
                            if any(h.name == victim and h.connector is s[2]
                                   for h in router.replicas()))
        _p, svc, connector, _m = victim_stack
        # The frame never reached the victim (half-open), so replay the
        # original fid by hand.
        fid_msg = {**encode_frame(np.zeros((32, 32), np.float32)),
                   "priority": "interactive",
                   "meta": {"seq": 0, "_fid": "f1"}}
        connector.inject(FRAME_TOPIC, fid_msg)
        deadline = time.monotonic() + 10.0
        while (not rm.counters().get(mn.ROUTER_HEDGE_WASTED)
               and time.monotonic() < deadline):
            time.sleep(0.01)
        counters = rm.counters()
        assert counters.get(mn.ROUTER_HEDGE_WASTED, 0) >= 1
        assert counters.get(mn.ROUTER_RESULTS_DEDUPED, 0) >= 1
    finally:
        netfi.heal_all_links()
        _stop_fleet(router, stacks)


# ---------- link supervision ----------


def test_link_supervision_fails_and_recovers_partitioned_replica():
    netfi = FaultInjector(seed=7)
    router, stacks, rm = _fleet(2, router_fault_injector=netfi,
                                link_deadline_s=0.2)
    router.start()
    try:
        time.sleep(0.3)
        assert all(r["link_up"] for r in router.registry())
        victim = router.registry()[0]["name"]
        netfi.set_partition(victim)
        deadline = time.monotonic() + 5.0
        while (next(r["link_up"] for r in router.registry()
                    if r["name"] == victim)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        reg = {r["name"]: r for r in router.registry()}
        assert not reg[victim]["link_up"]
        # A downed link is excluded from routing.
        for t in range(32):
            handle = router.route(f"camera/{t}")
            assert handle is None or handle.name != victim
        assert router.down_link_fraction() == 0.5
        counters = rm.counters()
        assert counters.get(mn.LINK_FAILURES, 0) >= 1
        assert counters.get(mn.LINK_HEARTBEATS_SENT, 0) >= 1
        netfi.heal_partition(victim)
        deadline = time.monotonic() + 5.0
        while (not next(r["link_up"] for r in router.registry()
                        if r["name"] == victim)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert next(r["link_up"] for r in router.registry()
                    if r["name"] == victim)
        assert rm.counters().get(mn.LINK_RECOVERIES, 0) >= 1
    finally:
        _stop_fleet(router, stacks)


def test_link_ping_echoed_as_pong():
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService

    connector = FakeConnector()
    RecognizerService(InstantPipeline((8, 8)), connector, batch_size=2,
                      frame_shape=(8, 8), similarity_threshold=0.0,
                      replica="r7")
    pongs = []
    connector.subscribe(LINK_PONG_TOPIC, lambda t, m: pongs.append(m))
    connector.inject(LINK_PING_TOPIC, {"ping": 3})
    assert pongs and pongs[0]["ping"] == 3 and pongs[0]["replica"] == "r7"


def test_link_health_objective_burn():
    box = {"down": 0.0}
    slo = link_health_objective(lambda: box["down"], max_down_fraction=0.5)
    assert slo.kind == "gauge"
    assert slo.value_fn() == 0.0
    box["down"] = 0.5  # exactly the allowed fraction: burn 1.0 (warn)
    assert slo.value_fn() == pytest.approx(1.0)
    box["down"] = 1.0
    assert slo.value_fn() == pytest.approx(2.0)
    # Critical must be REACHABLE: a fraction tops out at 1.0, so the
    # stock 6x threshold would never fire against the 0.5 bound — the
    # objective lowers it to the all-links-dark burn.
    assert slo.critical_burn == pytest.approx(2.0)
    assert slo.value_fn() >= slo.critical_burn
    tight = link_health_objective(lambda: 0.0, max_down_fraction=0.1)
    assert tight.critical_burn == pytest.approx(6.0)
    with pytest.raises(ValueError):
        link_health_objective(lambda: 0.0, max_down_fraction=0.0)


# ---------- probe-error streaks (satellite) ----------


def test_probe_error_streak_counts_and_logs_once(caplog):
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector

    boom = {"raise": True}

    def probe():
        if boom["raise"]:
            raise RuntimeError("probe down")
        return True

    m = Metrics()
    handle = ReplicaHandle("r0", FakeConnector(), health_fn=probe)
    router = TopicRouter([handle], metrics=m, health_interval_s=1e9)
    with caplog.at_level(logging.WARNING,
                         logger="opencv_facerecognizer_tpu.runtime.replication"):
        for _ in range(5):
            router.check_health()
    assert handle.probe_streak == 5
    assert m.counters().get(mn.ROUTER_PROBE_ERRORS) == 5
    warns = [r for r in caplog.records if "probe" in r.getMessage()
             and r.levelno >= logging.WARNING]
    assert len(warns) == 1  # logged once per streak, not once per cycle
    boom["raise"] = False
    router.check_health()
    assert handle.probe_streak == 0
    # A fresh streak logs again (new transition, new evidence).
    boom["raise"] = True
    with caplog.at_level(logging.WARNING,
                         logger="opencv_facerecognizer_tpu.runtime.replication"):
        router.check_health()
    assert handle.probe_streak == 1


def test_probe_error_streak_capped():
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector

    def probe():
        raise RuntimeError("always down")

    handle = ReplicaHandle("r0", FakeConnector(), health_fn=probe)
    router = TopicRouter([handle], health_interval_s=1e9)
    handle.probe_streak = TopicRouter.PROBE_STREAK_CAP
    router.check_health()
    assert handle.probe_streak == TopicRouter.PROBE_STREAK_CAP


# ---------- wildcard subscription x per-topic admission budgets ----------


def test_wildcard_forward_draws_frame_topic_budget():
    """The router's forward is topic-agnostic: every ``camera/*`` frame
    reaches the replica on ``FRAME_TOPIC``, so (a) a WILDCARD subscriber
    on the replica sees only ``FRAME_TOPIC`` frame deliveries, and (b)
    per-topic admission budgets keyed by camera topic are never
    consulted — the ``FRAME_TOPIC`` bucket is the one that gates, and
    the collapsed stream cannot bypass it."""
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService

    connector = FakeConnector()
    metrics = Metrics()
    admission = AdmissionController(
        rate_limit_fps={FRAME_TOPIC: 4.0, "camera/0": 1e9},
        burst_seconds=1.0)
    svc = RecognizerService(
        InstantPipeline((16, 16), dispatch_s=0.001), connector,
        batch_size=4, frame_shape=(16, 16), flush_timeout=0.02,
        similarity_threshold=0.0, metrics=metrics, admission=admission)
    seen_topics = []
    connector.subscribe(WILDCARD_TOPIC,
                        lambda t, m: seen_topics.append(t)
                        if "__frame__" in m else None)
    handle = ReplicaHandle("r0", connector)
    router = TopicRouter([handle], health_interval_s=1e9)
    svc.start(warmup=False)
    try:
        frame_msg = encode_frame(np.zeros((16, 16), np.float32))
        for i in range(12):
            router.publish(f"camera/{i % 3}",
                           {**frame_msg, "priority": "interactive",
                            "meta": {"seq": i}})
        svc.drain(timeout=10.0)
        counters = metrics.counters()
        # The FRAME_TOPIC bucket (4 fps, burst 4) gated the collapsed
        # stream: some of the 12 were rate-limited despite camera/0's
        # effectively infinite per-camera budget.
        assert counters.get(mn.FRAMES_ADMITTED, 0) <= 5
        assert counters.get("frames_rejected_rate_limit", 0) >= 7
        # And the wildcard subscriber saw the forwards as FRAME_TOPIC.
        assert set(seen_topics) == {FRAME_TOPIC}
    finally:
        router.stop()
        svc.stop()


# ---------- half-open writer (split-brain safety) ----------


def test_lease_unreachable_flips_degraded_and_rearms(tmp_path):
    from opencv_facerecognizer_tpu.runtime.resilience import DurabilityMonitor
    from opencv_facerecognizer_tpu.runtime.state_store import StateLifecycle

    fi = FaultInjector(seed=7)
    m = Metrics()
    state = StateLifecycle(str(tmp_path), metrics=m, checkpoint_every_s=1e9,
                           fault_injector=fi)
    mon = DurabilityMonitor(state, metrics=m, degraded_after=2,
                            probe_interval_s=0.0, fault_injector=fi)
    try:
        mon.tick(force=True, probe=True)
        assert not mon.degraded
        fi.rates["storage"] = {"read_error": 1.0, "eio": 1.0}
        mon.tick(force=True, probe=True)
        assert not mon.degraded  # one failure is a blip, not a verdict
        mon.tick(force=True, probe=True)
        assert mon.degraded
        assert mon.degraded_reason == "lease_unreachable"
        assert m.counters().get(mn.DURABILITY_LEASE_CHECK_FAILURES) >= 2
        # Probe cannot re-arm while the volume stays dark.
        mon.tick(force=True, probe=True)
        assert mon.degraded
        fi.rates["storage"] = {}
        mon.tick(force=True, probe=True)
        assert not mon.degraded
        assert mon.status()["consecutive_lease_failures"] == 0
    finally:
        state.close()


# ---------- the partition chaos scenario (fast tier-1 variant) ----------


def test_partition_scenario_fast_deterministic():
    """Tier-1 variant of ``--scenario partition``: 3 routed replicas;
    the busiest one is partitioned and healed, a second link flaps, a
    duplicate storm hits every crossing, and a half-open writer flips
    degraded — bounded failover, hedge rescue, exactly-once delivery,
    exact ledgers, split-brain fail-closed."""
    chaos_soak = _load_script("chaos_soak")
    report = chaos_soak.run_partition(seconds=4.0, seed=7)
    assert report["ok"], report["failures"]
    assert report["failover_s"] is not None
    assert report["router"].get("router_hedges", 0) >= 1
    assert report["deduped_total"] >= 1
    assert report["split_brain"]["refused"]
    assert report["split_brain"]["rearmed"]
