"""Image ops: oracle tests vs NumPy/OpenCV-semantics (SURVEY.md §4)."""

import numpy as np

from opencv_facerecognizer_tpu.ops import image as I

RNG = np.random.default_rng(2)


def test_grayscale_matches_luma():
    rgb = RNG.uniform(0, 255, size=(3, 6, 5, 3)).astype(np.float32)
    got = np.asarray(I.to_grayscale(rgb))
    want = rgb @ np.array([0.299, 0.587, 0.114], dtype=np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert got.shape == (3, 6, 5)


def test_resize_shapes_and_identity():
    img = RNG.uniform(0, 1, size=(4, 10, 8)).astype(np.float32)
    out = np.asarray(I.resize(img, (5, 4)))
    assert out.shape == (4, 5, 4)
    same = np.asarray(I.resize(img, (10, 8)))
    np.testing.assert_allclose(same, img, atol=1e-5)


def test_minmax_normalize_range():
    img = RNG.uniform(-3, 7, size=(2, 9, 9)).astype(np.float32)
    out = np.asarray(I.minmax_normalize(img, 0.0, 255.0))
    assert out.shape == img.shape
    np.testing.assert_allclose(out.min(axis=(1, 2)), 0.0, atol=1e-4)
    np.testing.assert_allclose(out.max(axis=(1, 2)), 255.0, atol=1e-2)


def test_histogram_equalize_flattens_histogram():
    # A low-contrast ramp should stretch to cover ~[0, 255].
    img = np.tile(np.linspace(100, 140, 64, dtype=np.float32), (64, 1))
    out = np.asarray(I.histogram_equalize(img))
    assert out.shape == img.shape
    assert out.min() < 10.0 and out.max() > 245.0
    # Monotone: equalization preserves ordering.
    row_in, row_out = img[0], out[0]
    assert np.all(np.diff(row_out[np.argsort(row_in)]) >= -1e-3)


def test_histogram_equalize_uniform_image_stable():
    img = np.full((16, 16), 55.0, dtype=np.float32)
    out = np.asarray(I.histogram_equalize(img))
    assert np.all(np.isfinite(out))
    assert np.ptp(out) < 1e-3


def test_gaussian_blur_preserves_mean_and_smooths():
    img = RNG.uniform(0, 1, size=(20, 20)).astype(np.float32)
    out = np.asarray(I.gaussian_blur(img, sigma=2.0))
    assert out.shape == img.shape
    np.testing.assert_allclose(out.mean(), img.mean(), rtol=0.05)
    assert out.var() < img.var()


def test_tan_triggs_bounded_and_illumination_invariant():
    base = RNG.uniform(0, 255, size=(30, 30)).astype(np.float32)
    out1 = np.asarray(I.tan_triggs(base))
    out2 = np.asarray(I.tan_triggs(base * 2.5))  # global illumination change
    assert np.all(np.abs(out1) <= 10.0 + 1e-4)  # tau bound
    # Tan-Triggs should make the two versions far closer than raw pixels.
    corr = np.corrcoef(out1.ravel(), out2.ravel())[0, 1]
    assert corr > 0.98


def test_crop_and_resize():
    frame = RNG.uniform(0, 1, size=(40, 50)).astype(np.float32)
    face = np.asarray(I.crop_and_resize(frame, (10, 5, 30, 35), (16, 16)))
    assert face.shape == (16, 16)
