"""CLI app tests: the reference's script surface (SURVEY.md §2.1
"Packaging/CLI") driven through main(argv)."""

import json
import os

import numpy as np
import pytest

from opencv_facerecognizer_tpu.apps import recognize as recognize_app
from opencv_facerecognizer_tpu.apps import train as train_app
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces, make_synthetic_scenes


def _write_dataset(root, images, labels, names):
    import cv2

    for name in names:
        os.makedirs(os.path.join(root, name), exist_ok=True)
    counters = {}
    for img, label in zip(images, labels):
        subject = names[label]
        i = counters.get(subject, 0)
        counters[subject] = i + 1
        cv2.imwrite(os.path.join(root, subject, f"{i}.png"), img.astype(np.uint8))


def test_train_app_classic(tmp_path, capsys):
    X, y, names = make_synthetic_faces(4, 6, (32, 32), seed=51)
    data_dir = str(tmp_path / "data")
    _write_dataset(data_dir, X, y, names)
    model_path = str(tmp_path / "model.ckpt")
    plot_path = str(tmp_path / "eigen.png")
    rc = train_app.main([
        data_dir, model_path, "--model", "fisherfaces", "--image-size", "32", "32",
        "--kfold", "2", "--eigenfaces-plot", plot_path,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mean k-fold accuracy" in out
    assert os.path.exists(model_path)
    assert os.path.exists(plot_path)

    from opencv_facerecognizer_tpu.utils import serialization

    model = serialization.load_model(model_path)
    assert model.subject_names == names


def test_train_app_rejects_bad_dataset(tmp_path):
    with pytest.raises((ValueError, FileNotFoundError)):
        train_app.main([str(tmp_path / "nope"), str(tmp_path / "m.ckpt")])


@pytest.fixture(scope="module")
def app_artifacts(tmp_path_factory):
    """Trained CNN model + detector checkpoints, gallery dir, frames dir —
    shared by the recognize-app tests (training them is the slow part)."""
    import cv2

    tmp_path = tmp_path_factory.mktemp("app_artifacts")
    X, y, names = make_synthetic_faces(3, 6, (32, 32), seed=53, noise=8.0)
    data_dir = str(tmp_path / "gallery")
    _write_dataset(data_dir, X, y, names)
    model_path = str(tmp_path / "cnn.ckpt")
    rc = train_app.main([
        data_dir, model_path, "--model", "cnn", "--image-size", "32", "32",
        "--kfold", "0", "--embed-dim", "32", "--train-steps", "30",
    ])
    assert rc == 0

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector

    scenes, boxes, counts = make_synthetic_scenes(32, (96, 96), max_faces=2, seed=55)
    det = CNNFaceDetector(features=(8, 16, 32), head_features=32, max_faces=4,
                          score_threshold=0.25)
    det.train(scenes, boxes, counts, steps=150, batch_size=16, learning_rate=2e-3)
    det_path = str(tmp_path / "det.ckpt")
    det.save(det_path)

    frames_dir = str(tmp_path / "frames")
    os.makedirs(frames_dir)
    test_scenes, _, test_counts = make_synthetic_scenes(4, (96, 96), max_faces=2, seed=57)
    for i, scene in enumerate(test_scenes):
        cv2.imwrite(os.path.join(frames_dir, f"f{i}.png"), scene.astype(np.uint8))

    return {
        "data_dir": data_dir, "model_path": model_path, "det_path": det_path,
        "frames_dir": frames_dir, "names": names, "test_scenes": test_scenes,
        "tmp_path": tmp_path,
    }


@pytest.mark.slow
def test_recognize_app_dir_mode(app_artifacts, capsys):
    a = app_artifacts
    profile_dir = str(a["tmp_path"] / "trace")
    rc = recognize_app.main([
        "--model", a["model_path"], "--detector", a["det_path"],
        "--gallery", a["data_dir"],
        "--source", "dir", "--dir", a["frames_dir"], "--frame-size", "96", "96",
        "--batch-size", "4", "--similarity-threshold", "0.0",
        "--profile-dir", profile_dir, "--profile-batches", "1",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert len(lines) == 4
    results = [json.loads(l) for l in lines]
    files = sorted(r["meta"]["file"] for r in results)
    assert files == [f"f{i}.png" for i in range(4)]
    for r in results:
        for face in r["faces"]:
            assert face["name"] in a["names"] or face["name"] == "unknown"
    # --profile-dir produced a loadable trace (SURVEY.md §5.1)
    trace_files = [
        os.path.join(root, f)
        for root, _dirs, fs in os.walk(profile_dir) for f in fs
    ]
    assert trace_files, "profiler trace directory is empty"


@pytest.mark.slow
def test_recognize_app_jsonl_stdin_eof_terminates(app_artifacts, monkeypatch, capsys):
    """Regression: jsonl mode used to spin `while True` forever after stdin
    EOF; it must now shut down cleanly on its own."""
    import io
    import sys
    import threading

    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.recognizer import FRAME_TOPIC

    a = app_artifacts
    n_frames = 5
    lines = [
        json.dumps({"topic": FRAME_TOPIC,
                    "data": {**encode_frame(a["test_scenes"][i % 4].astype(np.float32)),
                             "meta": {"seq": i}}})
        for i in range(n_frames)
    ]
    # Final line deliberately lacks the trailing newline: still a message.
    stdin_text = "\n".join(lines + [
        json.dumps({"topic": "ocvfacerec/control", "data": {"cmd": "stats"}})
    ])
    monkeypatch.setattr(sys, "stdin", io.StringIO(stdin_text))

    rc_box = {}

    def run():
        rc_box["rc"] = recognize_app.main([
            "--model", a["model_path"], "--detector", a["det_path"],
            "--gallery", a["data_dir"], "--source", "jsonl",
            "--frame-size", "96", "96", "--batch-size", "2",
            "--similarity-threshold", "0.0",
        ])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=300)
    assert not t.is_alive(), "jsonl mode did not terminate on stdin EOF"
    assert rc_box["rc"] == 0
    # EOF shutdown must DRAIN, not drop: every piped frame gets a result.
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    results = [json.loads(l) for l in out_lines
               if json.loads(l).get("topic") == "ocvfacerec/results"]
    seqs = sorted(r["data"]["meta"]["seq"] for r in results)
    assert seqs == list(range(n_frames)), seqs


def test_detector_checkpoint_roundtrip(tmp_path):
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector

    scenes, boxes, counts = make_synthetic_scenes(8, (64, 64), max_faces=1, seed=59)
    det = CNNFaceDetector(features=(8, 8, 16), head_features=16, max_faces=2)
    det.train(scenes, boxes, counts, steps=10, batch_size=8)
    path = str(tmp_path / "det.ckpt")
    det.save(path)
    restored = CNNFaceDetector.load(path)
    assert restored.max_faces == 2
    b1, s1, v1 = (np.asarray(v) for v in det.detect_batch(scenes[:2]))
    b2, s2, v2 = (np.asarray(v) for v in restored.detect_batch(scenes[:2]))
    np.testing.assert_allclose(b1, b2, atol=1e-5)
    np.testing.assert_array_equal(v1, v2)


def test_detector_save_before_train_raises(tmp_path):
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector

    with pytest.raises(RuntimeError):
        CNNFaceDetector().save(str(tmp_path / "x.ckpt"))


@pytest.mark.slow
def test_recognize_app_pp_mode(app_artifacts, capsys):
    """--parallel pp serves through the two-stage pipeline executor; on the
    8-virtual-device CPU mesh the devices split 4|4."""
    a = app_artifacts
    rc = recognize_app.main([
        "--model", a["model_path"], "--detector", a["det_path"],
        "--gallery", a["data_dir"],
        "--source", "dir", "--dir", a["frames_dir"], "--frame-size", "96", "96",
        "--batch-size", "4", "--similarity-threshold", "0.0",
        "--parallel", "pp",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert len(lines) == 4
    results = [json.loads(l) for l in lines]
    assert any(r["faces"] for r in results)
    for r in results:
        for face in r["faces"]:
            assert face["name"] in a["names"] or face["name"] == "unknown"
