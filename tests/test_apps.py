"""CLI app tests: the reference's script surface (SURVEY.md §2.1
"Packaging/CLI") driven through main(argv)."""

import json
import os

import numpy as np
import pytest

from opencv_facerecognizer_tpu.apps import recognize as recognize_app
from opencv_facerecognizer_tpu.apps import train as train_app
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces, make_synthetic_scenes


def _write_dataset(root, images, labels, names):
    import cv2

    for name in names:
        os.makedirs(os.path.join(root, name), exist_ok=True)
    counters = {}
    for img, label in zip(images, labels):
        subject = names[label]
        i = counters.get(subject, 0)
        counters[subject] = i + 1
        cv2.imwrite(os.path.join(root, subject, f"{i}.png"), img.astype(np.uint8))


def test_train_app_classic(tmp_path, capsys):
    X, y, names = make_synthetic_faces(4, 6, (32, 32), seed=51)
    data_dir = str(tmp_path / "data")
    _write_dataset(data_dir, X, y, names)
    model_path = str(tmp_path / "model.ckpt")
    plot_path = str(tmp_path / "eigen.png")
    rc = train_app.main([
        data_dir, model_path, "--model", "fisherfaces", "--image-size", "32", "32",
        "--kfold", "2", "--eigenfaces-plot", plot_path,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mean k-fold accuracy" in out
    assert os.path.exists(model_path)
    assert os.path.exists(plot_path)

    from opencv_facerecognizer_tpu.utils import serialization

    model = serialization.load_model(model_path)
    assert model.subject_names == names


def test_train_app_rejects_bad_dataset(tmp_path):
    with pytest.raises((ValueError, FileNotFoundError)):
        train_app.main([str(tmp_path / "nope"), str(tmp_path / "m.ckpt")])


@pytest.mark.slow
def test_recognize_app_dir_mode(tmp_path, capsys):
    import cv2

    # 1) train + save a tiny cnn model on face crops
    X, y, names = make_synthetic_faces(3, 6, (32, 32), seed=53, noise=8.0)
    data_dir = str(tmp_path / "gallery")
    _write_dataset(data_dir, X, y, names)
    model_path = str(tmp_path / "cnn.ckpt")
    rc = train_app.main([
        data_dir, model_path, "--model", "cnn", "--image-size", "32", "32",
        "--kfold", "0", "--embed-dim", "32", "--train-steps", "30",
    ])
    assert rc == 0

    # shrink the cnn for test speed: retrain tiny variant directly
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector

    scenes, boxes, counts = make_synthetic_scenes(32, (96, 96), max_faces=2, seed=55)
    det = CNNFaceDetector(features=(8, 16, 32), head_features=32, max_faces=4,
                          score_threshold=0.25)
    det.train(scenes, boxes, counts, steps=150, batch_size=16, learning_rate=2e-3)
    det_path = str(tmp_path / "det.ckpt")
    det.save(det_path)

    # 2) frames dir to replay
    frames_dir = str(tmp_path / "frames")
    os.makedirs(frames_dir)
    test_scenes, _, test_counts = make_synthetic_scenes(4, (96, 96), max_faces=2, seed=57)
    for i, scene in enumerate(test_scenes):
        cv2.imwrite(os.path.join(frames_dir, f"f{i}.png"), scene.astype(np.uint8))

    rc = recognize_app.main([
        "--model", model_path, "--detector", det_path, "--gallery", data_dir,
        "--source", "dir", "--dir", frames_dir, "--frame-size", "96", "96",
        "--batch-size", "4", "--similarity-threshold", "0.0",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert len(lines) == 4
    results = [json.loads(l) for l in lines]
    files = sorted(r["meta"]["file"] for r in results)
    assert files == [f"f{i}.png" for i in range(4)]
    for r in results:
        for face in r["faces"]:
            assert face["name"] in names or face["name"] == "unknown"


def test_detector_checkpoint_roundtrip(tmp_path):
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector

    scenes, boxes, counts = make_synthetic_scenes(8, (64, 64), max_faces=1, seed=59)
    det = CNNFaceDetector(features=(8, 8, 16), head_features=16, max_faces=2)
    det.train(scenes, boxes, counts, steps=10, batch_size=8)
    path = str(tmp_path / "det.ckpt")
    det.save(path)
    restored = CNNFaceDetector.load(path)
    assert restored.max_faces == 2
    b1, s1, v1 = (np.asarray(v) for v in det.detect_batch(scenes[:2]))
    b2, s2, v2 = (np.asarray(v) for v in restored.detect_batch(scenes[:2]))
    np.testing.assert_allclose(b1, b2, atol=1e-5)
    np.testing.assert_array_equal(v1, v2)


def test_detector_save_before_train_raises(tmp_path):
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector

    with pytest.raises(RuntimeError):
        CNNFaceDetector().save(str(tmp_path / "x.ckpt"))
