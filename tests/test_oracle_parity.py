"""Cross-checks between the NumPy oracle implementations
(scripts/oracle_parity.py) and the framework's device ops: the end-to-end
k-fold agreement in BASELINE.md is only meaningful if the primitives
genuinely compute the same published math, so pin that here on small
inputs (exact for integer-code ops, tolerance for float pipelines)."""

import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

# The oracle deliberately leans on scipy (independent eigensolver); scipy is
# an environment extra, not a package dependency — skip, don't crash
# collection, on installs without it.
pytest.importorskip("scipy")

from oracle_parity import (  # noqa: E402
    lbp_codes_np, spatial_hist_np, tan_triggs_np, pca_fit_np,
    fisherfaces_fit_np, nn_classify_np,
)

from opencv_facerecognizer_tpu.ops import histogram as hist_ops  # noqa: E402
from opencv_facerecognizer_tpu.ops import image as image_ops  # noqa: E402
from opencv_facerecognizer_tpu.ops import lbp as lbp_ops  # noqa: E402
from opencv_facerecognizer_tpu.ops import linalg as linalg_ops  # noqa: E402

RNG = np.random.default_rng(7)


def test_lbp_codes_exact_match():
    x = RNG.uniform(0, 255, (3, 20, 22)).astype(np.float32)
    ours = np.asarray(lbp_ops.extended_lbp(jnp.asarray(x), radius=2,
                                           neighbors=8))
    oracle = lbp_codes_np(x, radius=2, neighbors=8)
    # integer codes: any sampling-convention mismatch shows up as exact
    # inequality somewhere
    np.testing.assert_array_equal(ours, oracle)


def test_spatial_histogram_matches():
    codes = RNG.integers(0, 256, (2, 33, 35))
    ours = np.asarray(hist_ops.spatial_histogram(jnp.asarray(codes),
                                                 grid=(4, 4), num_bins=256))
    oracle = spatial_hist_np(codes, grid=(4, 4), num_bins=256)
    np.testing.assert_allclose(ours, oracle, atol=1e-6)


def test_tan_triggs_close():
    x = RNG.uniform(0, 255, (2, 40, 40)).astype(np.float32)
    ours = np.asarray(image_ops.tan_triggs(jnp.asarray(x), sigma0=2.0,
                                           sigma1=4.0))
    oracle = tan_triggs_np(x, sigma0=2.0, sigma1=4.0)
    # different blur implementations (separable static taps vs
    # scipy.ndimage): small edge/tap differences propagate through the
    # contrast equalization, so compare loosely but globally
    assert np.corrcoef(ours.ravel(), oracle.ravel())[0, 1] > 0.999
    np.testing.assert_allclose(ours, oracle, atol=0.35)


def test_pca_subspaces_align():
    X = RNG.normal(size=(30, 50)).astype(np.float32)
    k = 10
    mean_o, W_o = pca_fit_np(X.astype(np.float64), k)
    state = linalg_ops.pca_fit(jnp.asarray(X), k)
    W_f = np.asarray(state.components)  # [D, k]
    # same subspace: projector Frobenius distance ~ 0 (eigvector sign/
    # rotation within degenerate eigenvalues is not comparable directly)
    P_o = W_o @ W_o.T
    P_f = W_f @ W_f.T
    assert np.linalg.norm(P_o - P_f) < 1e-2
    np.testing.assert_allclose(np.asarray(state.mean), mean_o, atol=1e-4)


def test_fisherfaces_projection_separates_like_oracle():
    # 4 classes, 12 samples each, in 64-d with class-mean structure
    c, n_per, d = 4, 12, 64
    means = RNG.normal(size=(c, d)) * 3
    X = np.concatenate([means[i] + RNG.normal(size=(n_per, d))
                        for i in range(c)]).astype(np.float32)
    y = np.repeat(np.arange(c), n_per)
    # hold out 2 samples per class: self-matches at distance 0 would make
    # a train-on-train comparison tautological
    test_mask = np.zeros(len(y), bool)
    for cls in range(c):
        test_mask[np.flatnonzero(y == cls)[:2]] = True
    Xtr, ytr = X[~test_mask], y[~test_mask]
    Xte, yte = X[test_mask], y[test_mask]

    mean_o, W_o = fisherfaces_fit_np(Xtr.astype(np.float64), ytr)
    preds_o = nn_classify_np((Xtr - mean_o) @ W_o, ytr,
                             (Xte - mean_o) @ W_o, "euclidean")
    # framework: PCA(N-c) then LDA(c-1), as models.feature.Fisherfaces does
    from opencv_facerecognizer_tpu.models.feature import Fisherfaces

    ff = Fisherfaces()
    Ztr_f = np.asarray(ff.compute(Xtr.reshape(len(ytr), 8, 8), ytr))
    Zte_f = np.asarray(ff.extract(Xte.reshape(len(yte), 8, 8)))
    preds_f = nn_classify_np(Ztr_f, ytr, Zte_f, "euclidean")
    # both projections must classify HELD-OUT points of separable classes
    # perfectly — the end-to-end agreement bar
    assert (preds_o == yte).mean() == 1.0
    assert (preds_f == yte).mean() == 1.0
