"""Frame-lifecycle tracing tests (observability layer): span lifecycle &
ordering under the threaded serving pipeline, ring-buffer bounds,
deterministic sampling, the flight-recorder dump on an injected wedge,
the expo endpoint's read-only contract, and the Metrics empty-window /
reset_window fixes that ride along.

All over ``runtime.fakes.InstantPipeline`` — fast, deterministic, no
hardware.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
from opencv_facerecognizer_tpu.runtime.expo import ExpoServer, fold_attribution
from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
from opencv_facerecognizer_tpu.runtime.faults import FaultInjector
from opencv_facerecognizer_tpu.runtime.journal import DeadLetterJournal
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    RecognizerService,
)
from opencv_facerecognizer_tpu.runtime.resilience import ResiliencePolicy
from opencv_facerecognizer_tpu.utils import tracing
from opencv_facerecognizer_tpu.utils.metrics import Metrics
from opencv_facerecognizer_tpu.utils.tracing import Tracer, account_spans

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FRAME_HW = (16, 16)


def _make_service(tracer, **kwargs):
    pipeline = InstantPipeline(FRAME_HW, compute_s=0.001)
    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=4, frame_shape=FRAME_HW,
        flush_timeout=0.01, similarity_threshold=0.0, metrics=Metrics(),
        tracer=tracer, **kwargs)
    return pipeline, connector, service


def _drive(connector, n, start=0):
    frame = np.zeros(FRAME_HW, np.float32)
    for i in range(start, start + n):
        connector.inject(FRAME_TOPIC, {"frame": frame, "meta": {"seq": i}})


# ---- span lifecycle & ordering under the threaded pipeline ----


def test_span_lifecycle_and_ordering_through_pipeline():
    tracer = Tracer(ring_size=4096, sample=1.0)
    _pipe, connector, service = _make_service(tracer)
    service.start(warmup=False)
    try:
        _drive(connector, 12)
        assert service.drain(timeout=10.0)
    finally:
        service.stop()

    frame_spans = tracer.snapshot(topic=FRAME_TOPIC)
    by_trace = {}
    for span in frame_spans:
        by_trace.setdefault(span["trace"], []).append(span)
    assert len(by_trace) == 12
    batch_spans = tracer.snapshot(topic=tracing.BATCH_TOPIC)
    dispatch_by_batch = {s["trace"]: s for s in batch_spans
                        if s["stage"] == "dispatch"}
    for spans in by_trace.values():
        stages = [s["stage"] for s in spans]
        # Causal order: receive -> queue_wait -> settle, in emission order
        # (span ids are globally monotonic).
        assert stages == ["receive", "queue_wait", "settle"]
        assert spans[0]["span"] < spans[1]["span"] < spans[2]["span"]
        assert spans[0]["verdict"] == "admitted"
        assert spans[2]["outcome"] == tracing.OUTCOME_COMPLETED
        # Coalescing ancestry: the queue_wait span names the batch trace
        # that carried the frame, and that batch has a dispatch span with
        # the bucket it served at.
        batch = spans[1]["batch"]
        assert batch and batch == spans[2]["batch"]
        assert dispatch_by_batch[batch]["bucket"] >= 1
    # Batch spans: every dispatched batch has its round-trip recorded.
    stages = {s["stage"] for s in batch_spans}
    assert {"dispatch", "ready_wait", "publish"} <= stages
    # Span accounting mirrors the (settled) ledger exactly.
    acct = account_spans(frame_spans)
    ledger = service.ledger()
    assert acct["completed"] == int(ledger["completed"]) == 12
    assert acct["traced"] == int(ledger["admitted"])
    assert acct["drops"] == {}


def test_terminal_spans_cover_drops():
    """A frame that dies in the batcher still settles exactly once, with
    the ledger counter name as its outcome."""
    tracer = Tracer(sample=1.0)
    _pipe, connector, service = _make_service(tracer)
    # Malformed decode: admitted, then fails decode_frame.
    connector.inject(FRAME_TOPIC, {"__frame__": "corrupt!", "shape": [1],
                                   "dtype": "float32", "meta": {}})
    acct = account_spans(tracer.snapshot(topic=FRAME_TOPIC))
    assert acct["drops"] == {"frames_malformed": 1}
    # Wrong shape: the batcher's malformed drop settles the frame.
    connector.inject(FRAME_TOPIC, {"frame": np.zeros((3, 3), np.float32)})
    acct = account_spans(tracer.snapshot(topic=FRAME_TOPIC))
    assert acct["drops"] == {"frames_malformed": 1,
                             "batcher_dropped_malformed": 1}
    ledger = service.ledger()
    assert acct["traced"] == int(ledger["admitted"]) == 2
    assert {k: float(v) for k, v in acct["drops"].items()} \
        == ledger["drops_by_reason"]


# ---- ring-buffer bounds ----


def test_ring_buffer_bounded():
    tracer = Tracer(ring_size=16, sample=1.0)
    for i in range(100):
        tracer.emit(tracer.new_trace(), "stage", topic="t", seq=i)
    spans = tracer.snapshot(topic="t")
    assert len(spans) == 16
    # The ring keeps the NEWEST spans (flight-recorder semantics).
    assert [s["seq"] for s in spans] == list(range(84, 100))


# ---- deterministic sampling ----


def test_sampling_deterministic_under_fixed_seed():
    def sampled_set(seed, n=400, rate=0.5):
        tracer = Tracer(sample=rate, seed=seed)
        return {i for i in range(n) if tracer.start_trace("t")}

    a = sampled_set(seed=42)
    b = sampled_set(seed=42)
    assert a == b  # same seed -> exactly the same kept traces
    c = sampled_set(seed=43)
    assert a != c  # a different seed samples a different subset
    assert 0.3 < len(a) / 400 < 0.7  # and the rate is honored roughly


def test_sampling_edge_rates():
    always = Tracer(sample=1.0)
    assert all(always.start_trace("t") for _ in range(50))
    never = Tracer(sample=0.0)
    assert not any(never.start_trace("t") for _ in range(50))
    # Sampled-out frames record nothing anywhere.
    never.emit(0, "receive", topic="t")
    assert never.snapshot() == []


# ---- flight recorder ----


def test_flight_recorder_dump_on_injected_wedge(tmp_path):
    """A scripted stuck readback (runtime.faults) dead-letters its batch;
    the dead-letter must dump the rings atomically and thread the dump
    path + per-frame trace ids into the dead-letter journal record."""
    injector = FaultInjector(seed=3)
    injector.script("readback", "stuck")
    journal = DeadLetterJournal(str(tmp_path / "dead.jsonl"))
    tracer = Tracer(sample=1.0, dump_dir=str(tmp_path / "flight"),
                    min_dump_interval_s=0.0)
    _pipe, connector, service = _make_service(
        tracer, fault_injector=injector, dead_letter_journal=journal,
        resilience=ResiliencePolicy(readback_deadline_s=0.2))
    service.start(warmup=False)
    try:
        _drive(connector, 4)
        assert service.drain(timeout=10.0)
    finally:
        service.stop()
        journal.close()
    assert service.metrics.counter("frames_dead_lettered") == 4
    dumps = sorted(os.listdir(tmp_path / "flight"))
    assert dumps, "dead-letter did not dump the flight recorder"
    record = json.loads((tmp_path / "flight" / dumps[0]).read_text())
    assert record["reason"] == "dead_letter"
    assert record["extra"]["frames"] == 4
    # Every dead frame has its terminal span in the dump.
    acct = account_spans(record["spans"][FRAME_TOPIC])
    assert acct["drops"] == {"frames_dead_lettered": 4}
    # The journal row carries the dump path + per-frame trace_id/stage.
    rows = [r for r in journal.records() if r["reason"] == "dead_letter"]
    assert rows and rows[0]["dump"] == str(tmp_path / "flight" / dumps[0])
    for frame in rows[0]["frames"]:
        assert frame["stage"] == "readback.dead_letter"
        assert frame["trace_id"]


def test_dead_letter_slices_padded_and_trimmed_provenance(tmp_path):
    """A partial batch dead-letters with count < batch_size (padded metas)
    and count < len(trace_ids) (a brownout trim already settled the
    tail): the journal must get exactly ``count`` rows and the trimmed
    frames must NOT be settled a second time."""
    tracer = Tracer(sample=1.0)
    journal = DeadLetterJournal(str(tmp_path / "dead.jsonl"))
    _pipe, _connector, service = _make_service(
        tracer, dead_letter_journal=journal)
    tids = [tracer.start_trace(FRAME_TOPIC) for _ in range(3)]
    padded_metas = [{"seq": i} for i in range(3)] + [None] * 5  # batch_size pad
    # count=2: the third frame was brownout-trimmed (settled elsewhere).
    service._dead_letter(2, padded_metas, [1.0, 2.0, 3.0], tids,
                         batch=tracer.new_trace())
    journal.close()
    rows = [r for r in journal.records() if r["reason"] == "dead_letter"]
    assert len(rows[0]["frames"]) == 2  # count, not batch_size
    assert [f["meta"] for f in rows[0]["frames"]] == [{"seq": 0}, {"seq": 1}]
    acct = account_spans(tracer.snapshot(topic=FRAME_TOPIC))
    assert acct["drops"] == {"frames_dead_lettered": 2}  # tids[2] untouched


def test_dump_rate_limit_and_retention(tmp_path):
    tracer = Tracer(sample=1.0, dump_dir=str(tmp_path), keep_dumps=3,
                    min_dump_interval_s=60.0)
    tracer.emit(tracer.new_trace(), "s", topic="t")
    assert tracer.dump("dead_letter") is not None
    assert tracer.dump("dead_letter") is None  # rate-limited
    assert tracer.dump("dead_letter", force=True) is not None
    for _ in range(5):
        assert tracer.dump("end", force=True) is not None
    names = [n for n in os.listdir(tmp_path) if n.startswith("flight-")]
    assert len(names) == 3  # retention pruned the oldest


def test_dump_without_dir_is_none():
    tracer = Tracer(sample=1.0)
    assert tracer.dump("anything", force=True) is None


# ---- lifecycle spans ----


def test_lifecycle_context_manager_records_errors():
    tracer = Tracer(sample=1.0)
    with tracer.lifecycle("checkpoint", wal_seq=7) as attrs:
        attrs["rows"] = 3
    with pytest.raises(RuntimeError):
        with tracer.lifecycle("checkpoint"):
            raise RuntimeError("boom")
    spans = tracer.snapshot(topic=tracing.LIFECYCLE_TOPIC)
    assert len(spans) == 2
    assert spans[0]["ok"] and spans[0]["rows"] == 3 and spans[0]["wal_seq"] == 7
    assert spans[1]["ok"] is False and "boom" in spans[1]["error"]


def test_brownout_transition_emits_lifecycle_span():
    from opencv_facerecognizer_tpu.runtime.resilience import BrownoutPolicy

    tracer = Tracer(sample=1.0)
    _pipe, _connector, service = _make_service(
        tracer, brownout=BrownoutPolicy(queue_wait_s=0.01, dwell_s=0.0))
    service._note_queue_wait(1.0)  # EWMA over threshold -> level 1
    spans = [s for s in tracer.snapshot(topic=tracing.LIFECYCLE_TOPIC)
             if s["stage"] == "brownout"]
    assert spans and spans[0]["level"] == 1 and spans[0]["from_level"] == 0


# ---- expo endpoint ----


def _get(url):
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.status, json.loads(resp.read().decode())


def test_expo_endpoint_read_only_contract():
    tracer = Tracer(sample=1.0)
    _pipe, connector, service = _make_service(tracer)
    service.start(warmup=False)
    expo = ExpoServer(service, tracer=tracer, metrics=service.metrics,
                      port=0, bench_path=os.path.join(REPO_ROOT,
                                                      "BENCH_DETAIL.json"))
    expo.start()
    base = f"http://{expo.host}:{expo.port}"
    try:
        _drive(connector, 8)
        assert service.drain(timeout=10.0)

        status, index = _get(base + "/")
        assert status == 200 and "/metrics" in index["endpoints"]
        status, metrics = _get(base + "/metrics")
        assert status == 200
        assert metrics["frames_completed"] == 8
        status, ledger = _get(base + "/ledger")
        assert ledger["admitted"] == 8 and ledger["in_system"] == 0
        status, brownout = _get(base + "/brownout")
        assert brownout["level"] == 0
        status, spans = _get(base + f"/spans?topic={FRAME_TOPIC}&n=1000")
        assert {s["stage"] for s in spans["spans"]} \
            == {"receive", "queue_wait", "settle"}
        status, attribution = _get(base + "/attribution")
        assert status == 200 and "device_busy_fraction" in attribution

        # Unknown path -> 404; every mutating verb -> 405 (read-only).
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(base + "/nope")
        assert err.value.code == 404
        for method in ("POST", "PUT", "DELETE"):
            req = urllib.request.Request(base + "/metrics", data=b"{}",
                                         method=method)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5.0)
            assert err.value.code == 405, method
        assert service.metrics.counter("expo_requests") > 0
    finally:
        expo.stop()
        service.stop()


# ---- stage attribution ----


def test_device_busy_fraction_interval_union():
    now = 100.0
    spans = [
        {"stage": "ready_wait", "t0": 90.0, "dur": 2.0},
        {"stage": "ready_wait", "t0": 91.0, "dur": 2.0},  # overlaps above
        {"stage": "ready_wait", "t0": 95.0, "dur": 1.0},
        {"stage": "dispatch", "t0": 96.0, "dur": 50.0},  # wrong stage
        {"stage": "ready_wait", "t0": 10.0, "dur": 1.0},  # out of window
    ]
    busy = tracing.device_busy_fraction(spans, window_s=10.0, now=now)
    assert busy == pytest.approx((3.0 + 1.0) / 10.0)


def test_fold_attribution_sets_registered_gauges():
    tracer = Tracer(sample=1.0)
    batch_tid = tracer.new_trace()
    tracer.emit(batch_tid, "dispatch", topic=tracing.BATCH_TOPIC,
                dur=0.001, bucket=8, frames=8)
    tracer.emit(batch_tid, "ready_wait", topic=tracing.BATCH_TOPIC, dur=0.01)
    metrics = Metrics()
    gauges = fold_attribution(tracer, metrics,
                              bench_path=os.path.join(REPO_ROOT,
                                                      "BENCH_DETAIL.json"))
    assert "device_busy_fraction" in gauges
    assert metrics.gauge("device_busy_fraction") >= 0.0
    # Stage shares come from the committed bench stage table for the
    # observed bucket, sum to ~1, and ride registered gauge names.
    shares = {k: v for k, v in gauges.items()
              if k.startswith("stage_share_b8_")}
    if shares:  # only when BENCH_DETAIL.json carries the stage table
        assert sum(shares.values()) == pytest.approx(1.0)
        assert metrics.gauge("stage_share_b8_detect") == shares[
            "stage_share_b8_detect"]


# ---- Metrics empty/short-window fixes (satellite) ----


def test_metrics_summary_empty_window_reports_nulls():
    metrics = Metrics()
    metrics.observe("queue_wait", 0.005)
    assert metrics.summary()["queue_wait_p50_ms"] == pytest.approx(5.0, rel=0.1)  # histogram bucket precision
    metrics.reset_window("queue_wait")
    summary = metrics.summary()
    # Explicit nulls — never a stale value, a zero, or a KeyError.
    assert summary["queue_wait_p50_ms"] is None
    assert summary["queue_wait_p95_ms"] is None
    assert np.isnan(metrics.percentile("queue_wait", 50))
    # JSON-safe (the expo endpoint serves this dict verbatim).
    json.dumps(summary)


def test_metrics_reset_window_scopes():
    metrics = Metrics()
    metrics.observe("a", 0.001)
    metrics.observe("b", 0.002)
    metrics.incr("frames_completed", 3)
    metrics.reset_window("a")
    summary = metrics.summary()
    assert summary["a_p50_ms"] is None
    assert summary["b_p50_ms"] == pytest.approx(2.0, rel=0.1)  # histogram bucket precision
    metrics.reset_window()
    assert metrics.summary()["b_p50_ms"] is None
    # Counters are untouched by window resets.
    assert metrics.counter("frames_completed") == 3


# ---- journal CLI trace filter (satellite) ----


def test_journal_cli_prints_trace_and_stage(tmp_path, capsys):
    from opencv_facerecognizer_tpu.runtime import journal as journal_mod

    path = str(tmp_path / "dead.jsonl")
    journal = DeadLetterJournal(path)
    journal.append("stale", [journal.frame_entry(
        meta={"seq": 9}, enqueue_ts=1.0, priority=1, trace_id=77,
        stage="batcher.stale")])
    journal.append("dead_letter", [journal.frame_entry(
        meta={"seq": 10}, trace_id=78, stage="readback.dead_letter")],
        dump="/tmp/flight-x.json")
    journal.close()
    assert journal_mod.main([path, "--trace", "78"]) == 0
    lines = [json.loads(line) for line in
             capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 1
    assert lines[0]["frames"][0]["trace_id"] == 78
    assert lines[0]["frames"][0]["stage"] == "readback.dead_letter"
    assert lines[0]["dump"] == "/tmp/flight-x.json"


# ---- span JSONL export ----


def test_span_sink_streams_jsonl(tmp_path):
    from opencv_facerecognizer_tpu.utils.tracing import make_span_journal

    sink = make_span_journal(str(tmp_path / "spans.jsonl"))
    tracer = Tracer(sample=1.0, span_sink=sink)
    tid = tracer.new_trace()
    tracer.emit(tid, "receive", topic="frames", verdict="admitted")
    tracer.emit(tid, "settle", topic="frames", outcome="completed")
    sink.close()
    rows = [json.loads(line) for line in
            (tmp_path / "spans.jsonl").read_text().splitlines()]
    assert [r["stage"] for r in rows] == ["receive", "settle"]
    assert all(r["trace"] == tid and r["topic"] == "frames" for r in rows)
