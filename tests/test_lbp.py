"""LBP operators vs a pure-NumPy reference implementation (SURVEY.md §4)."""

import math

import numpy as np

from opencv_facerecognizer_tpu.ops import lbp

RNG = np.random.default_rng(1)
IMG = RNG.integers(0, 256, size=(12, 14)).astype(np.float32)


def numpy_original_lbp(x):
    h, w = x.shape
    out = np.zeros((h - 2, w - 2), dtype=np.int32)
    offs = [(-1, -1), (-1, 0), (-1, 1), (0, 1), (1, 1), (1, 0), (1, -1), (0, -1)]
    for yy in range(1, h - 1):
        for xx in range(1, w - 1):
            c = x[yy, xx]
            code = 0
            for i, (dy, dx) in enumerate(offs):
                if x[yy + dy, xx + dx] >= c:
                    code |= 1 << (7 - i)
            out[yy - 1, xx - 1] = code
    return out


def numpy_circular_samples(x, radius, neighbors):
    h, w = x.shape
    samples = np.zeros((neighbors, h - 2 * radius, w - 2 * radius), dtype=np.float64)
    for k in range(neighbors):
        theta = 2.0 * math.pi * k / neighbors
        dy, dx = -radius * math.sin(theta), radius * math.cos(theta)
        fy, fx = math.floor(dy), math.floor(dx)
        ty, tx = dy - fy, dx - fx
        taps = [((1 - ty) * (1 - tx), 0, 0), ((1 - ty) * tx, 0, 1),
                (ty * (1 - tx), 1, 0), (ty * tx, 1, 1)]
        for yy in range(radius, h - radius):
            for xx in range(radius, w - radius):
                y0, x0 = yy + fy, xx + fx
                v = sum(wt * x[y0 + oy, x0 + ox] for wt, oy, ox in taps if wt > 1e-12)
                samples[k, yy - radius, xx - radius] = v
    return samples


def test_original_lbp_matches_reference():
    got = np.asarray(lbp.original_lbp(IMG))
    np.testing.assert_array_equal(got, numpy_original_lbp(IMG))


def test_original_lbp_batched():
    batch = np.stack([IMG, IMG[::-1].copy()])
    got = np.asarray(lbp.original_lbp(batch))
    assert got.shape == (2, 10, 12)
    np.testing.assert_array_equal(got[0], numpy_original_lbp(IMG))
    np.testing.assert_array_equal(got[1], numpy_original_lbp(IMG[::-1]))


def test_extended_lbp_matches_reference():
    for radius, neighbors in [(1, 8), (2, 8), (2, 12)]:
        got = np.asarray(lbp.extended_lbp(IMG, radius, neighbors))
        samples = numpy_circular_samples(IMG.astype(np.float64), radius, neighbors)
        c = IMG[radius:-radius, radius:-radius]
        want = np.zeros_like(c, dtype=np.int64)
        for k in range(neighbors):
            want += (1 << k) * (samples[k] >= c - 1e-5)
        # Tolerate the rare off-by-one-bit where a bilinear sample sits
        # exactly on the center value (f32 vs f64 rounding).
        mismatch = np.mean(got != want)
        assert mismatch < 0.02, f"r={radius} P={neighbors}: {mismatch:.3f} codes differ"


def test_extended_lbp_shapes_and_range():
    out = np.asarray(lbp.extended_lbp(IMG, radius=2, neighbors=10))
    assert out.shape == (8, 10)
    assert out.min() >= 0 and out.max() < 1 << 10


def test_var_lbp_is_nonnegative_and_shaped():
    out = np.asarray(lbp.var_lbp(IMG, radius=1, neighbors=8))
    assert out.shape == (10, 12)
    assert np.all(out >= 0)
    # constant image has zero local variance
    const = np.full((8, 8), 7.0, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(lbp.var_lbp(const)), 0.0, atol=1e-6)
