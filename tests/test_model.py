"""PredictableModel composition + pickle-free checkpoint roundtrip
(SURVEY.md §3.4, §5.4): the minimum end-to-end slice of §7.4."""

import os

import numpy as np
import pytest

from opencv_facerecognizer_tpu.models import (
    ChainOperator,
    ExtendedPredictableModel,
    Fisherfaces,
    NearestNeighbor,
    PCA,
    PredictableModel,
    SpatialHistogram,
    TanTriggsPreprocessing,
)
from opencv_facerecognizer_tpu.ops.distance import ChiSquareDistance, EuclideanDistance
from opencv_facerecognizer_tpu.utils import serialization
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

X, Y, NAMES = make_synthetic_faces(num_subjects=6, per_subject=8, size=(24, 24), seed=3)


def test_eigenfaces_model_end_to_end():
    model = PredictableModel(PCA(num_components=20), NearestNeighbor(EuclideanDistance(), k=1))
    model.compute(X, Y)
    pred, info = model.predict(X)
    assert (np.asarray(pred) == Y).mean() == 1.0
    assert info["distances"].shape == (len(Y), 1)


def test_fisherfaces_model_batch_and_single():
    model = PredictableModel(Fisherfaces(), NearestNeighbor(k=1))
    model.compute(X, Y)
    label, info = model.predict(X[10])
    assert int(label) == int(Y[10])
    pred, _ = model.predict(X)
    assert (np.asarray(pred) == Y).mean() == 1.0


def test_lbph_model_with_chisquare():
    model = PredictableModel(
        SpatialHistogram(sz=(4, 4)), NearestNeighbor(ChiSquareDistance(), k=1)
    )
    model.compute(X, Y)
    pred, _ = model.predict(X)
    assert (np.asarray(pred) == Y).mean() == 1.0


def test_type_validation():
    with pytest.raises(TypeError):
        PredictableModel(PCA(5), PCA(5))
    with pytest.raises(TypeError):
        PredictableModel(NearestNeighbor(), NearestNeighbor())


@pytest.mark.parametrize(
    "make_model",
    [
        lambda: PredictableModel(PCA(15), NearestNeighbor(EuclideanDistance(), k=1)),
        lambda: PredictableModel(
            ChainOperator(TanTriggsPreprocessing(), Fisherfaces()),
            NearestNeighbor(k=3),
        ),
        lambda: ExtendedPredictableModel(
            SpatialHistogram(sz=(2, 2)),
            NearestNeighbor(ChiSquareDistance(), k=1),
            image_size=(24, 24),
            subject_names=NAMES,
        ),
    ],
    ids=["eigenfaces", "chain-fisherfaces", "extended-lbph"],
)
def test_save_load_roundtrip_preserves_predictions(tmp_path, make_model):
    model = make_model()
    model.compute(X, Y)
    pred_before, _ = model.predict(X)
    path = os.path.join(tmp_path, "model.ckpt")
    serialization.save_model(path, model)
    restored = serialization.load_model(path)
    pred_after, _ = restored.predict(X)
    np.testing.assert_array_equal(np.asarray(pred_before), np.asarray(pred_after))
    if isinstance(model, ExtendedPredictableModel):
        assert restored.image_size == (24, 24)
        assert restored.subject_names == NAMES
        assert restored.subject_name(0) == NAMES[0]


def test_load_model_truncated_or_garbage_raises_corrupt(tmp_path):
    """A truncated or garbage checkpoint must raise the explicit
    CheckpointCorruptError (recovery code falls back on it), never an
    opaque msgpack decode exception — and save_model's atomic write must
    leave no tmp debris."""
    model = PredictableModel(PCA(5), NearestNeighbor())
    model.compute(X, Y)
    path = os.path.join(tmp_path, "model.ckpt")
    serialization.save_model(path, model)
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    blob = open(path, "rb").read()
    truncated = os.path.join(tmp_path, "trunc.ckpt")
    open(truncated, "wb").write(blob[: len(blob) // 3])
    with pytest.raises(serialization.CheckpointCorruptError):
        serialization.load_model(truncated)
    garbage = os.path.join(tmp_path, "garbage.ckpt")
    open(garbage, "wb").write(b"\x00\xffnot-a-checkpoint" * 16)
    with pytest.raises(serialization.CheckpointCorruptError):
        serialization.load_model(garbage)
    # CheckpointCorruptError stays a ValueError for legacy handlers.
    assert issubclass(serialization.CheckpointCorruptError, ValueError)
    # The intact original still round-trips after all that.
    restored = serialization.load_model(path)
    p1, _ = model.predict(X)
    p2, _ = restored.predict(X)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))


def test_save_model_keep_previous_rotates(tmp_path):
    model = PredictableModel(PCA(5), NearestNeighbor())
    model.compute(X, Y)
    path = os.path.join(tmp_path, "model.ckpt")
    for _ in range(3):
        serialization.save_model(path, model, keep_previous=2)
    assert os.path.exists(path)
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")
    # every retained generation still loads
    for p in (path, path + ".1", path + ".2"):
        serialization.load_model(p)


def test_checkpoint_has_no_pickle(tmp_path):
    model = PredictableModel(PCA(5), NearestNeighbor())
    model.compute(X, Y)
    path = os.path.join(tmp_path, "model.ckpt")
    serialization.save_model(path, model)
    blob = open(path, "rb").read()
    assert b"__reduce__" not in blob and b"cnumpy" not in blob
    # future-version checkpoints are refused, not mis-read
    import json

    from flax import serialization as fs

    payload = fs.msgpack_restore(blob)
    payload["header"]["format_version"] = 99
    bad = os.path.join(tmp_path, "bad.ckpt")
    open(bad, "wb").write(fs.msgpack_serialize(payload))
    with pytest.raises(ValueError):
        serialization.load_model(bad)
