"""PredictableModel composition + pickle-free checkpoint roundtrip
(SURVEY.md §3.4, §5.4): the minimum end-to-end slice of §7.4."""

import os

import numpy as np
import pytest

from opencv_facerecognizer_tpu.models import (
    ChainOperator,
    ExtendedPredictableModel,
    Fisherfaces,
    NearestNeighbor,
    PCA,
    PredictableModel,
    SpatialHistogram,
    TanTriggsPreprocessing,
)
from opencv_facerecognizer_tpu.ops.distance import ChiSquareDistance, EuclideanDistance
from opencv_facerecognizer_tpu.utils import serialization
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

X, Y, NAMES = make_synthetic_faces(num_subjects=6, per_subject=8, size=(24, 24), seed=3)


def test_eigenfaces_model_end_to_end():
    model = PredictableModel(PCA(num_components=20), NearestNeighbor(EuclideanDistance(), k=1))
    model.compute(X, Y)
    pred, info = model.predict(X)
    assert (np.asarray(pred) == Y).mean() == 1.0
    assert info["distances"].shape == (len(Y), 1)


def test_fisherfaces_model_batch_and_single():
    model = PredictableModel(Fisherfaces(), NearestNeighbor(k=1))
    model.compute(X, Y)
    label, info = model.predict(X[10])
    assert int(label) == int(Y[10])
    pred, _ = model.predict(X)
    assert (np.asarray(pred) == Y).mean() == 1.0


def test_lbph_model_with_chisquare():
    model = PredictableModel(
        SpatialHistogram(sz=(4, 4)), NearestNeighbor(ChiSquareDistance(), k=1)
    )
    model.compute(X, Y)
    pred, _ = model.predict(X)
    assert (np.asarray(pred) == Y).mean() == 1.0


def test_type_validation():
    with pytest.raises(TypeError):
        PredictableModel(PCA(5), PCA(5))
    with pytest.raises(TypeError):
        PredictableModel(NearestNeighbor(), NearestNeighbor())


@pytest.mark.parametrize(
    "make_model",
    [
        lambda: PredictableModel(PCA(15), NearestNeighbor(EuclideanDistance(), k=1)),
        lambda: PredictableModel(
            ChainOperator(TanTriggsPreprocessing(), Fisherfaces()),
            NearestNeighbor(k=3),
        ),
        lambda: ExtendedPredictableModel(
            SpatialHistogram(sz=(2, 2)),
            NearestNeighbor(ChiSquareDistance(), k=1),
            image_size=(24, 24),
            subject_names=NAMES,
        ),
    ],
    ids=["eigenfaces", "chain-fisherfaces", "extended-lbph"],
)
def test_save_load_roundtrip_preserves_predictions(tmp_path, make_model):
    model = make_model()
    model.compute(X, Y)
    pred_before, _ = model.predict(X)
    path = os.path.join(tmp_path, "model.ckpt")
    serialization.save_model(path, model)
    restored = serialization.load_model(path)
    pred_after, _ = restored.predict(X)
    np.testing.assert_array_equal(np.asarray(pred_before), np.asarray(pred_after))
    if isinstance(model, ExtendedPredictableModel):
        assert restored.image_size == (24, 24)
        assert restored.subject_names == NAMES
        assert restored.subject_name(0) == NAMES[0]


def test_checkpoint_has_no_pickle(tmp_path):
    model = PredictableModel(PCA(5), NearestNeighbor())
    model.compute(X, Y)
    path = os.path.join(tmp_path, "model.ckpt")
    serialization.save_model(path, model)
    blob = open(path, "rb").read()
    assert b"__reduce__" not in blob and b"cnumpy" not in blob
    # future-version checkpoints are refused, not mis-read
    import json

    from flax import serialization as fs

    payload = fs.msgpack_restore(blob)
    payload["header"]["format_version"] = 99
    bad = os.path.join(tmp_path, "bad.ckpt")
    open(bad, "wb").write(fs.msgpack_serialize(payload))
    with pytest.raises(ValueError):
        serialization.load_model(bad)
