"""Versioned model registry tests (``runtime.registry`` + the
multi-role state machinery, ISSUE 18): the durable checksummed manifest
with monotonic per-role versions, the detection-parity swap gate and its
refusal of a degraded candidate, the FaceGate retrain that cuts over
atomically with a detector swap, the per-role tracker/cascade cache
flush, WAL-fenced cutover recovery (complete-or-abandon), replica
park/re-anchor on the registry fence, the offline verifier's manifest +
multi-role walk rc contract, the CLI startup fences and offline swap
runbook, ``GET /registry``, and the fast deterministic tier-1 variant of
``scripts/chaos_soak.py --scenario registry``."""

import glob
import importlib.util
import json
import os
import types
import urllib.request

import numpy as np
import pytest

from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.runtime import (
    FakeConnector,
    FaultInjector,
    ModelRegistry,
    ReadReplica,
    RecognizerService,
    RegistryStateError,
    RegistrySwapCoordinator,
    RolloutGateError,
    StateLifecycle,
    registry_params_path,
)
from opencv_facerecognizer_tpu.runtime.expo import ExpoServer
from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
from opencv_facerecognizer_tpu.runtime.registry import (
    DetectionParity,
    _file_sha256,
    box_iou,
)
from opencv_facerecognizer_tpu.runtime.tracker import (
    IdentityTracker,
    TrackerConfig,
)
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.metrics import Metrics
from opencv_facerecognizer_tpu.utils.tracing import Tracer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8

# yxyx corner boxes: OLD serving verdict, an AGREEING candidate (IoU
# ~0.78) and a DISAGREEING one (IoU 0.0) for the parity window.
OLD_BOX = (8.0, 8.0, 24.0, 24.0)
GOOD_BOX = (9.0, 9.0, 25.0, 25.0)
BAD_BOX = (0.0, 0.0, 6.0, 6.0)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _writer(tmp_path, mesh, **kw):
    metrics = kw.pop("metrics", Metrics())
    gallery = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names = []
    state = StateLifecycle(str(tmp_path), metrics=metrics,
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9, **kw)
    state.bind(gallery, names)
    state.attach_registry(ModelRegistry(str(tmp_path), metrics=metrics))
    return state, gallery, names, metrics


def _enroll(state, gallery, names, rng, i, n=1):
    emb = rng.normal(size=(n, DIM)).astype(np.float32)
    labels = np.full(n, i, np.int32)
    names.append(f"s{i}")
    state.append_enrollment(emb, labels, subject=f"s{i}", label=i,
                            apply_fn=lambda e=emb, l=labels:
                                gallery.add(e, l))
    return emb


def _stage_params(state_dir, role, version, payload=b"params-blob"):
    path = registry_params_path(str(state_dir), role, version)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as fh:
        fh.write(payload + f"-{role}-v{version}".encode())
    return path, _file_sha256(path)


def _det(box):
    def fn(frame):
        del frame  # synthetic verdict, content-independent
        return [np.asarray(box, np.float32)]
    return fn


def _frames(n, hw=(16, 16)):
    return [np.zeros(hw, np.float32) for _ in range(n)]


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, json.loads(resp.read().decode())


# ---------- manifest: durability + monotonicity ----------


def test_manifest_eager_write_and_monotonic_install(tmp_path):
    metrics = Metrics()
    reg = ModelRegistry(str(tmp_path), metrics=metrics)
    # Eager write: the manifest exists from construction, so recovery
    # and readers never have to guess versions.
    assert os.path.exists(os.path.join(str(tmp_path), "registry.json"))
    assert reg.stamp() == {"embedder": 1, "detector": 1, "cascade": 1}
    assert metrics.gauge(mn.MODEL_VERSION_PREFIX + "detector") == 1
    reg.install("detector", 2, params_path="p", params_sha256="x")
    assert reg.version("detector") == 2
    assert metrics.gauge(mn.MODEL_VERSION_PREFIX + "detector") == 2
    # A second mount reads the installed version back.
    other = ModelRegistry(str(tmp_path), readonly=True)
    assert other.version("detector") == 2
    # Monotonic: versions never move backward or repeat...
    with pytest.raises(ValueError):
        reg.install("detector", 2)
    with pytest.raises(ValueError):
        reg.install("detector", 1)
    # ...and a retired (abandoned-swap) number is burned forever.
    reg.retire("cascade", 2)
    assert reg.version("cascade") == 1  # retirement never serves
    with pytest.raises(ValueError):
        reg.install("cascade", 2)
    reg.install("cascade", 3)
    assert reg.version("cascade") == 3


def test_manifest_detects_torn_and_corrupt_bytes(tmp_path):
    ModelRegistry(str(tmp_path))
    path = os.path.join(str(tmp_path), "registry.json")
    # Bit-flip inside the roles object: checksum mismatch = corrupt.
    doc = json.load(open(path))
    doc["roles"]["detector"]["version"] = 9
    with open(path, "w") as fh:
        json.dump(doc, fh)
    with pytest.raises(RegistryStateError) as err:
        ModelRegistry.read_manifest(path)
    assert err.value.reason == "corrupt"
    # Torn write (not even JSON): unreadable — proves nothing.
    with open(path, "wb") as fh:
        fh.write(b"\x80\x81 torn manifest bytes")
    with pytest.raises(RegistryStateError) as err:
        ModelRegistry.read_manifest(path)
    assert err.value.reason == "unreadable"


# ---------- the detection-parity window ----------


def test_box_iou_and_parity_verdict_match():
    assert box_iou(OLD_BOX, OLD_BOX) == pytest.approx(1.0)
    assert box_iou(OLD_BOX, BAD_BOX) == 0.0
    assert box_iou(OLD_BOX, GOOD_BOX) == pytest.approx(
        (15.0 * 15.0) / (2 * 16.0 * 16.0 - 15.0 * 15.0))
    metrics = Metrics()
    parity = DetectionParity(_det(OLD_BOX), _det(GOOD_BOX),
                             min_samples=4, metrics=metrics)
    assert not parity.ok()  # below the sample floor nothing passes
    parity.score(_frames(4))
    assert parity.ok() and parity.agreement == 1.0
    assert metrics.gauge(mn.REGISTRY_PARITY_AGREEMENT) == 1.0
    # Verdict mismatch: the old side saw a face, the candidate none.
    miss = DetectionParity(_det(OLD_BOX), lambda f: [], min_samples=4)
    miss.score(_frames(4))
    assert not miss.ok() and miss.agreement == 0.0


# ---------- the gated swap: refusal, retrain, flush, rollback ----------


def test_detector_swap_parity_gate_refuses_degraded(tmp_path, mesh):
    rng = np.random.default_rng(0)
    state, gallery, names, metrics = _writer(tmp_path, mesh)
    _enroll(state, gallery, names, rng, 0)
    seq_before = state.wal_seq
    co = RegistrySwapCoordinator(
        state, state.registry, "detector", 2,
        old_detect_fn=_det(OLD_BOX), new_detect_fn=_det(BAD_BOX),
        parity_min_samples=8, metrics=metrics)
    co.score_parity(_frames(12))
    assert co.phase == "parity" and not co.parity_ok()
    with pytest.raises(RolloutGateError):
        co.cutover()
    # The refusal is total: no fence burned, no manifest movement.
    assert metrics.counter(mn.REGISTRY_SWAPS_BLOCKED) == 1
    assert state.registry.version("detector") == 1
    assert state.wal_seq == seq_before
    # A coordinator with NO parity window wired refuses too (force-only).
    blind = RegistrySwapCoordinator(state, state.registry, "cascade", 2,
                                    metrics=metrics)
    with pytest.raises(RolloutGateError):
        blind.cutover()
    # The embedder is not this coordinator's role: it needs the staged
    # re-embed machinery, not a params swap.
    with pytest.raises(ValueError):
        RegistrySwapCoordinator(state, state.registry, "embedder", 2)
    state.close()


def test_detector_swap_retrains_facegate_against_candidate(tmp_path, mesh):
    from opencv_facerecognizer_tpu.models.cascade import (
        FaceGate, evaluate_gate,
    )
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    rng = np.random.default_rng(1)
    state, gallery, names, metrics = _writer(tmp_path, mesh)
    _enroll(state, gallery, names, rng, 0)
    scenes, boxes, counts = make_synthetic_scenes(96, (96, 96), max_faces=2,
                                                  seed=3)

    def retrain():
        return FaceGate().train(scenes, boxes, counts, steps=300,
                                batch_size=32)

    path, sha = _stage_params(tmp_path, "detector", 2)
    co = RegistrySwapCoordinator(
        state, state.registry, "detector", 2,
        old_detect_fn=_det(OLD_BOX), new_detect_fn=_det(GOOD_BOX),
        params_path=path, gate_retrain_fn=retrain,
        parity_min_samples=8, metrics=metrics)
    assert co.params_sha256 == sha
    co.score_parity(_frames(8))
    assert co.phase == "ready"
    co.cutover()
    # The pair cut over atomically: the retrain ran BEFORE the fence.
    assert co.gate_retrained is not None
    assert metrics.counter(mn.REGISTRY_GATE_RETRAINS) == 1
    assert metrics.counter(mn.REGISTRY_SWAPS) == 1
    assert state.registry.version("detector") == 2
    assert state.registry.describe("detector")["params_sha256"] == sha

    # The retrained stage-1 gate holds the cascade's operating point
    # against the NEW detector's verdicts: recall >= 0.99 on a held-out
    # scene set, with a ground-truth-exact stage-2 oracle.
    class OracleDetector:
        def __init__(self, gt_boxes, gt_counts):
            self.gt_boxes, self.gt_counts, self.pos = gt_boxes, gt_counts, 0

        def detect_batch(self, chunk):
            sl = slice(self.pos, self.pos + len(chunk))
            self.pos += len(chunk)
            b = self.gt_boxes[sl]
            valid = (np.arange(b.shape[1])[None, :]
                     < self.gt_counts[sl][:, None])
            return b, valid.astype(np.float32), valid

    held, held_boxes, held_counts = make_synthetic_scenes(
        48, (96, 96), max_faces=2, seed=99)
    verdict = evaluate_gate(co.gate_retrained,
                            OracleDetector(held_boxes, held_counts),
                            held, gt_counts=held_counts)
    assert verdict["stage1_recall"] >= 0.99, verdict
    state.close()


def test_cutover_flushes_tracker_cache_per_role(tmp_path, mesh):
    rng = np.random.default_rng(2)
    state, gallery, names, metrics = _writer(tmp_path, mesh)
    _enroll(state, gallery, names, rng, 0)
    tracker = IdentityTracker(TrackerConfig(reverify_frames=4),
                              metrics=metrics)
    hw = (64, 64)
    pipeline = InstantPipeline(hw)
    svc = RecognizerService(
        pipeline, FakeConnector(), batch_size=4, frame_shape=hw,
        flush_timeout=0.02, inflight_depth=2, similarity_threshold=0.0,
        metrics=metrics, tracker=tracker)
    svc.registry = state.registry

    def confirm_track():
        frame = np.random.default_rng(0).integers(
            20, 90, size=hw).astype(np.float32)
        frame[10:26, 8:24] = 160.0
        face = {"box": [8, 10, 24, 26], "label": 0, "name": "s0",
                "similarity": 0.9, "detection_score": 0.9}
        for _ in range(2):
            tracker.update("cam0", [face], frame,
                           embedder_version=state.registry.stamp_key())

    confirm_track()
    assert tracker.stats()["tracks_live"] == 1
    co = RegistrySwapCoordinator(
        state, state.registry, "detector", 2,
        old_detect_fn=_det(OLD_BOX), new_detect_fn=_det(GOOD_BOX),
        parity_min_samples=4, flush_fn=svc.flush_model_caches,
        metrics=metrics)
    co.score_parity(_frames(4))
    co.cutover()
    # The detector cutover emptied the PR 17 identity cache eagerly (the
    # same flush covers the PR 13 cascade verdicts living in those
    # cached results).
    assert tracker.stats()["tracks_live"] == 0
    assert metrics.counter(mn.REGISTRY_CACHE_FLUSHES) == 1
    # A CASCADE cutover flushes again: per role, not once globally.
    confirm_track()
    assert tracker.stats()["tracks_live"] == 1
    RegistrySwapCoordinator(
        state, state.registry, "cascade", 2,
        flush_fn=svc.flush_model_caches, metrics=metrics).cutover(force=True)
    assert tracker.stats()["tracks_live"] == 0
    assert metrics.counter(mn.REGISTRY_CACHE_FLUSHES) == 2
    state.close()


def test_watch_regression_auto_rolls_back_with_flight_dump(tmp_path, mesh):
    rng = np.random.default_rng(3)
    state_dir = tmp_path / "state"
    trace_dir = tmp_path / "traces"
    state, gallery, names, metrics = _writer(state_dir, mesh)
    _enroll(state, gallery, names, rng, 0)
    tracer = Tracer(dump_dir=str(trace_dir), metrics=metrics,
                    min_dump_interval_s=0.0)
    behave = {"good": True}

    def candidate(frame):
        del frame
        return [np.asarray(GOOD_BOX if behave["good"] else BAD_BOX,
                           np.float32)]

    restored = []
    co = RegistrySwapCoordinator(
        state, state.registry, "detector", 2,
        old_detect_fn=_det(OLD_BOX), new_detect_fn=candidate,
        rollback_install_fn=lambda: restored.append(True),
        parity_min_samples=6, watch_min_samples=6, metrics=metrics,
        tracer=tracer)
    co.score_parity(_frames(6))
    co.cutover()
    assert co.phase == "watch"
    assert state.registry.version("detector") == 2
    # The candidate regresses INSIDE the watch window: the live samples
    # now disagree, and a completed window below the gate rolls back at
    # the NEXT monotonic version — number 2 is never reused.
    behave["good"] = False
    co.score_parity(_frames(6))
    assert co.phase == "rolled_back"
    assert restored == [True]
    assert state.registry.version("detector") == 3
    assert metrics.counter(mn.REGISTRY_AUTO_ROLLBACKS) == 1
    dumps = glob.glob(os.path.join(str(trace_dir),
                                   "flight-*registry_auto_rollback*.json"))
    assert dumps, "auto-rollback left no flight dump"
    with open(dumps[-1]) as fh:
        dump = json.load(fh)
    status = dump["extra"]["registry_swap"]
    assert status["role"] == "detector" and status["to_version"] == 2
    assert status["parity"]["agreement"] < status["parity"]["threshold"]
    state.close()


# ---------- recovery: complete-or-abandon the fenced swap ----------


def test_recovery_completes_fenced_detector_swap(tmp_path, mesh):
    rng = np.random.default_rng(4)
    injector = FaultInjector(seed=4)
    state, gallery, names, _m = _writer(tmp_path, mesh,
                                        fault_injector=injector)
    for i in range(3):
        _enroll(state, gallery, names, rng, i)
    assert state.checkpoint_now(wait=True)  # a pre-swap anchor
    _enroll(state, gallery, names, rng, 3)  # WAL-only row
    path, sha = _stage_params(tmp_path, "detector", 2)
    injector.script("cutover", "crash_after_record")
    with pytest.raises(InjectedCrashError):
        state.perform_registry_cutover("detector", 2, params_path=path,
                                       params_sha256=sha)
    # The dying process fsynced the fence but never installed: on-disk
    # manifest still serves v1.
    assert ModelRegistry(str(tmp_path), readonly=True) \
        .version("detector") == 1
    # "Restart": recovery verifies the staged params against the fence's
    # checksum and COMPLETES the swap.
    g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names2, m2 = [], Metrics()
    state2 = StateLifecycle(str(tmp_path), metrics=m2)
    report = state2.recover(g2, names2)
    done = report["completed_registry_swaps"]
    assert [(d["role"], d["to_version"]) for d in done] == [("detector", 2)]
    assert m2.counter(mn.REGISTRY_SWAPS_COMPLETED_RECOVERY) == 1
    assert state2.registry.version("detector") == 2  # auto-attached
    assert names2 == names and g2.size == 4
    state.close()
    state2.close()


def test_recovery_abandons_damaged_candidate_and_retires(tmp_path, mesh):
    rng = np.random.default_rng(5)
    injector = FaultInjector(seed=5)
    state, gallery, names, _m = _writer(tmp_path, mesh,
                                        fault_injector=injector)
    _enroll(state, gallery, names, rng, 0)
    path, sha = _stage_params(tmp_path, "detector", 2)
    injector.script("cutover", "crash_after_record")
    with pytest.raises(InjectedCrashError):
        state.perform_registry_cutover("detector", 2, params_path=path,
                                       params_sha256=sha)
    # Media damage after the fence fsynced: the staged bytes rot.
    with open(path, "ab") as fh:
        fh.write(b"bitrot")
    g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    m2 = Metrics()
    state2 = StateLifecycle(str(tmp_path), metrics=m2)
    report = state2.recover(g2, [])
    gone = report["abandoned_registry_swaps"]
    assert [(d["role"], d["to_version"]) for d in gone] == [("detector", 2)]
    assert m2.counter(mn.REGISTRY_SWAPS_ABANDONED_RECOVERY) == 1
    # The role never served v2 — and the number is burned, not reusable.
    assert state2.registry.version("detector") == 1
    with pytest.raises(ValueError):
        state2.registry.install("detector", 2)
    state2.registry.install("detector", 3)
    # The abort tombstone keeps the offline multi-role walk clean.
    verify = _load_script("verify_checkpoint")
    vreport = verify.verify_state_dir(str(tmp_path))
    assert vreport["ok"], vreport
    state.close()
    state2.close()


# ---------- fleet: the replica parks on the fence ----------


def test_replica_parks_on_registry_fence_then_reanchors(tmp_path, mesh):
    rng = np.random.default_rng(6)
    state, wg, wnames, _m = _writer(tmp_path, mesh)
    for i in range(3):
        _enroll(state, wg, wnames, rng, i)
    assert state.checkpoint_now(wait=True)
    rg = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    rmetrics = Metrics()
    rep = ReadReplica(str(tmp_path), rg, [], metrics=rmetrics,
                      poll_interval_s=0.0, name="r")
    rep.registry = ModelRegistry(str(tmp_path), metrics=rmetrics,
                                 readonly=True)
    flushes = []
    rep.on_registry_change = flushes.append
    rep.poll(force=True)
    assert rep.stats()["registry"]["detector"] == 1
    # The locked swap WITHOUT the trailing checkpoint, so the fence
    # window is observable.
    path, sha = _stage_params(tmp_path, "detector", 2)
    state.perform_registry_cutover("detector", 2, params_path=path,
                                   params_sha256=sha)
    rep.poll(force=True)
    parked = rep.stats()["awaiting_cutover"]
    assert parked and parked["role"] == "detector" \
        and parked["to_version"] == 2
    # Rows stamped with the post-swap registry must NOT apply while
    # parked — and the replica's served registry view has not moved.
    _enroll(state, wg, wnames, rng, 3)
    rep.poll(force=True)
    assert rep.gallery.size == 3
    assert rep.stats()["registry"]["detector"] == 1
    assert not flushes
    # The post-swap checkpoint lands: re-anchor, new manifest, cache
    # flush hook, tail caught up.
    assert state.checkpoint_now(wait=True)
    rep.poll(force=True)
    assert rep.stats()["awaiting_cutover"] is None
    assert rep.stats()["registry"]["detector"] == 2
    assert flushes and flushes[-1]["detector"] == 2
    rep.poll(force=True)
    assert rep.gallery.size == 4
    state.close()


# ---------- offline verifier: manifest + multi-role walk ----------


def test_verify_checkpoint_registry_fence_walk(tmp_path, mesh):
    rng = np.random.default_rng(7)
    state, gallery, names, _m = _writer(tmp_path, mesh)
    for i in range(2):
        _enroll(state, gallery, names, rng, i)
    verify = _load_script("verify_checkpoint")
    report = verify.verify_state_dir(str(tmp_path))
    assert report["ok"], report
    assert report["registry"]["roles"] == {"embedder": 1, "detector": 1,
                                           "cascade": 1}
    # A legitimate fenced swap keeps the walk clean.
    path, sha = _stage_params(tmp_path, "detector", 2)
    state.perform_registry_cutover("detector", 2, params_path=path,
                                   params_sha256=sha)
    _enroll(state, gallery, names, rng, 2)  # a post-fence row
    report = verify.verify_state_dir(str(tmp_path))
    assert report["ok"], report
    assert report["wal"]["registry_cutover_records"] == 1
    assert report["registry"]["roles"]["detector"] == 2
    # A row claiming a detector version NO fence introduced is the rc-2
    # unfenced-span breach.
    state.wal.append_enroll(99, np.ones((1, DIM), np.float32),
                            np.zeros(1, np.int32), embedder_version=1,
                            registry={"detector": 9, "cascade": 1})
    report = verify.verify_state_dir(str(tmp_path))
    assert not report["ok"]
    assert report["wal"]["version_violations"]
    assert verify.main([str(tmp_path)]) == 2
    state.close()


def test_verify_checkpoint_manifest_rc_contract(tmp_path, mesh):
    state, gallery, names, _m = _writer(tmp_path, mesh)
    _enroll(state, gallery, names, np.random.default_rng(8), 0)
    state.close()
    verify = _load_script("verify_checkpoint")
    assert verify.verify_state_dir(str(tmp_path))["ok"]
    path = os.path.join(str(tmp_path), "registry.json")
    # Checksum mismatch = corruption evidence: rc 2.
    doc = json.load(open(path))
    doc["roles"]["detector"]["version"] = 9
    with open(path, "w") as fh:
        json.dump(doc, fh)
    report = verify.verify_state_dir(str(tmp_path))
    assert not report["ok"] and report.get("registry_corrupt")
    assert verify.main([str(tmp_path)]) == 2
    # Torn/unparseable bytes = cannot verify: rc 3.
    with open(path, "wb") as fh:
        fh.write(b"\x80\x81 torn")
    report = verify.verify_state_dir(str(tmp_path))
    assert not report["ok"] and report.get("cannot_verify")
    assert verify.main([str(tmp_path)]) == 3


# ---------- CLI: startup fences + the offline swap runbook ----------


def test_cli_registry_fence_and_offline_swap(tmp_path):
    from opencv_facerecognizer_tpu.apps import recognize

    registry = ModelRegistry(str(tmp_path))
    ok = types.SimpleNamespace(detector_version=1, cascade_version=0)
    recognize._registry_fence(registry, ok, "writer")  # matching: starts
    for who in ("writer", "reader"):
        bad = types.SimpleNamespace(detector_version=3, cascade_version=0)
        with pytest.raises(SystemExit):
            recognize._registry_fence(registry, bad, who)
    with pytest.raises(SystemExit):
        recognize._registry_fence(
            registry,
            types.SimpleNamespace(detector_version=0, cascade_version=5),
            "writer")
    # --registry-swap argument contract: ROLE=VERSION, detector/cascade
    # only, positive integer, staged params required.
    for spec in ("detector", "detector=abc", "embedder=2", "detector=0"):
        with pytest.raises(SystemExit):
            recognize.run_registry_swap(types.SimpleNamespace(
                state_dir=str(tmp_path), registry_swap=spec))
    with pytest.raises(SystemExit):  # nothing staged yet
        recognize.run_registry_swap(types.SimpleNamespace(
            state_dir=str(tmp_path), registry_swap="detector=2"))
    # The happy-path runbook swap: stage, fence, install — rc 0, and the
    # manifest serves v2 for the next startup fence.
    _stage_params(tmp_path, "detector", 2)
    assert recognize.run_registry_swap(types.SimpleNamespace(
        state_dir=str(tmp_path), registry_swap="detector=2")) == 0
    assert ModelRegistry(str(tmp_path), readonly=True) \
        .version("detector") == 2
    with pytest.raises(SystemExit):  # non-monotonic re-swap refused
        recognize.run_registry_swap(types.SimpleNamespace(
            state_dir=str(tmp_path), registry_swap="detector=2"))
    # The full argparse path: --registry-swap runs WITHOUT the serving
    # stack's --model/--detector/--gallery...
    _stage_params(tmp_path, "cascade", 2)
    assert recognize.main(["--state-dir", str(tmp_path),
                           "--registry-swap", "cascade=2"]) == 0
    assert ModelRegistry(str(tmp_path), readonly=True) \
        .version("cascade") == 2
    # ...but every serving mode still requires them at parse time.
    with pytest.raises(SystemExit):
        recognize.main(["--state-dir", str(tmp_path)])


# ---------- GET /registry ----------


def test_expo_registry_endpoint(tmp_path):
    metrics = Metrics()
    expo = ExpoServer(metrics=metrics,
                      registry=ModelRegistry(str(tmp_path),
                                             metrics=metrics), port=0)
    expo.start()
    base = f"http://{expo.host}:{expo.port}"
    try:
        status, payload = _get_json(base + "/registry")
        assert status == 200
        assert payload["registry"]["roles"]["detector"]["version"] == 1
        assert payload["swap"] is None
        # The same versions ride the /prom gauges.
        with urllib.request.urlopen(base + "/prom", timeout=5) as resp:
            text = resp.read().decode()
        assert "ocvf_model_version_detector 1" in text
    finally:
        expo.stop()
    bare = ExpoServer(metrics=Metrics(), port=0)
    bare.start()
    try:
        status, payload = _get_json(
            f"http://{bare.host}:{bare.port}/registry")
        assert status == 200 and payload["registry"] is None
    finally:
        bare.stop()


# ---------- chaos: the fast deterministic tier-1 variant ----------


def test_registry_chaos_fast_deterministic():
    chaos_soak = _load_script("chaos_soak")
    report = chaos_soak.run_registry(seconds=3.0, seed=7)
    assert report["ok"], report["failures"]
    # Kill mid-detector-swap completed on restart; the damaged cascade
    # candidate was cleanly abandoned; the regressing detector
    # auto-rolled-back at the next monotonic version.
    roles = report["verify"]["registry"]["roles"]
    assert roles["detector"] == 4 and roles["cascade"] == 1
    assert report["auto_rollback"]["phase"] == "rolled_back"
    assert report["rollback_dump"]["role"] == "detector"
    assert report["verify"]["ok"]
