"""Pipeline parallelism (parallel/pp.py): stage-split correctness vs the
fused single-mesh pipeline, on the 8-virtual-device CPU mesh."""

import jax
import numpy as np
import pytest

from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
from opencv_facerecognizer_tpu.models.embedder import FaceEmbedNet, init_embedder
from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
from opencv_facerecognizer_tpu.parallel.pp import TwoStagePipeline, split_mesh
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes


@pytest.fixture(scope="module")
def stack():
    scenes, boxes, counts = make_synthetic_scenes(32, (96, 96), max_faces=2,
                                                  seed=3)
    det = CNNFaceDetector(features=(8, 16, 32), head_features=32, max_faces=4,
                          score_threshold=0.25)
    det.train(scenes, boxes, counts, steps=150, batch_size=16,
              learning_rate=2e-3)
    net = FaceEmbedNet(embed_dim=32, stem_features=8, stage_features=(8, 16),
                       stage_blocks=(1, 1))
    emb_params = init_embedder(net, num_classes=8, input_shape=(48, 48),
                               seed=0)["net"]
    rng = np.random.default_rng(0)
    emb = rng.normal(size=(64, 32)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    labels = rng.integers(0, 8, size=64)
    return det, net, emb_params, emb, labels, scenes


def test_split_mesh_halves_dp():
    mesh = make_mesh(dp=4, tp=2)
    a, b = split_mesh(mesh)
    assert a.shape == {"dp": 2, "tp": 2} and b.shape == {"dp": 2, "tp": 2}
    assert not set(d.id for d in a.devices.flat) & set(
        d.id for d in b.devices.flat)
    with pytest.raises(ValueError):
        split_mesh(make_mesh(dp=1, tp=8))
    with pytest.raises(ValueError):  # odd dp: unequal halves rejected
        from jax.sharding import Mesh
        from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS
        devs = np.asarray(jax.devices()[:6]).reshape(3, 2)
        split_mesh(Mesh(devs, (DP_AXIS, TP_AXIS)))


def test_pp_matches_fused_pipeline(stack):
    det, net, emb_params, emb, labels, scenes = stack
    mesh = make_mesh(dp=4, tp=2)
    gallery = ShardedGallery(capacity=64, dim=32, mesh=mesh)
    gallery.add(emb, labels)
    fused = RecognitionPipeline(det, net, emb_params, gallery,
                                face_size=(48, 48), top_k=2)
    frames = scenes[:8]
    ref = fused.recognize_batch(frames)

    mesh_a, mesh_b = split_mesh(mesh)
    gal_b = ShardedGallery(capacity=64, dim=32, mesh=mesh_b)
    gal_b.add(emb, labels)
    pp = TwoStagePipeline(det, net, emb_params, gal_b, mesh_a,
                          face_size=(48, 48), top_k=2)
    out = pp.recognize_batch(frames)

    np.testing.assert_allclose(np.asarray(out.boxes), np.asarray(ref.boxes),
                               atol=1e-4)
    np.testing.assert_array_equal(np.asarray(out.valid), np.asarray(ref.valid))
    np.testing.assert_array_equal(np.asarray(out.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_allclose(np.asarray(out.similarities),
                               np.asarray(ref.similarities), atol=2e-2)


def test_pp_stream_order_and_completeness(stack):
    det, net, emb_params, emb, labels, scenes = stack
    mesh_a, mesh_b = split_mesh(make_mesh(dp=2, tp=4))
    gal = ShardedGallery(capacity=64, dim=32, mesh=mesh_b)
    gal.add(emb, labels)
    pp = TwoStagePipeline(det, net, emb_params, gal, mesh_a,
                          face_size=(48, 48), top_k=1)
    batches = [scenes[i:i + 4] for i in range(0, 24, 4)]
    outs = list(pp.recognize_stream(iter(batches)))
    assert len(outs) == len(batches)
    # stream results must match one-at-a-time processing, in order
    for i, out in enumerate(outs):
        solo = pp.recognize_batch(batches[i])
        np.testing.assert_array_equal(np.asarray(out.labels),
                                      np.asarray(solo.labels))
        np.testing.assert_array_equal(np.asarray(out.valid),
                                      np.asarray(solo.valid))


def test_pp_stream_dispatches_next_stage_a_before_yield(stack):
    """The depth-2 overlap contract: stage A for batch i+1 must be
    DISPATCHED before batch i's result is handed to the consumer —
    otherwise the disjoint stage meshes serialize and PP degenerates to
    the fused pipeline's latency with an extra hop (VERDICT round-2
    item #6: assert the scheduling, since a one-chip box can't show the
    hardware win)."""
    det, net, emb_params, emb, labels, scenes = stack
    mesh_a, mesh_b = split_mesh(make_mesh(dp=2, tp=4))
    # Large CAPACITY (match cost scales with capacity, not rows): stage B
    # must out-run the host's dispatch turnaround for the overlap window
    # to be observable at all.
    gal = ShardedGallery(capacity=131072, dim=32, mesh=mesh_b)
    gal.add(emb, labels)
    pp = TwoStagePipeline(det, net, emb_params, gal, mesh_a,
                          face_size=(48, 48), top_k=1)

    events = []
    orig_a, orig_b = pp._submit_a, pp._submit_b
    counts = {"a": 0, "b": 0}

    def wrapped_a(frames):
        events.append(("A", counts["a"]))
        counts["a"] += 1
        return orig_a(frames)

    def wrapped_b(hopped):
        events.append(("B", counts["b"]))
        counts["b"] += 1
        return orig_b(hopped)

    pp._submit_a, pp._submit_b = wrapped_a, wrapped_b
    batches = [scenes[i:i + 4] for i in range(0, 16, 4)]
    for i, _out in enumerate(pp.recognize_stream(iter(batches))):
        events.append(("got", i))

    def pos(ev):
        return events.index(ev)

    assert counts["a"] == counts["b"] == 4
    for i in range(len(batches) - 1):
        # A(i+1) dispatched before result i reaches the consumer...
        assert pos(("A", i + 1)) < pos(("got", i)), events
        # ...and before B(i+1) (A feeds B, trivially, but pin the order).
        assert pos(("A", i + 1)) < pos(("B", i + 1)), events
    # depth 2, not unbounded: B(i) is submitted before A(i+2) is dispatched
    for i in range(len(batches) - 2):
        assert pos(("B", i)) < pos(("A", i + 2)), events


def test_pp_sees_live_enrolment(stack):
    """The gallery must stay live through PP: an enrolment after pipeline
    construction lands on the next batch (same contract as the fused
    pipeline), including through an auto-grow."""
    det, net, emb_params, emb, labels, scenes = stack
    mesh_a, mesh_b = split_mesh(make_mesh(dp=2, tp=4))
    gal = ShardedGallery(capacity=64, dim=32, mesh=mesh_b)
    gal.add(emb[:32], labels[:32])
    pp = TwoStagePipeline(det, net, emb_params, gal, mesh_a,
                          face_size=(48, 48), top_k=1)
    frames = scenes[:4]
    out0 = pp.recognize_batch(frames)
    # enroll more rows, growing past capacity (64 -> auto-grow)
    extra = np.tile(emb, (2, 1))
    gal.add(extra, np.full(len(extra), 7, np.int64))
    assert gal.capacity > 64  # grew
    out1 = pp.recognize_batch(frames)
    assert out1.labels.shape == out0.labels.shape
    # old rows must still be matchable after the grow+swap
    q = emb[:8]
    lab, _, _ = gal.match(np.asarray(q), k=1)
    assert (np.asarray(lab)[:, 0] == labels[:8]).mean() >= 0.9


def test_pp_rejects_overlapping_meshes(stack):
    det, net, emb_params, emb, labels, _ = stack
    mesh = make_mesh(dp=2, tp=4)
    gal = ShardedGallery(capacity=64, dim=32, mesh=mesh)
    gal.add(emb, labels)
    mesh_a, _ = split_mesh(mesh)
    with pytest.raises(ValueError):
        TwoStagePipeline(det, net, emb_params, gal, mesh_a,
                         face_size=(48, 48))


def test_pp_drop_in_for_recognizer_service(stack):
    """TwoStagePipeline implements the pipeline surface RecognizerService
    needs (recognize_batch_packed + gallery/top_k/face_size/embed_*), so
    PP serves frames end-to-end through the same runtime."""
    import time

    from opencv_facerecognizer_tpu.runtime.connector import (
        FakeConnector, encode_frame)
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC, RecognizerService)

    det, net, emb_params, emb, labels, scenes = stack
    mesh_a, mesh_b = split_mesh(make_mesh(dp=2, tp=4))
    gal = ShardedGallery(capacity=64, dim=32, mesh=mesh_b)
    gal.add(emb, labels)
    pp = TwoStagePipeline(det, net, emb_params, gal, mesh_a,
                          face_size=(48, 48), top_k=1)
    connector = FakeConnector()
    service = RecognizerService(
        pp, connector, batch_size=4, frame_shape=(96, 96),
        flush_timeout=0.02, similarity_threshold=0.0,
        subject_names=[f"p{i}" for i in range(8)],
    )
    service.start()
    try:
        for i, scene in enumerate(scenes[:8]):
            connector.inject(FRAME_TOPIC,
                             {**encode_frame(scene), "meta": {"frame_id": i}})
        deadline = time.monotonic() + 30
        while (len(connector.messages(RESULT_TOPIC)) < 8
               and time.monotonic() < deadline):
            time.sleep(0.05)
    finally:
        service.stop()
    results = connector.messages(RESULT_TOPIC)
    assert len(results) == 8
    assert any(r["faces"] for r in results)


def test_pp_stream_execution_occupancy_windows(stack):
    """Execution-LEVEL occupancy instrumentation for the depth-2 claim
    (VERDICT r3 item #7), with the platform's limits measured, not
    hand-waved.

    In-graph ``io_callback`` probes timestamp when each stage's device
    execution actually RUNS (stage A additionally holds a 60 ms brake so
    windows dwarf scheduling noise). What this backend can and cannot
    show, measured on this box:

    - The forced-host-platform CPU client executes computations from ALL
      virtual devices on ONE executor thread: an independent 0.5 s braked
      computation on devices 0-3 and a 0.26 s matmul on devices 4-7,
      dispatched back-to-back, complete in 0.74 s (the sum, not the max).
      Wall-clock overlap between disjoint stage meshes is therefore
      physically unobservable here — for ANY schedule — so a
      "streamed < serial wall-clock" assertion would be vacuous.
    - What IS observable at the execution level: the per-batch order in
      which stage computations reach the devices. Depth-2 pipelining
      admits A(i+1) to the device queue immediately behind B(i) — before
      the consumer has drained result i — so the executed order is
      strict alternation A1 B1 A2 B2 ... with every A(i+1) EXECUTING
      before B(i+1) and before the consumer's drain of i+1 completes.

    Loss-of-pipelining in the generator (draining before submitting the
    next batch) is guarded by the dispatch-order assertions in
    ``test_pp_stream_dispatches_next_stage_a_before_yield``; this test
    pins the same schedule at the device-execution level and exercises
    the occupancy instrument that shows full window overlap on real
    multi-chip hardware."""
    import threading
    import time as _time

    import jax.numpy as jnp
    from jax.experimental import io_callback

    det, net, emb_params, emb, labels, scenes = stack
    mesh_a, mesh_b = split_mesh(make_mesh(dp=2, tp=4))
    gal = ShardedGallery(capacity=64, dim=32, mesh=mesh_b)
    gal.add(emb, labels)
    pp = TwoStagePipeline(det, net, emb_params, gal, mesh_a,
                          face_size=(48, 48), top_k=1)

    events = []
    lock = threading.Lock()
    counts = {"A": 0, "B": 0}

    def probe(stage, brake_s):
        def cb(_x):
            with lock:
                events.append((stage, counts[stage], _time.perf_counter()))
                counts[stage] += 1
            if brake_s:
                _time.sleep(brake_s)
            return np.float32(0.0)
        return cb

    a_cb = probe("A", 0.06)
    b_cb = probe("B", 0.0)

    @jax.jit
    def braked_a(boxes):
        z = io_callback(a_cb, jax.ShapeDtypeStruct((), jnp.float32),
                        jnp.sum(boxes))
        return boxes + 0.0 * z

    @jax.jit
    def probed_b(labels_arr):
        z = io_callback(b_cb, jax.ShapeDtypeStruct((), jnp.float32),
                        jnp.sum(labels_arr.astype(jnp.float32)))
        return labels_arr + (0.0 * z).astype(labels_arr.dtype)

    orig_a, orig_b = pp._submit_a, pp._submit_b

    def instrumented_a(frames):
        boxes, scores, valid, crops = orig_a(frames)
        return braked_a(boxes), scores, valid, crops

    def instrumented_b(hopped):
        res = orig_b(hopped)
        return res._replace(labels=probed_b(res.labels))

    pp._submit_a, pp._submit_b = instrumented_a, instrumented_b
    batches = [scenes[i:i + 4] for i in range(0, 16, 4)]
    # Warmup pass: compiles otherwise land inside the measured windows.
    for out in pp.recognize_stream(iter(batches[:2])):
        _ = np.asarray(out.labels)
    with lock:
        events.clear()
        counts["A"] = counts["B"] = 0

    for i, out in enumerate(pp.recognize_stream(iter(batches))):
        _ = np.asarray(out.labels)  # blocking drain, as the serving loop does
        with lock:
            events.append(("got", i, _time.perf_counter()))

    assert counts["A"] == counts["B"] == len(batches)

    def t_of(kind, idx):
        return next(t for k, j, t in events if k == kind and j == idx)

    order = [(k, j) for k, j, _ in events]
    for i in range(len(batches)):
        # feed order at the EXECUTION level: A(i) ran before B(i)...
        assert t_of("A", i) < t_of("B", i), order
        # ...and B(i) ran before the consumer finished draining it.
        assert t_of("B", i) < t_of("got", i), order
    for i in range(len(batches) - 1):
        # strict alternation: B(i) executed before A(i+1) reached the
        # devices (depth-2 keeps ONE batch per stage, never two).
        assert t_of("B", i) < t_of("A", i + 1), order
