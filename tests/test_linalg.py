"""PCA/LDA eigen-solver oracle tests vs sklearn/NumPy (SURVEY.md §4, §7.2)."""

import numpy as np
import pytest

from opencv_facerecognizer_tpu.ops import linalg as L

RNG = np.random.default_rng(3)


def _random_lowrank(n=40, d=300, rank=10):
    a = RNG.normal(size=(n, rank)).astype(np.float32)
    b = RNG.normal(size=(rank, d)).astype(np.float32)
    return a @ b + 0.01 * RNG.normal(size=(n, d)).astype(np.float32)


def _subspace_angle(a, b):
    """Largest principal angle between column spaces (0 == identical)."""
    qa, _ = np.linalg.qr(a)
    qb, _ = np.linalg.qr(b)
    s = np.linalg.svd(qa.T @ qb, compute_uv=False)
    return float(np.arccos(np.clip(s.min(), -1, 1)))


@pytest.mark.parametrize("n,d", [(40, 300), (60, 20)])  # gram trick + direct path
def test_pca_matches_sklearn_subspace(n, d):
    from sklearn.decomposition import PCA as SkPCA

    x = _random_lowrank(n=n, d=d, rank=8)
    k = 6
    st = L.pca_fit(x, k)
    sk = SkPCA(n_components=k, svd_solver="full").fit(x.astype(np.float64))
    assert np.asarray(st.components).shape == (d, k)
    # Same subspace (eigenvector signs/rotations may differ within ties).
    assert _subspace_angle(np.asarray(st.components), sk.components_.T) < 0.05
    # Eigenvalues of scatter = explained_variance * (n - 1).
    np.testing.assert_allclose(
        np.asarray(st.eigenvalues), sk.explained_variance_ * (n - 1), rtol=0.02
    )


def test_pca_project_reconstruct_roundtrip():
    x = _random_lowrank(n=30, d=100, rank=5)
    st = L.pca_fit(x, 5)
    z = L.pca_project(st, x)
    xr = np.asarray(L.pca_reconstruct(st, z))
    # rank-5 data + 5 components => near-perfect reconstruction
    rel = np.linalg.norm(xr - x) / np.linalg.norm(x)
    assert rel < 0.05


def test_pca_validates_num_components():
    x = _random_lowrank(n=10, d=20)
    with pytest.raises(ValueError):
        L.pca_fit(x, 0)
    with pytest.raises(ValueError):
        L.pca_fit(x, 11)


def _class_blobs(num_classes=5, per_class=12, d=30, sep=4.0):
    centers = RNG.normal(scale=sep, size=(num_classes, d)).astype(np.float32)
    x = np.concatenate(
        [c + RNG.normal(size=(per_class, d)).astype(np.float32) for c in centers]
    )
    y = np.repeat(np.arange(num_classes), per_class)
    return x, y


def test_lda_separates_classes_like_sklearn():
    from sklearn.discriminant_analysis import LinearDiscriminantAnalysis

    x, y = _class_blobs()
    c, k = 5, 4
    st = L.lda_fit(x, y, num_classes=c, num_components=k)
    proj = np.asarray(L.lda_project(st, x))
    sk = LinearDiscriminantAnalysis(n_components=k, solver="eigen", shrinkage=None)
    sk_proj = sk.fit_transform(x.astype(np.float64), y)
    # Compare class separability (Fisher criterion) achieved, not raw axes.
    def fisher_score(p):
        means = np.array([p[y == i].mean(axis=0) for i in range(c)])
        within = sum(((p[y == i] - means[i]) ** 2).sum() for i in range(c))
        between = sum((y == i).sum() * ((means[i] - p.mean(axis=0)) ** 2).sum() for i in range(c))
        return between / within

    assert fisher_score(proj) > 0.8 * fisher_score(sk_proj)


def test_lda_nearest_class_mean_accuracy():
    x, y = _class_blobs(sep=3.0)
    st = L.lda_fit(x, y, num_classes=5, num_components=4)
    proj = np.asarray(L.lda_project(st, x))
    means = np.stack([proj[y == i].mean(axis=0) for i in range(5)])
    pred = np.argmin(((proj[:, None, :] - means[None]) ** 2).sum(-1), axis=1)
    assert (pred == y).mean() > 0.95
