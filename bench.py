"""Headline benchmark: faces/sec/chip of the fused detect->align->embed->
match pipeline (the BASELINE.json:5 north-star metric; baseline target
2000 faces/sec/chip on v5e).

Prints ONE JSON line on stdout: {"metric", "value", "unit", "vs_baseline"}.
Everything a reviewer needs to believe (or attack) the number goes to stderr
and ``BENCH_DETAIL.json``:

- analytic FLOPs of the compiled graph (XLA cost analysis) -> TFLOP/s and
  MFU vs the 197 TFLOP/s bf16 peak of a v5e chip;
- batch sweep {8, 32, 128};
- DISTINCT pre-generated input batches cycled per iteration (no backend
  same-buffer caching) — frames are synthetic scenes with real faces, and
  the detector is briefly trained first, so "valid faces" is meaningful;
- device compute timed by CHAINED DIFFERENCING (see below) — the only
  defensible method on this backend;
- the H2D transfer cost measured separately per batch size;
- slot throughput (batch x max_faces slots — what the graph always
  computes) reported separately from valid-face throughput (slots the
  trained detector actually marked valid).

TIMING METHOD — critical on the axon (tunneled PJRT) backend:
``block_until_ready`` does NOT await execution here (measured: a 275-GFLOP
matmul "blocks" in 0.03 ms, and a naive per-iteration timed loop yields
>250% MFU at batch 128 — physically impossible). Forced readbacks would
work but drop the process into ~100 ms sync-poll mode, quantizing every
later measurement. So device compute is timed by running the fused step K1
and K2 times CHAINED inside one jit (iteration i's frames carry a 1e-30-
scaled dependency on iteration i-1's outputs, forcing serialization), with
one tiny readback at the end; (min T(K2) - min T(K1)) / (K2 - K1), minima
over MEASURE_PAIRS repeats PER CHAIN LENGTH, cancels the fixed
dispatch+sync overhead and is robust to jitter (which only ever adds to a
single chain's wall time; min-ing differenced pairs instead is biased low).
K2 escalates up CHAIN_K2_LADDER until the delta clears the ~100 ms readback
quantization (MIN_DELTA_S). The method reproduces 218 TFLOP/s on a bare
4096^3 bf16 matmul (nominal peak 197) — calibration within instrument
error. Per-iteration latency percentiles are
NOT reported for device compute (they would be dispatch-latency fiction);
end-to-end serving latency lives in bench_serving.py, where readbacks are
part of the path being measured.
"""

import functools
import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from opencv_facerecognizer_tpu.utils.benchtime import (
    CHAIN_K1, CHAIN_K2_LADDER, MEASURE_PAIRS, MIN_DELTA_S, measure_chained,
)

BASELINE_FACES_PER_SEC = 2000.0
V5E_BF16_PEAK_TFLOPS = 197.0
BATCH_SWEEP = (8, 32, 128)
HEADLINE_BATCH = 32
DISTINCT_INPUTS = 8
H2D_ITERS = 20


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _retry(fn, attempts: int = 3, sleep_s: float = 20.0):
    """Retry a thunk across transient tunnel faults (the axon PJRT backend
    occasionally drops a remote_compile/readback mid-run — observed:
    'response body closed before all bytes were read'). Persistent errors
    still raise."""
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — backend fault surface is broad
            if attempt == attempts - 1:
                raise
            _log(f"transient backend error ({type(exc).__name__}: {exc}); "
                 f"retry {attempt + 1}/{attempts - 1} in {sleep_s:.0f}s")
            time.sleep(sleep_s)


def _graph_flops(compiled) -> float:
    """Analytic FLOPs of a compiled executable via XLA cost analysis."""
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        return float(ca.get("flops", float("nan")))
    except Exception:  # noqa: BLE001 — cost analysis is best-effort per backend
        return float("nan")


#: IVF ladder points (rows) and per-arm chain lengths for the matcher-only
#: chained-differencing measurement.
IVF_LADDER_ROWS = (1_048_576, 4_194_304, 10_485_760)
IVF_LADDER_Q = 256
IVF_LADDER_NPROBE = 8
IVF_GEN_CHUNK = 1 << 19  # row-generation chunk: never a [10M, D] f32 host array


def _build_ivf_arrays(rng, big_n: int, embed_dim: int, nlist: int, _log):
    """Chunk-wise gallery + IVF structure build for one ladder point:
    returns (g_big bf16 device, ivf device tuple, queries f32, spill).
    Host peak is the int8 copy (~2.5 GB at 10M), never the f32 rows."""
    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.parallel.quantizer import (
        _kmeans, pack_inverted_lists, quantize_rows,
    )

    q8_all = np.empty((big_n, embed_dim), np.int8)
    scale_all = np.empty((big_n,), np.float32)
    cells_all = np.empty((big_n,), np.int32)
    g_parts = []
    centroids = None
    cent_dev = None
    assign_jit = jax.jit(
        lambda x, c: jnp.argmax(x @ c.T, axis=1).astype(jnp.int32))
    queries = None
    for off in range(0, big_n, IVF_GEN_CHUNK):
        n = min(IVF_GEN_CHUNK, big_n - off)
        chunk = rng.normal(size=(n, embed_dim)).astype(np.float32)
        chunk /= np.linalg.norm(chunk, axis=-1, keepdims=True)
        if centroids is None:
            # First chunk doubles as the seeded k-means training sample
            # and the query source (queries = perturbed enrolled rows —
            # the serving distribution: a probe of an enrolled identity).
            centroids = _kmeans(chunk[:131072], nlist, 10, 0)
            cent_dev = jnp.asarray(centroids)
            noise = rng.normal(size=(IVF_LADDER_Q, embed_dim)) * 0.05
            queries = chunk[:IVF_LADDER_Q] + noise.astype(np.float32)
            queries /= np.linalg.norm(queries, axis=-1, keepdims=True)
        q8, sc = quantize_rows(chunk)
        q8_all[off:off + n] = q8
        scale_all[off:off + n] = sc
        cells_all[off:off + n] = np.asarray(assign_jit(jnp.asarray(chunk),
                                                      cent_dev))
        g_parts.append(jnp.asarray(chunk).astype(jnp.bfloat16))
    g_big = jnp.concatenate(g_parts)
    del g_parts
    # Tighter slack than serving (1.5 vs 2.0): the ladder's 10M point puts
    # gallery bf16 + cell-resident int8 on one chip's HBM.
    packed = pack_inverted_lists(np.arange(big_n, dtype=np.int32), cells_all,
                                 q8_all, scale_all, nlist, cell_slack=1.5)
    (cell_rows, cell_q8, cell_scale, spill_rows, spill_q8, spill_scale,
     _counts, overflow) = packed
    del q8_all
    ivf = tuple(jax.device_put(jnp.asarray(a)) for a in (
        centroids, cell_rows, cell_q8, cell_scale, spill_rows, spill_q8,
        spill_scale))
    _log(f"[ivf {big_n}] nlist={nlist} max_cell={cell_rows.shape[1]} "
         f"spill={overflow}")
    return g_big, ivf, queries, overflow


def ivf_ladder_section(rng, embed_dim: int, _log):
    """1M–10M matcher-only ladder: exact pallas_stream vs two-stage ivf,
    chained-differencing timing + tie-aware recall on shared queries."""
    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.ops.ivf_match import (
        ivf_match_topk, tie_aware_agreement,
    )
    from opencv_facerecognizer_tpu.ops.pallas_match import streaming_match_topk
    from opencv_facerecognizer_tpu.parallel.quantizer import CoarseQuantizer

    section = {"q_batch": IVF_LADDER_Q, "nprobe": IVF_LADDER_NPROBE, "k": 1,
               "targets": {"speedup_at_1m": ">= 3x vs pallas_stream",
                           "ms_at_10m": "< 10 ms/batch"},
               "rows": {}}

    def chain_time(fn, q_dev, *args):
        """The SHARED chained-differencing instrument
        (utils.benchtime.measure_chained — K2 escalates until the delta
        clears MIN_DELTA_S, so a ~1 ms ivf arm is never reported as
        readback-quantization noise): the next call's queries depend on
        the previous sims, one readback per chain. Returns
        (mean_s_or_None, samples)."""
        def chain(n):
            vals, idx = fn(q_dev, *args)
            for _ in range(n - 1):
                vals, idx = fn(q_dev + vals[0, 0] * 1e-30, *args)
            return float(np.asarray(vals).sum())

        chain(2)  # compile + warm

        def timed_chain(n):
            t0 = time.perf_counter()
            chain(n)
            return time.perf_counter() - t0

        t1s, t2s, k2_used, mean_s = measure_chained(timed_chain)
        return mean_s, t1s + t2s

    for big_n in IVF_LADDER_ROWS:
        nlist = CoarseQuantizer.default_nlist(big_n)
        row = {"nlist": nlist}
        g_big = ivf = q_dev = valid_big = None
        try:
            # Build INSIDE the try: the 10M point's ~7.5 GB of device
            # arrays is the likeliest OOM site, and a failing point must
            # not void the smaller points already measured.
            g_big, ivf, queries, spill = _build_ivf_arrays(
                rng, big_n, embed_dim, nlist, _log)
            row["spill_rows"] = spill
            valid_big = jnp.ones((big_n,), bool)
            q_dev = jnp.asarray(queries)

            exact_fn = jax.jit(functools.partial(streaming_match_topk, k=1))
            ivf_fn = jax.jit(functools.partial(
                ivf_match_topk, k=1, nprobe=IVF_LADDER_NPROBE))

            e_s, e_samples = chain_time(exact_fn, q_dev, g_big, valid_big)
            i_s, i_samples = chain_time(ivf_fn, q_dev, valid_big, ivf)
            x_vals, x_idx = (np.asarray(v) for v in
                             exact_fn(q_dev, g_big, valid_big))
            p_vals, p_idx = (np.asarray(v) for v in
                             ivf_fn(q_dev, valid_big, ivf))
            recall = tie_aware_agreement(p_vals, p_idx, x_vals, x_idx)
            row.update({
                "exact_ms_per_batch": (None if e_s is None
                                       else round(e_s * 1e3, 3)),
                "ivf_ms_per_batch": (None if i_s is None
                                     else round(i_s * 1e3, 3)),
                "speedup": (round(e_s / i_s, 3)
                            if e_s is not None and i_s else None),
                "tie_aware_recall_at_1": round(recall, 4),
                "t_exact_k_samples_s": [round(t, 4) for t in e_samples],
                "t_ivf_k_samples_s": [round(t, 4) for t in i_samples],
            })
            if e_s is None or i_s is None:
                row["invalid"] = ("chain delta never cleared MIN_DELTA_S "
                                  "(under-resolved vs readback "
                                  "quantization); no ms recorded")
                _log(f"[ivf {big_n}] timing under-resolved; "
                     f"recall {recall:.4f}")
            else:
                _log(f"[ivf {big_n}] exact {e_s * 1e3:.3f} ms vs ivf "
                     f"{i_s * 1e3:.3f} ms ({e_s / max(i_s, 1e-9):.2f}x), "
                     f"recall {recall:.4f}")
        except Exception as exc:  # noqa: BLE001 — a ladder point that does
            # not fit this chip's HBM must not void the smaller points
            row["error"] = repr(exc)
            _log(f"[ivf {big_n}] FAILED: {exc!r}")
        section["rows"][str(big_n)] = row
        g_big = ivf = q_dev = valid_big = None  # free before the next point
    return section


def ivf_smoke() -> int:
    """Fast tier-1 recall gate over a small synthetic gallery — the
    ``--ivf-smoke`` mode the test suite runs on every commit so a recall
    regression in the two-stage path fails loud, on CPU, in seconds.
    Exercises the REAL serving path (ShardedGallery + CoarseQuantizer +
    gallery.match mode selection), not a re-implementation. Prints one
    JSON line; rc 0 iff the gate holds."""
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # This environment's sitecustomize force-registers the TPU backend
        # over the env var (tests/conftest.py gotcha) — honor the tier-1
        # contract explicitly.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401  (backend init after config)

    from jax.sharding import Mesh

    from opencv_facerecognizer_tpu.ops.ivf_match import tie_aware_agreement
    from opencv_facerecognizer_tpu.parallel import ShardedGallery
    from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS

    from opencv_facerecognizer_tpu.parallel.quantizer import CoarseQuantizer

    rows, dim, nlist, nprobe, n_q = 16384, 64, 128, 8, 64
    rng = np.random.default_rng(11)
    emb = rng.normal(size=(rows, dim)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    labels = np.arange(rows, dtype=np.int32)

    # Single-device mesh regardless of host virtual-device count: the
    # two-stage path is gated to mesh.size == 1 (like the pallas matcher)
    # and the smoke must exercise IT, not silently fall back to exact.
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                (DP_AXIS, TP_AXIS))
    gallery = ShardedGallery(capacity=rows, dim=dim, mesh=mesh)
    gallery.add(emb, labels)
    quantizer = CoarseQuantizer(nlist=nlist, nprobe=nprobe, seed=5,
                                kmeans_iters=8, train_sample=8192)
    gallery.attach_quantizer(quantizer, mode="ivf")
    t0 = time.perf_counter()
    if not quantizer.rebuild_now():
        # Explicit, not an assert: python -O would strip the build call
        # itself, and a genuine build failure deserves a clear verdict.
        print(json.dumps({"metric": "ivf_smoke", "ok": False,
                          "error": "quantizer build failed"}))
        return 1
    build_s = time.perf_counter() - t0

    # Serving-distribution queries: perturbed enrolled rows.
    queries = emb[:n_q] + 0.05 * rng.normal(size=(n_q, dim)).astype(np.float32)
    queries /= np.linalg.norm(queries, axis=-1, keepdims=True)
    t0 = time.perf_counter()
    _lab_i, sims_i, idx_i = (np.asarray(v) for v in gallery.match(queries, k=1))
    match_s = time.perf_counter() - t0
    # Brute-force oracle in f32 with the stable (lowest-index) tie order.
    sims = queries @ emb.T
    idx_x = np.argmax(sims, axis=1)
    vals_x = sims[np.arange(n_q), idx_x]
    recall = tie_aware_agreement(sims_i, idx_i, vals_x, idx_x)

    # Incremental assignment: a freshly enrolled row must be findable
    # through the two-stage path immediately (cell insert or spill).
    new = rng.normal(size=(4, dim)).astype(np.float32)
    new /= np.linalg.norm(new, axis=-1, keepdims=True)
    start = gallery.size
    gallery.add(new, np.arange(rows, rows + 4, dtype=np.int32))
    _l, _s, idx_new = (np.asarray(v) for v in gallery.match(
        np.concatenate([new, new]), k=1))
    incremental_ok = bool(np.array_equal(
        idx_new[:4, 0], np.arange(start, start + 4)))

    ok = bool(recall >= 0.99 and incremental_ok and gallery._ivf_enabled())
    print(json.dumps({
        "metric": "ivf_smoke",
        "ivf_enabled": gallery._ivf_enabled(),
        "rows": rows, "nlist": nlist, "nprobe": nprobe,
        "tie_aware_recall_at_1": round(recall, 4),
        "incremental_rows_found": incremental_ok,
        "quantizer_build_s": round(build_s, 2),
        "two_stage_match_s": round(match_s, 3),
        "stats": quantizer.stats(),
        "ok": ok,
    }))
    return 0 if ok else 1


def main():
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector, decode_detections
    from opencv_facerecognizer_tpu.models.embedder import (
        SERVING_EMBEDDER_KWARGS, SERVING_FACE_SIZE, FaceEmbedNet,
        init_embedder, normalize_faces,
    )
    from opencv_facerecognizer_tpu.ops import image as image_ops
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    # Deadline-bounded backend check BEFORE any in-process backend init: the
    # axon tunnel's hang-mode (round-4 outage) makes a bare jax.devices()
    # block forever, and its fast-fail mode dies in a raw traceback. Either
    # way the driver should get ONE structured JSON line saying the backend
    # is down, promptly (rc=3 distinguishes "backend down, nothing measured"
    # from a real bench crash).
    from opencv_facerecognizer_tpu.utils.backend_probe import probe_default_backend

    # allow_cpu=False: a silent fallback to the CPU backend must fast-fail
    # too — a faces/sec/CHIP number measured on host CPU would be a lie.
    usable, reason = probe_default_backend(min_devices=1, allow_cpu=False)
    if not usable:
        print(json.dumps({
            "metric": "faces_per_sec_per_chip", "value": None,
            "unit": "faces/sec/chip", "vs_baseline": None,
            "error": "backend_unavailable", "reason": reason,
        }))
        _log(f"backend unavailable ({reason}); structured fast-fail")
        sys.exit(3)

    dev = jax.devices()[0]
    _log(f"device: {dev}")

    # Serving-shaped workload: 256x256 frames, 8 face slots each, aligned
    # crops at the accuracy-gated resolution, 256-d embeddings vs a 16k
    # gallery in HBM. r4: the embedder is the accuracy-gated structure at
    # its gated 64x64 input (models.embedder.SERVING_EMBEDDER_KWARGS —
    # measured rationale there); r3 ran 112x112 crops with a 128-d net
    # that no accuracy protocol had gated.
    height, width = 256, 256
    face_size = SERVING_FACE_SIZE
    max_faces = 8
    gallery_size = 16384
    embed_dim = SERVING_EMBEDDER_KWARGS["embed_dim"]

    det = CNNFaceDetector(max_faces=max_faces, score_threshold=0.3)
    net = FaceEmbedNet(**SERVING_EMBEDDER_KWARGS)
    emb_params = init_embedder(net, num_classes=64, input_shape=face_size, seed=0)["net"]

    # Brief detector training on synthetic scenes so the valid-face numbers
    # mean something (an untrained detector on noise detects ~nothing).
    t0 = time.perf_counter()
    train_scenes, train_boxes, train_counts = make_synthetic_scenes(
        num_scenes=64, scene_size=(height, width), max_faces=max_faces,
        face_size_range=(24, 56), seed=7,
    )
    det.train(train_scenes, train_boxes, train_counts, steps=200, batch_size=16)
    _log(f"detector warm-trained in {time.perf_counter() - t0:.1f}s")

    rng = np.random.default_rng(0)
    gallery = rng.normal(size=(gallery_size, embed_dim)).astype(np.float32)
    gallery /= np.linalg.norm(gallery, axis=-1, keepdims=True)
    labels = rng.integers(0, 512, size=gallery_size).astype(np.int32)
    # bf16 rows: the serving default (ocvf-recognize --gallery-dtype).
    # Identical math — the matcher computes bf16 x bf16 -> f32 either way
    # (the cast just pre-pays at enrolment); measured 1.24x at 1M rows
    # (BENCH_DETAIL.json:gallery_dtype), ~noise at this 16k headline size.
    # Transfer f32 and cast ON DEVICE: a host-side ml_dtypes array misses
    # PJRT's zero-copy put (gallery._put_emb documents the 25x penalty).
    g = jnp.asarray(gallery).astype(jnp.bfloat16)
    lab = jnp.asarray(labels)
    det_params = det.params

    def xla_matcher(emb, gallery):
        sims = jax.lax.dot_general(
            emb.astype(jnp.bfloat16), gallery.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        return jax.lax.top_k(sims, 1)

    # OCVF_FUSED_EMBEDDER=1 runs the embed stage on the fused pallas
    # schedule (ops.pallas_sepblock; equivalence pinned in tests) so the
    # measurement queue can re-measure the headline under the alternative
    # schedule right after scripts/bench_sepblock.py's A/B, without a code
    # edit. The committed default stays the flax graph until the A/B
    # measures a win.
    fused_embedder = os.environ.get("OCVF_FUSED_EMBEDDER", "") not in ("", "0")
    if fused_embedder:
        from opencv_facerecognizer_tpu.models.embedder import fused_forward

        _log("embed stage: fused pallas schedule (OCVF_FUSED_EMBEDDER)")
        embed_apply = lambda p, x: fused_forward(net, p, x)  # noqa: E731
    else:
        embed_apply = lambda p, x: net.apply({"params": p}, x)  # noqa: E731

    def make_step(batch, matcher=xla_matcher):
        def step(det_params, emb_params, gallery, labels, frames):
            outputs = det.net.apply({"params": det_params}, frames)
            boxes, det_scores, valid = decode_detections(
                outputs, max_faces, det.score_threshold, det.iou_threshold
            )
            crops = image_ops.batched_crop_resize(frames, boxes, face_size)
            flat = crops.reshape((batch * max_faces, *face_size))
            emb = embed_apply(emb_params, normalize_faces(flat, face_size))
            top_sims, top_idx = matcher(emb, gallery)
            return boxes, valid, jnp.take(labels, top_idx), top_sims

        return step

    def measure_chained_retrying(run_chain):
        """Shared instrument (utils.benchtime.measure_chained) with each
        chain run wrapped in the transient-tunnel-fault retry."""
        return measure_chained(lambda k: _retry(lambda: run_chain(k)))

    def make_chained(batch, step):
        """K serialized runs of ``step`` in ONE jit: frames for iteration i
        carry a negligible (1e-30-scaled) dependency on iteration i-1's
        outputs, so XLA cannot overlap or elide any of them. Returns a tiny
        accumulator whose readback forces completion of the whole chain."""

        def chained(det_params, emb_params, gallery, labels, frames_stack, k):
            def body(i, carry):
                dep, acc = carry
                frames = jax.lax.dynamic_index_in_dim(
                    frames_stack, i % DISTINCT_INPUTS, axis=0, keepdims=False
                )
                boxes, valid, top_labels, top_sims = step(
                    det_params, emb_params, gallery, labels, frames + dep
                )
                dep = (jnp.sum(top_sims) + jnp.sum(boxes)) * 1e-30
                acc = acc + jnp.sum(valid) + dep
                return dep, acc

            _, acc = jax.lax.fori_loop(0, k, body, (jnp.float32(0.0), jnp.float32(0.0)))
            return acc

        return jax.jit(chained, static_argnums=5)

    detail = {"device": str(dev), "config": {
        "frame": [height, width], "max_faces": max_faces, "face_size": list(face_size),
        "gallery_size": gallery_size, "embed_dim": embed_dim,
        "distinct_inputs": DISTINCT_INPUTS,
        "chain_k1": CHAIN_K1, "chain_k2_ladder": list(CHAIN_K2_LADDER),
        "min_delta_s": MIN_DELTA_S, "h2d_iters": H2D_ITERS,
        "bf16_peak_tflops": V5E_BF16_PEAK_TFLOPS,
        "timing_method": "chained differencing (see bench.py module docstring)",
        "fused_embedder": fused_embedder,
    }, "sweep": {}}
    headline = None

    # -- pass 0: DISTINCT input batches per batch size (different seeds) --
    all_host = {}
    all_dev = {}
    for batch in BATCH_SWEEP:
        host_inputs = []
        dev_inputs = []
        for i in range(DISTINCT_INPUTS):
            scenes, _, _ = make_synthetic_scenes(
                num_scenes=batch, scene_size=(height, width), max_faces=max_faces,
                face_size_range=(24, 56), seed=100 + i,
            )
            host_inputs.append(np.asarray(scenes, np.float32))
            dev_inputs.append(jax.device_put(jnp.asarray(scenes, jnp.float32)))
        all_host[batch] = host_inputs
        all_dev[batch] = dev_inputs

    # -- pass 1: H2D transfer timing for ALL batch sizes, BEFORE any D2H
    # readback happens (the first readback flips this backend into ~100 ms
    # sync-poll mode, which would quantize these measurements) --
    for batch in BATCH_SWEEP:
        detail["sweep"][str(batch)] = {}
        # The pinned-ring arm (runtime.ingest.StagingRing): ONE
        # pre-allocated recycled uint8 staging buffer per batch size,
        # copied into and uploaded — the serving ingest path's exact
        # staging discipline, timed next to the fresh-allocation arms so
        # the old-vs-new p99 story (the --transfer-uint8 118 ms tail came
        # from unpinned per-batch staging allocations) is a committed
        # artifact, not a claim.
        ring_stage = np.zeros((batch, height, width), np.uint8)
        host_u8 = [np.clip(arr, 0, 255).astype(np.uint8)
                   for arr in all_host[batch]]
        for dtype, tag, bytes_per in (
                (np.float32, "h2d_transfer", 4),
                (np.uint8, "h2d_transfer_uint8", 1),
                (np.uint8, "h2d_transfer_uint8_pinned", 1)):
            pinned = tag.endswith("_pinned")
            h2d_lat = []
            for it in range(H2D_ITERS):
                arr = all_host[batch][it % DISTINCT_INPUTS]
                if pinned:
                    # Staging copy INSIDE the timed region: the recycled
                    # ring buffer's point is that copy+upload from warm
                    # reused pages has a stable tail, where the unpinned
                    # arm's fresh per-batch allocation (made outside its
                    # timed region here, but ON the hot path in the old
                    # serving code) is what fed the 118 ms p99. The two
                    # legacy arms keep their historical pure-put timing
                    # for artifact comparability.
                    src = host_u8[it % DISTINCT_INPUTS]
                    t0 = time.perf_counter()
                    np.copyto(ring_stage, src)
                    arr = ring_stage
                else:
                    if dtype is np.uint8:
                        arr = host_u8[it % DISTINCT_INPUTS].copy()
                    t0 = time.perf_counter()
                frames = jax.device_put(arr)
                jax.block_until_ready(frames)
                h2d_lat.append(time.perf_counter() - t0)
            h2d_lat = np.asarray(h2d_lat)
            frame_mb = batch * height * width * bytes_per / 1e6
            detail["sweep"][str(batch)][tag] = {
                "mb_per_batch": round(frame_mb, 2),
                "p50_ms": round(float(np.percentile(h2d_lat, 50) * 1e3), 3),
                "p99_ms": round(float(np.percentile(h2d_lat, 99) * 1e3), 3),
                "mean_ms": round(float(h2d_lat.mean()) * 1e3, 3),
                "gb_per_s": round(frame_mb / 1e3 / float(h2d_lat.mean()), 3),
            }
            _log(f"[batch {batch}] {tag} {h2d_lat.mean() * 1e3:.2f} ms/batch "
                 f"({frame_mb / 1e3 / h2d_lat.mean():.3f} GB/s)")

    # -- pass 2: compile + chained-differencing device compute + valid runs --
    for batch in BATCH_SWEEP:
        step = make_step(batch)
        t0 = time.perf_counter()
        compiled = jax.jit(step).lower(
            det_params, emb_params, g, lab, all_dev[batch][0]
        ).compile()
        flops = _graph_flops(compiled)
        compile_s = time.perf_counter() - t0

        frames_stack = jnp.stack(all_dev[batch])  # [DISTINCT_INPUTS, B, H, W]
        chained = make_chained(batch, step)

        def timed_chain(k):
            acc = chained(det_params, emb_params, g, lab, frames_stack, k)
            _ = np.asarray(acc)  # warm: compile this k
            t0 = time.perf_counter()
            acc = chained(det_params, emb_params, g, lab, frames_stack, k)
            _ = np.asarray(acc)  # forces completion of the whole chain
            return time.perf_counter() - t0

        t1s, t2s, k2_used, mean_s = measure_chained_retrying(timed_chain)
        if mean_s is None:
            detail["sweep"][str(batch)]["device_compute"] = {
                "invalid": "chain delta min(T(K2)) - min(T(K1)) never "
                           f"cleared MIN_DELTA_S over {MEASURE_PAIRS} "
                           "repeats (non-positive or under-resolved vs "
                           "readback quantization); no number recorded",
                "t_k1_samples_s": [round(t, 4) for t in t1s],
                "t_k2_samples_s": [round(t, 4) for t in t2s],
            }
            _log(f"[batch {batch}] SKIPPED: timing invalid t1={t1s} t2={t2s}")
            continue
        slot_tput = batch * max_faces / mean_s
        tflops = flops / mean_s / 1e12 if np.isfinite(flops) else float("nan")
        mfu = tflops / V5E_BF16_PEAK_TFLOPS if np.isfinite(tflops) else float("nan")

        # valid-slot fraction: one untimed run per distinct input
        valid_frac = float(np.mean([
            np.asarray(compiled(det_params, emb_params, g, lab, frames)[1]).mean()
            for frames in all_dev[batch]
        ]))
        valid_tput = slot_tput * valid_frac

        entry = detail["sweep"][str(batch)]
        h2d_mean_s = entry["h2d_transfer"]["mean_ms"] / 1e3
        entry.update({
            "compile_s": round(compile_s, 2),
            "analytic_gflop_per_batch": round(flops / 1e9, 3) if np.isfinite(flops) else None,
            "valid_slot_fraction": round(valid_frac, 4),
            "device_compute": {
                "method": f"chained diff of per-length minima "
                          f"(min of {MEASURE_PAIRS} T(K={CHAIN_K1}) chains "
                          f"vs min of {MEASURE_PAIRS} T(K={k2_used}) "
                          "chains, one readback each; K2 escalated until "
                          f"delta >= {MIN_DELTA_S}s)",
                "k2_used": k2_used,
                "t_k1_samples_s": [round(t, 4) for t in t1s],
                "t_k2_samples_s": [round(t, 4) for t in t2s],
                "min_diff_ms_per_batch": round(mean_s * 1e3, 3),
                "slot_throughput_per_s": round(slot_tput, 1),
                "valid_face_throughput_per_s": round(valid_tput, 1),
                "tflops_per_s": round(tflops, 2) if np.isfinite(tflops) else None,
                "mfu_vs_bf16_peak": round(mfu, 4) if np.isfinite(mfu) else None,
            },
            "e2e_estimate": {
                "note": "device compute + H2D transfer, serialized; the "
                        "serving runtime overlaps these, so this is an "
                        "upper bound per batch. uint8 variant = the "
                        "--transfer-uint8 serving path (cast on device)",
                "ms_per_batch": round((mean_s + h2d_mean_s) * 1e3, 3),
                "valid_face_throughput_per_s": round(
                    batch * max_faces * valid_frac / (mean_s + h2d_mean_s), 1
                ),
                "ms_per_batch_uint8": round(
                    (mean_s + entry["h2d_transfer_uint8"]["mean_ms"] / 1e3)
                    * 1e3, 3),
                "valid_face_throughput_per_s_uint8": round(
                    batch * max_faces * valid_frac
                    / (mean_s + entry["h2d_transfer_uint8"]["mean_ms"] / 1e3), 1
                ),
            },
        })
        _log(f"[batch {batch}] compile {compile_s:.1f}s, "
             f"{flops / 1e9:.1f} GFLOP/batch; device {mean_s * 1e3:.3f} ms/batch "
             f"-> {slot_tput:,.0f} slots/s, {tflops:.1f} TFLOP/s, MFU {mfu:.1%}; "
             f"valid {valid_frac:.3f} -> {valid_tput:,.0f} faces/s")
        if batch == HEADLINE_BATCH:
            headline = valid_tput

    # -- pass 2b: per-stage cost attribution at the headline batch (VERDICT
    # round-2 item #1). Ablated prefixes of the fused graph — detect,
    # detect+crop, detect+crop+embed, full — each timed with the SAME
    # chained-differencing instrument; stage cost = delta between
    # consecutive prefixes. Each prefix returns a scalar folding in every
    # computed output (no DCE), and per-prefix analytic FLOPs from XLA cost
    # analysis give per-stage MFU — the roofline evidence for where the
    # batch's milliseconds and the chip's idle fraction actually live.
    def make_prefix_step(batch, upto: str):
        def step(det_params, emb_params, gallery, labels, frames):
            outputs = det.net.apply({"params": det_params}, frames)
            boxes, det_scores, valid = decode_detections(
                outputs, max_faces, det.score_threshold, det.iou_threshold
            )
            out = jnp.sum(boxes) + jnp.sum(det_scores) + jnp.sum(valid)
            if upto != "detect":
                crops = image_ops.batched_crop_resize(frames, boxes, face_size)
                flat = crops.reshape((batch * max_faces, *face_size))
                out = out + jnp.sum(flat) * 1e-6
            if upto in ("embed", "full"):
                # embed_apply, not net.apply: the stage attribution must
                # measure the SAME schedule as the headline (a fused-
                # schedule re-run with flax attribution would silently
                # label the wrong graph's costs).
                emb = embed_apply(emb_params, normalize_faces(flat, face_size))
                out = out + jnp.sum(emb)
            if upto == "full":
                top_sims, top_idx = xla_matcher(emb, gallery)
                out = out + jnp.sum(top_sims) + jnp.sum(top_idx) * 1e-9
            return out

        return step

    def make_chained_scalar(step):
        def chained(det_params, emb_params, gallery, labels, frames_stack, k):
            def body(i, carry):
                dep, acc = carry
                frames = jax.lax.dynamic_index_in_dim(
                    frames_stack, i % DISTINCT_INPUTS, axis=0, keepdims=False
                )
                out = step(det_params, emb_params, gallery, labels, frames + dep)
                dep = out * 1e-30
                return dep, acc + out

            _, acc = jax.lax.fori_loop(
                0, k, body, (jnp.float32(0.0), jnp.float32(0.0))
            )
            return acc

        return jax.jit(chained, static_argnums=5)

    def attribute_stages(batch):
        """Ablated-prefix stage table for one batch size."""
        frames_stack = jnp.stack(all_dev[batch])
        prefix_ms, prefix_flops = {}, {}
        for upto in ("detect", "crop", "embed", "full"):
            step = make_prefix_step(batch, upto)
            compiled = jax.jit(step).lower(
                det_params, emb_params, g, lab, all_dev[batch][0]
            ).compile()
            prefix_flops[upto] = _graph_flops(compiled)
            chained = make_chained_scalar(step)

            def timed_chain(k):
                acc = chained(det_params, emb_params, g, lab, frames_stack, k)
                _ = np.asarray(acc)
                t0 = time.perf_counter()
                acc = chained(det_params, emb_params, g, lab, frames_stack, k)
                _ = np.asarray(acc)
                return time.perf_counter() - t0

            t1s, t2s, k2_used, mean_s = measure_chained_retrying(timed_chain)
            if mean_s is None:
                # mirror pass 2's explicit invalid record: NaN in the JSON
                # breaks strict parsers and explains nothing
                return prefix_ms, {
                    "invalid": f"prefix {upto!r} under-resolved (chain "
                               "delta never cleared MIN_DELTA_S)",
                }
            prefix_ms[upto] = mean_s * 1e3
            _log(f"[b{batch} stage prefix {upto}] {prefix_ms[upto]:.3f} "
                 f"ms/batch ({prefix_flops[upto] / 1e9:.1f} GFLOP)")

        stage_order = [("detect", "detect", None), ("crop", "crop", "detect"),
                       ("embed", "embed", "crop"), ("match", "full", "embed")]
        stages = {}
        assert all(k in prefix_ms for k in ("detect", "crop", "embed", "full"))
        for name, cur, prev in stage_order:
            ms = prefix_ms[cur] - (prefix_ms[prev] if prev else 0.0)
            fl = prefix_flops[cur] - (prefix_flops[prev] if prev else 0.0)
            tf = fl / (ms / 1e3) / 1e12 if ms > 0 else float("nan")
            stages[name] = {
                "ms_per_batch": round(ms, 3),
                "gflop_per_batch": round(fl / 1e9, 3),
                "tflops_per_s": round(tf, 2) if np.isfinite(tf) else None,
                "mfu_vs_bf16_peak": (round(tf / V5E_BF16_PEAK_TFLOPS, 4)
                                     if np.isfinite(tf) else None),
            }
            _log(f"[b{batch} stage {name}] {ms:.3f} ms/batch, "
                 f"{fl / 1e9:.1f} GFLOP, MFU "
                 f"{stages[name]['mfu_vs_bf16_peak']}")
        return prefix_ms, stages

    # Headline batch first (round-over-round comparability), then the rest
    # of the sweep — the batch-128 MFU bend needs per-stage evidence at
    # every sweep point, not just the headline (VERDICT r3 item #2).
    per_batch = {}
    headline_prefix_ms, headline_stages = attribute_stages(HEADLINE_BATCH)
    per_batch[str(HEADLINE_BATCH)] = headline_stages
    for b in BATCH_SWEEP:
        if b != HEADLINE_BATCH:
            per_batch[str(b)] = attribute_stages(b)[1]
    detail["stage_attribution"] = {
        "batch": HEADLINE_BATCH,
        "method": ("ablated graph prefixes (detect | +crop | +embed | "
                   "+match), each timed by chained differencing; stage = "
                   "delta of consecutive prefixes; FLOPs = delta of XLA "
                   "cost analysis. Prefix totals listed for cross-checking "
                   "against the pass-2 full-step time. per_batch holds the "
                   "same stage table at every sweep batch size."),
        "prefix_ms": {k: round(v, 3) for k, v in headline_prefix_ms.items()},
        "stages": headline_stages,
        "per_batch": per_batch,
    }

    # -- pass 2c: cascade detect split (ISSUE 13) — stage-1-only vs the
    # full detector at every sweep rung, with the SAME chained-diff
    # instrument, so BENCH_DETAIL attribution covers the two-stage
    # cascade: the per-rung ratio is the raw device-time budget an
    # early-exited (face-free) frame saves, and the number the serving
    # gate's operating-point math starts from.
    from opencv_facerecognizer_tpu.models.cascade import (
        FaceGate, frame_scores as cascade_frame_scores,
    )

    gate = FaceGate()
    t0 = time.perf_counter()
    gate.train(train_scenes, train_boxes, train_counts, steps=300,
               batch_size=16)
    _log(f"cascade gate warm-trained in {time.perf_counter() - t0:.1f}s")
    gate_net, gate_params = gate.net, gate.params

    def make_stage1_step():
        def step(det_params, emb_params, gallery, labels, frames):
            # Params ride as a jit closure constant: the stage-1 graph
            # has no gallery/embedder inputs, but the shared chained
            # instrument threads the standard signature through.
            return jnp.sum(cascade_frame_scores(gate_net, gate_params,
                                                frames))

        return step

    cascade_rows = {}
    for batch in BATCH_SWEEP:
        frames_stack = jnp.stack(all_dev[batch])
        chained = make_chained_scalar(make_stage1_step())

        def timed_chain(k):
            acc = chained(det_params, emb_params, g, lab, frames_stack, k)
            _ = np.asarray(acc)
            t0 = time.perf_counter()
            acc = chained(det_params, emb_params, g, lab, frames_stack, k)
            _ = np.asarray(acc)
            return time.perf_counter() - t0

        t1s, t2s, k2_used, mean_s = measure_chained_retrying(timed_chain)
        detect_ms = (per_batch.get(str(batch)) or {}).get(
            "detect", {}).get("ms_per_batch")
        if mean_s is None:
            cascade_rows[str(batch)] = {
                "invalid": "stage-1 chain delta never cleared MIN_DELTA_S",
                "t_k1_samples_s": [round(t, 4) for t in t1s],
                "t_k2_samples_s": [round(t, 4) for t in t2s],
                "full_detect_ms_per_batch": detect_ms,
            }
            continue
        stage1_ms = mean_s * 1e3
        cascade_rows[str(batch)] = {
            "stage1_ms_per_batch": round(stage1_ms, 4),
            "k2_used": k2_used,
            "full_detect_ms_per_batch": detect_ms,
            "detect_over_stage1": (round(detect_ms / stage1_ms, 2)
                                   if detect_ms and stage1_ms > 0 else None),
        }
        _log(f"[b{batch} cascade] stage-1 {stage1_ms:.4f} ms/batch vs "
             f"full detect {detect_ms} ms/batch")
    detail["cascade_detect"] = {
        "note": ("stage-1 cascade (models.cascade.FaceGate, 4x avg-pool "
                 "downsample + two conv blocks, per-tile logits -> max) "
                 "vs the full detect stage (stage_attribution's ablated "
                 "prefix) at every sweep rung, chained-diff timing. "
                 "detect_over_stage1 is the device-time multiple a "
                 "face-free frame's early exit saves on the detect "
                 "budget."),
        "per_batch": cascade_rows,
    }

    # -- pass 3: large-gallery scaling — the fused pipeline at 262k and 1M
    # enrolled rows, pallas streaming matcher (the ShardedGallery auto
    # fast path above 64k) vs the XLA materialize+top_k formulation. The
    # headline stays the 16k/XLA configuration for round-over-round
    # comparability; this section shows serving holds up as the gallery
    # scales past HBM-comfortable score-matrix sizes — including the 1M
    # in-pipeline point the round-2 verdict asked for (the kernel's
    # matcher-only 1.73x at 1M, now measured inside the serving graph).
    from opencv_facerecognizer_tpu.ops.pallas_match import streaming_match_topk

    batch = HEADLINE_BATCH
    frames_stack = jnp.stack(all_dev[batch])

    def embed_for_parity(det_params, emb_params, frames):
        outputs = det.net.apply({"params": det_params}, frames)
        boxes, _, _ = decode_detections(
            outputs, max_faces, det.score_threshold, det.iou_threshold
        )
        crops = image_ops.batched_crop_resize(frames, boxes, face_size)
        flat = crops.reshape((batch * max_faces, *face_size))
        return net.apply({"params": emb_params}, normalize_faces(flat, face_size))

    compiled_embed_for_parity = jax.jit(embed_for_parity)
    detail["large_gallery"] = {"batch": batch, "rows": {}}
    for big_n in (262_144, 1_048_576):
        # bf16, matching the serving default (see headline gallery note:
        # f32 over the wire, cast on device)
        g_big = jnp.asarray(
            rng.normal(size=(big_n, embed_dim)).astype(np.float32)
        ).astype(jnp.bfloat16)
        lab_big = jnp.asarray(rng.integers(0, 512, size=big_n).astype(np.int32))
        valid_big = jnp.ones((big_n,), bool)

        def pallas_matcher(emb, gallery, _valid=valid_big):
            vals, idx = streaming_match_topk(emb, gallery, _valid, k=1)
            return vals, idx

        row = {}
        for name, matcher in (("pallas_stream", pallas_matcher),
                              ("xla_materialize", xla_matcher)):
            chained = make_chained(batch, make_step(batch, matcher))

            def timed_chain(k):
                acc = chained(det_params, emb_params, g_big, lab_big, frames_stack, k)
                _ = np.asarray(acc)
                t0 = time.perf_counter()
                acc = chained(det_params, emb_params, g_big, lab_big, frames_stack, k)
                _ = np.asarray(acc)
                return time.perf_counter() - t0

            t1s, t2s, k2_used, mean_s = measure_chained_retrying(timed_chain)
            if mean_s is None:
                row[name] = {
                    "invalid": "chain delta never cleared MIN_DELTA_S "
                               "(non-positive or under-resolved)",
                    "t_k1_samples_s": [round(t, 4) for t in t1s],
                    "t_k2_samples_s": [round(t, 4) for t in t2s],
                }
                continue
            row[name] = {
                "min_diff_ms_per_batch": round(mean_s * 1e3, 3),
                "k2_used": k2_used,
                "t_k1_samples_s": [round(t, 4) for t in t1s],
                "t_k2_samples_s": [round(t, 4) for t in t2s],
                "slot_throughput_per_s": round(batch * max_faces / mean_s, 1),
            }
            _log(f"[gallery {big_n}] {name}: {mean_s * 1e3:.3f} ms/batch "
                 f"(diff of per-length minima over {MEASURE_PAIRS})")
        if ("min_diff_ms_per_batch" in row.get("pallas_stream", {})
                and "min_diff_ms_per_batch" in row.get("xla_materialize", {})):
            row["pallas_speedup_in_pipeline"] = round(
                row["xla_materialize"]["min_diff_ms_per_batch"]
                / row["pallas_stream"]["min_diff_ms_per_batch"], 3)
        detail["large_gallery"]["rows"][str(big_n)] = row

        # On-chip COMPILED-kernel parity vs the XLA matcher (VERDICT round-2
        # item #4: interpret-mode CPU tests cannot catch compiled-lowering
        # divergence — round 3 found exactly one, the argmax-tie sentinel).
        # Compare top-1 labels and sims over real pipeline embeddings.
        # The comparator is TIE-AWARE (ops.ivf_match.tie_aware_agreement —
        # shared with the IVF recall gate): BENCH_r05 reported "idx match
        # 0.6914" with |sim diff| exactly 0 because tie POSITIONS were
        # counted as errors; any index attaining the max similarity is a
        # correct answer, so ``ok`` now reflects real disagreement only.
        from opencv_facerecognizer_tpu.ops.ivf_match import tie_aware_agreement

        emb_batch = np.asarray(compiled_embed_for_parity(
            det_params, emb_params, all_dev[batch][0]
        ))
        p_vals, p_idx = (np.asarray(v) for v in streaming_match_topk(
            jnp.asarray(emb_batch), g_big, valid_big, k=1))
        x_vals, x_idx = (np.asarray(v) for v in jax.jit(xla_matcher)(
            jnp.asarray(emb_batch), g_big))
        idx_match = float(np.mean(p_idx == x_idx))
        sim_diff = float(np.max(np.abs(p_vals - x_vals)))
        agreement = tie_aware_agreement(p_vals, p_idx, x_vals, x_idx)
        row["pallas_parity"] = {
            "idx_match_fraction_raw": round(idx_match, 4),
            "tie_aware_agreement": round(agreement, 4),
            "max_abs_sim_diff": round(sim_diff, 6),
            # Two orthogonal criteria, neither tolerating partial failure:
            # EVERY row's winner must agree modulo ties, and even
            # same-winner rows must report values within bf16 tolerance.
            "ok": bool(agreement == 1.0 and sim_diff < 2e-2),
        }
        _log(f"[gallery {big_n}] pallas parity: raw idx match "
             f"{idx_match:.4f}, tie-aware agreement {agreement:.4f}, "
             f"max |sim diff| {sim_diff:.2e}, ok={row['pallas_parity']['ok']}")

    # -- pass 4: IVF two-stage ladder, 1M -> 10M rows (ROADMAP item #1).
    # The exact scan is linear in gallery size; the two-stage path
    # (ops.ivf_match: centroid shortlist -> int8 cell gather -> exact
    # pallas rerank over the bucket) scales with the probed cells.
    # Matcher-only timing with the same chained-differencing discipline
    # (dependency threaded through the returned sims), bf16 exact arm vs
    # the ivf arm, plus a tie-aware recall column on the same queries —
    # a speedup bought with recall would be a lie by omission.
    detail["ivf_ladder"] = ivf_ladder_section(rng, embed_dim, _log)

    # Merge-preserve sections other tools own (scripts/bench_lifecycle.py
    # writes "lifecycle"; this run's keys always win for its own sections).
    # OCVF_DETAIL_SECTION nests this run's whole detail under that key
    # instead — the queue's conditional fused-schedule re-run records
    # itself as a sibling section rather than clobbering the default
    # schedule's sweep.
    section = os.environ.get("OCVF_DETAIL_SECTION", "")
    try:
        with open("BENCH_DETAIL.json") as fh:
            existing = json.load(fh)
    except (OSError, json.JSONDecodeError):
        existing = {}
    if section:
        existing[section] = detail
        out_doc = existing
    else:
        for key, value in existing.items():
            detail.setdefault(key, value)
        out_doc = detail
    with open("BENCH_DETAIL.json", "w") as fh:
        json.dump(out_doc, fh, indent=2)
    _log("wrote BENCH_DETAIL.json"
         + (f" (section {section!r})" if section else ""))

    if headline is None:
        _log("FATAL: headline batch timing was invalid; no result")
        sys.exit(1)
    hb = detail["sweep"][str(HEADLINE_BATCH)]
    print(json.dumps({
        "metric": (
            f"detected faces/sec/chip, fused detect-align-embed-match "
            f"(256x256 scene frames, {max_faces} slots, 16k gallery, batch "
            f"{HEADLINE_BATCH}, distinct inputs, trained detector, chained-"
            f"diff timing; valid-slot fraction {hb['valid_slot_fraction']}, "
            f"slot throughput {hb['device_compute']['slot_throughput_per_s']:,.0f}/s, "
            f"MFU {hb['device_compute']['mfu_vs_bf16_peak']}, "
            f"h2d {hb['h2d_transfer']['mean_ms']} ms/batch separate)"
        ),
        "value": round(float(headline), 1),
        "unit": "faces/s",
        "vs_baseline": round(float(headline) / BASELINE_FACES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser(
        prog="bench.py",
        description="headline fused-pipeline bench (default) or the fast "
                    "IVF recall smoke")
    parser.add_argument("--ivf-smoke", action="store_true",
                        help="run only the fast two-stage-matcher recall "
                             "gate on a small synthetic gallery (CPU-"
                             "friendly; tier-1 runs this) and exit 0/1")
    cli_args = parser.parse_args()
    if cli_args.ivf_smoke:
        sys.exit(ivf_smoke())
    main()
