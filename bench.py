"""Headline benchmark: faces/sec/chip of the fused detect->align->embed->
match pipeline (the BASELINE.json:5 north-star metric; baseline target
2000 faces/sec/chip on v5e).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
supporting numbers on stderr. Runs on whatever jax.devices() offers (the
driver runs it on the real chip; `JAX_PLATFORMS=axon` is already the
environment default there).
"""

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

BASELINE_FACES_PER_SEC = 2000.0


def main():
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector, decode_detections
    from opencv_facerecognizer_tpu.models.embedder import FaceEmbedNet, init_embedder, normalize_faces
    from opencv_facerecognizer_tpu.ops import image as image_ops

    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)

    # Serving-shaped workload: VGA-ish frames, 8 face slots each, 112x112
    # aligned crops, 128-d embeddings vs a 16k gallery in HBM.
    batch, height, width = 32, 256, 256
    face_size = (112, 112)
    max_faces = 8
    gallery_size, embed_dim = 16384, 128

    det = CNNFaceDetector(max_faces=max_faces, score_threshold=0.3)
    det_params = det.net.init(jax.random.PRNGKey(0), jnp.zeros((1, height, width)))["params"]
    net = FaceEmbedNet(embed_dim=embed_dim)
    emb_params = init_embedder(net, num_classes=64, input_shape=face_size, seed=0)["net"]

    rng = np.random.default_rng(0)
    gallery = rng.normal(size=(gallery_size, embed_dim)).astype(np.float32)
    gallery /= np.linalg.norm(gallery, axis=-1, keepdims=True)
    labels = rng.integers(0, 512, size=gallery_size).astype(np.int32)

    @jax.jit
    def step(det_params, emb_params, gallery, labels, frames):
        outputs = det.net.apply({"params": det_params}, frames)
        boxes, det_scores, valid = decode_detections(
            outputs, max_faces, det.score_threshold, det.iou_threshold
        )
        crops = image_ops.batched_crop_resize(frames, boxes, face_size)
        flat = crops.reshape((batch * max_faces, *face_size))
        emb = net.apply({"params": emb_params}, normalize_faces(flat, face_size))
        sims = jax.lax.dot_general(
            emb.astype(jnp.bfloat16), gallery.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        top_sims, top_idx = jax.lax.top_k(sims, 1)
        return boxes, valid, jnp.take(labels, top_idx), top_sims

    frames = jnp.asarray(rng.uniform(0, 255, size=(batch, height, width)).astype(np.float32))
    g = jnp.asarray(gallery)
    l = jnp.asarray(labels)

    t0 = time.perf_counter()
    out = step(det_params, emb_params, g, l, frames)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    print(f"first call (incl compile): {compile_s:.1f}s", file=sys.stderr)

    # Steady state: timed loop, per-batch latencies for p50.
    iters = 30
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = step(det_params, emb_params, g, l, frames)
        jax.block_until_ready(out)
        lat.append(time.perf_counter() - t0)
    lat = np.asarray(lat)
    faces_per_batch = batch * max_faces
    faces_per_sec = faces_per_batch / lat.mean()
    p50_ms = float(np.percentile(lat, 50) * 1e3)
    print(
        f"steady: {faces_per_sec:,.0f} faces/sec/chip "
        f"({batch} frames x {max_faces} slots, p50 {p50_ms:.2f} ms/batch, "
        f"gallery {gallery_size})",
        file=sys.stderr,
    )

    print(json.dumps({
        "metric": "faces/sec/chip (fused detect-align-embed-match, 256x256 frames, "
                  "8 slots, 16k gallery)",
        "value": round(float(faces_per_sec), 1),
        "unit": "faces/s",
        "vs_baseline": round(float(faces_per_sec) / BASELINE_FACES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
