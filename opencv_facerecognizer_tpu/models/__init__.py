"""The plugin boundary (SURVEY.md §1 L2-L4): features, classifiers, models.

This is the one piece of the reference's architecture the north star
explicitly preserves (BASELINE.json:5): ``AbstractFeature.compute/extract``,
``AbstractClassifier.compute/predict``, ``PredictableModel`` composing them.
Implementations are batched, jittable device functions.
"""

from opencv_facerecognizer_tpu.models.classifier import (
    AbstractClassifier,
    KernelSVM,
    NearestNeighbor,
    SVM,
)
from opencv_facerecognizer_tpu.models.feature import (
    AbstractFeature,
    Fisherfaces,
    HistogramEqualization,
    Identity,
    LDA,
    MinMaxNormalize,
    PCA,
    Resize,
    SpatialHistogram,
    TanTriggsPreprocessing,
)
from opencv_facerecognizer_tpu.models.model import ExtendedPredictableModel, PredictableModel
from opencv_facerecognizer_tpu.models.operators import (
    ChainOperator,
    CombineOperator,
    CombineOperatorND,
    FeatureOperator,
)

__all__ = [
    "AbstractClassifier",
    "AbstractFeature",
    "ChainOperator",
    "CombineOperator",
    "CombineOperatorND",
    "ExtendedPredictableModel",
    "FeatureOperator",
    "Fisherfaces",
    "HistogramEqualization",
    "Identity",
    "LDA",
    "MinMaxNormalize",
    "KernelSVM",
    "NearestNeighbor",
    "PCA",
    "PredictableModel",
    "Resize",
    "SpatialHistogram",
    "SVM",
    "TanTriggsPreprocessing",
]
