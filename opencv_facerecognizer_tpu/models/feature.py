"""Feature plugins: the ``AbstractFeature.compute/extract`` boundary.

Rebuilds the reference's ``facerec/feature.py`` + ``facerec/preprocessing.py``
capabilities (SURVEY.md §2.1): Identity, PCA (Eigenfaces), LDA, Fisherfaces,
SpatialHistogram (LBPH), and the preprocessing plugins that share the feature
protocol so they chain (TanTriggs, HistogramEqualization, Resize, MinMax).

TPU-first redesign decisions:
- ``compute(X, y)`` fits on the *whole batch at once* (one eigh / one pass),
  returns the projected batch — no per-sample Python loops anywhere.
- ``extract(X)`` is batched: it accepts either a single sample (the
  reference's contract) or a batch with a leading N dim, and the math is a
  pure jnp function either way, so callers can wrap it in jit/vmap/shard_map.
- Fit state is held as arrays on the instance (a pytree via
  ``get_state/set_state``), keeping the reference's stateful-plugin API while
  the compute itself stays functional.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from opencv_facerecognizer_tpu.ops import histogram as hist_ops
from opencv_facerecognizer_tpu.ops import image as image_ops
from opencv_facerecognizer_tpu.ops import lbp as lbp_ops
from opencv_facerecognizer_tpu.ops import linalg as linalg_ops


def as_row_matrix(x) -> jnp.ndarray:
    """List-of-images or array [N, ...] -> [N, D] float32 row matrix.

    The reference's ``asRowMatrix`` (SURVEY.md §2.1 "Matrix/dataset utils").
    """
    if isinstance(x, (list, tuple)):
        x = jnp.stack([jnp.asarray(v) for v in x])
    x = jnp.asarray(x, dtype=jnp.float32)
    return x.reshape((x.shape[0], -1))


def as_column_matrix(x) -> jnp.ndarray:
    return as_row_matrix(x).T


def _labels_to_indices(y) -> Tuple[np.ndarray, np.ndarray]:
    """Arbitrary int labels -> (classes sorted unique, contiguous indices)."""
    y = np.asarray(y)
    classes, idx = np.unique(y, return_inverse=True)
    return classes, idx.astype(np.int32)


class AbstractFeature:
    """``compute(X, y)`` fits on a dataset and returns projected features;
    ``extract(X)`` transforms new sample(s). SURVEY.md §1 L2."""

    name = "abstract_feature"
    #: ndim of one raw input sample (2 = grayscale image); used to decide
    #: whether ``extract`` got a single sample or a batch.
    sample_ndim = 2

    def compute(self, X, y):
        raise NotImplementedError

    def extract(self, X):
        """Dispatch single-sample vs batch, delegate to ``_extract_batch``."""
        X = jnp.asarray(X, dtype=jnp.float32)
        if X.ndim == self.sample_ndim:
            return self._extract_batch(X[None])[0]
        return self._extract_batch(X)

    def _extract_batch(self, X: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    # -- serialization protocol (utils.serialization) --
    def get_config(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, config: dict) -> "AbstractFeature":
        return cls(**config)

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass

    def __repr__(self) -> str:
        cfg = ", ".join(f"{k}={v}" for k, v in self.get_config().items())
        return f"{type(self).__name__}({cfg})"


class Identity(AbstractFeature):
    """Flattens samples to vectors; the no-op feature."""

    name = "identity"

    def compute(self, X, y):
        return as_row_matrix(X)

    def _extract_batch(self, X):
        return X.reshape((X.shape[0], -1))


class _SubspaceFeature(AbstractFeature):
    """Shared extract dispatch for features projecting flat [D] vectors.

    A fitted subspace knows its input dim D, so single-vs-batch is decided
    by element count, not a fixed sample ndim: a [H, W] image, a [D] vector,
    or anything with exactly D elements is ONE sample (unless it is an
    explicit [1, D] batch); everything else is a batch flattened to
    [N, D]. This keeps the reference's single-sample contract working for
    chains whose intermediate features are 1-D (e.g. PCA -> LDA).
    """

    def _input_dim(self) -> int:
        raise NotImplementedError

    def extract(self, X):
        X = jnp.asarray(X, dtype=jnp.float32)
        d = self._input_dim()
        if X.size == d and not (X.ndim == 2 and X.shape[0] == 1):
            return self._extract_batch(X.reshape((1, -1)))[0]
        return self._extract_batch(X.reshape((X.shape[0], -1)))


class PCA(_SubspaceFeature):
    """Eigenfaces: mean-center, eigh via the small-matrix trick, top-k
    eigenvectors (SURVEY.md §2.1, §3.1). ``num_components=0`` keeps all."""

    name = "pca"

    def __init__(self, num_components: int = 0):
        self.num_components = int(num_components)
        self._state: Optional[linalg_ops.PCAState] = None

    def compute(self, X, y):
        Xm = as_row_matrix(X)
        n, d = Xm.shape
        k = self.num_components if self.num_components > 0 else min(n, d)
        k = min(k, n, d)
        self._state = linalg_ops.pca_fit(Xm, k)
        return linalg_ops.pca_project(self._state, Xm)

    def _input_dim(self):
        if self._state is None:
            raise RuntimeError("PCA.extract called before compute()")
        return int(self._state.components.shape[0])

    def _extract_batch(self, X):
        if self._state is None:
            raise RuntimeError("PCA.extract called before compute()")
        return linalg_ops.pca_project(self._state, X.reshape((X.shape[0], -1)))

    def reconstruct(self, z):
        return linalg_ops.pca_reconstruct(self._state, jnp.asarray(z))

    @property
    def mean(self):
        return self._state.mean if self._state else None

    @property
    def eigenvectors(self):
        return self._state.components if self._state else None

    @property
    def eigenvalues(self):
        return self._state.eigenvalues if self._state else None

    def get_config(self):
        return {"num_components": self.num_components}

    def get_state(self):
        if self._state is None:
            return {}
        return {
            "mean": self._state.mean,
            "components": self._state.components,
            "eigenvalues": self._state.eigenvalues,
        }

    def set_state(self, state):
        if state:
            self._state = linalg_ops.PCAState(
                mean=jnp.asarray(state["mean"]),
                components=jnp.asarray(state["components"]),
                eigenvalues=jnp.asarray(state["eigenvalues"]),
            )


class LDA(_SubspaceFeature):
    """Fisher LDA on flattened samples. ``num_components=0`` -> classes-1."""

    name = "lda"

    def __init__(self, num_components: int = 0):
        self.num_components = int(num_components)
        self._state: Optional[linalg_ops.LDAState] = None

    def compute(self, X, y):
        Xm = as_row_matrix(X)
        _, y_idx = _labels_to_indices(y)
        c = int(y_idx.max()) + 1
        k = self.num_components if self.num_components > 0 else c - 1
        k = min(k, c - 1)
        self._state = linalg_ops.lda_fit(Xm, y_idx, num_classes=c, num_components=k)
        return linalg_ops.lda_project(self._state, Xm)

    def _input_dim(self):
        if self._state is None:
            raise RuntimeError("LDA.extract called before compute()")
        return int(self._state.components.shape[0])

    def _extract_batch(self, X):
        if self._state is None:
            raise RuntimeError("LDA.extract called before compute()")
        return linalg_ops.lda_project(self._state, X.reshape((X.shape[0], -1)))

    def get_config(self):
        return {"num_components": self.num_components}

    def get_state(self):
        if self._state is None:
            return {}
        return {"components": self._state.components, "eigenvalues": self._state.eigenvalues}

    def set_state(self, state):
        if state:
            self._state = linalg_ops.LDAState(
                components=jnp.asarray(state["components"]),
                eigenvalues=jnp.asarray(state["eigenvalues"]),
            )


class Fisherfaces(_SubspaceFeature):
    """PCA to (N - c) dims, LDA to (c - 1): W = W_pca @ W_lda.

    The reference's flagship classic feature (SURVEY.md §2.1, §3.1;
    BASELINE.json:8). One projection matrix at extract time — a single
    MXU matmul per batch.
    """

    name = "fisherfaces"

    def __init__(self, num_components: int = 0):
        self.num_components = int(num_components)
        self._mean = None
        self._components = None
        self._eigenvalues = None

    def compute(self, X, y):
        Xm = as_row_matrix(X)
        n, d = Xm.shape
        _, y_idx = _labels_to_indices(y)
        c = int(y_idx.max()) + 1
        pca_k = max(1, min(n - c, n, d))
        pca_state = linalg_ops.pca_fit(Xm, pca_k)
        proj = linalg_ops.pca_project(pca_state, Xm)
        k = self.num_components if self.num_components > 0 else c - 1
        k = min(k, c - 1, pca_k)
        lda_state = linalg_ops.lda_fit(proj, y_idx, num_classes=c, num_components=k)
        self._mean = pca_state.mean
        self._components = jnp.matmul(pca_state.components, lda_state.components, precision=jax.lax.Precision.HIGHEST)  # [D, k]
        self._eigenvalues = lda_state.eigenvalues
        return self._extract_batch(Xm)

    def _input_dim(self):
        if self._components is None:
            raise RuntimeError("Fisherfaces.extract called before compute()")
        return int(self._components.shape[0])

    def _extract_batch(self, X):
        if self._components is None:
            raise RuntimeError("Fisherfaces.extract called before compute()")
        Xf = X.reshape((X.shape[0], -1))
        return jnp.matmul(Xf - self._mean, self._components, precision=jax.lax.Precision.HIGHEST)

    @property
    def eigenvectors(self):
        return self._components

    @property
    def eigenvalues(self):
        return self._eigenvalues

    def get_config(self):
        return {"num_components": self.num_components}

    def get_state(self):
        if self._components is None:
            return {}
        return {
            "mean": self._mean,
            "components": self._components,
            "eigenvalues": self._eigenvalues,
        }

    def set_state(self, state):
        if state:
            self._mean = jnp.asarray(state["mean"])
            self._components = jnp.asarray(state["components"])
            self._eigenvalues = jnp.asarray(state["eigenvalues"])


class SpatialHistogram(AbstractFeature):
    """LBPH: LBP code map -> grid of cell histograms, concatenated
    (SURVEY.md §2.1, BASELINE.json:9). Stateless; fully batched."""

    name = "spatial_histogram"

    def __init__(self, lbp_operator: Optional[lbp_ops.LocalBinaryOperator] = None,
                 sz: Tuple[int, int] = (8, 8)):
        self.lbp_operator = lbp_operator or lbp_ops.ExtendedLBP(radius=1, neighbors=8)
        self.sz = tuple(int(v) for v in sz)

    def compute(self, X, y):
        if isinstance(X, (list, tuple)):
            X = jnp.stack([jnp.asarray(v) for v in X])
        return self._extract_batch(jnp.asarray(X, dtype=jnp.float32))

    def _extract_batch(self, X):
        codes = self.lbp_operator(X)
        return hist_ops.spatial_histogram(
            codes, grid=self.sz, num_bins=self.lbp_operator.num_bins
        )

    def get_config(self):
        return {
            "lbp_operator": {
                "type": self.lbp_operator.name,
                "config": self.lbp_operator.get_config(),
            },
            "sz": list(self.sz),
        }

    @classmethod
    def from_config(cls, config):
        op_spec = config.get("lbp_operator")
        op = None
        if op_spec:
            op = lbp_ops.LBP_OPERATORS[op_spec["type"]].from_config(op_spec["config"])
        return cls(lbp_operator=op, sz=tuple(config.get("sz", (8, 8))))


# ---------------------------------------------------------------------------
# Preprocessing plugins — share the feature protocol so they chain
# (SURVEY.md §2.1 "Preprocessing"). All stateless.
# ---------------------------------------------------------------------------


class _StatelessImageFeature(AbstractFeature):
    def compute(self, X, y):
        if isinstance(X, (list, tuple)):
            X = jnp.stack([jnp.asarray(v) for v in X])
        return self._extract_batch(jnp.asarray(X, dtype=jnp.float32))


class TanTriggsPreprocessing(_StatelessImageFeature):
    name = "tan_triggs"

    def __init__(self, alpha: float = 0.1, tau: float = 10.0, gamma: float = 0.2,
                 sigma0: float = 1.0, sigma1: float = 2.0):
        self.alpha, self.tau, self.gamma = float(alpha), float(tau), float(gamma)
        self.sigma0, self.sigma1 = float(sigma0), float(sigma1)

    def _extract_batch(self, X):
        return image_ops.tan_triggs(
            X, self.alpha, self.tau, self.gamma, self.sigma0, self.sigma1
        )

    def get_config(self):
        return {"alpha": self.alpha, "tau": self.tau, "gamma": self.gamma,
                "sigma0": self.sigma0, "sigma1": self.sigma1}


class HistogramEqualization(_StatelessImageFeature):
    name = "histogram_equalization"

    def __init__(self, num_bins: int = 256):
        self.num_bins = int(num_bins)

    def _extract_batch(self, X):
        return image_ops.histogram_equalize(X, self.num_bins)

    def get_config(self):
        return {"num_bins": self.num_bins}


class Resize(_StatelessImageFeature):
    name = "resize"

    def __init__(self, size: Tuple[int, int] = (70, 70)):
        self.size = tuple(int(v) for v in size)

    def _extract_batch(self, X):
        return image_ops.resize(X, self.size)

    def get_config(self):
        return {"size": list(self.size)}

    @classmethod
    def from_config(cls, config):
        return cls(size=tuple(config["size"]))


class MinMaxNormalize(_StatelessImageFeature):
    name = "minmax_normalize"

    def __init__(self, low: float = 0.0, high: float = 1.0):
        self.low, self.high = float(low), float(high)

    def _extract_batch(self, X):
        return image_ops.minmax_normalize(X, self.low, self.high)

    def get_config(self):
        return {"low": self.low, "high": self.high}
