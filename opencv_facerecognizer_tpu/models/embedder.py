"""CNN face embedder (Flax): the TPU-native replacement for the reference's
subspace projections on the north-star path (BASELINE.json:5: "feature
.compute() (PCA/LDA/LBP projection) is swapped for a FaceNet-style embedding
CNN compiled via XLA"; PAPERS.md:8 multibatch metric embedding).

Design, TPU-first:
- MobileFaceNet-style separable-conv net ending in a global depthwise conv
  and a linear embedding head, L2-normalized. Compute in bfloat16 (MXU),
  params in float32.
- Training uses an ArcFace (additive angular margin) softmax head — the
  strongest-known recipe for verification accuracy at this model size —
  with an optax train step under ``jit``; the whole epoch loop is host-side
  only over device-resident batches.
- ``CNNEmbedding`` adapts the trained net to the ``AbstractFeature``
  boundary, so ``PredictableModel(CNNEmbedding(...), NearestNeighbor(
  CosineDistance()))`` is exactly the reference's model composition with the
  CNN swapped in — the plugin gating the north star demands.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from opencv_facerecognizer_tpu.models.feature import AbstractFeature
from opencv_facerecognizer_tpu.ops import image as image_ops


class _SepBlock(nn.Module):
    """Depthwise-separable conv block with optional stride + residual.

    ``norm="light"`` drops the GroupNorm between the depthwise and
    pointwise convs (keeping the ReLU): each GroupNorm is a cross-channel
    reduction the VPU runs between MXU calls, and at 2 per block they are
    pure inter-matmul stall time. Measured (scripts/explore_perf.py r4):
    the light scheme is what lifted the separable net's MFU; training
    stability is covered by the remaining per-block GroupNorm.
    """

    features: int
    stride: int = 1
    norm: str = "full"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        inp = x
        ch = x.shape[-1]
        x = nn.Conv(
            ch, (3, 3), strides=(self.stride, self.stride),
            feature_group_count=ch, use_bias=False, dtype=self.dtype,
        )(x)
        if self.norm == "full":
            x = nn.GroupNorm(num_groups=4, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=4, dtype=self.dtype)(x)
        if self.stride == 1 and ch == self.features:
            x = x + inp
        return nn.relu(x)


class _DenseBlock(nn.Module):
    """Plain 3x3 conv block with optional stride + residual.

    The MXU-friendly alternative to ``_SepBlock``: a depthwise 3x3 is
    VPU-bound (one lane per channel), while a dense 3x3 at these channel
    widths is a batched matmul the systolic array runs near peak — ~8x the
    FLOPs but measured wall-clock competitive, with more model capacity."""

    features: int
    stride: int = 1
    norm: str = "full"  # dense blocks have one norm either way
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        inp = x
        x = nn.Conv(self.features, (3, 3), strides=(self.stride, self.stride),
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=4, dtype=self.dtype)(x)
        if self.stride == 1 and inp.shape[-1] == self.features:
            x = x + inp
        return nn.relu(x)


#: The serving-default embedder: the HARD-protocol accuracy-gated
#: structure at the GATED input resolution. Round-4 measurements
#: (scripts/.gate_embedder.jsonl, scripts/explore_perf.py):
#: - every stem structural speedup (space_to_depth 2/4, light norm, dense
#:   blocks) measured BELOW the baseline structure's verification accuracy
#:   at equal training (0.9655-0.9902 vs 0.9937 @ 9000 steps), so the
#:   structure stays s1/full/separable;
#: - the >=0.99 north-star numbers (0.9943 +/- 0.0020, fold_min 0.9917 @
#:   30000 steps, batch 192) are measured AT 64x64 INPUT — serving crops
#:   at 112x112 was never accuracy-justified, and embedding at the gated
#:   64x64 cuts the embed+crop stage cost ~3x with no accuracy claim lost.
SERVING_EMBEDDER_KWARGS = dict(
    embed_dim=256,
    stem_features=32,
    stage_features=(64, 128, 256),
    stage_blocks=(2, 2, 2),
    block="separable",
    space_to_depth=1,
    norm="full",
)
#: the accuracy protocol's input resolution — serving crops to the same
SERVING_FACE_SIZE = (64, 64)


class FaceEmbedNet(nn.Module):
    """MobileFaceNet-lite: stem conv -> conv stages -> global depthwise
    conv -> linear embedding, L2-normalized.

    ``stage_features``/``stage_blocks`` scale the net: the default is sized
    for one v5e chip at batch 256; tests use a tiny variant. ``block``
    picks the stage op: "separable" (depthwise+pointwise, fewer FLOPs,
    VPU-heavy) or "dense" (plain 3x3 convs, MXU-native).

    ``space_to_depth`` folds an s x s pixel block into s^2 input channels
    before the stem conv (lossless) — the same MXU-starving-stem fix the
    detector uses (detector.py:46-50): a 1-input-channel conv at 112x112
    feeds the 128-lane systolic array 9 rows of work per tile. The net's
    TOTAL downsample (2^(1 + len(stages))) is preserved: stem/stage
    strides drop to 1 once the folding already covered them, so the final
    spatial extent (and the GDC kernel) is identical for every setting.
    ``norm`` ("full" | "light") picks the per-block norm scheme (see
    ``_SepBlock``).
    """

    embed_dim: int = 128
    stem_features: int = 32
    stage_features: Sequence[int] = (64, 128, 128)
    stage_blocks: Sequence[int] = (2, 2, 2)
    block: str = "separable"
    space_to_depth: int = 1
    norm: str = "full"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # [N, H, W] grayscale or [N, H, W, C]
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        total_stride = 2 ** (1 + len(self.stage_features))
        s = int(self.space_to_depth)
        if s > 1:
            if total_stride % s:
                raise ValueError(
                    f"space_to_depth={s} must divide the net's total "
                    f"downsample {total_stride}"
                )
            n, h, w, c = x.shape
            if h % s or w % s:
                raise ValueError(
                    f"input {h}x{w} not divisible by space_to_depth={s}"
                )
            x = x.reshape(n, h // s, s, w // s, s, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // s, w // s, s * s * c)
        remaining = total_stride // s
        accum = 1
        stem_stride = 2 if accum < remaining else 1
        accum *= stem_stride
        x = nn.Conv(self.stem_features, (3, 3),
                    strides=(stem_stride, stem_stride), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=4, dtype=self.dtype)(x)
        x = nn.relu(x)
        block_cls = {"separable": _SepBlock, "dense": _DenseBlock}[self.block]
        for feats, blocks in zip(self.stage_features, self.stage_blocks):
            stride = 2 if accum < remaining else 1
            accum *= stride
            x = block_cls(feats, stride=stride, norm=self.norm,
                          dtype=self.dtype)(x)
            for _ in range(blocks - 1):
                x = block_cls(feats, stride=1, norm=self.norm,
                              dtype=self.dtype)(x)
        # Global depthwise conv (GDC): one weight per spatial position/channel.
        h, w, c = x.shape[1], x.shape[2], x.shape[3]
        x = nn.Conv(c, (h, w), padding="VALID", feature_group_count=c,
                    use_bias=False, dtype=self.dtype)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.embed_dim, use_bias=True, dtype=self.dtype)(x)
        x = x.astype(jnp.float32)
        return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)


def fused_forward(net: "FaceEmbedNet", params: Dict[str, Any],
                  x: jnp.ndarray, *, interpret: bool = False,
                  block_b: int = 8) -> jnp.ndarray:
    """Serving-only fused forward of a separable ``FaceEmbedNet``: same
    params, same math, different schedule.

    Stage blocks run as one pallas call each (``ops.pallas_sepblock`` —
    the activation never leaves VMEM inside a block, and the depthwise
    conv avoids XLA's grouped-conv lowering); the GDC runs as an einsum
    (``nhwc,hwc->nc`` — a multiply+reduce instead of a C-group grouped
    convolution); stem conv and embedding head stay XLA (dense convs and
    matmuls are already MXU-native). Training and the accuracy gate keep
    the flax graph — this path only re-schedules inference, and
    tests/test_pallas_sepblock.py pins the numerical equivalence
    (cosine > 0.9999 against ``net.apply``).

    Mirrors ``FaceEmbedNet.__call__``'s stride/naming scheme exactly
    (params: Conv_0/GroupNorm_0 stem, _SepBlock_i blocks, Conv_1 GDC,
    Dense_0 head); raises for configs it does not cover rather than
    silently diverging.
    """
    if net.block != "separable":
        raise ValueError("fused_forward covers block='separable' only")
    if net.norm != "full":
        raise ValueError("fused_forward covers norm='full' only")
    from opencv_facerecognizer_tpu.ops.pallas_sepblock import fused_sep_block

    dtype = net.dtype
    if x.ndim == 3:
        x = x[..., None]
    x = x.astype(dtype)
    total_stride = 2 ** (1 + len(net.stage_features))
    s = int(net.space_to_depth)
    if s > 1:
        n, h, w, c = x.shape
        x = x.reshape(n, h // s, s, w // s, s, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // s, w // s, s * s * c)
    remaining = total_stride // s
    accum = 1
    stem_stride = 2 if accum < remaining else 1
    accum *= stem_stride

    x = jax.lax.conv_general_dilated(
        x.astype(dtype), params["Conv_0"]["kernel"].astype(dtype),
        window_strides=(stem_stride, stem_stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    # the stem norm IS the flax module (same graph, no duplicated math —
    # only the stage blocks get the pallas schedule)
    x = nn.GroupNorm(num_groups=4, dtype=dtype).apply(
        {"params": params["GroupNorm_0"]}, x)
    x = jnp.maximum(x, 0.0).astype(dtype)

    i = 0
    for feats, blocks in zip(net.stage_features, net.stage_blocks):
        for b in range(blocks):
            stride = 2 if (b == 0 and accum < remaining) else 1
            if b == 0:
                accum *= stride
            p = params[f"_SepBlock_{i}"]
            in_ch = x.shape[-1]
            x = fused_sep_block(
                x,
                p["Conv_0"]["kernel"], p["GroupNorm_0"]["scale"],
                p["GroupNorm_0"]["bias"], p["Conv_1"]["kernel"],
                p["GroupNorm_1"]["scale"], p["GroupNorm_1"]["bias"],
                stride=stride, residual=(stride == 1 and in_ch == feats),
                block_b=block_b, interpret=interpret,
            )
            i += 1

    # GDC as multiply+reduce: kernel [h, w, 1, C] applied per channel
    gdc = params["Conv_1"]["kernel"].astype(dtype)
    x = jnp.einsum("nhwc,hwc->nc", x.astype(dtype), gdc[:, :, 0, :])
    dense = params["Dense_0"]
    x = (x.astype(dtype) @ dense["kernel"].astype(dtype)
         + dense["bias"].astype(dtype))
    x = x.astype(jnp.float32)
    return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)


def arcface_loss(
    embeddings: jnp.ndarray,
    labels: jnp.ndarray,
    weights: jnp.ndarray,
    margin: float = 0.5,
    scale: float = 32.0,
) -> jnp.ndarray:
    """Additive angular margin softmax loss.

    ``weights`` [C, E] are per-class directions (L2-normalized here);
    the true-class logit's angle is widened by ``margin`` before the scaled
    softmax, pushing embeddings toward tight per-class cones.
    """
    w = weights / jnp.maximum(jnp.linalg.norm(weights, axis=-1, keepdims=True), 1e-12)
    cos = jnp.clip(embeddings @ w.T, -1.0 + 1e-6, 1.0 - 1e-6)  # [N, C]
    theta = jnp.arccos(cos)
    onehot = jax.nn.one_hot(labels, w.shape[0], dtype=cos.dtype)
    cos_margin = jnp.cos(theta + margin)
    logits = scale * (onehot * cos_margin + (1.0 - onehot) * cos)
    return optax.softmax_cross_entropy(logits, onehot).mean()


def augment_batch(key: jax.Array, x: jnp.ndarray, *, occlusion_p: float = 0.5,
                  max_shift: int = 3, max_rotate_deg: float = 14.0,
                  scale_jitter: float = 0.1) -> jnp.ndarray:
    """On-device train-time augmentation for STANDARDIZED [N, H, W] faces:
    per-sample horizontal flip, rotation/scale resample, +/-max_shift
    translation (edge-padded dynamic slice), and a mean-fill cutout
    rectangle with probability ``occlusion_p`` — the invariances (pose,
    partial occlusion) a robust verifier needs but a 10-views-per-identity
    enrolment set cannot teach on its own. Pure jnp: runs inside the
    jitted train step."""
    from jax.scipy.ndimage import map_coordinates

    n, h, w = x.shape
    (k_flip, k_oy, k_ox, k_app, k_oh, k_ow, k_cy, k_cx,
     k_rot, k_sc) = jax.random.split(key, 10)
    flip = jax.random.bernoulli(k_flip, 0.5, (n,))
    x = jnp.where(flip[:, None, None], x[:, :, ::-1], x)
    if max_rotate_deg or scale_jitter:
        ang = jax.random.uniform(k_rot, (n,), minval=-max_rotate_deg,
                                 maxval=max_rotate_deg) * (jnp.pi / 180.0)
        sc = jax.random.uniform(k_sc, (n,), minval=1.0 - scale_jitter,
                                maxval=1.0 + scale_jitter)
        cy0, cx0 = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = jnp.mgrid[0:h, 0:w]

        def _warp(img, a, s):
            cos_a, sin_a = jnp.cos(a), jnp.sin(a)
            y0 = yy - cy0
            x0 = xx - cx0
            ys = (cos_a * y0 + sin_a * x0) / s + cy0
            xs = (-sin_a * y0 + cos_a * x0) / s + cx0
            return map_coordinates(img, [ys, xs], order=1, mode="nearest")

        x = jax.vmap(_warp)(x, ang, sc)
    pad = max_shift
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad)), mode="edge")
    oy = jax.random.randint(k_oy, (n,), 0, 2 * pad + 1)
    ox = jax.random.randint(k_ox, (n,), 0, 2 * pad + 1)
    x = jax.vmap(
        lambda img, a, b: jax.lax.dynamic_slice(img, (a, b), (h, w))
    )(xp, oy, ox)
    apply = jax.random.bernoulli(k_app, occlusion_p, (n,))
    oh = jax.random.randint(k_oh, (n,), h // 5, h // 2)
    ow = jax.random.randint(k_ow, (n,), w // 5, w // 2)
    cy = jax.random.randint(k_cy, (n,), 0, h)
    cx = jax.random.randint(k_cx, (n,), 0, w)
    yy = jnp.arange(h)[None, :, None]
    xx = jnp.arange(w)[None, None, :]
    box = ((yy >= cy[:, None, None]) & (yy < (cy + oh)[:, None, None])
           & (xx >= cx[:, None, None]) & (xx < (cx + ow)[:, None, None]))
    # mean fill (inputs are per-image standardized, so 0 == the mean)
    return jnp.where(box & apply[:, None, None], 0.0, x)


def make_train_step(model: FaceEmbedNet, optimizer, margin: float = 0.5,
                    scale: float = 32.0, augment: bool = False):
    """Returns a jitted (params, opt_state, batch_x, batch_y, key,
    margin_scale) -> updated step; ``augment`` applies ``augment_batch``
    in-graph; ``margin_scale`` (traced f32 in [0, 1]) ramps the angular
    margin so hard distributions don't collapse at cold start."""

    @jax.jit
    def step(params, opt_state, x, y, key, margin_scale):
        if augment:
            x = augment_batch(key, x)

        def loss_fn(p):
            emb = model.apply({"params": p["net"]}, x)
            return arcface_loss(emb, y, p["head"], margin * margin_scale, scale)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def init_embedder(
    model: FaceEmbedNet, num_classes: int, input_shape: Tuple[int, int], seed: int = 0
) -> Dict[str, Any]:
    """Initialize {net, head} params for training."""
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, *input_shape), dtype=jnp.float32)
    variables = model.init(rng, dummy)
    head = jax.random.normal(
        jax.random.fold_in(rng, 1), (num_classes, model.embed_dim), dtype=jnp.float32
    )
    return {"net": variables["params"], "head": head}


def train_embedder(
    model: FaceEmbedNet,
    params: Dict[str, Any],
    images: np.ndarray,
    labels: np.ndarray,
    *,
    steps: int = 200,
    batch_size: int = 64,
    learning_rate: float = 1e-3,
    margin: float = 0.5,
    scale: float = 32.0,
    seed: int = 0,
    augment: bool = False,
    lr_schedule: str = "constant",
    log_every: int = 0,
) -> Dict[str, Any]:
    """Host loop of jitted ArcFace steps over shuffled fixed-size batches.

    ``lr_schedule="cosine"`` decays to lr/100 over ``steps`` — the standard
    recipe once augmentation makes long runs productive."""
    if lr_schedule == "cosine":
        sched = optax.cosine_decay_schedule(learning_rate, steps, alpha=0.01)
        optimizer = optax.adam(sched)
    else:
        optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    step = make_train_step(model, optimizer, margin, scale, augment=augment)
    x = jnp.asarray(images, dtype=jnp.float32)
    y = jnp.asarray(labels, dtype=jnp.int32)
    n = x.shape[0]
    batch_size = min(batch_size, n)
    rng = np.random.default_rng(seed)
    key = jax.random.PRNGKey(seed)
    warmup = max(1, int(0.1 * steps))  # margin ramp: 0 -> full over 10%
    for i in range(steps):
        idx = jnp.asarray(rng.choice(n, size=batch_size, replace=n < batch_size))
        key, sub = jax.random.split(key)
        mscale = jnp.float32(min(1.0, i / warmup))
        params, opt_state, loss = step(params, opt_state, x[idx], y[idx],
                                       sub, mscale)
        if log_every and (i + 1) % log_every == 0:
            print(f"  arcface step {i + 1}/{steps}: loss {float(loss):.4f}")
    return params


def normalize_faces(x: jnp.ndarray, size: Tuple[int, int]) -> jnp.ndarray:
    """Serving-path face normalization: resize + per-image standardize."""
    x = image_ops.resize(jnp.asarray(x, jnp.float32), size)
    mean = jnp.mean(x, axis=(-2, -1), keepdims=True)
    std = jnp.maximum(jnp.std(x, axis=(-2, -1), keepdims=True), 1e-6)
    return (x - mean) / std


class CNNEmbedding(AbstractFeature):
    """The CNN embedder behind the ``AbstractFeature`` boundary.

    ``compute(X, y)`` trains (or fine-tunes preloaded params) with ArcFace on
    the enrolled dataset and returns embeddings; ``extract`` embeds new
    faces. Composes with ``NearestNeighbor(CosineDistance())`` into the
    north-star ``PredictableModel``.
    """

    name = "cnn_embedding"
    sample_ndim = 2

    def __init__(
        self,
        embed_dim: int = 128,
        input_size: Tuple[int, int] = (112, 112),
        stem_features: int = 32,
        stage_features: Sequence[int] = (64, 128, 128),
        stage_blocks: Sequence[int] = (2, 2, 2),
        block: str = "separable",
        space_to_depth: int = 1,
        norm: str = "full",
        train_steps: int = 200,
        batch_size: int = 64,
        learning_rate: float = 1e-3,
        seed: int = 0,
        augment: bool = False,
        lr_schedule: str = "constant",
        tta: bool = False,
    ):
        self.embed_dim = int(embed_dim)
        self.input_size = tuple(int(v) for v in input_size)
        self.stem_features = int(stem_features)
        self.stage_features = tuple(int(v) for v in stage_features)
        self.stage_blocks = tuple(int(v) for v in stage_blocks)
        self.block = str(block)
        self.space_to_depth = int(space_to_depth)
        self.norm = str(norm)
        self.train_steps = int(train_steps)
        self.batch_size = int(batch_size)
        self.learning_rate = float(learning_rate)
        self.seed = int(seed)
        self.augment = bool(augment)
        self.lr_schedule = str(lr_schedule)
        self.tta = bool(tta)
        self.net = FaceEmbedNet(
            embed_dim=self.embed_dim,
            stem_features=self.stem_features,
            stage_features=self.stage_features,
            stage_blocks=self.stage_blocks,
            block=self.block,
            space_to_depth=self.space_to_depth,
            norm=self.norm,
        )
        self._params: Optional[Dict[str, Any]] = None
        self._apply = jax.jit(lambda p, x: self.net.apply({"params": p}, x))

    # -- feature protocol --
    def compute(self, X, y):
        if isinstance(X, (list, tuple)):
            X = np.stack([np.asarray(v) for v in X])
        x = np.asarray(normalize_faces(X, self.input_size))
        y = np.asarray(y, dtype=np.int32)
        # Remap to 0-based contiguous indices before sizing the ArcFace
        # head: sparse labels ({5, 900}) must not allocate a 901-row head,
        # and negative labels must not silently produce wrong one-hot rows.
        # (The mapping itself isn't kept: the head is training-only scaffold;
        # prediction goes through the classifier's own label handling.)
        if len(y):
            classes, y = np.unique(y, return_inverse=True)
            y = y.astype(np.int32)
            num_classes = len(classes)
        else:
            num_classes = 1
        params = self._params
        if params is None:
            params = init_embedder(self.net, num_classes, self.input_size, self.seed)
        elif params["head"].shape[0] != num_classes:
            rng = jax.random.PRNGKey(self.seed + 1)
            params = dict(params, head=jax.random.normal(
                rng, (num_classes, self.embed_dim), dtype=jnp.float32))
        if self.train_steps > 0:
            params = train_embedder(
                self.net, params, x, y,
                steps=self.train_steps, batch_size=self.batch_size,
                learning_rate=self.learning_rate, seed=self.seed,
                augment=self.augment, lr_schedule=self.lr_schedule,
            )
        self._params = params
        return self._extract_batch(jnp.asarray(X, jnp.float32))

    def _extract_batch(self, X):
        if self._params is None:
            raise RuntimeError("CNNEmbedding.extract called before compute()")
        x = normalize_faces(X, self.input_size)
        emb = self._apply(self._params["net"], x)
        if self.tta:
            # Flip test-time augmentation (standard verification practice):
            # average the embedding with the mirrored view's, re-normalize.
            emb_f = self._apply(self._params["net"], x[:, :, ::-1])
            emb = emb + emb_f
            emb = emb / jnp.maximum(
                jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-12)
        return emb

    def load_params(self, params: Dict[str, Any]) -> None:
        """Install pretrained {net, head} params (skips/limits training)."""
        self._params = params

    # -- serialization protocol --
    def get_config(self):
        return {
            "embed_dim": self.embed_dim,
            "input_size": list(self.input_size),
            "stem_features": self.stem_features,
            "stage_features": list(self.stage_features),
            "stage_blocks": list(self.stage_blocks),
            "block": self.block,
            "space_to_depth": self.space_to_depth,
            "norm": self.norm,
            "train_steps": self.train_steps,
            "batch_size": self.batch_size,
            "learning_rate": self.learning_rate,
            "seed": self.seed,
            "augment": self.augment,
            "lr_schedule": self.lr_schedule,
            "tta": self.tta,
        }

    @classmethod
    def from_config(cls, config):
        config = dict(config)
        config["input_size"] = tuple(config.get("input_size", (112, 112)))
        config["stage_features"] = tuple(config.get("stage_features", (64, 128, 128)))
        config["stage_blocks"] = tuple(config.get("stage_blocks", (2, 2, 2)))
        config.setdefault("block", "separable")  # pre-r3 checkpoints
        config.setdefault("space_to_depth", 1)  # pre-r4 checkpoints
        config.setdefault("norm", "full")
        config.setdefault("augment", False)
        config.setdefault("lr_schedule", "constant")
        config.setdefault("tta", False)
        return cls(**config)

    def get_state(self):
        if self._params is None:
            return {}
        flat = jax.tree_util.tree_flatten_with_path(self._params["net"])[0]
        state = {"head": np.asarray(self._params["head"])}
        for path, leaf in flat:
            key = "net/" + "/".join(str(getattr(p, "key", p)) for p in path)
            state[key] = np.asarray(leaf)
        return state

    def set_state(self, state):
        if not state:
            return
        net: Dict[str, Any] = {}
        for key, leaf in state.items():
            if key == "head":
                continue
            parts = key.split("/")[1:]
            node = net
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = jnp.asarray(leaf)
        self._params = {"net": net, "head": jnp.asarray(state["head"])}
