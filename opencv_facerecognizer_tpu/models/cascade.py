"""Stage-1 face-proposal network: the compact front of the detection
cascade (ISSUE 13; design anchors PAPERS.md — *Compact Convolutional
Neural Network Cascade for Face Detection* (1508.01292) and *A Fast Face
Detection Method via CNN* (1803.10103)).

BENCH_DETAIL says detect dominates device cost at every dispatch bucket
(b128: 0.716 ms detect vs 0.449/0.561/0.454 ms for crop/embed/match), yet
most real camera frames carry zero faces. The cascade answer: run a tiny
proposal net at REDUCED resolution over every frame first, and invoke the
full detector only on frames it scores face-possible. This module is that
first stage:

- ``CascadeNet`` average-pools the input down by ``downsample`` (256x256
  -> 64x64 at the default 4), then a two-block stride-4 conv stack emits
  a coarse TILE logit map — one logit per ``downsample * 4``-pixel tile,
  so the decision is tileable (a per-tile consumer can gate regions; the
  serving runtime gates whole frames on the max tile).
- ``frame_scores`` reduces the tile map to one face-possible probability
  per frame: ``sigmoid(max(tile logits))`` — a frame is worth the full
  detector iff ANY tile might hold a face. Recall-shaped by construction:
  one confident tile keeps the frame.
- Training is per-tile weighted BCE against box-derived tile targets
  (a tile is positive when a face center lands in it, dilated by one tile
  so boundary-straddling faces never train as pure negatives), with
  ``pos_weight`` biasing toward recall — a stage-1 false negative is a
  face the system never sees, while a false positive merely wastes one
  full-detector slot.

The serving integration (``RecognitionPipeline.cascade_scores`` +
``RecognizerService``) compacts surviving frames into the bucketed
dispatch ladder and settles rejected frames as ``completed_empty``; see
runtime/recognizer.py. ``evaluate_gate`` measures the operating point the
bench gate enforces: recall vs the full detector's own verdicts.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

#: pixels per tile logit at ``downsample=d``: each conv block halves the
#: pooled map twice, so one logit covers ``d * TILE_CONV_STRIDE`` pixels.
TILE_CONV_STRIDE = 4

#: The default operating point (``FaceGate.threshold`` and the serving
#: ``--cascade-threshold`` default): chosen recall-first — the bench gate
#: requires >= 0.99 of stage-2-detectable faces to survive stage 1 here,
#: and the per-tile pos_weight training pushes face tiles far above it.
DEFAULT_THRESHOLD = 0.3


class CascadeNet(nn.Module):
    """Tiny stride-``downsample * 4`` FCN: avg-pool downsample -> two
    conv blocks -> per-tile face logit map ``[N, Ht, Wt]``.

    Sized to be orders cheaper than ``DetectorNet``: the pool shrinks the
    spatial extent ``downsample**2``-fold before the first conv, and the
    widest layer is ``features[-1]`` channels at 1/(4*downsample) of the
    input resolution — the whole forward is a rounding error next to one
    full-detector pass, which is what makes rejecting a face-free frame
    here a near-free early exit.
    """

    features: Sequence[int] = (8, 16)
    downsample: int = 4
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype) / 255.0
        d = int(self.downsample)
        if d > 1:
            x = nn.avg_pool(x, (d, d), strides=(d, d))
        for feats in self.features:
            x = nn.Conv(feats, (3, 3), strides=(2, 2), use_bias=False,
                        dtype=self.dtype)(x)
            x = nn.GroupNorm(num_groups=min(4, int(feats)),
                             dtype=self.dtype)(x)
            x = nn.relu(x)
        # Negative bias init: an untrained gate scores everything
        # face-unlikely instead of passing noise through at ~0.5 — the
        # fail-closed-toward-stage-2 direction is set by TRAINING, not
        # by the init (see pos_weight in train_face_gate).
        logits = nn.Conv(1, (1, 1), dtype=jnp.float32,
                         bias_init=nn.initializers.constant(-2.0))(x)
        return logits[..., 0]  # [N, Ht, Wt] tile logits


def frame_scores(net: CascadeNet, params: Dict[str, Any],
                 frames: jnp.ndarray) -> jnp.ndarray:
    """[N, H, W] frames -> [N] face-possible probabilities: the max tile
    logit through a sigmoid. Pure and jit-friendly — the serving pipeline
    compiles exactly this per dispatch rung."""
    logits = net.apply({"params": params}, frames)
    return jax.nn.sigmoid(jnp.max(logits, axis=(1, 2)))


def tile_targets(boxes: np.ndarray, num_boxes: np.ndarray,
                 image_size: Tuple[int, int], tile_px: int) -> np.ndarray:
    """Host-side per-tile targets from padded pixel yxyx boxes: a tile is
    positive when a face-box center lands in it, dilated by one tile in
    every direction (a face straddling a tile boundary must not teach its
    neighbors 'no face here'). Returns ``[N, Ht, Wt]`` float32 0/1."""
    n = boxes.shape[0]
    ht = max(1, image_size[0] // tile_px)
    wt = max(1, image_size[1] // tile_px)
    targets = np.zeros((n, ht, wt), dtype=np.float32)
    for i in range(n):
        for b in range(int(num_boxes[i])):
            y0, x0, y1, x1 = boxes[i, b]
            ty = int(np.clip((y0 + y1) / 2 / tile_px, 0, ht - 1))
            tx = int(np.clip((x0 + x1) / 2 / tile_px, 0, wt - 1))
            targets[i, max(0, ty - 1):ty + 2, max(0, tx - 1):tx + 2] = 1.0
    return targets


def gate_loss(logits: jnp.ndarray, targets: jnp.ndarray,
              pos_weight: float = 2.0) -> jnp.ndarray:
    """Per-tile weighted BCE. ``pos_weight`` > 1 buys recall: a missed
    face tile costs ``pos_weight`` x a passed background tile, so the
    trained operating curve puts face frames far above any reasonable
    threshold before background frames start leaking through."""
    p = jnp.clip(jax.nn.sigmoid(logits), 1e-6, 1.0 - 1e-6)
    bce = -(pos_weight * targets * jnp.log(p)
            + (1.0 - targets) * jnp.log(1.0 - p))
    return jnp.mean(bce)


def train_face_gate(net: CascadeNet, images: np.ndarray, boxes: np.ndarray,
                    num_boxes: np.ndarray, *, steps: int = 400,
                    batch_size: int = 32, learning_rate: float = 3e-3,
                    pos_weight: float = 2.0, seed: int = 0,
                    params: Optional[Dict] = None,
                    log_every: int = 0) -> Dict[str, Any]:
    """Train on (images [N,H,W] in [0,255], padded boxes, counts): the
    same scene format ``train_detector`` consumes, so one synthetic-scene
    set trains both cascade stages."""
    h, w = images.shape[1], images.shape[2]
    tile_px = int(net.downsample) * TILE_CONV_STRIDE
    targets = tile_targets(boxes, num_boxes, (h, w), tile_px)
    if params is None:
        params = net.init(jax.random.PRNGKey(seed),
                          jnp.zeros((1, h, w)))["params"]
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)

    @jax.jit  # ocvf-lint: boundary=jit-recompile-hazard -- offline training step, one fixed batch shape per train() call; never reached from the serving loop
    def step(params, opt_state, x, t):
        def loss_fn(p):
            return gate_loss(net.apply({"params": p}, x), t, pos_weight)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    n = images.shape[0]
    batch_size = min(batch_size, n)
    rng = np.random.default_rng(seed)
    x_all = jnp.asarray(images, jnp.float32)
    t_all = jnp.asarray(targets)
    for i in range(steps):
        idx = jnp.asarray(rng.choice(n, size=batch_size, replace=n < batch_size))
        params, opt_state, loss = step(params, opt_state, x_all[idx], t_all[idx])
        if log_every and (i + 1) % log_every == 0:
            print(f"  gate step {i + 1}/{steps}: loss {float(loss):.4f}")  # ocvf-lint: boundary=host-sync -- offline training progress log; nothing here runs on the serving loop
    return params


class FaceGate:
    """Stage-1 wrapper with the ``CNNFaceDetector``-shaped lifecycle:
    ``train`` / ``score_batch`` / ``save`` / ``load``. Holds the
    operating ``threshold`` the serving runtime defaults to (overridable
    per service via ``--cascade-threshold``)."""

    def __init__(self, features: Sequence[int] = (8, 16),
                 downsample: int = 4,
                 threshold: float = DEFAULT_THRESHOLD):
        self.net = CascadeNet(features=tuple(features),
                              downsample=int(downsample))
        self.threshold = float(threshold)
        self._params: Optional[Dict] = None

        def _score(params, frames):
            return frame_scores(self.net, params, frames)

        self._score_jit = jax.jit(_score)  # ocvf-lint: boundary=jit-recompile-hazard -- built ONCE at construction for the offline score_batch convenience path; serving compiles through RecognitionPipeline.cascade_scores' cache-keyed builder instead

    @property
    def params(self):
        return self._params

    def load_params(self, params) -> None:
        self._params = params

    @property
    def tile_px(self) -> int:
        return int(self.net.downsample) * TILE_CONV_STRIDE

    def train(self, images, boxes, num_boxes, **kwargs) -> "FaceGate":
        self._params = train_face_gate(self.net, images, boxes, num_boxes,
                                       params=self._params, **kwargs)
        return self

    def score_batch(self, frames) -> jnp.ndarray:
        """[N, H, W] -> [N] face-possible probabilities (device array;
        callers materialize). Offline/eval convenience — serving goes
        through ``RecognitionPipeline.cascade_scores`` for the per-rung
        compile cache."""
        if self._params is None:
            raise RuntimeError("FaceGate.score_batch before train()/load()")
        return self._score_jit(self._params, jnp.asarray(frames, jnp.float32))

    # -- checkpointing (msgpack, pickle-free, like CNNFaceDetector) --

    def save(self, path: str) -> None:
        import json

        from flax import serialization as flax_serialization

        from opencv_facerecognizer_tpu.utils.serialization import (
            atomic_write_bytes,
        )

        if self._params is None:
            raise RuntimeError("FaceGate.save called before train()/load()")
        payload = {
            "header": {
                "format_version": 1,
                "config_json": json.dumps({
                    "features": list(self.net.features),
                    "downsample": self.net.downsample,
                    "threshold": self.threshold,
                }),
            },
            "params": jax.tree_util.tree_map(np.asarray, self._params),
        }
        atomic_write_bytes(path, flax_serialization.msgpack_serialize(payload))

    @classmethod
    def load(cls, path: str) -> "FaceGate":
        import json

        from flax import serialization as flax_serialization

        with open(path, "rb") as fh:
            payload = flax_serialization.msgpack_restore(fh.read())
        config = json.loads(payload["header"]["config_json"])
        gate = cls(features=tuple(config["features"]),
                   downsample=config["downsample"],
                   threshold=config.get("threshold", DEFAULT_THRESHOLD))
        gate.load_params(jax.tree_util.tree_map(jnp.asarray,
                                                payload["params"]))
        return gate


def evaluate_gate(gate: FaceGate, detector, scenes: np.ndarray,
                  gt_counts: Optional[np.ndarray] = None,
                  threshold: Optional[float] = None,
                  batch_size: int = 32) -> Dict[str, Any]:
    """The cascade's operating-point measurement, AGAINST THE FULL
    DETECTOR'S OWN VERDICTS: stage-1 recall = the fraction of
    stage-2-detectable face frames that stage 1 keeps (a face stage 2
    cannot detect is not a cascade loss — it was never going to be
    served either way), and the face-free reject rate = the early-exit
    win on frames stage 2 would have scanned for nothing. The bench
    gate pins recall >= 0.99 at the default threshold.

    With ``gt_counts`` (per-scene ground-truth face counts), a
    "detectable face frame" requires BOTH a stage-2 detection AND a real
    face: a detector FALSE POSITIVE on a background frame is not a face
    the cascade can lose — the gate rejecting it is a precision win,
    reported separately as ``detector_fp_suppressed``. Without
    ``gt_counts`` every stage-2 firing counts as detectable (the
    conservative, label-free form)."""
    thr = gate.threshold if threshold is None else float(threshold)
    scenes = np.asarray(scenes, np.float32)
    detectable = kept_detectable = facefree = rejected_facefree = 0
    fp_frames = fp_suppressed = 0
    for start in range(0, len(scenes), batch_size):
        chunk = scenes[start:start + batch_size]
        _boxes, _scores, valid = detector.detect_batch(chunk)
        fires = np.asarray(valid).any(axis=1)  # ocvf-lint: boundary=host-sync -- offline evaluation readback; never on the serving loop
        scores = np.asarray(gate.score_batch(chunk))  # ocvf-lint: boundary=host-sync -- offline evaluation readback; never on the serving loop
        keep = scores >= thr
        if gt_counts is not None:
            gt = np.asarray(gt_counts[start:start + batch_size]) > 0
            has_face = fires & gt
            fp = fires & ~gt
            fp_frames += int(fp.sum())
            fp_suppressed += int((fp & ~keep).sum())
        else:
            has_face = fires
        detectable += int(has_face.sum())
        kept_detectable += int((has_face & keep).sum())
        facefree += int((~has_face).sum())
        rejected_facefree += int((~has_face & ~keep).sum())
    out = {
        "threshold": thr,
        "detectable_frames": detectable,
        "stage1_recall": (kept_detectable / detectable
                          if detectable else float("nan")),
        "facefree_frames": facefree,
        "facefree_reject_rate": (rejected_facefree / facefree
                                 if facefree else float("nan")),
    }
    if gt_counts is not None:
        out["detector_fp_frames"] = fp_frames
        out["detector_fp_suppressed"] = fp_suppressed
    return out
