"""Classifier plugins: the ``AbstractClassifier.compute/predict`` boundary.

Rebuilds the reference's ``facerec/classifier.py`` (SURVEY.md §2.1
"Classifiers"): NearestNeighbor (k-NN over a pluggable AbstractDistance) and
SVM. TPU-first redesign:

- ``NearestNeighbor.predict`` on a batch is ONE pairwise-distance block
  (a matmul for Euclidean/cosine) + ``lax.top_k`` + a one-hot vote — the
  reference's per-query "distances to ALL gallery vectors -> argsort" hot
  loop (SURVEY.md §3.4) collapses into a single fused device computation.
  This same math is what ``parallel.gallery`` shards across devices when the
  gallery outgrows one chip's HBM.
- ``SVM`` is a linear multi-class SVM trained on device with optax (the
  reference wrapped libsvm/cv2.ml, which do not exist here — SURVEY.md §7
  notes even cv2.face is absent in this environment).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from opencv_facerecognizer_tpu.ops import distance as distance_ops


def _require_int_labels(y) -> np.ndarray:
    """Labels must be integers (the reference's convention too — subject
    *names* belong in ExtendedPredictableModel.subject_names). String labels
    would also poison the array-only checkpoint state."""
    y = np.asarray(y)
    if not np.issubdtype(y.dtype, np.integer):
        raise TypeError(
            f"labels must be integers, got dtype {y.dtype}; map subject names to "
            "ids and carry the names in ExtendedPredictableModel.subject_names"
        )
    return y


class AbstractClassifier:
    """``compute(X, y)`` fits/enrolls; ``predict(q)`` -> (label, info)."""

    name = "abstract_classifier"

    def compute(self, X, y):
        raise NotImplementedError

    def predict(self, q):
        raise NotImplementedError

    # -- serialization protocol --
    def get_config(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, config: dict) -> "AbstractClassifier":
        return cls(**config)

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


def knn_predict(
    pairwise_fn,
    gallery: jnp.ndarray,
    gallery_labels: jnp.ndarray,
    num_classes: int,
    queries: jnp.ndarray,
    k: int,
):
    """Pure jittable k-NN: returns (pred_class_idx [Q], top-k labels [Q,k],
    top-k distances [Q,k]).

    Majority vote over the k nearest, ties broken toward the nearest
    neighbor's class (a 0.5-vote bonus — exactly one winner, no data-dependent
    control flow, so the whole thing jits).
    """
    d = pairwise_fn(queries, gallery)  # [Q, G]
    k = min(int(k), int(gallery.shape[0]))
    neg_topd, top_idx = jax.lax.top_k(-d, k)  # nearest = largest negative
    top_labels = jnp.take(gallery_labels, top_idx)  # [Q, k]
    votes = jax.nn.one_hot(top_labels, num_classes, dtype=jnp.float32).sum(axis=-2)
    nearest_bonus = 0.5 * jax.nn.one_hot(top_labels[..., 0], num_classes, dtype=jnp.float32)
    pred = jnp.argmax(votes + nearest_bonus, axis=-1)
    return pred, top_labels, -neg_topd


class NearestNeighbor(AbstractClassifier):
    """Brute-force k-NN over the enrolled gallery (SURVEY.md §3.4), batched."""

    name = "nearest_neighbor"

    def __init__(self, dist_metric: Optional[distance_ops.AbstractDistance] = None, k: int = 1):
        self.dist_metric = dist_metric or distance_ops.EuclideanDistance()
        self.k = int(k)
        self._gallery = None  # [G, D] float32
        self._labels = None  # [G] int32 class indices
        self._classes = None  # [C] original label values

    def compute(self, X, y):
        X = jnp.asarray(X, dtype=jnp.float32)
        self._gallery = X.reshape((X.shape[0], -1))
        classes, idx = np.unique(_require_int_labels(y), return_inverse=True)
        self._classes = np.asarray(classes)
        self._labels = jnp.asarray(idx, dtype=jnp.int32)

    def predict(self, q):
        """Single query -> [label, {"labels": [k], "distances": [k]}] (the
        reference's return shape); batch [Q, D] -> (labels [Q], info dict)."""
        if self._gallery is None:
            raise RuntimeError("NearestNeighbor.predict called before compute()")
        q = jnp.asarray(q, dtype=jnp.float32)
        single = q.ndim == 1
        qb = q[None] if single else q.reshape((q.shape[0], -1))
        pred_idx, top_labels, top_dist = knn_predict(
            self.dist_metric.pairwise,
            self._gallery,
            self._labels,
            len(self._classes),
            qb,
            self.k,
        )
        pred = self._classes[np.asarray(pred_idx)]
        info = {
            "labels": self._classes[np.asarray(top_labels)],
            "distances": np.asarray(top_dist),
        }
        if single:
            return [pred[0], {"labels": info["labels"][0], "distances": info["distances"][0]}]
        return pred, info

    def get_config(self):
        return {
            "dist_metric": {"type": self.dist_metric.name, "config": self.dist_metric.get_config()},
            "k": self.k,
        }

    @classmethod
    def from_config(cls, config):
        spec = config.get("dist_metric")
        metric = None
        if spec:
            metric = distance_ops.DISTANCES[spec["type"]].from_config(spec["config"])
        return cls(dist_metric=metric, k=config.get("k", 1))

    def get_state(self):
        if self._gallery is None:
            return {}
        return {
            "gallery": self._gallery,
            "labels": self._labels,
            "classes": jnp.asarray(self._classes),
        }

    def set_state(self, state):
        if state:
            self._gallery = jnp.asarray(state["gallery"])
            self._labels = jnp.asarray(state["labels"], dtype=jnp.int32)
            self._classes = np.asarray(state["classes"])

    def __repr__(self):
        return f"NearestNeighbor(dist_metric={self.dist_metric!r}, k={self.k})"


def _svm_train_step(params, opt_state, x, y_onehot, optimizer, reg):
    def loss_fn(p):
        logits = x @ p["w"] + p["b"]
        # Multi-class hinge (Crammer-Singer): max over wrong classes.
        correct = jnp.sum(logits * y_onehot, axis=-1)
        wrong = jnp.max(logits - 1e9 * y_onehot, axis=-1)
        hinge = jnp.maximum(0.0, 1.0 + wrong - correct)
        return jnp.mean(hinge) + reg * jnp.sum(p["w"] ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


class SVM(AbstractClassifier):
    """Linear multi-class SVM (Crammer-Singer hinge), trained on device.

    Capability stand-in for the reference's libsvm/cv2.ml wrapper
    (SURVEY.md §2.1); linear kernel covers the reference's default usage on
    subspace features. Training runs ``epochs`` full-batch Adam steps under
    ``lax.scan`` — one compiled loop, no Python iteration per step.
    """

    name = "svm"

    def __init__(self, reg: float = 1e-4, learning_rate: float = 0.05, epochs: int = 300):
        self.reg = float(reg)
        self.learning_rate = float(learning_rate)
        self.epochs = int(epochs)
        self._params = None
        self._classes = None
        self._feat_mean = None
        self._feat_scale = None

    def compute(self, X, y):
        X = jnp.asarray(X, dtype=jnp.float32)
        X = X.reshape((X.shape[0], -1))
        classes, idx = np.unique(_require_int_labels(y), return_inverse=True)
        self._classes = np.asarray(classes)
        c = len(classes)
        # Standardize features for conditioning; stored for predict.
        self._feat_mean = jnp.mean(X, axis=0)
        self._feat_scale = jnp.maximum(jnp.std(X, axis=0), 1e-6)
        Xs = (X - self._feat_mean) / self._feat_scale
        y_onehot = jax.nn.one_hot(jnp.asarray(idx), c, dtype=jnp.float32)
        d = Xs.shape[1]
        params = {
            "w": jnp.zeros((d, c), dtype=jnp.float32),
            "b": jnp.zeros((c,), dtype=jnp.float32),
        }
        optimizer = optax.adam(self.learning_rate)
        opt_state = optimizer.init(params)
        reg = self.reg

        def step(carry, _):
            p, s = carry
            p, s, loss = _svm_train_step(p, s, Xs, y_onehot, optimizer, reg)
            return (p, s), loss

        (params, _), _ = jax.lax.scan(step, (params, opt_state), None, length=self.epochs)
        self._params = params

    def decision_function(self, q):
        q = jnp.asarray(q, dtype=jnp.float32)
        qb = q.reshape((-1, q.shape[-1])) if q.ndim > 1 else q[None]
        qs = (qb.reshape((qb.shape[0], -1)) - self._feat_mean) / self._feat_scale
        return qs @ self._params["w"] + self._params["b"]

    def predict(self, q):
        if self._params is None:
            raise RuntimeError("SVM.predict called before compute()")
        q = jnp.asarray(q, dtype=jnp.float32)
        single = q.ndim == 1
        logits = self.decision_function(q)
        idx = np.asarray(jnp.argmax(logits, axis=-1))
        pred = self._classes[idx]
        info = {"logits": np.asarray(logits)}
        if single:
            return [pred[0], {"logits": info["logits"][0]}]
        return pred, info

    def get_config(self):
        return {"reg": self.reg, "learning_rate": self.learning_rate, "epochs": self.epochs}

    def get_state(self):
        if self._params is None:
            return {}
        return {
            "w": self._params["w"],
            "b": self._params["b"],
            "classes": jnp.asarray(self._classes),
            "feat_mean": self._feat_mean,
            "feat_scale": self._feat_scale,
        }

    def set_state(self, state):
        if state:
            self._params = {"w": jnp.asarray(state["w"]), "b": jnp.asarray(state["b"])}
            self._classes = np.asarray(state["classes"])
            self._feat_mean = jnp.asarray(state["feat_mean"])
            self._feat_scale = jnp.asarray(state["feat_scale"])


CLASSIFIERS = {cls.name: cls for cls in (NearestNeighbor, SVM)}
