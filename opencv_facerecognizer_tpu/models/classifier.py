"""Classifier plugins: the ``AbstractClassifier.compute/predict`` boundary.

Rebuilds the reference's ``facerec/classifier.py`` (SURVEY.md §2.1
"Classifiers"): NearestNeighbor (k-NN over a pluggable AbstractDistance) and
SVM. TPU-first redesign:

- ``NearestNeighbor.predict`` on a batch is ONE pairwise-distance block
  (a matmul for Euclidean/cosine) + ``lax.top_k`` + a one-hot vote — the
  reference's per-query "distances to ALL gallery vectors -> argsort" hot
  loop (SURVEY.md §3.4) collapses into a single fused device computation.
  This same math is what ``parallel.gallery`` shards across devices when the
  gallery outgrows one chip's HBM.
- ``SVM`` is a linear multi-class SVM trained on device with optax (the
  reference wrapped libsvm/cv2.ml, which do not exist here — SURVEY.md §7
  notes even cv2.face is absent in this environment).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from opencv_facerecognizer_tpu.ops import distance as distance_ops


def _require_int_labels(y) -> np.ndarray:
    """Labels must be integers (the reference's convention too — subject
    *names* belong in ExtendedPredictableModel.subject_names). String labels
    would also poison the array-only checkpoint state."""
    y = np.asarray(y)
    if not np.issubdtype(y.dtype, np.integer):
        raise TypeError(
            f"labels must be integers, got dtype {y.dtype}; map subject names to "
            "ids and carry the names in ExtendedPredictableModel.subject_names"
        )
    return y


class AbstractClassifier:
    """``compute(X, y)`` fits/enrolls; ``predict(q)`` -> (label, info)."""

    name = "abstract_classifier"

    def compute(self, X, y):
        raise NotImplementedError

    def predict(self, q):
        raise NotImplementedError

    # -- serialization protocol --
    def get_config(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, config: dict) -> "AbstractClassifier":
        return cls(**config)

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


def knn_predict(
    pairwise_fn,
    gallery: jnp.ndarray,
    gallery_labels: jnp.ndarray,
    num_classes: int,
    queries: jnp.ndarray,
    k: int,
):
    """Pure jittable k-NN: returns (pred_class_idx [Q], top-k labels [Q,k],
    top-k distances [Q,k]).

    Majority vote over the k nearest, ties broken toward the nearest
    neighbor's class (a 0.5-vote bonus — exactly one winner, no data-dependent
    control flow, so the whole thing jits).
    """
    d = pairwise_fn(queries, gallery)  # [Q, G]
    k = min(int(k), int(gallery.shape[0]))
    neg_topd, top_idx = jax.lax.top_k(-d, k)  # nearest = largest negative
    top_labels = jnp.take(gallery_labels, top_idx)  # [Q, k]
    votes = jax.nn.one_hot(top_labels, num_classes, dtype=jnp.float32).sum(axis=-2)
    nearest_bonus = 0.5 * jax.nn.one_hot(top_labels[..., 0], num_classes, dtype=jnp.float32)
    pred = jnp.argmax(votes + nearest_bonus, axis=-1)
    return pred, top_labels, -neg_topd


class NearestNeighbor(AbstractClassifier):
    """Brute-force k-NN over the enrolled gallery (SURVEY.md §3.4), batched."""

    name = "nearest_neighbor"

    def __init__(self, dist_metric: Optional[distance_ops.AbstractDistance] = None, k: int = 1):
        self.dist_metric = dist_metric or distance_ops.EuclideanDistance()
        self.k = int(k)
        self._gallery = None  # [G, D] float32
        self._labels = None  # [G] int32 class indices
        self._classes = None  # [C] original label values

    def compute(self, X, y):
        X = jnp.asarray(X, dtype=jnp.float32)
        self._gallery = X.reshape((X.shape[0], -1))
        classes, idx = np.unique(_require_int_labels(y), return_inverse=True)
        self._classes = np.asarray(classes)
        self._labels = jnp.asarray(idx, dtype=jnp.int32)

    def predict(self, q):
        """Single query -> [label, {"labels": [k], "distances": [k]}] (the
        reference's return shape); batch [Q, D] -> (labels [Q], info dict)."""
        if self._gallery is None:
            raise RuntimeError("NearestNeighbor.predict called before compute()")
        q = jnp.asarray(q, dtype=jnp.float32)
        single = q.ndim == 1
        qb = q[None] if single else q.reshape((q.shape[0], -1))
        pred_idx, top_labels, top_dist = knn_predict(
            self.dist_metric.pairwise,
            self._gallery,
            self._labels,
            len(self._classes),
            qb,
            self.k,
        )
        pred = self._classes[np.asarray(pred_idx)]
        info = {
            "labels": self._classes[np.asarray(top_labels)],
            "distances": np.asarray(top_dist),
        }
        if single:
            return [pred[0], {"labels": info["labels"][0], "distances": info["distances"][0]}]
        return pred, info

    def get_config(self):
        return {
            "dist_metric": {"type": self.dist_metric.name, "config": self.dist_metric.get_config()},
            "k": self.k,
        }

    @classmethod
    def from_config(cls, config):
        spec = config.get("dist_metric")
        metric = None
        if spec:
            metric = distance_ops.DISTANCES[spec["type"]].from_config(spec["config"])
        return cls(dist_metric=metric, k=config.get("k", 1))

    def get_state(self):
        if self._gallery is None:
            return {}
        return {
            "gallery": self._gallery,
            "labels": self._labels,
            "classes": jnp.asarray(self._classes),
        }

    def set_state(self, state):
        if state:
            self._gallery = jnp.asarray(state["gallery"])
            self._labels = jnp.asarray(state["labels"], dtype=jnp.int32)
            self._classes = np.asarray(state["classes"])

    def __repr__(self):
        return f"NearestNeighbor(dist_metric={self.dist_metric!r}, k={self.k})"


def _crammer_singer_hinge(logits, y_onehot):
    """Multi-class hinge (Crammer-Singer): margin vs the best wrong class."""
    correct = jnp.sum(logits * y_onehot, axis=-1)
    wrong = jnp.max(logits - 1e9 * y_onehot, axis=-1)
    return jnp.maximum(0.0, 1.0 + wrong - correct)


def _logits_predict(classes, logits, single):
    """Shared (label, {"logits"}) return shape for the SVM family."""
    idx = np.asarray(jnp.argmax(logits, axis=-1))
    pred = classes[idx]
    info = {"logits": np.asarray(logits)}
    if single:
        return [pred[0], {"logits": info["logits"][0]}]
    return pred, info


def _svm_train_step(params, opt_state, x, y_onehot, optimizer, reg):
    def loss_fn(p):
        logits = x @ p["w"] + p["b"]
        hinge = _crammer_singer_hinge(logits, y_onehot)
        return jnp.mean(hinge) + reg * jnp.sum(p["w"] ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


class SVM(AbstractClassifier):
    """Linear multi-class SVM (Crammer-Singer hinge), trained on device.

    Capability stand-in for the reference's libsvm/cv2.ml wrapper
    (SURVEY.md §2.1); linear kernel covers the reference's default usage on
    subspace features. Training runs ``epochs`` full-batch Adam steps under
    ``lax.scan`` — one compiled loop, no Python iteration per step.
    """

    name = "svm"

    def __init__(self, reg: float = 1e-4, learning_rate: float = 0.05, epochs: int = 300):
        self.reg = float(reg)
        self.learning_rate = float(learning_rate)
        self.epochs = int(epochs)
        self._params = None
        self._classes = None
        self._feat_mean = None
        self._feat_scale = None

    def compute(self, X, y):
        X = jnp.asarray(X, dtype=jnp.float32)
        X = X.reshape((X.shape[0], -1))
        classes, idx = np.unique(_require_int_labels(y), return_inverse=True)
        self._classes = np.asarray(classes)
        c = len(classes)
        # Standardize features for conditioning; stored for predict.
        self._feat_mean = jnp.mean(X, axis=0)
        self._feat_scale = jnp.maximum(jnp.std(X, axis=0), 1e-6)
        Xs = (X - self._feat_mean) / self._feat_scale
        y_onehot = jax.nn.one_hot(jnp.asarray(idx), c, dtype=jnp.float32)
        d = Xs.shape[1]
        params = {
            "w": jnp.zeros((d, c), dtype=jnp.float32),
            "b": jnp.zeros((c,), dtype=jnp.float32),
        }
        optimizer = optax.adam(self.learning_rate)
        opt_state = optimizer.init(params)
        reg = self.reg

        def step(carry, _):
            p, s = carry
            p, s, loss = _svm_train_step(p, s, Xs, y_onehot, optimizer, reg)
            return (p, s), loss

        (params, _), _ = jax.lax.scan(step, (params, opt_state), None, length=self.epochs)
        self._params = params

    def decision_function(self, q):
        q = jnp.asarray(q, dtype=jnp.float32)
        qb = q.reshape((-1, q.shape[-1])) if q.ndim > 1 else q[None]
        qs = (qb.reshape((qb.shape[0], -1)) - self._feat_mean) / self._feat_scale
        return qs @ self._params["w"] + self._params["b"]

    def predict(self, q):
        if self._params is None:
            raise RuntimeError("SVM.predict called before compute()")
        single = jnp.asarray(q).ndim == 1
        return _logits_predict(self._classes, self.decision_function(q), single)

    def get_config(self):
        return {"reg": self.reg, "learning_rate": self.learning_rate, "epochs": self.epochs}

    def get_state(self):
        if self._params is None:
            return {}
        return {
            "w": self._params["w"],
            "b": self._params["b"],
            "classes": jnp.asarray(self._classes),
            "feat_mean": self._feat_mean,
            "feat_scale": self._feat_scale,
        }

    def set_state(self, state):
        if state:
            self._params = {"w": jnp.asarray(state["w"]), "b": jnp.asarray(state["b"])}
            self._classes = np.asarray(state["classes"])
            self._feat_mean = jnp.asarray(state["feat_mean"])
            self._feat_scale = jnp.asarray(state["feat_scale"])


def _kernel_matrix(kind: str, gamma, coef0, degree, A: jnp.ndarray, B: jnp.ndarray):
    """K[i, j] = k(A[i], B[j]) — every kernel is matmul-shaped for the MXU."""
    if kind == "linear":
        return A @ B.T
    if kind == "poly":
        return (gamma * (A @ B.T) + coef0) ** degree
    if kind == "rbf":
        sq = (
            jnp.sum(A * A, axis=-1)[:, None]
            - 2.0 * (A @ B.T)
            + jnp.sum(B * B, axis=-1)[None, :]
        )
        return jnp.exp(-gamma * jnp.maximum(sq, 0.0))
    raise ValueError(f"unknown kernel {kind!r}; pick linear | poly | rbf")


class KernelSVM(AbstractClassifier):
    """Multi-class kernel SVM (RBF / polynomial / linear), trained on device.

    Completes the reference's kernel-capable ``libsvm``/``cv2.ml`` SVM
    surface (SURVEY.md §2.1 "Classifiers"; §2.2 lists libsvm as imported
    native code) that the linear :class:`SVM` only partially covered.

    TPU-first formulation instead of an SMO port: by the representer
    theorem the decision function is ``f_c(x) = sum_i alpha[i,c] *
    k(x_i, x) + b_c``, so training optimizes ``alpha`` ([N, C]) directly
    with Crammer-Singer hinge loss plus the RKHS norm ``tr(alpha^T K
    alpha)`` — the same objective class libsvm solves in the dual, but as
    dense matmuls under one ``lax.scan`` Adam loop (static shapes, no
    per-sample working-set loop, kernel matrix computed once on the MXU).
    ``gamma`` defaults to sklearn's "scale" heuristic 1/(D * var(X)).
    """

    name = "kernel_svm"

    def __init__(self, kernel: str = "rbf", gamma: Optional[float] = None,
                 coef0: float = 1.0, degree: int = 3, reg: float = 1e-3,
                 learning_rate: float = 0.05, epochs: int = 400):
        if kernel not in ("linear", "poly", "rbf"):
            raise ValueError(f"unknown kernel {kernel!r}; pick linear | poly | rbf")
        self.kernel = kernel
        self.gamma = None if gamma is None else float(gamma)
        self.coef0 = float(coef0)
        self.degree = int(degree)
        self.reg = float(reg)
        self.learning_rate = float(learning_rate)
        self.epochs = int(epochs)
        self._sv = None        # [N, D] support/training vectors
        self._alpha = None     # [N, C]
        self._b = None         # [C]
        self._gamma_eff = None
        self._classes = None

    def _k(self, A, B):
        return _kernel_matrix(self.kernel, self._gamma_eff, self.coef0,
                              self.degree, A, B)

    def compute(self, X, y):
        X = jnp.asarray(X, dtype=jnp.float32).reshape((np.shape(X)[0], -1))
        classes, idx = np.unique(_require_int_labels(y), return_inverse=True)
        self._classes = np.asarray(classes)
        c = len(classes)
        self._sv = X
        if self.gamma is not None:
            self._gamma_eff = self.gamma
        else:
            var = float(jnp.var(X))
            self._gamma_eff = 1.0 / (X.shape[1] * max(var, 1e-12))
        K = self._k(X, X)  # [N, N], once
        y_onehot = jax.nn.one_hot(jnp.asarray(idx), c, dtype=jnp.float32)
        params = {
            "alpha": jnp.zeros((X.shape[0], c), dtype=jnp.float32),
            "b": jnp.zeros((c,), dtype=jnp.float32),
        }
        optimizer = optax.adam(self.learning_rate)
        opt_state = optimizer.init(params)
        reg = self.reg

        def loss_fn(p):
            logits = K @ p["alpha"] + p["b"]
            hinge = _crammer_singer_hinge(logits, y_onehot)
            rkhs = jnp.sum(p["alpha"] * (K @ p["alpha"]))
            return jnp.mean(hinge) + reg * rkhs

        def step(carry, _):
            p, s = carry
            loss, grads = jax.value_and_grad(loss_fn)(p)
            updates, s = optimizer.update(grads, s, p)
            return (optax.apply_updates(p, updates), s), loss

        (params, _), _ = jax.lax.scan(step, (params, opt_state), None,
                                      length=self.epochs)
        self._alpha = params["alpha"]
        self._b = params["b"]

    def decision_function(self, q):
        q = jnp.asarray(q, dtype=jnp.float32)
        qb = q[None] if q.ndim == 1 else q.reshape((q.shape[0], -1))
        return self._k(qb, self._sv) @ self._alpha + self._b

    def predict(self, q):
        if self._alpha is None:
            raise RuntimeError("KernelSVM.predict called before compute()")
        single = jnp.asarray(q).ndim == 1
        return _logits_predict(self._classes, self.decision_function(q), single)

    def get_config(self):
        return {
            "kernel": self.kernel, "gamma": self.gamma, "coef0": self.coef0,
            "degree": self.degree, "reg": self.reg,
            "learning_rate": self.learning_rate, "epochs": self.epochs,
        }

    def get_state(self):
        if self._alpha is None:
            return {}
        return {
            "sv": self._sv,
            "alpha": self._alpha,
            "b": self._b,
            "gamma_eff": jnp.float32(self._gamma_eff),
            "classes": jnp.asarray(self._classes),
        }

    def set_state(self, state):
        if state:
            self._sv = jnp.asarray(state["sv"])
            self._alpha = jnp.asarray(state["alpha"])
            self._b = jnp.asarray(state["b"])
            self._gamma_eff = float(state["gamma_eff"])
            self._classes = np.asarray(state["classes"])

    def __repr__(self):
        return (f"KernelSVM(kernel={self.kernel!r}, gamma={self.gamma}, "
                f"degree={self.degree}, reg={self.reg})")


CLASSIFIERS = {cls.name: cls for cls in (NearestNeighbor, SVM, KernelSVM)}
