"""Model composition: ``PredictableModel`` (SURVEY.md §1 L4, §3.4).

``compute(X, y)`` = feature.compute then classifier.compute on the projected
batch; ``predict(X)`` = classifier.predict(feature.extract(X)). Both accept
batches, so the serving path runs detect -> extract -> predict as one device
computation per frame batch instead of the reference's per-face Python loop.

``ExtendedPredictableModel`` carries ``image_size`` + subject-name list, the
fork's addition used by the apps (SURVEY.md §2.1 "Model").
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from opencv_facerecognizer_tpu.models.classifier import AbstractClassifier
from opencv_facerecognizer_tpu.models.feature import AbstractFeature


class PredictableModel:
    name = "predictable_model"

    def __init__(self, feature: AbstractFeature, classifier: AbstractClassifier):
        if not isinstance(feature, AbstractFeature):
            raise TypeError(f"feature must be an AbstractFeature, got {type(feature)}")
        if not isinstance(classifier, AbstractClassifier):
            raise TypeError(f"classifier must be an AbstractClassifier, got {type(classifier)}")
        self.feature = feature
        self.classifier = classifier

    def compute(self, X, y):
        features = self.feature.compute(X, y)
        self.classifier.compute(features, y)
        return features

    def predict(self, X):
        return self.classifier.predict(self.feature.extract(X))

    # -- serialization protocol --
    def get_config(self) -> dict:
        from opencv_facerecognizer_tpu.utils import serialization

        return {
            "feature": serialization.serialize_spec(self.feature),
            "classifier": serialization.serialize_spec(self.classifier),
        }

    @classmethod
    def from_config(cls, config: dict) -> "PredictableModel":
        from opencv_facerecognizer_tpu.utils import serialization

        return cls(
            feature=serialization.deserialize_spec(config["feature"]),
            classifier=serialization.deserialize_spec(config["classifier"]),
        )

    def get_state(self) -> dict:
        return {"feature": self.feature.get_state(), "classifier": self.classifier.get_state()}

    def set_state(self, state: dict) -> None:
        if state:
            self.feature.set_state(state.get("feature", {}))
            self.classifier.set_state(state.get("classifier", {}))

    def __repr__(self):
        return f"{type(self).__name__}(feature={self.feature!r}, classifier={self.classifier!r})"


class ExtendedPredictableModel(PredictableModel):
    """PredictableModel + image_size + subject names (SURVEY.md §2.1)."""

    name = "extended_predictable_model"

    def __init__(
        self,
        feature: AbstractFeature,
        classifier: AbstractClassifier,
        image_size: Tuple[int, int] = (70, 70),
        subject_names: Optional[List[str]] = None,
    ):
        super().__init__(feature, classifier)
        self.image_size = tuple(int(v) for v in image_size)
        self.subject_names = list(subject_names) if subject_names else []

    def subject_name(self, label: int) -> str:
        if 0 <= int(label) < len(self.subject_names):
            return self.subject_names[int(label)]
        return str(label)

    def get_config(self) -> dict:
        cfg = super().get_config()
        cfg["image_size"] = list(self.image_size)
        cfg["subject_names"] = list(self.subject_names)
        return cfg

    @classmethod
    def from_config(cls, config: dict) -> "ExtendedPredictableModel":
        from opencv_facerecognizer_tpu.utils import serialization

        return cls(
            feature=serialization.deserialize_spec(config["feature"]),
            classifier=serialization.deserialize_spec(config["classifier"]),
            image_size=tuple(config.get("image_size", (70, 70))),
            subject_names=config.get("subject_names", []),
        )
