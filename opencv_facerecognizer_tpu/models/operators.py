"""Feature operators: compose features as a DAG (SURVEY.md §2.1
"Feature operators": FeatureOperator, ChainOperator, CombineOperator).

Composition is plain function composition over batched extracts, so a chain
like Resize -> TanTriggs -> Fisherfaces stays one jittable device graph.
"""

from __future__ import annotations

import jax.numpy as jnp

from opencv_facerecognizer_tpu.models.feature import AbstractFeature


class FeatureOperator(AbstractFeature):
    """Base for binary feature operators."""

    name = "feature_operator"

    def __init__(self, model1: AbstractFeature, model2: AbstractFeature):
        self.model1 = model1
        self.model2 = model2

    @property
    def sample_ndim(self):  # type: ignore[override]
        return self.model1.sample_ndim

    def get_config(self):
        from opencv_facerecognizer_tpu.utils import serialization

        return {
            "model1": serialization.serialize_spec(self.model1),
            "model2": serialization.serialize_spec(self.model2),
        }

    @classmethod
    def from_config(cls, config):
        from opencv_facerecognizer_tpu.utils import serialization

        return cls(
            serialization.deserialize_spec(config["model1"]),
            serialization.deserialize_spec(config["model2"]),
        )

    def get_state(self):
        return {"model1": self.model1.get_state(), "model2": self.model2.get_state()}

    def set_state(self, state):
        if state:
            self.model1.set_state(state.get("model1", {}))
            self.model2.set_state(state.get("model2", {}))

    def __repr__(self):
        return f"{type(self).__name__}({self.model1!r}, {self.model2!r})"


class ChainOperator(FeatureOperator):
    """model2(model1(X)): e.g. TanTriggs -> Fisherfaces (SURVEY.md §3.4)."""

    name = "chain_operator"

    def compute(self, X, y):
        return self.model2.compute(self.model1.compute(X, y), y)

    def extract(self, X):
        return self.model2.extract(self.model1.extract(X))

    def _extract_batch(self, X):
        return self.extract(X)


class CombineOperator(FeatureOperator):
    """Concatenate both features' flattened outputs along the last axis."""

    name = "combine_operator"

    @staticmethod
    def _flat2(a: jnp.ndarray, batched: bool) -> jnp.ndarray:
        if batched:
            return a.reshape((a.shape[0], -1))
        return a.reshape((-1,))

    def compute(self, X, y):
        a = jnp.asarray(self.model1.compute(X, y))
        b = jnp.asarray(self.model2.compute(X, y))
        return jnp.concatenate([self._flat2(a, True), self._flat2(b, True)], axis=-1)

    def extract(self, X):
        X = jnp.asarray(X, dtype=jnp.float32)
        batched = X.ndim != self.sample_ndim
        a = jnp.asarray(self.model1.extract(X))
        b = jnp.asarray(self.model2.extract(X))
        return jnp.concatenate([self._flat2(a, batched), self._flat2(b, batched)], axis=-1)

    def _extract_batch(self, X):
        return self.extract(X)
