"""Feature operators: compose features as a DAG (SURVEY.md §2.1
"Feature operators": FeatureOperator, ChainOperator, CombineOperator).

Composition is plain function composition over batched extracts, so a chain
like Resize -> TanTriggs -> Fisherfaces stays one jittable device graph.
"""

from __future__ import annotations

import jax.numpy as jnp

from opencv_facerecognizer_tpu.models.feature import AbstractFeature


class FeatureOperator(AbstractFeature):
    """Base for binary feature operators."""

    name = "feature_operator"

    def __init__(self, model1: AbstractFeature, model2: AbstractFeature):
        self.model1 = model1
        self.model2 = model2

    @property
    def sample_ndim(self):  # type: ignore[override]
        return self.model1.sample_ndim

    def get_config(self):
        from opencv_facerecognizer_tpu.utils import serialization

        return {
            "model1": serialization.serialize_spec(self.model1),
            "model2": serialization.serialize_spec(self.model2),
        }

    @classmethod
    def from_config(cls, config):
        from opencv_facerecognizer_tpu.utils import serialization

        return cls(
            serialization.deserialize_spec(config["model1"]),
            serialization.deserialize_spec(config["model2"]),
        )

    def get_state(self):
        return {"model1": self.model1.get_state(), "model2": self.model2.get_state()}

    def set_state(self, state):
        if state:
            self.model1.set_state(state.get("model1", {}))
            self.model2.set_state(state.get("model2", {}))

    def __repr__(self):
        return f"{type(self).__name__}({self.model1!r}, {self.model2!r})"


class ChainOperator(FeatureOperator):
    """model2(model1(X)): e.g. TanTriggs -> Fisherfaces (SURVEY.md §3.4)."""

    name = "chain_operator"

    def compute(self, X, y):
        return self.model2.compute(self.model1.compute(X, y), y)

    def extract(self, X):
        return self.model2.extract(self.model1.extract(X))

    def _extract_batch(self, X):
        return self.extract(X)


class CombineOperatorND(FeatureOperator):
    """Concatenate both features' outputs along a chosen axis *without*
    flattening (SURVEY.md §2.1 "Feature operators": upstream
    ``operators.py`` CombineOperatorND).

    Unlike :class:`CombineOperator`, per-sample structure is preserved: two
    features emitting ``(B, H, W)`` maps combine to ``(B, H, 2W)`` with
    ``hstack_axis=-1``. Both features must agree on every axis except the
    concatenation axis. ``hstack_axis`` addresses the *per-sample* axes
    (0 = first sample axis), so batched and single-sample calls concatenate
    along the same semantic axis.
    """

    name = "combine_operator_nd"

    def __init__(self, model1: AbstractFeature, model2: AbstractFeature,
                 hstack_axis: int = -1):
        super().__init__(model1, model2)
        self.hstack_axis = int(hstack_axis)

    def _axis(self, out_ndim: int, batched: bool) -> int:
        # Negative axes already count from the end; shift non-negative
        # per-sample axes past the batch dim when the output is batched.
        if self.hstack_axis < 0:
            return self.hstack_axis
        return self.hstack_axis + (1 if batched else 0)

    def get_config(self):
        cfg = super().get_config()
        cfg["hstack_axis"] = self.hstack_axis
        return cfg

    @classmethod
    def from_config(cls, config):
        from opencv_facerecognizer_tpu.utils import serialization

        return cls(
            serialization.deserialize_spec(config["model1"]),
            serialization.deserialize_spec(config["model2"]),
            hstack_axis=config.get("hstack_axis", -1),
        )

    def compute(self, X, y):
        a = jnp.asarray(self.model1.compute(X, y))
        b = jnp.asarray(self.model2.compute(X, y))
        return jnp.concatenate([a, b], axis=self._axis(a.ndim, batched=True))

    def extract(self, X):
        X = jnp.asarray(X, dtype=jnp.float32)
        batched = X.ndim != self.sample_ndim
        a = jnp.asarray(self.model1.extract(X))
        b = jnp.asarray(self.model2.extract(X))
        return jnp.concatenate([a, b], axis=self._axis(a.ndim, batched))

    def _extract_batch(self, X):
        return self.extract(X)


class CombineOperator(FeatureOperator):
    """Concatenate both features' flattened outputs along the last axis."""

    name = "combine_operator"

    @staticmethod
    def _flat2(a: jnp.ndarray, batched: bool) -> jnp.ndarray:
        if batched:
            return a.reshape((a.shape[0], -1))
        return a.reshape((-1,))

    def compute(self, X, y):
        a = jnp.asarray(self.model1.compute(X, y))
        b = jnp.asarray(self.model2.compute(X, y))
        return jnp.concatenate([self._flat2(a, True), self._flat2(b, True)], axis=-1)

    def extract(self, X):
        X = jnp.asarray(X, dtype=jnp.float32)
        batched = X.ndim != self.sample_ndim
        a = jnp.asarray(self.model1.extract(X))
        b = jnp.asarray(self.model2.extract(X))
        return jnp.concatenate([self._flat2(a, batched), self._flat2(b, batched)], axis=-1)

    def _extract_batch(self, X):
        return self.extract(X)
