"""CNN face detector (Flax): the TPU-native replacement for the reference's
Haar-cascade ``detectMultiScale`` stage (BASELINE.json:5: "the Haar-cascade
detectMultiScale stage becomes a batched ... CNN detector"; design anchors
PAPERS.md:6-7 — CNN-cascade / single-pass CNN detection).

Instead of translating the cascade's image pyramid + sliding window (serial,
shape-dynamic — hostile to XLA), this is a single-stage anchor-free
("center-heatmap") detector:

- A small FCN backbone at stride 8 emits a face-center heatmap plus box
  size and sub-cell offset maps — all dense convs, MXU work.
- Decode is static-shape end-to-end (SURVEY.md §7 "hard parts"): 3x3
  max-pool peak suppression, ``top_k`` K candidates, box assembly, then the
  fixed-K ``ops.nms`` mask. One jitted graph, batchable under vmap — the
  "fixed-size outputs + on-device NMS" contract from SURVEY.md §2.2.
- Training: penalty-reduced focal loss on a Gaussian-splatted heatmap +
  masked L1 on size/offset (the standard center-heatmap recipe), jitted.

``CNNFaceDetector.detect(img)`` keeps the reference's ``CascadedDetector``
API (SURVEY.md §2.1 "Face detector wrapper"): returns a host-side list of
(x0, y0, x1, y1) boxes for one image; the batched device path used by the
serving runtime is ``detect_batch``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from opencv_facerecognizer_tpu.ops import nms as nms_ops

STRIDE = 8


class DetectorNet(nn.Module):
    """Stride-8 FCN: downsampling conv blocks -> heatmap/size/offset heads.

    ``space_to_depth`` folds an s x s pixel block into s^2 input channels
    before the first conv (lossless). Why: the MXU is a 128-lane systolic
    array, and convs with 1-16 input channels at 128x128+ resolution run at
    a small fraction of peak (round-3 stage attribution measured the
    default stem at MFU 0.08 — 55% of the whole fused batch). With s2d=4
    every conv sees >=16 input channels at <=64x64, the net stride stays 8
    (conv blocks downsample 8/s2d), and the per-cell receptive field is
    unchanged in pixels. Decode/train code is stride-8 either way.
    """

    features: Sequence[int] = (16, 32, 64)
    head_features: int = 64
    space_to_depth: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype) / 255.0
        s = int(self.space_to_depth)
        if STRIDE % s:
            # A non-divisor would FLOOR remaining (s=3 -> remaining 2, net
            # stride 6) while decode still scales by STRIDE=8 — every box
            # silently mis-scaled. Refuse instead.
            raise ValueError(
                f"space_to_depth={s} must divide the decode stride {STRIDE}"
            )
        if s > 1:
            n, h, w, c = x.shape
            x = x.reshape(n, h // s, s, w // s, s, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // s, w // s, s * s * c)
        remaining = STRIDE // s  # conv blocks must still reach stride 8
        accum = 1
        for feats in self.features:
            stride = 2 if accum < remaining else 1
            accum *= stride
            x = nn.Conv(feats, (3, 3), strides=(stride, stride),
                        use_bias=False, dtype=self.dtype)(x)
            x = nn.GroupNorm(num_groups=4, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(feats, (3, 3), use_bias=False, dtype=self.dtype)(x)
            x = nn.GroupNorm(num_groups=4, dtype=self.dtype)(x)
            x = nn.relu(x)
        if accum != remaining:
            raise ValueError(
                f"features={self.features!r} with space_to_depth={s} cannot "
                f"reach stride {STRIDE}: blocks provide x{accum}, need "
                f"x{remaining} (add blocks or lower space_to_depth)"
            )
        h = nn.Conv(self.head_features, (3, 3), dtype=self.dtype)(x)
        h = nn.relu(h)
        heatmap = nn.Conv(1, (1, 1), dtype=jnp.float32,
                          bias_init=nn.initializers.constant(-4.0))(h)
        size = nn.Conv(2, (1, 1), dtype=jnp.float32)(h)
        offset = nn.Conv(2, (1, 1), dtype=jnp.float32)(h)
        return {
            "heatmap": heatmap[..., 0],  # [N, Hs, Ws] logits
            "size": size,  # [N, Hs, Ws, 2] (h, w) in output-cell units
            "offset": offset,  # [N, Hs, Ws, 2] sub-cell (dy, dx)
        }


def decode_detections(
    outputs: Dict[str, jnp.ndarray],
    max_faces: int = 16,
    score_threshold: float = 0.3,
    iou_threshold: float = 0.4,
):
    """Batched static-shape decode: outputs -> (boxes [N,K,4] pixel yxyx,
    scores [N,K], valid [N,K])."""
    heat = jax.nn.sigmoid(outputs["heatmap"])  # [N, Hs, Ws]
    size = outputs["size"]
    offset = outputs["offset"]
    n, hs, ws = heat.shape

    # CenterNet peak NMS: keep cells that are their 3x3 neighborhood max.
    pooled = nn.max_pool(heat[..., None], (3, 3), strides=(1, 1), padding="SAME")[..., 0]
    peaks = jnp.where(heat >= pooled - 1e-6, heat, 0.0)

    flat = peaks.reshape(n, hs * ws)
    k = min(max_faces * 4, hs * ws)  # over-collect, NMS trims
    scores, idx = jax.lax.top_k(flat, k)  # [N, k]
    cy = (idx // ws).astype(jnp.float32)
    cx = (idx % ws).astype(jnp.float32)
    take = lambda m: jnp.take_along_axis(m.reshape(n, hs * ws, 2), idx[..., None], axis=1)
    sz = take(size)
    off = take(offset)
    cy = cy + off[..., 0]
    cx = cx + off[..., 1]
    bh = jnp.maximum(sz[..., 0], 1e-3)
    bw = jnp.maximum(sz[..., 1], 1e-3)
    boxes = jnp.stack(
        [
            (cy - bh / 2) * STRIDE,
            (cx - bw / 2) * STRIDE,
            (cy + bh / 2) * STRIDE,
            (cx + bw / 2) * STRIDE,
        ],
        axis=-1,
    )  # [N, k, 4]

    def per_image(b, s):
        return nms_ops.nms_fixed(b, s, max_faces, iou_threshold, score_threshold)

    boxes, scores, valid = jax.vmap(per_image)(boxes, scores)
    # Clamp to the decoded canvas: cy +/- bh/2 freely projects past the
    # edge for border faces, and every consumer (serving pipeline included
    # — this is the shared decode) expects in-frame pixel boxes. Bounds are
    # EXCLUSIVE yxyx (y1 == H is a legal bottom-edge box, matching dataset
    # targets and crop slicing). Invalid slots are zero boxes, unaffected.
    # detect_batch additionally clips to the caller's pre-padding extent.
    lim = jnp.asarray(
        [hs * STRIDE, ws * STRIDE, hs * STRIDE, ws * STRIDE], boxes.dtype
    )
    boxes = jnp.clip(boxes, 0.0, lim)
    return boxes, scores, valid


def gaussian_heatmap_targets(
    boxes: np.ndarray, num_boxes: np.ndarray, image_size: Tuple[int, int], max_boxes: int
):
    """Host-side target builder: padded pixel yxyx boxes [N, B, 4] + counts
    -> (heatmap [N,Hs,Ws], size [N,Hs,Ws,2], offset [N,Hs,Ws,2],
    mask [N,Hs,Ws]). Gaussian splat radius follows the box size."""
    n = boxes.shape[0]
    hs, ws = image_size[0] // STRIDE, image_size[1] // STRIDE
    heat = np.zeros((n, hs, ws), dtype=np.float32)
    size = np.zeros((n, hs, ws, 2), dtype=np.float32)
    offset = np.zeros((n, hs, ws, 2), dtype=np.float32)
    mask = np.zeros((n, hs, ws), dtype=np.float32)
    ys, xs = np.mgrid[0:hs, 0:ws]
    for i in range(n):
        for b in range(int(num_boxes[i])):
            y0, x0, y1, x1 = boxes[i, b] / STRIDE
            cy, cx = (y0 + y1) / 2, (x0 + x1) / 2
            bh, bw = max(y1 - y0, 1e-3), max(x1 - x0, 1e-3)
            iy, ix = int(np.clip(cy, 0, hs - 1)), int(np.clip(cx, 0, ws - 1))
            sigma = max((bh + bw) / 8.0, 0.7)
            g = np.exp(-((ys - iy) ** 2 + (xs - ix) ** 2) / (2 * sigma**2))
            heat[i] = np.maximum(heat[i], g)
            size[i, iy, ix] = (bh, bw)
            offset[i, iy, ix] = (cy - iy, cx - ix)
            mask[i, iy, ix] = 1.0
    return heat, size, offset, mask


def detector_loss(outputs, targets, alpha: float = 2.0, beta: float = 4.0):
    """Penalty-reduced focal loss on the heatmap + masked L1 on size/offset."""
    pred = jax.nn.sigmoid(outputs["heatmap"])
    pred = jnp.clip(pred, 1e-6, 1.0 - 1e-6)
    gt = targets["heatmap"]
    pos = (gt >= 0.999).astype(jnp.float32)
    pos_loss = -pos * ((1 - pred) ** alpha) * jnp.log(pred)
    neg_loss = -(1 - pos) * ((1 - gt) ** beta) * (pred**alpha) * jnp.log(1 - pred)
    num_pos = jnp.maximum(jnp.sum(pos), 1.0)
    heat_loss = (jnp.sum(pos_loss) + jnp.sum(neg_loss)) / num_pos
    m = targets["mask"][..., None]
    size_loss = jnp.sum(jnp.abs(outputs["size"] - targets["size"]) * m) / num_pos
    off_loss = jnp.sum(jnp.abs(outputs["offset"] - targets["offset"]) * m) / num_pos
    return heat_loss + 0.1 * size_loss + off_loss


def make_detector_train_step(model: DetectorNet, optimizer):
    @jax.jit
    def step(params, opt_state, images, targets):
        def loss_fn(p):
            return detector_loss(model.apply({"params": p}, images), targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def train_detector(
    model: DetectorNet,
    images: np.ndarray,
    boxes: np.ndarray,
    num_boxes: np.ndarray,
    *,
    steps: int = 300,
    batch_size: int = 16,
    learning_rate: float = 1e-3,
    seed: int = 0,
    params: Optional[Dict] = None,
    log_every: int = 0,
):
    """Train on (images [N,H,W], padded boxes [N,B,4], counts [N])."""
    h, w = images.shape[1], images.shape[2]
    heat, size, offset, mask = gaussian_heatmap_targets(
        boxes, num_boxes, (h, w), boxes.shape[1]
    )
    if params is None:
        params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, h, w)))["params"]
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    step = make_detector_train_step(model, optimizer)
    n = images.shape[0]
    batch_size = min(batch_size, n)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(images, jnp.float32)
    t_all = {
        "heatmap": jnp.asarray(heat),
        "size": jnp.asarray(size),
        "offset": jnp.asarray(offset),
        "mask": jnp.asarray(mask),
    }
    for i in range(steps):
        idx = jnp.asarray(rng.choice(n, size=batch_size, replace=n < batch_size))
        batch_t = {k: v[idx] for k, v in t_all.items()}
        params, opt_state, loss = step(params, opt_state, x[idx], batch_t)
        if log_every and (i + 1) % log_every == 0:
            print(f"  detector step {i + 1}/{steps}: loss {float(loss):.4f}")
    return params


def evaluate_detector(
    detector: "CNNFaceDetector",
    scenes: np.ndarray,
    gt_boxes: np.ndarray,
    gt_counts: np.ndarray,
    iou_threshold: float = 0.5,
    batch_size: int = 32,
):
    """Detection quality vs oracle boxes: recall/precision@IoU (VERDICT
    round-1 item #4 — the Haar-cascade replacement must be measurably good,
    not merely present).

    Greedy matching per image: predictions in descending score order claim
    the best still-unmatched ground-truth box with IoU >= threshold.
    Returns {"recall", "precision", "f1", "mean_matched_iou",
    "num_gt", "num_pred"}.
    """
    scenes = np.asarray(scenes, np.float32)
    gt_boxes = np.asarray(gt_boxes, np.float32)
    gt_counts = np.asarray(gt_counts)
    tp = fp = 0
    total_gt = int(gt_counts.sum())
    matched_ious = []
    for start in range(0, len(scenes), batch_size):
        chunk = scenes[start : start + batch_size]
        boxes, scores, valid = (np.asarray(v) for v in detector.detect_batch(chunk))
        for i in range(len(chunk)):
            gi = start + i
            gts = gt_boxes[gi, : int(gt_counts[gi])]
            taken = np.zeros(len(gts), dtype=bool)
            order = np.argsort(-scores[i])
            for j in order:
                if not valid[i, j]:
                    continue
                py0, px0, py1, px1 = boxes[i, j]
                best_iou, best_g = 0.0, -1
                for gidx, (gy0, gx0, gy1, gx1) in enumerate(gts):
                    if taken[gidx]:
                        continue
                    iy = max(0.0, min(py1, gy1) - max(py0, gy0))
                    ix = max(0.0, min(px1, gx1) - max(px0, gx0))
                    inter = iy * ix
                    union = ((py1 - py0) * (px1 - px0)
                             + (gy1 - gy0) * (gx1 - gx0) - inter)
                    iou = inter / union if union > 0 else 0.0
                    if iou > best_iou:
                        best_iou, best_g = iou, gidx
                if best_g >= 0 and best_iou >= iou_threshold:
                    taken[best_g] = True
                    tp += 1
                    matched_ious.append(best_iou)
                else:
                    fp += 1
    recall = tp / total_gt if total_gt else float("nan")
    precision = tp / (tp + fp) if (tp + fp) else float("nan")
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall > 0 else 0.0)
    return {
        "recall": recall,
        "precision": precision,
        "f1": f1,
        "mean_matched_iou": float(np.mean(matched_ious)) if matched_ious else 0.0,
        "num_gt": total_gt,
        "num_pred": tp + fp,
    }


class CNNFaceDetector:
    """``CascadedDetector``-shaped wrapper (SURVEY.md §2.1): ``detect(img)``
    -> list of (x0, y0, x1, y1) int tuples, plus the batched device path."""

    #: Default config selected by measurement (scripts/explore_perf.py,
    #: 2026-07-30, v5e): s2d=4/(64,64) runs the batch-32 forward in 0.199 ms
    #: vs 0.584 ms for the old 1-channel-stem (16,32,64) net — 2.9x — at
    #: equal-or-better detection quality (recall 1.0, precision 1.0,
    #: IoU 0.904 vs 0.901 on the held-out synthetic scenes).
    def __init__(
        self,
        features: Sequence[int] = (64, 64),
        head_features: int = 64,
        max_faces: int = 16,
        score_threshold: float = 0.3,
        iou_threshold: float = 0.4,
        space_to_depth: int = 4,
    ):
        self.net = DetectorNet(features=tuple(features),
                               head_features=head_features,
                               space_to_depth=space_to_depth)
        self.max_faces = int(max_faces)
        self.score_threshold = float(score_threshold)
        self.iou_threshold = float(iou_threshold)
        self._params: Optional[Dict] = None

        def _detect(params, images):
            outputs = self.net.apply({"params": params}, images)
            return decode_detections(
                outputs, self.max_faces, self.score_threshold, self.iou_threshold
            )

        self._detect_jit = jax.jit(_detect)

    def train(self, images, boxes, num_boxes, **kwargs):
        self._params = train_detector(
            self.net, images, boxes, num_boxes, params=self._params, **kwargs
        )
        return self

    def load_params(self, params) -> None:
        self._params = params

    @property
    def params(self):
        return self._params

    # -- checkpointing (msgpack, pickle-free, like utils.serialization) --

    def save(self, path: str) -> None:
        import json

        from flax import serialization as flax_serialization

        if self._params is None:
            raise RuntimeError("CNNFaceDetector.save called before train()/load_params()")
        payload = {
            "header": {
                "format_version": 1,
                "config_json": json.dumps({
                    "features": list(self.net.features),
                    "head_features": self.net.head_features,
                    "max_faces": self.max_faces,
                    "score_threshold": self.score_threshold,
                    "iou_threshold": self.iou_threshold,
                    "space_to_depth": self.net.space_to_depth,
                }),
            },
            "params": jax.tree_util.tree_map(np.asarray, self._params),
        }
        from opencv_facerecognizer_tpu.utils.serialization import atomic_write_bytes

        atomic_write_bytes(path, flax_serialization.msgpack_serialize(payload))

    @classmethod
    def load(cls, path: str) -> "CNNFaceDetector":
        import json

        from flax import serialization as flax_serialization

        with open(path, "rb") as fh:
            payload = flax_serialization.msgpack_restore(fh.read())
        config = json.loads(payload["header"]["config_json"])
        det = cls(
            features=tuple(config["features"]),
            head_features=config["head_features"],
            max_faces=config["max_faces"],
            score_threshold=config["score_threshold"],
            iou_threshold=config["iou_threshold"],
            space_to_depth=config.get("space_to_depth", 1),  # pre-r3 ckpts
        )
        det.load_params(jax.tree_util.tree_map(jnp.asarray, payload["params"]))
        return det

    def detect_batch(self, images: jnp.ndarray):
        """[N, H, W] -> (boxes [N,K,4] yxyx, scores [N,K], valid [N,K]) on device.

        Arbitrary H/W are accepted (the CascadedDetector-shaped contract):
        inputs are edge-padded up to the next multiple of the decode stride
        (which every space_to_depth setting divides), and box coordinates
        are unaffected since padding grows only the bottom/right."""
        if self._params is None:
            raise RuntimeError("CNNFaceDetector.detect called before train()/load_params()")
        images = jnp.asarray(images, jnp.float32)
        h, w = images.shape[1], images.shape[2]
        ph, pw = (-h) % STRIDE, (-w) % STRIDE
        if ph or pw:
            images = jnp.pad(images, ((0, 0), (0, ph), (0, pw)), mode="edge")
        boxes, scores, valid = self._detect_jit(self._params, images)
        # Decode clamps to its (possibly padded) canvas; additionally clip
        # to the CALLER's pre-padding extent so border faces never report
        # coordinates inside the padding strip. Bounds are exclusive yxyx
        # (y1 == h is a legal bottom-edge box).
        lim = jnp.asarray([h, w, h, w], boxes.dtype)
        boxes = jnp.clip(boxes, 0.0, lim)
        return boxes, scores, valid

    def detect(self, img: np.ndarray):
        """Single grayscale image -> [(x0, y0, x1, y1)] like the reference's
        CascadedDetector.detect (x/y order flipped to its x-first tuples)."""
        boxes, scores, valid = self.detect_batch(jnp.asarray(img, jnp.float32)[None])
        boxes = np.asarray(boxes[0])
        valid = np.asarray(valid[0])
        out = []
        for b, ok in zip(boxes, valid):
            if ok:
                y0, x0, y1, x1 = (int(round(float(v))) for v in b)
                out.append((x0, y0, x1, y1))
        return out
