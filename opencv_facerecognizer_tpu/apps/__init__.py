"""CLI entry points (SURVEY.md §2.1 "Packaging/CLI"): the reference's
``ocvf_*`` script surface as argparse apps — train, recognize (JSONL or
video transport), interactive enrolment via the control topic."""
