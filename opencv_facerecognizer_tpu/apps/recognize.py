"""``ocvf-recognize``: the live recognizer node (SURVEY.md §2.1 "Standalone
recognizer app" / "ROS recognizer node", rebuilt per §3.3): frames in ->
fused TPU batch recognition -> results out.

Transports:
- ``--source jsonl`` (default): frames as JSONL on stdin (see
  runtime.connector.encode_frame for the schema), results as JSONL on
  stdout — the shippable default in a ROS-less environment. The enrolment
  protocol rides the same stream ({"topic": "ocvfacerec/control",
  "data": {"cmd": "enroll", ...}}).
- ``--source dir``: replay a directory of images once and exit — demo/
  verification mode.

Needs a CNN embedding model checkpoint (ocvf-train --model cnn) and a
detector checkpoint (CNNFaceDetector.save).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ocvf-recognize",
                                description="Live face recognition on TPU")
    # Required for every SERVING mode; the offline --registry-swap
    # runbook touches only the state dir and needs none of them, so the
    # requirement is enforced in main() rather than by argparse.
    p.add_argument("--model", help="CNN model checkpoint (ocvf-train --model cnn)")
    p.add_argument("--detector", help="detector checkpoint (CNNFaceDetector.save)")
    p.add_argument("--gallery",
                   help="dataset dir to enroll at startup (folder per subject)")
    p.add_argument("--source", choices=["jsonl", "socket", "dir"], default="jsonl")
    p.add_argument("--dir", help="image directory for --source dir")
    p.add_argument("--port", type=int, default=5600,
                   help="TCP port for --source socket (JSONL over TCP)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address for --source socket")
    p.add_argument("--profile-dir",
                   help="capture a jax.profiler trace of the first "
                        "--profile-batches batches into this directory "
                        "(open with TensorBoard or xprof)")
    p.add_argument("--profile-batches", type=int, default=20)
    p.add_argument("--frame-size", type=int, nargs=2, default=(256, 256), metavar=("H", "W"))
    p.add_argument("--parallel", choices=["fused", "pp"], default="fused",
                   help="fused: one sharded graph over all devices (default); "
                        "pp: two-stage pipeline parallelism — detector on "
                        "half the devices, embedder+gallery on the other "
                        "half (needs an even device count >= 2)")
    p.add_argument("--fused-embedder", action="store_true",
                   help="run the embed stage on the fused pallas schedule "
                        "(ops.pallas_sepblock; single-device mesh only — "
                        "flip after scripts/bench_sepblock.py measures a "
                        "win on your chip)")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--flush-ms", type=float, default=30.0,
                   help="max age of the oldest buffered frame before a "
                        "partial batch flushes; with --target-latency-ms "
                        "this is the CAP of the adaptive deadline")
    # ---- overlapped serving pipeline (runtime.recognizer docstring) ----
    p.add_argument("--target-latency-ms", type=float, default=None,
                   help="continuous-batching latency target: a partial "
                        "batch waits only target minus the EWMA of the "
                        "measured downstream service time (clamped to "
                        "[2 ms, --flush-ms]) instead of the fixed flush "
                        "window — trickle load stops paying the full "
                        "--flush-ms of batching delay")
    p.add_argument("--bucket-sizes", type=int, nargs="+",
                   default=[8, 32, 128], metavar="B",
                   help="dispatch bucket ladder: a partial batch is sliced "
                        "to the smallest bucket >= its real frame count "
                        "(every bucket is compiled at warmup, so partial "
                        "batches never recompile); 0 disables slicing")
    p.add_argument("--no-readback-worker", action="store_true",
                   help="fall back to the pre-worker serving loop that "
                        "drains readbacks inline with is_ready polling "
                        "(the two --*-poll-ms knobs) instead of the "
                        "event-driven readback worker thread")
    p.add_argument("--readback-poll-ms", type=float, default=5.0,
                   help="fallback-path poll interval while waiting out an "
                        "over-depth/forced readback (only used with "
                        "--no-readback-worker, or for a proxy readback "
                        "that cannot be blocked on)")
    p.add_argument("--drain-poll-ms", type=float, default=50.0,
                   help="completion-wait tick: how often drain() and the "
                        "fallback path re-check for finished work")
    # ---- ingest pipeline (runtime.ingest; README "Ingest pipeline") ----
    p.add_argument("--ingest-mode", choices=["f32", "uint8", "jpeg"],
                   default=None,
                   help="ingest transfer mode. f32 (default): legacy "
                        "float staging. uint8: frames stage and cross "
                        "host->device as uint8 through the pre-allocated "
                        "staging ring (4x less transfer volume; the cast/"
                        "normalize fuses into the detect prologue on "
                        "device). jpeg: uint8 plus compressed camera "
                        "payloads ({'__jpeg__': base64}) decoded off the "
                        "hot thread by the decode worker pool directly "
                        "into the staging ring")
    p.add_argument("--ingest-ring-depth", type=int, default=0,
                   help="staging buffers pre-allocated per dispatch-"
                        "bucket rung. 0 (default) = auto: sized to the "
                        "in-flight window + 2 so the bounded ring never "
                        "caps pipeline overlap (every overlapped batch "
                        "holds a buffer, plus the one being assembled). "
                        "Ring exhaustion backpressures through admission "
                        "(reason=staging), never allocates")
    p.add_argument("--ingest-decode-workers", type=int, default=2,
                   help="decode worker threads for --ingest-mode jpeg "
                        "(corrupt payloads dead-letter with reason "
                        "decode_error; depth/latency on the metrics "
                        "surface)")
    p.add_argument("--transfer-uint8", action="store_true",
                   help="DEPRECATED (one release): alias for "
                        "--ingest-mode uint8. The old unpinned-staging "
                        "uint8 path (batch-8 p99 measured ~109-118 ms "
                        "under load) is gone — this flag now routes "
                        "through the pre-allocated staging ring, which "
                        "keeps the 4x byte win without the p99 pathology")
    # ---- cascade early-exit detection (models.cascade; README) ----
    p.add_argument("--cascade", metavar="PATH",
                   help="stage-1 FaceGate checkpoint (models.cascade."
                        "FaceGate.save): score every frame at reduced "
                        "resolution first and dispatch only face-possible "
                        "frames to the full detector; face-free frames "
                        "settle as completed_empty with an empty result "
                        "publish. Unset = single-stage serving")
    p.add_argument("--cascade-threshold", type=float, default=None,
                   metavar="P",
                   help="stage-1 operating point: frames scoring below P "
                        "exit early. Default: the checkpoint's own trained "
                        "threshold. Brownout level >= 1 tightens it one "
                        "notch (rejecting borderline frames) before "
                        "shedding admitted intake")
    p.add_argument("--no-cascade", action="store_true",
                   help="escape hatch: serve single-stage even with a "
                        "--cascade checkpoint loaded (e.g. to A/B the "
                        "gate's recall in production)")
    # ---- temporal identity cache (runtime.tracker; README) ----
    p.add_argument("--track-reverify-frames", type=int, default=8,
                   metavar="N",
                   help="temporal identity cache: a track whose stream "
                        "stays coherent serves its confirmed identity "
                        "from the cache (frames settle completed_cached, "
                        "skipping detect+embed+match) for at most N-1 "
                        "consecutive frames before a scheduled full "
                        "re-verify; appearance drift or association "
                        "ambiguity re-verifies immediately. Brownout "
                        "level >= 1 stretches the interval before "
                        "shedding intake")
    p.add_argument("--track-iou-min", type=float, default=0.3,
                   metavar="IOU",
                   help="minimum box IoU for frame-to-frame track "
                        "association (centroid fallback below it)")
    p.add_argument("--no-track-cache", action="store_true",
                   help="escape hatch: disable the temporal identity "
                        "cache — every frame takes the full "
                        "detect+embed+match path")
    p.add_argument("--similarity-threshold", type=float, default=0.3)
    p.add_argument("--capacity", type=int, default=4096, help="gallery capacity")
    p.add_argument("--gallery-dtype", choices=["bf16", "f32"], default="bf16",
                   help="device dtype of gallery rows. bf16 (default): half "
                        "the gallery HBM and 1.24x faster match at 1M rows "
                        "(measured, BENCH_DETAIL.json:gallery_dtype), "
                        "numerically identical — both matchers compute "
                        "bf16 x bf16 -> f32 regardless of storage")
    # ---- large-gallery matching (parallel.quantizer / ops.ivf_match;
    # README "Large-gallery matching") ----
    p.add_argument("--match-mode", choices=["auto", "exact", "ivf"],
                   default="auto",
                   help="gallery matcher selection. auto (default): exact "
                        "scan below the IVF capacity threshold (262k "
                        "rows), two-stage IVF shortlist + exact rerank "
                        "above it; exact: always brute-force; ivf: "
                        "two-stage whenever the quantizer is trained "
                        "(falls back to exact until then). The exact "
                        "scan is linear in gallery size — million-"
                        "identity galleries need ivf/auto")
    p.add_argument("--ivf-nlist", type=int, default=0,
                   help="k-means cell count of the IVF coarse quantizer; "
                        "0 = auto (~4*sqrt(capacity), power of two). More "
                        "cells = smaller rerank buckets but a costlier "
                        "stage-1 scan and retrain")
    p.add_argument("--ivf-nprobe", type=int, default=8,
                   help="shortlisted cells per query: the recall-vs-"
                        "latency knob (each probe adds one cell's rows "
                        "to the exact rerank bucket)")
    p.add_argument("--async-grow", action="store_true",
                   help="gallery auto-grow compiles + installs the next "
                        "tier on a background thread: overflowing "
                        "enrolments return immediately and become "
                        "matchable seconds later, instead of stalling the "
                        "serving loop for the XLA recompile")
    p.add_argument("--metrics-jsonl", help="append per-batch metrics to this file")
    # ---- steady-state failure handling (runtime.resilience) ----
    p.add_argument("--readback-deadline", type=float, default=30.0,
                   metavar="S",
                   help="dead-letter a dispatched batch whose device->host "
                        "readback is not ready after this many seconds "
                        "(the hang-mode outage costs one deadline, never "
                        "a wedge)")
    p.add_argument("--dispatch-retries", type=int, default=3,
                   help="retries per batch on transient (outage-shaped) "
                        "dispatch failures, with exponential backoff")
    p.add_argument("--degraded-after", type=int, default=3,
                   help="consecutive dispatch failures before the service "
                        "publishes degraded mode on the status topic and "
                        "(with --probe-on-degraded) checks the backend")
    p.add_argument("--probe-on-degraded", action="store_true",
                   help="on entering degraded mode, run the bounded "
                        "subprocess backend probe (utils.backend_probe) "
                        "and attach its verdict to the status message")
    p.add_argument("--supervised", action="store_true",
                   help="wrap the service in a ServiceSupervisor: a crash "
                        "that kills the serving loop is restarted with "
                        "the last-known-good gallery snapshot (bounded "
                        "restarts)")
    # ---- overload protection (runtime.admission / README section) ----
    p.add_argument("--max-inflight-frames", type=int, default=0,
                   help="admission bound: reject new frames (explicit "
                        "'rejected' status, reason=overload) once this "
                        "many admitted frames are still in the system; "
                        "bulk-priority frames are rejected at 75%% of the "
                        "bound so interactive traffic keeps headroom. "
                        "0 = unbounded")
    p.add_argument("--rate-limit-fps", type=float, default=0.0,
                   help="per-topic token-bucket rate limit (frames/s, "
                        "burst = 1 s of rate): producers above it get "
                        "explicit 'rejected' statuses (reason=rate_limit) "
                        "instead of silently displacing queued frames. "
                        "0 = off")
    p.add_argument("--brownout-queue-wait-ms", type=float, default=0.0,
                   help="brownout threshold: when the queue-wait EWMA "
                        "crosses this, degrade work per frame (level 1: "
                        "skip-shed half the bulk frames; level 2: shed "
                        "all bulk + cap the dispatch ladder at its "
                        "smallest bucket), announced on the status topic "
                        "with a brownout_level gauge and automatic "
                        "hysteresis recovery. 0 = off")
    p.add_argument("--shed-stale-after-ms", type=float, default=0.0,
                   help="freshness bound: a queued frame older than this "
                        "is shed (reason=stale) instead of wasting a "
                        "dispatch slot. 0 = off")
    p.add_argument("--dead-letter-journal", metavar="PATH",
                   help="append dead-lettered/shed frame metadata + "
                        "reason to this bounded rotating JSONL journal "
                        "(replayable: python -m opencv_facerecognizer_tpu"
                        ".runtime.journal PATH)")
    # ---- crash-safe state lifecycle (runtime.state_store / README
    # "State durability") ----
    p.add_argument("--state-dir", metavar="DIR",
                   help="durable state directory: atomic checksummed "
                        "gallery checkpoints + an enrollment write-ahead "
                        "log. On startup the newest verified checkpoint "
                        "is restored and the WAL replayed (superseding "
                        "the --gallery startup enrollment); enrollments "
                        "accepted while serving then survive restarts. "
                        "Unset = state lives only in memory")
    p.add_argument("--embedder-version", type=int, default=0, metavar="N",
                   help="declare the loaded --model's embedder version "
                        "(rollout fencing; README 'Live embedder "
                        "rollout'). 0 (default) = adopt whatever version "
                        "the state dir's newest checkpoint carries. "
                        "Nonzero: startup FAILS CLOSED when the recovered "
                        "state serves a different version — a new "
                        "embedder's rows must arrive via the staged "
                        "re-embed cutover (or this binary must complete a "
                        "pending one), never by silently mixing spaces")
    p.add_argument("--detector-version", type=int, default=0, metavar="N",
                   help="declare the loaded --detector's registry version "
                        "(model-registry fencing; README 'Model "
                        "registry'). 0 (default) = adopt whatever the "
                        "state dir's manifest serves. Nonzero: startup "
                        "FAILS CLOSED — writer and reader both — when the "
                        "manifest serves a different detector version; a "
                        "new detector arrives via the fenced registry "
                        "swap, never by silently starting a different "
                        "checkpoint")
    p.add_argument("--cascade-version", type=int, default=0, metavar="N",
                   help="declare the loaded --cascade stage-1 gate's "
                        "registry version: same fail-closed startup fence "
                        "as --detector-version, for the cascade role")
    p.add_argument("--registry-swap", metavar="ROLE=VERSION",
                   help="runbook entry point: perform ONE fenced model-"
                        "registry swap against --state-dir and exit. The "
                        "candidate params must already be staged at the "
                        "registry convention path (state_dir/registry/"
                        "<role>-v<N>.params); the swap appends the WAL "
                        "fence, installs the manifest atomically, and "
                        "exits 0 — serving writers pick the new version "
                        "up at their next startup fence, readers across "
                        "their next re-anchor. Roles: detector, cascade")
    p.add_argument("--checkpoint-every-s", type=float, default=300.0,
                   help="age threshold for background checkpoints: WAL "
                        "entries older than this trigger one (only "
                        "meaningful with --state-dir)")
    p.add_argument("--checkpoint-wal-rows", type=int, default=256,
                   help="row-count threshold: a WAL holding this many "
                        "enrolled rows triggers a background checkpoint")
    p.add_argument("--keep-checkpoints", type=int, default=3,
                   help="checkpoint retention: newest N kept; older ones "
                        "(and quarantined corrupt files beyond N) pruned")
    p.add_argument("--disk-low-watermark", type=float, default=256.0,
                   metavar="MB",
                   help="disk-pressure low watermark on the --state-dir "
                        "volume (MB free; README 'Degraded-durability "
                        "runbook'). Below it: one preemptive WAL "
                        "compaction (forced checkpoint) + retention "
                        "shrink per pressure episode, and the "
                        "disk_free SLO burns >= 1 (warn). Below "
                        "watermark/6: durability flips to degraded "
                        "BEFORE ENOSPC ever lands (enrollments refused "
                        "closed, serving continues). 0 disables the "
                        "watermark (the WAL-failure trigger stays armed)")
    p.add_argument("--durability-probe-s", type=float, default=5.0,
                   help="degraded-durability recovery probe cadence: "
                        "every N seconds the monitor durably writes + "
                        "fsyncs + unlinks a tmp file in --state-dir; a "
                        "success re-arms durability with a "
                        "durability_restored announcement. Also the "
                        "disk-watermark refresh interval")
    p.add_argument("--journal-fsync", choices=["never", "interval", "always"],
                   default="never",
                   help="fsync policy of the dead-letter journal: never "
                        "(default — flush per record, the original "
                        "behavior), interval (fsync at most once per "
                        "second), always (fsync per record). The "
                        "enrollment WAL always runs at 'always' — its "
                        "acknowledgments promise durability")
    # ---- frame-lifecycle tracing / flight recorder / exposition
    # (utils.tracing, runtime.expo; README "Observability") ----
    p.add_argument("--trace-sample", type=float, default=0.0,
                   help="frame-trace sampling rate in [0, 1]: each sampled "
                        "frame records causal spans (receive -> queue_wait "
                        "-> settle, with batch ancestry) into bounded "
                        "per-topic ring buffers. Deterministic per trace "
                        "id. 0 (default) = frame tracing off; lifecycle "
                        "spans (checkpoint/WAL/retrain/brownout) are "
                        "always recorded once a tracer exists")
    p.add_argument("--trace-ring", type=int, default=4096,
                   help="spans kept per topic ring (the flight recorder's "
                        "horizon)")
    p.add_argument("--trace-jsonl", metavar="PATH",
                   help="additionally stream every span as JSONL into this "
                        "bounded rotating file (offline analysis beyond "
                        "the ring horizon; adds a file write per span)")
    p.add_argument("--flight-dir", metavar="DIR",
                   help="flight-recorder dump directory: the span rings "
                        "are dumped atomically here on dead-letter, "
                        "supervisor restart, wedge detection, and SIGTERM "
                        "drain (bounded retention; dump path rides the "
                        "dead-letter journal record)")
    p.add_argument("--expo-port", type=int, default=None, metavar="PORT",
                   help="serve the read-only observability endpoint "
                        "(GET /metrics /prom /health /ledger /brownout "
                        "/spans /attribution) on this TCP port; 0 binds "
                        "an ephemeral port (printed on stderr). Off-hot-"
                        "path threads; unset = off. /prom is Prometheus "
                        "text format; /health is the SLO verdict (503 "
                        "when critical)")
    # ---- SLO burn-rate monitor (runtime.slo; README "Observability") ----
    p.add_argument("--slo", action="store_true",
                   help="run the SLO burn-rate monitor: interactive e2e "
                        "p99, queue-wait p99, ledger completion ratio and "
                        "(with --state-dir) durability lag evaluated on "
                        "multi-window burn rates into an ok/warn/critical "
                        "health state machine — served at /health, "
                        "published on the status topic by the supervisor, "
                        "consumed by brownout as intake pressure at "
                        "critical, and dumped to the flight recorder on a "
                        "critical transition")
    p.add_argument("--slo-interval-s", type=float, default=5.0,
                   help="seconds between SLO evaluations (the serving "
                        "loop's tick cadence; the expo refresh thread "
                        "backstops it when the loop wedges)")
    p.add_argument("--slo-e2e-p99-ms", type=float, default=500.0,
                   help="interactive end-to-end latency objective: 99%% "
                        "of interactive frames must publish within this "
                        "(the error budget is the other 1%%)")
    p.add_argument("--slo-queue-wait-p99-ms", type=float, default=250.0,
                   help="queue-wait objective: 99%% of frames must leave "
                        "the batcher queue within this")
    p.add_argument("--slo-completion-target", type=float, default=0.999,
                   help="completion-ratio objective: the target fraction "
                        "of admitted frames that must publish (drops burn "
                        "the remaining budget)")
    p.add_argument("--slo-durability-rows", type=int, default=1024,
                   help="durability-lag objective bound: WAL rows not yet "
                        "covered by a checkpoint (wal_seq minus the last "
                        "checkpoint's seq) above this read as burn >= 1; "
                        "needs --state-dir")
    p.add_argument("--slo-windows", type=float, nargs=2,
                   default=(60.0, 600.0), metavar=("SHORT_S", "LONG_S"),
                   help="the two burn-rate windows (seconds): a severity "
                        "fires only when BOTH windows burn past its rate "
                        "(short reacts, long filters blips)")
    # ---- multi-replica serving (runtime.replication; README
    # "Horizontal scale-out") ----
    p.add_argument("--replica-role", choices=["writer", "reader"],
                   default="writer",
                   help="role against a shared --state-dir. writer "
                        "(default): owns enrollment — acquires the fcntl "
                        "writer lease in the state dir and FAILS CLOSED "
                        "when another live writer holds it (split-brain "
                        "protection). reader: opens the WAL strictly "
                        "read-only, anchors on the newest checkpoint, and "
                        "tails new enrollment rows between batches; "
                        "enroll commands are rejected with an explicit "
                        "status. Only meaningful with --state-dir")
    p.add_argument("--replica-poll-ms", type=float, default=50.0,
                   help="reader role: WAL tail poll interval — bounds "
                        "replication staleness (plus append visibility) "
                        "per replica")
    p.add_argument("--replication-lag-rows", type=int, default=4096,
                   help="reader role with --slo: replication-lag gauge "
                        "objective bound — unapplied WAL rows above this "
                        "read as burn >= 1 (warn; critical at 6x feeds "
                        "one level of brownout intake pressure)")
    p.add_argument("--router", metavar="HOST:PORT[,HOST:PORT...]",
                   help="run as a model-free TOPIC ROUTER instead of a "
                        "recognizer: frames arriving on --source are "
                        "spread across these replica endpoints (JSONL "
                        "over TCP, i.e. each replica runs --source "
                        "socket) by rendezvous-hashing their topic, with "
                        "health-based failover; results/status fan back "
                        "to the source. All model/gallery flags are "
                        "ignored in this mode")
    p.add_argument("--router-health", metavar="URL[,URL...]",
                   help="per-replica /health URLs (same order as "
                        "--router): 503/unreachable marks the replica "
                        "critical and reroutes its topics. Unset = "
                        "replicas are assumed healthy")
    p.add_argument("--router-budget-fps", type=float, default=0.0,
                   help="per-replica admission budget (frames/s token "
                        "bucket): an over-budget topic spills to its "
                        "next-preferred replica instead of overrunning "
                        "one. 0 = unbudgeted")
    p.add_argument("--router-writer", type=int, default=0, metavar="IDX",
                   help="index (into --router) of the replica that owns "
                        "enrollment: control-topic traffic routes only "
                        "there")
    p.add_argument("--router-link-deadline-s", type=float, default=0.0,
                   help="link supervision: app-level heartbeat (ping/pong "
                        "over the data link itself) per replica per health "
                        "cycle; a pong older than this marks the LINK down "
                        "— routing excludes it and the flight recorder "
                        "dumps a failover — independent of /health, which "
                        "a partition can leave green. 0 = off")
    p.add_argument("--router-hedge-deadline-s", type=float, default=0.0,
                   help="interactive hedging: an interactive frame with no "
                        "result after this many seconds is re-sent once to "
                        "the next-preferred replica (same frame id — the "
                        "loser's result is deduped at fan-in). 0 = off")
    p.add_argument("--router-dedup-window", type=int, default=4096,
                   help="idempotent fan-in: remember this many recent "
                        "frame ids at the router's result intake so a "
                        "duplicated or hedged result publishes upstream "
                        "exactly once (replica intake keeps its own "
                        "window). 0 = off")
    p.add_argument("--slo-loop-stale-s", type=float, default=30.0,
                   help="loop-liveness objective bound: seconds without a "
                        "serving-loop iteration before the gauge reads "
                        "burn >= 1 (warn; critical at 6x). A wedged loop "
                        "produces no latency/ratio events, so only this "
                        "gauge — evaluated by the expo backstop thread — "
                        "can escalate it. 0 = off")
    return p


def _load_stack(args):
    import numpy as np

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import CNNEmbedding
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.utils import dataset as dataset_utils
    from opencv_facerecognizer_tpu.utils import serialization

    # Pure argument validation FIRST — before checkpoint loads and the
    # full gallery embedding pass, which can take minutes.
    if args.fused_embedder and args.parallel == "pp":
        raise SystemExit("--fused-embedder applies to --parallel fused only "
                         "(stage-B meshes aren't single-device)")
    if args.match_mode == "ivf" and args.parallel == "pp":
        raise SystemExit("--match-mode ivf applies to --parallel fused only "
                         "(the two-stage path is single-device, like the "
                         "pallas streaming matcher)")
    if args.cascade and args.parallel == "pp":
        raise SystemExit("--cascade applies to --parallel fused only (the "
                         "pipeline-parallel path carries no stage-1 gate)")

    serialization.register(CNNEmbedding)
    model = serialization.load_model(args.model)
    feature = model.feature
    if not isinstance(feature, CNNEmbedding):
        raise SystemExit("--model must be a cnn checkpoint (ocvf-train --model cnn)")
    detector = CNNFaceDetector.load(args.detector)
    face_gate = None
    if args.cascade:
        from opencv_facerecognizer_tpu.models.cascade import FaceGate

        face_gate = FaceGate.load(args.cascade)

    images, labels, names = dataset_utils.read_images(
        args.gallery, image_size=feature.input_size
    )
    emb = np.array(feature.extract(images))
    mesh_a = None
    if args.parallel == "pp":
        # Two-stage pipeline parallelism: detector on the first mesh half,
        # embedder + gallery on the second (parallel/pp.py).
        import jax

        from opencv_facerecognizer_tpu.parallel import split_mesh

        n = len(jax.devices())
        # Keep both axes useful after the split: 8 devices -> (dp=4, tp=2)
        # halves into two (2, 2) stage meshes. Below 8, tp=2 would collapse
        # the halves to dp=1 (replicated detector work), so stay tp=1.
        tp = 2 if n % 4 == 0 and n >= 8 else 1
        try:
            mesh_a, gallery_mesh = split_mesh(make_mesh(dp=n // tp, tp=tp))
        except ValueError as e:
            raise SystemExit(
                f"--parallel pp needs an even device count >= 2 (have {n}): "
                f"{e}; use --parallel fused on this host"
            )
    else:
        gallery_mesh = make_mesh()

    import jax.numpy as jnp

    gallery = ShardedGallery(capacity=max(args.capacity, 2 * len(emb)),
                             dim=emb.shape[1], mesh=gallery_mesh,
                             async_grow=args.async_grow,
                             store_dtype=(jnp.bfloat16
                                          if args.gallery_dtype == "bf16"
                                          else jnp.float32),
                             embedder_version=args.embedder_version or 1)
    gallery.add(emb, labels)  # ocvf-lint: boundary=wal-before-mutate -- startup ingest of the model's frozen subject set, BEFORE recovery/serving; durable enrollments arrive later via StateLifecycle replay
    if args.match_mode == "ivf" and gallery_mesh.size > 1:
        # Fail fast, like the pp guard above: the two-stage path is
        # single-device (GSPMD cannot partition the bucket gather +
        # pallas rerank), and silently serving the linear exact scan
        # under an explicit --match-mode ivf would blow the very
        # deadlines the flag exists to protect.
        raise SystemExit("--match-mode ivf requires a single-device mesh "
                         f"(got {gallery_mesh.size} devices); use "
                         "--match-mode auto/exact on this host")
    if (args.match_mode != "exact" and mesh_a is None
            and gallery_mesh.size == 1):
        # Attach the IVF coarse quantizer AFTER the startup enrolment:
        # pre-build incremental assignment is a no-op, and attaching late
        # keeps the one explicit startup build (main(), post state
        # recovery) from racing an add-triggered background one.
        from opencv_facerecognizer_tpu.parallel.quantizer import CoarseQuantizer

        gallery.attach_quantizer(
            CoarseQuantizer(
                nlist=(args.ivf_nlist
                       or CoarseQuantizer.default_nlist(gallery.capacity)),
                nprobe=args.ivf_nprobe,
                # --ivf-nlist 0: re-derive the cell count from the actual
                # row set at every (re)build — state recovery or runtime
                # growth must not freeze the startup capacity guess.
                auto_nlist=not args.ivf_nlist,
            ),
            mode=args.match_mode,
        )
    if mesh_a is not None:
        from opencv_facerecognizer_tpu.parallel import TwoStagePipeline

        pipeline = TwoStagePipeline(
            detector, feature.net, feature._params["net"], gallery, mesh_a,
            face_size=feature.input_size,
        )
    else:
        from opencv_facerecognizer_tpu.runtime.ingest import (
            resolve_ingest_mode,
        )

        import jax

        # Buffer donation through the bucketed ladder: only when the
        # ingest uploader feeds each dispatch a fresh device array AND
        # the backend implements input donation (CPU ignores it with a
        # warning per compiled step — noise, not a win).
        donate = (resolve_ingest_mode(args.ingest_mode, args.transfer_uint8,
                                      warn=False) != "f32"
                  and jax.devices()[0].platform in ("tpu", "gpu"))
        pipeline = RecognitionPipeline(
            detector, feature.net, feature._params["net"], gallery,
            face_size=feature.input_size,
            fused_embedder=args.fused_embedder,
            donate_frames=donate,
            cascade=face_gate,
        )
    return pipeline, names


def run_router(args) -> int:
    """Model-free router mode (``--router``): spread incoming camera
    topics across replica endpoints with rendezvous hashing + health
    failover (``runtime.replication.TopicRouter``), fanning results and
    statuses back to the source. No model, no gallery, no device — the
    whole process is transport + routing, so it starts in milliseconds
    and can sit in front of replicas on other hosts."""
    import signal
    import threading

    from opencv_facerecognizer_tpu.runtime.connector import (
        WILDCARD_TOPIC, JSONLConnector, SocketConnector,
    )
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        RESULT_TOPIC, STATUS_TOPIC,
    )
    from opencv_facerecognizer_tpu.runtime.replication import (
        ReplicaHandle, TopicRouter, http_health_probe,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    metrics = Metrics()
    tracer = None
    if args.flight_dir or args.expo_port is not None:
        tracer = Tracer(ring_size=args.trace_ring, sample=args.trace_sample,
                        dump_dir=args.flight_dir, metrics=metrics)
    endpoints = [e.strip() for e in args.router.split(",") if e.strip()]
    healths = ([u.strip() or None for u in args.router_health.split(",")]
               if args.router_health else [None] * len(endpoints))
    if len(healths) != len(endpoints):
        raise SystemExit(f"--router-health lists {len(healths)} URLs for "
                         f"{len(endpoints)} --router endpoints")
    if not 0 <= args.router_writer < len(endpoints):
        raise SystemExit(f"--router-writer {args.router_writer} is out of "
                         f"range for {len(endpoints)} endpoints")
    replicas = []
    for i, endpoint in enumerate(endpoints):
        host, _, port = endpoint.rpartition(":")
        try:
            conn = SocketConnector(host=host or "127.0.0.1", port=int(port),
                                   listen=False, metrics=metrics)
            conn.start()  # a replica that was never there is a config error
        except (OSError, ValueError) as exc:
            raise SystemExit(f"--router endpoint {endpoint!r}: {exc}")
        replicas.append(ReplicaHandle(
            endpoint, conn,
            health_fn=(http_health_probe(healths[i]) if healths[i] else None),
            budget_fps=args.router_budget_fps or None,
            writer=i == args.router_writer))
    router = TopicRouter(
        replicas, metrics=metrics, tracer=tracer,
        link_deadline_s=args.router_link_deadline_s or None,
        hedge_deadline_s=args.router_hedge_deadline_s or None,
        dedup_window=args.router_dedup_window)
    slo_monitor = None
    if args.slo and args.router_link_deadline_s:
        from opencv_facerecognizer_tpu.runtime.slo import (
            SLOMonitor, link_health_objective,
        )

        # The router's /health speaks for the FABRIC, not a model: the
        # only objective that makes sense here is the supervised-link
        # fraction (one dark replica = failover's job, a majority dark
        # = a network event the fleet cannot route around).
        slo_monitor = SLOMonitor(
            metrics, [link_health_objective(router.down_link_fraction)],
            tracer=tracer)
    if args.source == "socket":
        upstream = SocketConnector(host=args.host, port=args.port,
                                   listen=True, metrics=metrics)
    else:
        upstream = JSONLConnector(sys.stdin, sys.stdout, metrics=metrics)
    upstream.subscribe(WILDCARD_TOPIC,
                       lambda topic, msg: router.publish(topic, msg))
    for topic in (RESULT_TOPIC, STATUS_TOPIC):
        upstream_topic = topic
        router.subscribe(topic, lambda _t, msg, _up=upstream_topic:
                         upstream.publish(_up, msg))
    expo = None
    if args.expo_port is not None:
        from opencv_facerecognizer_tpu.runtime.expo import ExpoServer

        expo = ExpoServer(metrics=metrics, tracer=tracer, router=router,
                          slo=slo_monitor, port=args.expo_port)
        expo.start()
        print(f"router expo endpoint: http://{expo.host}:{expo.port}/",
              file=sys.stderr)
    term_event = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda s, f: term_event.set())
    except ValueError:
        pass
    router.start()
    upstream.start()
    print(f"routing {len(replicas)} replicas: {', '.join(endpoints)}",
          file=sys.stderr)
    try:
        while not upstream.eof.wait(timeout=0.5):
            if term_event.is_set():
                break
    except KeyboardInterrupt:
        pass
    finally:
        if expo is not None:
            expo.stop()
        upstream.stop()
        router.stop()
        for handle in replicas:
            handle.connector.stop()
        print(f"router registry at shutdown: "
              f"{[r['name'] for r in router.registry()]}", file=sys.stderr)
    return 0


def _registry_fence(registry, args, who: str) -> None:
    """Fail-closed startup fence for the non-embedder registry roles
    (mirrors the --embedder-version fence): a declared version that the
    state dir's manifest doesn't serve refuses to start — writer AND
    reader — because serving a detector/cascade the manifest doesn't
    name is exactly the silent unfenced swap the registry exists to
    prevent."""
    for role, declared in (("detector", args.detector_version),
                           ("cascade", args.cascade_version)):
        if declared and registry.version(role) != declared:
            raise SystemExit(
                f"ocvf-recognize: --{role}-version {declared} declared "
                f"but the state dir's registry manifest serves {role} "
                f"v{registry.version(role)} — a {who} never serves a "
                f"model set the manifest doesn't name. Swap the {role} "
                f"through the fenced registry (--registry-swap {role}=N "
                f"or the live coordinator), or start the matching "
                f"checkpoint")


def run_registry_swap(args) -> int:
    """One fenced model-registry swap against ``--state-dir``, then exit
    (README "Model registry" runbook): validate the staged candidate
    params at the registry convention path, take the writer lease (a
    live writer must never race the manifest install — drive a swap
    through ITS coordinator instead), append the ``registry_cutover``
    WAL fence and install the manifest atomically. No serving process is
    touched: writers adopt the new version at their next startup fence,
    readers across their next re-anchor."""
    from opencv_facerecognizer_tpu.runtime.registry import (
        ModelRegistry, _file_sha256, registry_params_path,
    )
    from opencv_facerecognizer_tpu.runtime.replication import (
        WriterLease, WriterLeaseHeldError,
    )
    from opencv_facerecognizer_tpu.runtime.state_store import StateLifecycle
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    if not args.state_dir:
        raise SystemExit("ocvf-recognize: --registry-swap requires "
                         "--state-dir")
    role, sep, version = args.registry_swap.partition("=")
    role = role.strip()
    try:
        to_version = int(version)
    except ValueError:
        to_version = 0
    if not sep or role not in ("detector", "cascade") or to_version <= 0:
        raise SystemExit(
            "ocvf-recognize: --registry-swap wants ROLE=VERSION with role "
            "in (detector, cascade) and a positive integer version, got "
            f"{args.registry_swap!r}")
    params_path = registry_params_path(args.state_dir, role, to_version)
    if not os.path.exists(params_path):
        raise SystemExit(
            f"ocvf-recognize: stage the candidate params first — "
            f"{params_path} does not exist (CNNFaceDetector.save / "
            f"FaceGate.save to the registry convention path)")
    metrics = Metrics()
    lease = WriterLease(args.state_dir, metrics=metrics)
    try:
        lease.acquire()
    except WriterLeaseHeldError as exc:
        raise SystemExit(
            f"ocvf-recognize: {exc} — stop the writer first (or drive the "
            f"swap through its live coordinator); the offline runbook swap "
            f"needs exclusive ownership of the state dir")
    try:
        state = StateLifecycle(args.state_dir, metrics=metrics)
        state.attach_registry(ModelRegistry(args.state_dir, metrics=metrics))
        state.adopt_wal_seq()
        try:
            seq = state.perform_registry_cutover(
                role, to_version, params_path=params_path,
                params_sha256=_file_sha256(params_path))
        except ValueError as exc:
            raise SystemExit(f"ocvf-recognize: {exc}")
        print(f"registry swap fenced at WAL seq {seq}; manifest now "
              f"serves {state.registry.stamp()} (readers re-anchor once "
              f"the next writer checkpoint covers the fence)",
              file=sys.stderr)
    finally:
        lease.release()
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.registry_swap:
        return run_registry_swap(args)
    if not (args.model and args.detector and args.gallery):
        parser.error("the following arguments are required: --model, "
                     "--detector, --gallery (only --registry-swap runs "
                     "without a serving stack)")
    if args.router:
        return run_router(args)
    from opencv_facerecognizer_tpu.runtime.connector import (
        FakeConnector, JSONLConnector, SocketConnector, encode_frame,
    )
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC, RecognizerService,
    )
    from opencv_facerecognizer_tpu.runtime.admission import AdmissionController
    from opencv_facerecognizer_tpu.runtime.journal import DeadLetterJournal
    from opencv_facerecognizer_tpu.runtime.resilience import (
        BrownoutPolicy, ResiliencePolicy, ServiceSupervisor,
        rebuild_pipeline_on_cpu,
    )
    from opencv_facerecognizer_tpu.runtime.ingest import (
        IngestConfig, resolve_ingest_mode,
    )
    from opencv_facerecognizer_tpu.runtime.state_store import StateLifecycle
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    # The --transfer-uint8 deprecation warning fires HERE, once (the
    # _load_stack probe resolves silently).
    ingest_mode = resolve_ingest_mode(args.ingest_mode, args.transfer_uint8)
    ingest_cfg = IngestConfig(mode=ingest_mode,
                              ring_depth=args.ingest_ring_depth or None,
                              decode_workers=args.ingest_decode_workers)
    pipeline, names = _load_stack(args)
    metrics_sink = open(args.metrics_jsonl, "a") if args.metrics_jsonl else None
    # The latency rolling horizon must cover the longest SLO evaluation
    # window and the ring resolution must cover the shortest (SLOMonitor
    # refuses both at construction) — a user asking for a 1 h long window
    # gets a 1 h ring, and a 5 s short window gets <=5 s slices, not a
    # silent truncation/dilution of either. Slices are capped: past the
    # cap the monitor's loud constructor names the incompatible pair.
    metrics_window_s, metrics_window_slices = 600.0, 20
    if args.slo:
        import math as _math

        slo_short_s = min(args.slo_windows)
        metrics_window_s = max(metrics_window_s, *args.slo_windows)
        metrics_window_slices = min(960, max(
            20, int(_math.ceil(metrics_window_s
                               / max(1e-3, min(30.0, slo_short_s))))))
    metrics = Metrics(sink=metrics_sink, window_s=metrics_window_s,
                      window_slices=metrics_window_slices)

    # Frame-lifecycle tracer: built whenever ANY observability surface is
    # requested (sampled frame spans, flight dumps, span JSONL, or the
    # expo endpoint — lifecycle spans make the latter two useful even at
    # sample 0). None otherwise: tracing fully off costs nothing.
    from opencv_facerecognizer_tpu.utils.tracing import (
        Tracer, make_span_journal,
    )

    tracer = None
    span_journal = None
    if (args.trace_sample > 0 or args.flight_dir or args.trace_jsonl
            or args.expo_port is not None):
        if args.trace_jsonl:
            span_journal = make_span_journal(args.trace_jsonl,
                                             metrics=metrics)
        tracer = Tracer(ring_size=args.trace_ring,
                        sample=args.trace_sample,
                        dump_dir=args.flight_dir,
                        span_sink=span_journal,
                        metrics=metrics)

    quantizer = getattr(pipeline.gallery, "quantizer", None)
    if quantizer is not None:
        quantizer.metrics = metrics
        quantizer.tracer = tracer

    admission = None
    if args.max_inflight_frames > 0 or args.rate_limit_fps > 0:
        admission = AdmissionController(
            max_inflight_frames=args.max_inflight_frames or None,
            rate_limit_fps=args.rate_limit_fps or None,
        )
    brownout = (BrownoutPolicy(queue_wait_s=args.brownout_queue_wait_ms / 1e3)
                if args.brownout_queue_wait_ms > 0 else None)
    journal = (DeadLetterJournal(args.dead_letter_journal, metrics=metrics,
                                 fsync=args.journal_fsync)
               if args.dead_letter_journal else None)

    state = None
    replica = None
    lease = None
    if args.state_dir and args.replica_role == "reader":
        # Read replica: strictly read-only against the shared state dir —
        # no lease, no WAL writes, no checkpoints. Initial sync anchors
        # on the newest checkpoint and replays the WAL tail; the serving
        # loop then polls for new rows between batches.
        from opencv_facerecognizer_tpu.runtime.replication import ReadReplica

        replica = ReadReplica(args.state_dir, pipeline.gallery, names,
                              metrics=metrics, tracer=tracer,
                              poll_interval_s=args.replica_poll_ms / 1e3)
        report = replica.resync()
        print(f"replica initial sync: {report}", file=sys.stderr)
        if (args.embedder_version
                and replica.embedder_version != args.embedder_version):
            raise SystemExit(
                f"ocvf-recognize: --embedder-version {args.embedder_version}"
                f" declared but the state dir's checkpoint serves embedder "
                f"v{replica.embedder_version} — a reader never mixes "
                f"versions; start with the matching model (or wait for the "
                f"writer's cutover checkpoint to land)")
        # Read-only registry view: the reader fences its detector/cascade
        # versions against the manifest exactly like the embedder above,
        # and the replica's tail parks on registry fences from here on.
        from opencv_facerecognizer_tpu.runtime.registry import ModelRegistry

        replica.registry = ModelRegistry(args.state_dir, metrics=metrics,
                                         readonly=True)
        _registry_fence(replica.registry, args, "reader")
    elif args.state_dir:
        # Writer role: exactly one enrollment owner per state dir. The
        # fcntl lease is taken BEFORE the lifecycle touches anything — a
        # split-brain second writer must fail closed with zero side
        # effects on the live writer's WAL/checkpoints.
        from opencv_facerecognizer_tpu.runtime.replication import (
            WriterLease, WriterLeaseHeldError,
        )

        lease = WriterLease(args.state_dir, metrics=metrics)
        try:
            lease.acquire()
        except WriterLeaseHeldError as exc:
            raise SystemExit(f"ocvf-recognize: {exc}")
        state = StateLifecycle(
            args.state_dir, metrics=metrics,
            keep_checkpoints=args.keep_checkpoints,
            checkpoint_wal_rows=args.checkpoint_wal_rows,
            checkpoint_every_s=args.checkpoint_every_s,
            tracer=tracer,
        )
        # Startup recovery: newest verified checkpoint + WAL replay
        # supersede the fresh --gallery enrollment (the baseline rows are
        # part of the state dir's own first checkpoint, taken below).
        report = state.recover(pipeline.gallery, names)
        print(f"state recovery: {report}", file=sys.stderr)
        recovered_version = int(report.get("embedder_version", 1))
        if args.embedder_version and recovered_version != args.embedder_version:
            # Version fence at the front door: serving a v-N model over
            # v-M rows is exactly the mixed-score corruption the rollout
            # subsystem exists to prevent. (A PENDING cutover to the
            # declared version is completed inside recover() and lands
            # here as a match.)
            raise SystemExit(
                f"ocvf-recognize: --embedder-version {args.embedder_version}"
                f" declared but recovery landed on embedder "
                f"v{recovered_version} — refusing to serve mixed spaces. "
                f"Roll the new embedder out via the staged re-embed "
                f"(runtime.rollout: stage + parity gate + cutover), or "
                f"start the matching model")
        # Model registry (ISSUE 18): recovery attaches one on the fly
        # when the dir already carries a manifest (and completes or
        # abandons any fenced-but-uninstalled swap); a fresh dir gets
        # its manifest created here. The embedder slot mirrors the
        # recovered gallery version, then the same fail-closed startup
        # fence as --embedder-version runs for the other roles.
        from opencv_facerecognizer_tpu.runtime.registry import ModelRegistry

        if state.registry is None:
            state.attach_registry(ModelRegistry(args.state_dir,
                                                metrics=metrics))
        state.registry.mirror_embedder(recovered_version)
        _registry_fence(state.registry, args, "writer")
        if report["recovered_checkpoint"] is None and not report["replayed_records"]:
            # First run against this state dir: make the baseline gallery
            # durable NOW, so a crash before the first enrollment still
            # restarts into a serving gallery.
            state.checkpoint_now(wait=True)

    durability = None
    if state is not None:
        # Degraded-durability state machine + disk-pressure watermarks
        # (README "Degraded-durability runbook"): sustained WAL failure
        # or a critical watermark refuses enrollments closed while
        # serving continues; the probe re-arms automatically. Attaches
        # itself to the lifecycle; the service wires its status channel.
        from opencv_facerecognizer_tpu.runtime.resilience import (
            DurabilityMonitor,
        )

        durability = DurabilityMonitor(
            state, metrics=metrics, tracer=tracer,
            probe_interval_s=args.durability_probe_s,
            low_watermark_bytes=int(args.disk_low_watermark * (1 << 20)))
        # Non-critical sinks shed (with exact per-sink counters) while
        # degraded — the disk's last bytes belong to the WAL.
        durability.attach_sinks(journal=journal, span_sink=span_journal,
                                tracer=tracer)

    if (quantizer is not None and not quantizer.ready
            and pipeline.gallery._ivf_wanted()):
        # Sidecar missed (or no --state-dir): train the shortlist before
        # serving starts — predictable startup beats a recall-free window.
        # skip_if_ready rides out the background build a recovery poke
        # may already have fired instead of training a second time.
        # --match-mode auto below the capacity threshold skips this and
        # lets the staleness poke build it if the gallery ever grows there.
        print("training IVF coarse quantizer "
              f"(nlist={quantizer.nlist})...", file=sys.stderr)
        quantizer.rebuild_now(wait=True, skip_if_ready=True)
        print(f"IVF quantizer: {quantizer.stats()}", file=sys.stderr)

    slo_monitor = None
    if args.slo:
        from opencv_facerecognizer_tpu.runtime.slo import (
            SLOMonitor, default_objectives,
        )

        short_s, long_s = args.slo_windows
        slo_monitor = SLOMonitor(
            metrics,
            default_objectives(
                drop_counters=RecognizerService.LEDGER_DROP_COUNTERS,
                state=state,
                e2e_p99_s=args.slo_e2e_p99_ms / 1e3,
                queue_wait_p99_s=args.slo_queue_wait_p99_ms / 1e3,
                completion_target=args.slo_completion_target,
                durability_rows=args.slo_durability_rows,
                short_s=short_s, long_s=long_s,
            ),
            tracer=tracer,
            interval_s=args.slo_interval_s,
        )
        if durability is not None and durability.low_watermark_bytes:
            # Disk-pressure SLO: burn = watermark/free (warn at the
            # watermark, critical at 1/6 of it — the same point the
            # monitor pre-empts the degraded flip). Reads the monitor's
            # cached statvfs sample, so /health and the watermark
            # actions see one probe.
            from opencv_facerecognizer_tpu.runtime.slo import (
                disk_free_objective,
            )

            slo_monitor.add_objective(disk_free_objective(
                durability.free_bytes, durability.low_watermark_bytes,
                short_s=short_s, long_s=long_s))

    if args.source == "jsonl":
        connector = JSONLConnector(sys.stdin, sys.stdout, metrics=metrics)
    elif args.source == "socket":
        connector = SocketConnector(host=args.host, port=args.port,
                                    listen=True, metrics=metrics)
    else:
        connector = FakeConnector()

    tracker = None
    if not args.no_track_cache:
        from opencv_facerecognizer_tpu.runtime.tracker import (
            IdentityTracker, TrackerConfig,
        )

        # Replica-local by construction: the tracker lives on THIS
        # service instance, and PR 10's rendezvous routing pins each
        # topic to one replica — failover/resync lands on a replica
        # whose cache simply starts cold.
        tracker = IdentityTracker(
            TrackerConfig(reverify_frames=max(1, args.track_reverify_frames),
                          iou_min=args.track_iou_min),
            metrics=metrics)

    service = RecognizerService(
        pipeline, connector,
        batch_size=args.batch_size,
        frame_shape=tuple(args.frame_size),
        flush_timeout=args.flush_ms / 1e3,
        similarity_threshold=args.similarity_threshold,
        subject_names=names,
        metrics=metrics,
        # The ingest config owns the transfer dtype now (uint8/jpeg stage
        # as uint8 through the ring; f32 keeps the legacy dtype).
        ingest=ingest_cfg,
        readback_worker=not args.no_readback_worker,
        readback_poll_s=args.readback_poll_ms / 1e3,
        drain_poll_s=args.drain_poll_ms / 1e3,
        bucket_sizes=tuple(b for b in args.bucket_sizes if b > 0),
        target_latency_s=(None if args.target_latency_ms is None
                          else args.target_latency_ms / 1e3),
        admission=admission,
        brownout=brownout,
        dead_letter_journal=journal,
        shed_stale_after_s=(args.shed_stale_after_ms / 1e3
                            if args.shed_stale_after_ms > 0 else None),
        state_store=state,
        resilience=ResiliencePolicy(
            dispatch_retries=args.dispatch_retries,
            readback_deadline_s=args.readback_deadline,
            degraded_after=args.degraded_after,
            probe_backend_on_degraded=args.probe_on_degraded,
        ),
        # Dead accelerator -> rebuild the pipeline on host devices: the
        # job degrades to CPU speed instead of wedging (README "Failure
        # handling"). Only reachable with --probe-on-degraded.
        cpu_fallback=rebuild_pipeline_on_cpu if args.probe_on_degraded else None,
        tracer=tracer,
        slo_monitor=slo_monitor,
        replica=replica,
        cascade=not args.no_cascade,
        cascade_threshold=args.cascade_threshold,
        tracker=tracker,
    )
    # Registry wiring: published results + the tracker key on the full
    # stamp; a reader's re-anchor onto a post-swap manifest flushes the
    # identity caches (the writer-side flush rides the swap coordinator).
    if state is not None and state.registry is not None:
        service.registry = state.registry
    elif replica is not None and replica.registry is not None:
        service.registry = replica.registry
        replica.on_registry_change = service.flush_model_caches
    if slo_monitor is not None and replica is not None:
        # Stale-replica brownout: the lag gauge objective rides the same
        # health verdict the brownout controller already consumes at
        # critical, so a replica that falls behind sheds bulk serving
        # load until its tail catches up.
        from opencv_facerecognizer_tpu.runtime.slo import (
            replication_lag_objective,
        )

        short_s, long_s = args.slo_windows
        slo_monitor.add_objective(replication_lag_objective(
            replica, rows_bound=args.replication_lag_rows,
            short_s=short_s, long_s=long_s))
    if slo_monitor is not None and args.slo_loop_stale_s > 0:
        # Registered after construction: the gauge closes over the
        # service, which is built WITH the monitor (runtime.slo
        # loop_liveness_objective docstring).
        from opencv_facerecognizer_tpu.runtime.slo import (
            loop_liveness_objective,
        )

        short_s, long_s = args.slo_windows
        slo_monitor.add_objective(loop_liveness_objective(
            service, stale_s=args.slo_loop_stale_s,
            short_s=short_s, long_s=long_s))
    supervisor = (ServiceSupervisor(service, state=state)
                  if args.supervised else None)
    expo = None
    if args.expo_port is not None:
        from opencv_facerecognizer_tpu.runtime.expo import ExpoServer

        expo = ExpoServer(service, tracer=tracer, metrics=metrics,
                          port=args.expo_port)
        expo.start()
        print(f"expo endpoint: http://{expo.host}:{expo.port}/",
              file=sys.stderr)
    if supervisor is not None:
        supervisor.start()
    else:
        service.start()

    # Graceful SIGTERM (README "State durability"): drain in-flight
    # batches, final checkpoint, WAL truncate, exit 0 — a deploy-level
    # stop must not cost acknowledged enrollments or in-flight frames.
    import signal
    import threading

    term_event = threading.Event()
    try:
        signal.signal(signal.SIGTERM,
                      lambda signum, frame: term_event.set())
    except ValueError:
        pass  # not the main thread (tests drive main() from a worker)

    profiling = False
    if args.profile_dir:
        import jax

        # Post-warmup so the trace shows steady-state device work, not the
        # one-off XLA compiles (SURVEY.md §5.1; read with TensorBoard's
        # profile plugin or xprof pointed at the directory).
        jax.profiler.start_trace(args.profile_dir)
        profiling = True

    def _stop_profile_if_due() -> None:
        nonlocal profiling
        if profiling and metrics.counter("batches_dispatched") >= args.profile_batches:
            import jax

            jax.profiler.stop_trace()
            profiling = False
            print(f"profile trace written to {args.profile_dir}", file=sys.stderr)

    interrupted = False
    try:
        if args.source == "dir":
            import json

            from opencv_facerecognizer_tpu.ops import image as image_ops
            from opencv_facerecognizer_tpu.utils.dataset import _imread_gray

            files = sorted(
                f for f in os.listdir(args.dir)
                if f.lower().endswith((".png", ".jpg", ".jpeg", ".pgm", ".bmp"))
            )
            for fn in files:
                img = _imread_gray(os.path.join(args.dir, fn))
                if img is None:
                    continue
                img = np.asarray(image_ops.resize(img, tuple(args.frame_size)))
                connector.inject(FRAME_TOPIC, {**encode_frame(img), "meta": {"file": fn}})
            deadline = time.monotonic() + 60
            while (len(connector.messages(RESULT_TOPIC)) < len(files)
                   and time.monotonic() < deadline
                   and not term_event.is_set()):
                _stop_profile_if_due()
                time.sleep(0.05)
            for message in connector.messages(RESULT_TOPIC):
                print(json.dumps(message))
        else:
            # Serve until the input stream/socket ends (stdin EOF terminates
            # the process instead of spinning forever), SIGTERM, or Ctrl-C;
            # then let every frame already accepted finish and publish
            # before the teardown in `finally` discards the queues.
            while not connector.eof.wait(timeout=0.5):
                _stop_profile_if_due()
                if term_event.is_set():
                    print("SIGTERM: draining before shutdown", file=sys.stderr)
                    break
            service.drain()
    except KeyboardInterrupt:
        interrupted = True
    finally:
        if profiling:
            import jax

            jax.profiler.stop_trace()
        # ONE shutdown sequence — the exported helper the recovery chaos
        # scenario validates (drain -> stop -> final checkpoint -> WAL
        # truncate), not a hand-rolled copy that could drift from it.
        # Ctrl-C keeps its prompt-teardown semantics via a zero drain
        # budget; EOF/SIGTERM paths already drained above, so the
        # helper's drain is a fast no-op there.
        from opencv_facerecognizer_tpu.runtime.state_store import (
            graceful_shutdown,
        )

        if expo is not None:
            expo.stop()
        shutdown = graceful_shutdown(service, state=state,
                                     supervisor=supervisor,
                                     drain_timeout=0.0 if interrupted else 30.0)
        if shutdown.get("flight_dump"):
            print(f"flight-recorder dump: {shutdown['flight_dump']}",
                  file=sys.stderr)
        if state is not None:
            print(f"final checkpoint: "
                  f"{'written' if shutdown['final_checkpoint'] else 'FAILED (previous kept)'}",
                  file=sys.stderr)
        summary = metrics.summary()
        if summary:
            print(f"metrics: {summary}", file=sys.stderr)
        if shutdown["ledger"]["admitted"]:
            print(f"admission ledger: {shutdown['ledger']}", file=sys.stderr)
        if journal is not None:
            journal.close()
        if span_journal is not None:
            span_journal.close()
        if lease is not None:
            # Last: the final checkpoint/WAL truncate above ran under the
            # lease; releasing it hands enrollment ownership to the next
            # writer with the state dir already quiesced.
            lease.release()
        if metrics_sink:
            metrics_sink.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
