"""``ocvf-train``: dataset dir -> validated, checkpointed model.

The reference flow (SURVEY.md §3.1): walk folder-per-subject dataset,
resize, fit Fisherfaces+NN, k-fold validate, save. Flags cover the §5.6
config surface; ``--model cnn`` swaps in the ArcFace CNN backend.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ocvf-train", description="Train a face recognition model on TPU"
    )
    p.add_argument("dataset", help="dataset dir: one sub-folder of images per subject")
    p.add_argument("model_path", help="output checkpoint path (.ckpt)")
    p.add_argument("--model", default="fisherfaces",
                   choices=["fisherfaces", "eigenfaces", "lbph",
                            "lbp_fisherfaces", "cnn", "auto"],
                   help="model family; 'auto' k-folds every family on the "
                        "dataset and keeps the measured winner")
    p.add_argument("--image-size", type=int, nargs=2, default=(70, 70),
                   metavar=("H", "W"))
    p.add_argument("--kfold", type=int, default=3)
    p.add_argument("--num-components", type=int, default=0)
    p.add_argument("--knn-k", type=int, default=1)
    p.add_argument("--no-tan-triggs", action="store_true")
    p.add_argument("--classifier", default="nn",
                   choices=["nn", "svm", "kernel_svm"],
                   help="classifier stage over the feature projection")
    p.add_argument("--svm-kernel", default="rbf",
                   choices=["rbf", "poly", "linear"],
                   help="kernel for --classifier kernel_svm")
    p.add_argument("--embed-dim", type=int, default=128)
    p.add_argument("--train-steps", type=int, default=200)
    p.add_argument("--eigenfaces-plot", default=None,
                   help="optional PNG path: render top subspace components")
    p.add_argument("--profile-dir",
                   help="capture a jax.profiler trace of the whole train+"
                        "validate run into this directory (open with "
                        "TensorBoard or xprof)")
    p.add_argument("--keep-checkpoints", type=int, default=0,
                   help="retain the previous N model checkpoints as "
                        "model.ckpt.1..N when overwriting (the write "
                        "itself is always atomic: tmp + fsync + rename, "
                        "so a crash mid-save never corrupts the existing "
                        "checkpoint)")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    # Contradictory combinations fail loudly instead of silently training a
    # different model than the flags suggest.
    if args.svm_kernel != "rbf" and args.classifier != "kernel_svm":
        parser.error("--svm-kernel only applies with --classifier kernel_svm")
    if args.knn_k != 1 and args.classifier != "nn":
        parser.error(f"--knn-k only applies with --classifier nn "
                     f"(got --classifier {args.classifier})")
    from opencv_facerecognizer_tpu.runtime.trainer import TheTrainer, TrainerConfig

    if args.model == "auto":
        # Flags that select a specific artifact shape don't compose with
        # selection — fail loudly (this file's policy) instead of silently
        # ignoring them.
        if args.profile_dir or args.eigenfaces_plot or args.keep_checkpoints:
            parser.error("--profile-dir/--eigenfaces-plot/--keep-checkpoints "
                         "don't apply with --model auto (selection saves "
                         "candidate models repeatedly; run the winner "
                         "single-model to use them)")
        from opencv_facerecognizer_tpu.runtime.trainer import select_model
        from opencv_facerecognizer_tpu.utils import dataset as dataset_utils

        images, labels, names = dataset_utils.read_images(
            args.dataset, image_size=tuple(args.image_size))
        trainer, scores = select_model(
            images, labels, names, model_path=args.model_path,
            image_size=tuple(args.image_size), kfold=args.kfold,
            num_components=args.num_components, knn_k=args.knn_k,
            tan_triggs=not args.no_tan_triggs, embed_dim=args.embed_dim,
            train_steps=args.train_steps,
            classifier=args.classifier, svm_kernel=args.svm_kernel,
        )
        for kind in sorted(scores, key=scores.get, reverse=True):
            print(f"  {kind:>16}: {scores[kind]:.4f} k-fold")
        print(f"selected: {trainer.config.model} "
              f"({trainer.mean_accuracy:.4f} mean k-fold accuracy)")
        print(f"model saved to {args.model_path}")
        return 0

    config = TrainerConfig(
        model=args.model,
        image_size=tuple(args.image_size),
        kfold=args.kfold,
        num_components=args.num_components,
        knn_k=args.knn_k,
        tan_triggs=not args.no_tan_triggs,
        classifier=args.classifier,
        svm_kernel=args.svm_kernel,
        embed_dim=args.embed_dim,
        train_steps=args.train_steps,
    )
    trainer = TheTrainer(config)
    trainer.keep_checkpoints = args.keep_checkpoints
    if args.profile_dir:
        import jax

        jax.profiler.start_trace(args.profile_dir)
    try:
        model = trainer.train_from_dir(args.dataset, model_path=args.model_path)
    finally:
        if args.profile_dir:
            import jax

            jax.profiler.stop_trace()
            print(f"profile trace written to {args.profile_dir}", file=sys.stderr)
    if trainer.validation:
        for result in trainer.validation.results:
            print(result)
        print(f"mean k-fold accuracy: {trainer.mean_accuracy:.4f}")
    print(f"subjects: {model.subject_names}")
    print(f"model saved to {args.model_path}")
    if args.eigenfaces_plot:
        from opencv_facerecognizer_tpu.models import Fisherfaces, PCA
        from opencv_facerecognizer_tpu.models.operators import FeatureOperator
        from opencv_facerecognizer_tpu.utils import visual

        feature = model.feature
        while isinstance(feature, FeatureOperator):
            feature = feature.model2
        if isinstance(feature, (PCA, Fisherfaces)):
            path = visual.plot_eigenfaces(feature, tuple(args.image_size),
                                          filename=args.eigenfaces_plot)
            print(f"eigenfaces plot: {path}")
        else:
            print("eigenfaces plot skipped: model has no subspace components")
    return 0


if __name__ == "__main__":
    sys.exit(main())
