"""Ingest pipeline: the subsystem between connector/admission and the
dispatch ladder (ROADMAP item #1 — the serving loop is transfer-bound).

BENCH_DETAIL's evidence: b32 H2D crosses at 6.2 ms p50 (1.3 GB/s, f32)
against ~0.64 ms of device compute, so e2e is ~10x device cost — and the
old ``--transfer-uint8`` shortcut, which cut bytes 4x, paid a catastrophic
118 ms p99 because every batch staged through a freshly-allocated host
array (page faults + allocator churn on the hot path) and synchronized
under load. This module is the real fix, three pieces:

- **Staging ring** (``StagingRing``): a recycled, double-buffered ring of
  pre-allocated host staging buffers, one small pool per dispatch-bucket
  rung, grown out of the PR-2 zero-alloc pool seam in
  ``runtime/batcher.py``. Batch n+1 assembles into a warm recycled buffer
  while batch n's dispatch is in flight, so steady-state ingest allocates
  NOTHING (``ingest_staging_allocs`` stays at the construction-time
  preallocation — asserted by test). Exhaustion under flood is explicit
  backpressure: the batch waits queued and admission rejects new intake
  (reason ``staging``) — never a fresh allocation.
- **uint8 end-to-end upload** (``IngestPipeline.upload``): frames cross
  host->device as uint8 (4x fewer bytes) through one explicit
  ``jax.device_put`` per dispatch attempt, with the cast/normalize fused
  into the detect prologue on device (``RecognitionPipeline``'s in-graph
  ``astype``) and the frames argument donated through the bucketed ladder
  on backends that support donation (``donate_frames``).
- **Compressed-frame intake** (``DecodeWorkerPool``): JPEG camera payloads
  (the live-video workload of PAPERS.md 1811.07339 — what real camera
  fleets actually send) are accepted at the connector and decoded OFF the
  hot thread by a small worker pool directly into the staging path. Decode
  failures dead-letter through the journal/ledger machinery with reason
  ``decode_error``; depth and latency ride the shared Metrics surface.

Lock order: the batcher acquires ring buffers while holding its own queue
lock, so the sanctioned nesting is ``FrameBatcher._lock -> StagingRing
._lock``; the ring NEVER calls back into the batcher (or Metrics) under
its own lock — release notifications and counter mirrors fire after the
lock is dropped.
"""

from __future__ import annotations

import base64
import logging
import threading
import time
import warnings
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from opencv_facerecognizer_tpu.utils import metric_names as mn

#: the three ingest modes ``ocvf-recognize --ingest-mode`` exposes.
INGEST_MODES = ("f32", "uint8", "jpeg")

#: wire key of a compressed-frame payload (base64 JPEG bytes) — the
#: compressed sibling of ``connector.encode_frame``'s ``__frame__``.
JPEG_KEY = "__jpeg__"


def resolve_ingest_mode(ingest_mode: Optional[str],
                        transfer_uint8: bool = False,
                        warn: bool = True) -> str:
    """CLI mode resolution, including the ``--transfer-uint8`` deprecation
    alias: the old flag routes through the new uint8 ingest path (pinned
    staging ring + fused on-device cast), so its 118 ms p99 pathology is
    untriggerable. An explicit ``--ingest-mode`` always wins."""
    if transfer_uint8:
        if warn:
            warnings.warn(
                "--transfer-uint8 is deprecated and will be removed next "
                "release; it now aliases --ingest-mode uint8 (the pinned "
                "staging-ring upload path)", DeprecationWarning,
                stacklevel=2)
        if ingest_mode is None:
            return "uint8"
    mode = ingest_mode or "f32"
    if mode not in INGEST_MODES:
        raise ValueError(f"unknown ingest mode {mode!r} "
                         f"(valid: {INGEST_MODES})")
    return mode


def encode_jpeg_message(jpeg_bytes: bytes) -> Dict[str, Any]:
    """JPEG bytes -> the wire payload dict a camera producer publishes on
    the frame topic (merge ``meta``/``priority`` in alongside)."""
    return {JPEG_KEY: base64.b64encode(bytes(jpeg_bytes)).decode("ascii")}


def decode_jpeg_payload(message: Dict[str, Any]) -> bytes:
    return base64.b64decode(message[JPEG_KEY])


#: resolved-once (encode, decode) pair — the decode pool calls
#: ``decode_jpeg`` per frame, so the import probing must not re-run on
#: the hot path.
_CODEC_CACHE: Optional[Tuple[Any, Any]] = None


def _jpeg_codec():
    """(encode_fn, decode_fn) over whatever codec this container ships —
    PIL first, cv2 second — or (None, None). Nothing is installed for
    this; environments without either get a loud construction-time error
    from the decode pool instead of a hot-path surprise. Resolution runs
    once per process (cached)."""
    global _CODEC_CACHE
    if _CODEC_CACHE is None:
        _CODEC_CACHE = _resolve_jpeg_codec()
    return _CODEC_CACHE


def _resolve_jpeg_codec():
    try:
        import io

        from PIL import Image

        def encode(frame: np.ndarray, quality: int = 85) -> bytes:
            buf = io.BytesIO()
            Image.fromarray(np.asarray(frame, np.uint8), mode="L").save(
                buf, format="JPEG", quality=int(quality))
            return buf.getvalue()

        def decode(data: bytes) -> np.ndarray:
            with Image.open(io.BytesIO(data)) as img:
                return np.asarray(img.convert("L"))

        return encode, decode
    except ImportError:
        pass
    try:
        import cv2

        def encode(frame: np.ndarray, quality: int = 85) -> bytes:
            ok, buf = cv2.imencode(".jpg", np.asarray(frame, np.uint8),
                                   [int(cv2.IMWRITE_JPEG_QUALITY),
                                    int(quality)])
            if not ok:
                raise ValueError("cv2.imencode failed")
            return buf.tobytes()

        def decode(data: bytes) -> np.ndarray:
            arr = cv2.imdecode(np.frombuffer(data, np.uint8),
                               cv2.IMREAD_GRAYSCALE)
            if arr is None:
                raise ValueError("cv2.imdecode failed")
            return arr

        return encode, decode
    except ImportError:
        return None, None


def jpeg_supported() -> bool:
    return _jpeg_codec()[0] is not None


def encode_jpeg(frame: np.ndarray, quality: int = 85) -> bytes:
    """Grayscale [H, W] uint8-ish frame -> baseline JPEG bytes."""
    encode, _ = _jpeg_codec()
    if encode is None:
        raise RuntimeError("no JPEG codec available (PIL or cv2 required)")
    return encode(np.clip(np.asarray(frame), 0, 255).astype(np.uint8),
                  quality)


def decode_jpeg(data: bytes) -> np.ndarray:
    """JPEG bytes -> grayscale [H, W] uint8 frame (raises on corrupt or
    truncated payloads — the decode pool's dead-letter trigger)."""
    _, decode = _jpeg_codec()
    if decode is None:
        raise RuntimeError("no JPEG codec available (PIL or cv2 required)")
    arr = np.asarray(decode(bytes(data)))
    if arr.ndim != 2 or arr.size == 0:
        raise ValueError(f"decoded JPEG has shape {arr.shape}, "
                         "expected a 2-D grayscale frame")
    return arr


@dataclass
class IngestConfig:
    """Knobs of the ingest subsystem (``ocvf-recognize --ingest-*``)."""

    #: ``f32`` (legacy transfer dtype), ``uint8`` (4x cheaper H2D, cast
    #: fused on device), ``jpeg`` (uint8 + compressed intake decoded off
    #: the hot thread).
    mode: str = "f32"
    #: staging buffers preallocated per dispatch-bucket rung. None (the
    #: default) = auto: the service sizes it to ``inflight_depth + 2``
    #: (every overlapped in-flight batch holds a buffer, plus the batch
    #: being assembled and one completing), so the bounded ring never
    #: caps pipeline overlap below the in-flight window. An explicit
    #: value is honored as given (floor 1).
    ring_depth: Optional[int] = None
    #: decode worker threads (jpeg mode only).
    decode_workers: int = 2
    #: bounded decode intake queue; beyond it admitted compressed frames
    #: drop with ledger reason ``frames_dropped_decode`` (journal reason
    #: ``decode_backlog``) instead of growing an unbounded backlog.
    decode_queue: int = 128
    #: route dispatches through one explicit ``jax.device_put`` per
    #: attempt (measured as the ``upload`` span + ``ingest_upload``
    #: window). False keeps the implicit jit-internal transfer.
    upload: bool = True

    def __post_init__(self):
        if self.mode not in INGEST_MODES:
            raise ValueError(f"unknown ingest mode {self.mode!r} "
                             f"(valid: {INGEST_MODES})")
        if self.ring_depth is not None:
            self.ring_depth = max(1, int(self.ring_depth))
        self.decode_workers = max(1, int(self.decode_workers))
        self.decode_queue = max(1, int(self.decode_queue))

    def resolve_ring_depth(self, inflight_depth: int) -> int:
        """The effective per-rung depth: the explicit knob, or the
        auto-sizing rule (``inflight_depth + 2`` — see ``ring_depth``)."""
        if self.ring_depth is not None:
            return self.ring_depth
        return max(1, int(inflight_depth)) + 2

    @property
    def transfer_dtype(self):
        """Host staging / H2D dtype the mode implies."""
        return np.float32 if self.mode == "f32" else np.uint8


class StagingRing:
    """Recycled ring of pre-allocated host staging buffers, one pool per
    dispatch-bucket rung (module docstring).

    ``acquire(count)`` hands back a free buffer of the smallest rung that
    fits ``count`` real frames (falling upward to a bigger rung before
    reporting exhaustion — a large buffer carries a small batch fine; the
    dispatch bucket is picked by count, not buffer length), or ``None``
    when every fitting rung is in flight: the caller must WAIT, never
    allocate. ``release`` returns a buffer to its rung's pool;
    ``forfeit`` tells the ring a buffer is gone for good (dead-letter /
    crash paths must not recycle a staging array whose async H2D read may
    still be pending) so a later exhausted acquire may heal with ONE
    replacement allocation — the only post-construction allocation path,
    and it only opens on outages.

    Thread-safe; never calls out (notify hooks, Metrics) under its lock.
    """

    def __init__(self, rung_sizes: Sequence[int],
                 frame_shape: Tuple[int, int], dtype, depth: int = 2,
                 metrics=None):
        rungs = sorted({int(r) for r in rung_sizes if int(r) > 0})
        if not rungs:
            raise ValueError("StagingRing needs at least one rung size")
        self.frame_shape = tuple(frame_shape)
        self.dtype = np.dtype(dtype)
        self.depth = max(1, int(depth))
        self.rungs = rungs
        self.metrics = metrics
        self._lock = threading.Lock()
        self._free: Dict[int, deque] = {
            r: deque(np.zeros((r, *self.frame_shape), self.dtype)
                     for _ in range(self.depth))
            for r in rungs
        }
        self._forfeited: Dict[int, int] = {r: 0 for r in rungs}
        self._notify: List[Callable[[], None]] = []
        # Lock-free mirror of the TOP rung's free+heal count for the
        # per-frame admission read (``free_slots``): written under the
        # ring lock by every mutation, read bare (an int load is atomic
        # in CPython; a transiently stale read only shifts WHICH frame a
        # flood sheds, which is fine for a bound).
        self._top_free = self.depth
        #: total buffers ever allocated (preallocation + outage heals) —
        #: the zero-steady-state-allocation assertion reads this.
        self.alloc_count = len(rungs) * self.depth
        self.preallocated = self.alloc_count
        if metrics is not None:
            metrics.incr(mn.INGEST_STAGING_ALLOCS, self.preallocated)
            metrics.set_gauge(mn.INGEST_STAGING_FREE, self.preallocated)

    def add_notify(self, fn: Callable[[], None]) -> None:
        """Register a release notification (the batcher wakes its consumer
        wait on it). Called OUTSIDE the ring lock."""
        self._notify.append(fn)

    def _fitting(self, count: int) -> List[int]:
        fits = [r for r in self.rungs if r >= count]
        return fits or [self.rungs[-1]]

    def _refresh_top_free_locked(self) -> None:
        """Caller holds the lock: refresh the lock-free admission mirror
        after any mutation of the top rung's free/heal state."""
        top = self.rungs[-1]
        self._top_free = len(self._free[top]) + self._forfeited[top]

    def acquire(self, count: int, quiet: bool = False) -> Optional[np.ndarray]:
        """A free staging buffer of the smallest fitting rung, or None
        (exhausted — wait and retry; the ring refuses to allocate).
        ``quiet=True`` marks a parked consumer's RE-check: a miss there
        is the same exhaustion episode still in progress, so the
        ``ingest_staging_exhausted`` counter stays per-episode (alertable
        as a rate) instead of ticking once per 10 ms poll."""
        buf = None
        healed = False
        with self._lock:
            fits = self._fitting(count)
            for rung in fits:
                if self._free[rung]:
                    buf = self._free[rung].popleft()
                    break
            if buf is None:
                # Outage heal: a forfeited buffer (dead-lettered batch)
                # will never come back — replace it, once, here, so a
                # chaos window cannot permanently shrink the ring.
                for rung in fits:
                    if self._forfeited[rung] > 0:
                        self._forfeited[rung] -= 1
                        buf = np.zeros((rung, *self.frame_shape), self.dtype)
                        self.alloc_count += 1
                        healed = True
                        break
            self._refresh_top_free_locked()
            free_now = sum(len(q) for q in self._free.values())
        if self.metrics is not None:
            if buf is None:
                if not quiet:
                    self.metrics.incr(mn.INGEST_STAGING_EXHAUSTED)
            elif healed:
                self.metrics.incr(mn.INGEST_STAGING_ALLOCS)
            else:
                self.metrics.incr(mn.INGEST_STAGING_REUSE)
            self.metrics.set_gauge(mn.INGEST_STAGING_FREE, free_now)
        return buf

    def release(self, buf) -> None:
        """Return a buffer once its batch's readback completed and every
        view was copied out. Foreign shapes/dtypes are dropped silently
        (mirrors the legacy pool's recycle contract)."""
        if (not isinstance(buf, np.ndarray) or buf.dtype != self.dtype
                or buf.ndim != 1 + len(self.frame_shape)
                or buf.shape[1:] != self.frame_shape
                or buf.shape[0] not in self._free):
            return
        rung = buf.shape[0]
        returned = False
        with self._lock:
            if len(self._free[rung]) < self.depth + self._forfeited[rung]:
                self._free[rung].append(buf)
                returned = True
            self._refresh_top_free_locked()
            free_now = sum(len(q) for q in self._free.values())
        if returned:
            for fn in self._notify:
                fn()
        if self.metrics is not None:
            self.metrics.set_gauge(mn.INGEST_STAGING_FREE, free_now)

    def forfeit(self, buf) -> None:
        """Mark one in-flight buffer as never coming back (dead-letter /
        crash: the backend's async read of it may still be pending, so it
        must stay out of circulation). Opens one replacement-allocation
        credit for its rung."""
        if (not isinstance(buf, np.ndarray)
                or buf.ndim != 1 + len(self.frame_shape)
                or buf.shape[0] not in self._free):
            return
        with self._lock:
            self._forfeited[buf.shape[0]] += 1
            self._refresh_top_free_locked()
        if self.metrics is not None:
            self.metrics.incr(mn.INGEST_STAGING_FORFEITS)

    def free_slots(self) -> int:
        """Free buffers in the LARGEST rung (plus its heal credits) — the
        admission backpressure signal (reason ``staging`` at 0). The top
        rung is the binding constraint: ``acquire`` only falls UPWARD, so
        small-rung buffers can never stage a full batch — counting them
        would leave the front door open while every full-batch flush is
        parked (and top-rung exhaustion with smaller rungs still free
        already means >= depth full batches are in flight: overload). A
        heal credit counts because an exhausted ring that can still
        self-replace is not wedged.

        LOCK-FREE on purpose: this runs on the connector thread for
        every offered frame (the documented lock-free admit path), so it
        reads the mirror the mutators maintain under the ring lock — a
        transiently stale value only shifts which frame a flood sheds."""
        return self._top_free

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rungs": list(self.rungs),
                "depth": self.depth,
                "free": {r: len(q) for r, q in self._free.items()},
                "forfeited": dict(self._forfeited),
                "alloc_count": self.alloc_count,
                "preallocated": self.preallocated,
            }


class DecodeWorkerPool:
    """Small worker pool decoding compressed camera payloads OFF the
    serving hot thread, directly into the staging path.

    ``submit`` enqueues one admitted payload (returns False when the
    bounded queue is full — the caller settles the ledger drop); workers
    decode and hand the pixel frame to ``sink`` (the service's intake
    continuation: brownout check + batcher put). A payload that fails to
    decode goes to ``on_error`` instead — corrupt camera bytes must cost
    one frame, one counted ledger drop, one journal row, never a worker.

    The chaos boundary ``decode`` (``runtime.faults``) installs here:
    ``slow`` sleeps the injector's ``slow_decode_s`` before decoding (the
    congested-decoder shape the off-thread pool must absorb without
    stalling dispatch), ``corrupt`` replaces the payload with bytes no
    decoder accepts.

    A worker counts as busy until its sink/on_error call RETURNS, so
    ``idle()`` has no in-transit gap — ``RecognizerService.drain`` relies
    on that to cover frames mid-decode.
    """

    def __init__(self, workers: int = 2, max_queue: int = 128,
                 decode_fn: Optional[Callable[[bytes], np.ndarray]] = None,
                 metrics=None, tracer=None, trace_topic: Optional[str] = None,
                 fault_injector=None):
        if decode_fn is None and not jpeg_supported():
            raise RuntimeError(
                "compressed-frame intake needs a JPEG codec (PIL or cv2); "
                "neither is importable here — pass decode_fn explicitly "
                "or use --ingest-mode uint8")
        self.workers = max(1, int(workers))
        self.max_queue = max(1, int(max_queue))
        self._decode = decode_fn or decode_jpeg
        self.metrics = metrics
        self._tracer = tracer
        self._trace_topic = trace_topic
        self._faults = fault_injector
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._busy = 0
        self._running = False
        self._threads: List[threading.Thread] = []
        self._sink: Optional[Callable] = None
        self._on_error: Optional[Callable] = None

    def start(self, sink: Callable, on_error: Callable) -> None:
        """``sink(frame, message, priority, trace_id)`` on success;
        ``on_error(message, priority, trace_id, reason)`` on failure."""
        if self._running:
            return
        self._sink = sink
        self._on_error = on_error
        self._running = True
        for i in range(self.workers):
            thread = threading.Thread(target=self._run, daemon=True,
                                      name=f"ocvf-decode-{i}")
            thread.start()
            self._threads.append(thread)

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        for thread in self._threads:
            thread.join(timeout=2.0)
        self._threads.clear()

    def submit(self, message: Dict[str, Any], priority: int,
               trace_id: int) -> bool:
        """Enqueue one admitted compressed frame; False = queue full (the
        caller owns the ledger settlement of the drop)."""
        with self._cv:
            if not self._running or len(self._q) >= self.max_queue:
                accepted = False
            else:
                self._q.append((message, int(priority), int(trace_id),
                                time.monotonic()))
                accepted = True
                depth = len(self._q)
                self._cv.notify()
        if accepted and self.metrics is not None:
            self.metrics.set_gauge(mn.DECODE_QUEUE_DEPTH, depth)
        return accepted

    def idle(self) -> bool:
        """Queue empty AND no worker mid-decode (including mid-sink)."""
        with self._cv:
            return not self._q and self._busy == 0

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q)

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._q:
                    self._cv.wait()
                if not self._q:
                    if not self._running:
                        return
                    continue
                message, priority, tid, t_enq = self._q.popleft()
                self._busy += 1
                depth = len(self._q)
            try:
                if self.metrics is not None:
                    self.metrics.set_gauge(mn.DECODE_QUEUE_DEPTH, depth)
                self._decode_one(message, priority, tid)
            except Exception:  # noqa: BLE001 — backstop: _decode_one contains every expected failure; anything escaping is a bug that must cost one frame's accounting, never the worker
                logging.getLogger(__name__).exception(
                    "decode worker iteration failed")
                if self.metrics is not None:
                    self.metrics.incr(mn.DECODE_ERRORS)
            finally:
                with self._cv:
                    self._busy -= 1

    def _decode_one(self, message, priority: int, tid: int) -> None:
        t0 = time.perf_counter()
        try:
            payload = decode_jpeg_payload(message)
            if self._faults is not None:
                payload = self._faults.on_decode(payload)
            frame = self._decode(payload)
        except Exception:  # noqa: BLE001 — corrupt payloads are the failure mode this pool exists to contain
            if self.metrics is not None:
                self.metrics.incr(mn.DECODE_ERRORS)
                self.metrics.observe(mn.DECODE_LATENCY,
                                     time.perf_counter() - t0)
            if self._tracer is not None and tid:
                self._tracer.emit(tid, "decode", topic=self._trace_topic,
                                  dur=time.perf_counter() - t0, ok=False)
            self._settle_error(message, priority, tid)
            return
        dur = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.incr(mn.DECODE_FRAMES)
            self.metrics.observe(mn.DECODE_LATENCY, dur)
        if self._tracer is not None and tid:
            self._tracer.emit(tid, "decode", topic=self._trace_topic,
                              dur=dur, ok=True)
        try:
            self._sink(frame, message, priority, tid)
        except Exception:  # noqa: BLE001 — a raising intake continuation (journal IOError under stress, a brownout-path bug) must cost this FRAME, never a worker thread: a dead pool with submit() still accepting would silently stop all camera traffic
            logging.getLogger(__name__).exception(
                "decode sink failed; settling the frame as a decode drop")
            if self.metrics is not None:
                self.metrics.incr(mn.DECODE_ERRORS)
            self._settle_error(message, priority, tid)

    def _settle_error(self, message, priority: int, tid: int) -> None:
        """Route one failed frame to ``on_error`` (the service's ledger
        settlement). Its own failure is logged, never raised — the ledger
        leak is the service's bug to find via the error log + counter,
        and a worker thread must survive it either way."""
        try:
            self._on_error(message, priority, tid, "decode_error")
        except Exception:  # noqa: BLE001 — see _settle_error docstring: the worker must outlive a broken settlement callback
            logging.getLogger(__name__).exception(
                "decode on_error callback failed; frame may be "
                "unsettled in the admission ledger")
            if self.metrics is not None:
                self.metrics.incr(mn.DECODE_ERRORS)


class IngestPipeline:
    """The assembled ingest subsystem one ``RecognizerService`` owns:
    staging ring + (jpeg mode) decode pool + the explicit device uploader.
    Construction is pure wiring; ``start``/``stop`` manage the decode
    workers; ``upload`` runs on the dispatch path (one call per dispatch
    attempt, so a retry after a donated-buffer dispatch re-uploads from
    the host staging view)."""

    def __init__(self, config: IngestConfig, rung_sizes: Sequence[int],
                 frame_shape: Tuple[int, int], metrics=None, tracer=None,
                 trace_topic: Optional[str] = None, fault_injector=None,
                 decode_fn=None, inflight_depth: int = 4):
        self.config = config
        self.metrics = metrics
        self.transfer_dtype = np.dtype(config.transfer_dtype)
        self.staging = StagingRing(
            rung_sizes, frame_shape, self.transfer_dtype,
            depth=config.resolve_ring_depth(inflight_depth),
            metrics=metrics)
        self.decoder = None
        if config.mode == "jpeg":
            self.decoder = DecodeWorkerPool(
                workers=config.decode_workers,
                max_queue=config.decode_queue,
                decode_fn=decode_fn, metrics=metrics, tracer=tracer,
                trace_topic=trace_topic, fault_injector=fault_injector)
        # Upload placement override (None = the default device). The
        # CPU-fallback path (resilience.rebuild_pipeline_on_cpu) pins
        # this to the CPU device it rebuilt the pipeline on: a bare
        # device_put would otherwise keep committing frames to the DEAD
        # accelerator — every dispatch attempt failing against the very
        # fallback built to survive it (the same retargeting the
        # enrolment graph's _embed_device does).
        self.upload_device = None

    def start(self, sink: Callable, on_error: Callable) -> None:
        if self.decoder is not None:
            self.decoder.start(sink, on_error)

    def stop(self) -> None:
        if self.decoder is not None:
            self.decoder.stop()

    def idle(self) -> bool:
        return self.decoder is None or self.decoder.idle()

    def submit_decode(self, message: Dict[str, Any], priority: int,
                      trace_id: int) -> bool:
        if self.decoder is None:
            return False
        return self.decoder.submit(message, priority, trace_id)

    def upload(self, frames) -> Tuple[Any, int, float]:
        """Ship one staged batch view host->device explicitly: returns
        ``(device_frames, nbytes, enqueue_seconds)``. The put is async —
        the duration is the host enqueue cost, not transfer completion
        (that lands in ``ready_wait``, where it always did). With
        ``config.upload`` off this is a passthrough."""
        if not self.config.upload:
            return frames, int(getattr(frames, "nbytes", 0)), 0.0
        import jax

        nbytes = int(frames.nbytes)
        t0 = time.perf_counter()
        device_frames = jax.device_put(frames, self.upload_device)
        dur = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.incr(mn.INGEST_UPLOAD_BYTES, nbytes)
            self.metrics.observe(mn.INGEST_UPLOAD, dur)
        return device_frames, nbytes, dur

    def stats(self) -> Dict[str, Any]:
        out = {"mode": self.config.mode,
               "transfer_dtype": str(self.transfer_dtype),
               "staging": self.staging.stats()}
        if self.decoder is not None:
            out["decode_queue_depth"] = self.decoder.queue_depth()
        return out
