"""Temporal identity cache (ISSUE 17): per-stream box tracking feeding a
track -> identity cache, so coherent video skips embed + gallery match.

The serving pipeline is ONE fused device call per batch (detect -> align
-> embed -> match, ``parallel/pipeline.py``) — there is no detect-only
entry to split per face — so the cache gates at FRAME granularity, like
the stage-1 cascade (ISSUE 13): a frame whose stream's confirmed tracks
are all fresh (not due for re-verify, appearance signature unmoved,
embedder version matching) settles as ``completed_cached`` with the
cached identities and never dispatches at all; everything else takes the
full path, whose published result both answers the frame and re-verifies
the stream's tracks.

Association is greedy IoU with a centroid-distance fallback over
consecutive FULL results (pure NumPy on host frames — no new jit
surface). The poisoning guarantees, each enforced structurally:

- **stale identity is never served past the re-verify window**: a track
  serves at most ``reverify_frames - 1`` consecutive cached frames
  (stretched under brownout) before a scheduled full verify; appearance
  drift (median pooled-patch signature delta above ``drift_threshold``)
  forces the verify immediately, so an in-place identity swap is caught
  on the very next lookup, not at the window edge;
- **identity change / verify mismatch invalidates, never serves**: a
  full result whose associated face carries a different label (or a
  collapsed similarity) flushes the track (reason ``identity``) — the
  FRESH result is what publishes;
- **poisoning cannot cross tracks**: two live tracks overlapping above
  ``iou_ambiguity`` flush BOTH (reason ``ambiguity``) before either
  could capture the other's identity;
- **cutover flushes are automatic**: cache entries stamp the gallery's
  ``embedder_version`` at verify time; a lookup against a different
  version flushes (reason ``version``) — a PR 11 rollout cutover
  cold-starts the cache with no coordination;
- **replica-local by construction**: state lives in this object, owned
  by one service — PR 10's rendezvous routing pins a topic to one
  replica, so nothing replicates and failover simply cold-starts.

Thread model: ``lookup`` runs on the dispatch thread, ``update`` /
``note_miss`` on the readback worker — one lock guards the registry;
every operation is a handful of tiny NumPy reductions.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from opencv_facerecognizer_tpu.utils import metric_names as mn

#: Flush-reason suffixes (``track_flushes_<reason>`` counter family):
#: ``identity`` — re-verify saw a different label / collapsed similarity;
#: ``ambiguity`` — two live tracks overlapped above the IoU ceiling;
#: ``version``  — embedder-version fence (rollout cutover);
#: ``lost``     — track missed too many consecutive full observations;
#: ``reset``    — explicit cold start (gallery reload, flush_all).
FLUSH_IDENTITY = "identity"
FLUSH_AMBIGUITY = "ambiguity"
FLUSH_VERSION = "version"
FLUSH_LOST = "lost"
FLUSH_RESET = "reset"


@dataclass
class TrackerConfig:
    """Operating knobs for the temporal identity cache.

    ``reverify_frames`` is the staleness bound: a confirmed track serves
    at most that many consecutive frames (one of which is the full
    verify) before the next full pass — the window every freshness
    guarantee is stated against. ``brownout_stretch`` multiplies it at
    effective brownout level >= 1 (mirroring the cascade threshold
    notch: shed device work BEFORE shedding intake)."""

    #: full re-verify every N frames per track (``--track-reverify-frames``).
    reverify_frames: int = 8
    #: association floor: a result box claims a track only at IoU >= this
    #: (``--track-iou-min``); below it the centroid fallback may still
    #: associate (small fast faces), else the face is a new track.
    iou_min: float = 0.3
    #: ambiguity ceiling: two LIVE tracks overlapping at IoU >= this are
    #: both flushed — identity can never bleed across crossing tracks.
    iou_ambiguity: float = 0.6
    #: centroid-fallback radius as a fraction of the frame's long side.
    centroid_frac: float = 0.15
    #: consecutive verified associations (with a known identity) before a
    #: track is confirmed and cache-eligible.
    confirm_hits: int = 2
    #: consecutive full observations without an association before a
    #: track is flushed ``lost``.
    miss_ttl: int = 2
    #: median abs pooled-signature cell delta (uint8 levels) that forces
    #: an immediate re-verify: box-local motion only disturbs edge cells
    #: (median ~0), an in-place identity swap or a vacated box moves the
    #: majority of cells by the full content delta.
    drift_threshold: float = 8.0
    #: pooled appearance-signature side (sig_pool x sig_pool block means).
    sig_pool: int = 8
    #: per-stream track registry bound (oldest flushed ``lost`` beyond it).
    max_tracks_per_stream: int = 16
    #: re-verify interval multiplier at effective brownout level >= 1.
    brownout_stretch: float = 2.0


@dataclass(eq=False)
class _Track:
    track_id: int
    box: np.ndarray                # (y0, x0, y1, x1) float32
    label: int
    name: str
    similarity: float
    detection_score: float
    signature: np.ndarray          # (sig_pool, sig_pool) float32
    embedder_version: Optional[int]
    hits: int = 1
    misses: int = 0
    confirmed: bool = False
    frames_since_verify: int = 0
    #: set when a scheduled/drift re-verify is owed — counted once, and
    #: every lookup until the next full association declines the cache.
    pending_verify: bool = False


@dataclass
class _Stream:
    tracks: List[_Track] = field(default_factory=list)
    lookups: int = 0
    hits: int = 0


def _iou(a: np.ndarray, b: np.ndarray) -> float:  # ocvf-lint: boundary-block=host-sync -- 4-element HOST arrays (publish-path face boxes, already materialized): float() here is scalar math, not a device readback
    """IoU of two (y0, x0, y1, x1) boxes (host floats)."""
    y0 = max(a[0], b[0])
    x0 = max(a[1], b[1])
    y1 = min(a[2], b[2])
    x1 = min(a[3], b[3])
    inter = max(0.0, float(y1 - y0)) * max(0.0, float(x1 - x0))
    if inter <= 0.0:
        return 0.0
    area_a = max(0.0, float(a[2] - a[0])) * max(0.0, float(a[3] - a[1]))
    area_b = max(0.0, float(b[2] - b[0])) * max(0.0, float(b[3] - b[1]))
    denom = area_a + area_b - inter
    return inter / denom if denom > 0.0 else 0.0


def _centroid(box: np.ndarray) -> tuple:
    return (float(box[0] + box[2]) * 0.5, float(box[1] + box[3]) * 0.5)


class IdentityTracker:
    """The track -> identity cache (module docstring). One instance per
    service replica; the service consults ``lookup`` before the cascade
    gate and feeds every full published result back through ``update``.
    """

    def __init__(self, config: Optional[TrackerConfig] = None,
                 metrics=None):
        self.config = config or TrackerConfig()
        self.metrics = metrics
        self._lock = threading.Lock()
        self._streams: Dict[Any, _Stream] = {}
        self._next_id = 1
        self._lookups = 0
        self._hits = 0

    # ---- host-side appearance signature ----

    def _signature(self, frame: np.ndarray, box: np.ndarray) -> np.ndarray:  # ocvf-lint: boundary-block=host-sync -- pure host-NumPy by design (module docstring): ``frame`` is the intake host array, never a device value; the integral-image pooling is the tracker's budgeted ~60us of dispatch-thread work
        """Mean-pooled patch at ``box`` (clipped to the frame): a
        sig_pool x sig_pool float32 appearance fingerprint. Pooling
        softens box-edge motion (a 1-2 px drift moves a couple of edge
        cells by a few levels) while an in-place content change (identity
        swap, vacated box) moves most cells by the full fill delta."""
        pool = self.config.sig_pool
        h, w = frame.shape[:2]
        y0 = min(max(int(box[0]), 0), max(0, h - 1))
        x0 = min(max(int(box[1]), 0), max(0, w - 1))
        y1 = min(max(int(np.ceil(box[2])), y0 + 1), h)
        x1 = min(max(int(np.ceil(box[3])), x0 + 1), w)
        patch = np.asarray(frame[y0:y1, x0:x1], dtype=np.float32)
        ys = np.linspace(0, patch.shape[0], pool + 1).astype(int)
        xs = np.linspace(0, patch.shape[1], pool + 1).astype(int)
        # Degenerate-bin guard for patches smaller than the pool grid:
        # every cell spans at least one pixel (clamped to the edge).
        r1s = np.minimum(np.maximum(ys[1:], ys[:-1] + 1), patch.shape[0])
        r0s = np.minimum(ys[:-1], r1s - 1)
        c1s = np.minimum(np.maximum(xs[1:], xs[:-1] + 1), patch.shape[1])
        c0s = np.minimum(xs[:-1], c1s - 1)
        # Integral image gives every cell's block SUM in one vectorized
        # gather — this runs per track per lookup on the dispatch
        # thread, so a Python cell loop here would tax the very latency
        # the cache exists to protect.
        ii = np.zeros((patch.shape[0] + 1, patch.shape[1] + 1), np.float64)
        np.cumsum(patch, axis=0, out=ii[1:, 1:])
        np.cumsum(ii[1:, 1:], axis=1, out=ii[1:, 1:])
        sums = (ii[np.ix_(r1s, c1s)] - ii[np.ix_(r0s, c1s)]
                - ii[np.ix_(r1s, c0s)] + ii[np.ix_(r0s, c0s)])
        areas = np.outer(r1s - r0s, c1s - c0s)
        return (sums / areas).astype(np.float32)

    # ---- metrics plumbing (all under self._lock) ----

    def _incr(self, name: str, value: float = 1.0) -> None:
        if self.metrics is not None:
            # ocvf-lint: disable=metrics-registry -- thin None-guard shim; _incr is itself in the rule's NAME_METHODS, so every caller's argument is validated against the registry at its own call site
            self.metrics.incr(name, value)

    def _flush(self, stream: _Stream, track: _Track, reason: str) -> None:
        if track in stream.tracks:
            stream.tracks.remove(track)
        self._incr(mn.TRACK_FLUSHES_PREFIX + reason)

    def _set_gauges(self) -> None:
        if self.metrics is None:
            return
        live = sum(len(s.tracks) for s in self._streams.values())
        self.metrics.set_gauge(mn.TRACKS_LIVE, live)
        self.metrics.set_gauge(
            mn.TRACK_CACHE_HIT_RATE, self._hits / max(1, self._lookups))

    # ---- the serving-path API ----

    def lookup(self, stream_key: Any, frame: np.ndarray,
               embedder_version: Optional[int] = None,
               reverify_stretch: float = 1.0) -> Optional[Dict[str, Any]]:
        """Cache verdict for one frame of ``stream_key``: the cached
        result payload (``faces`` shaped exactly like the publish path's,
        each carrying its ``track_id``) when EVERY live track of the
        stream is confirmed, version-matched, inside its re-verify window
        and appearance-stable at its box — else None (the frame takes the
        full pipeline, whose published result re-verifies via
        ``update``). Conservative by design: one doubtful track sends the
        whole frame to the full path."""
        with self._lock:
            self._lookups += 1
            self._incr(mn.TRACK_LOOKUPS)
            st = self._streams.get(stream_key)
            if st is None or not st.tracks:
                self._set_gauges()
                return None
            st.lookups += 1
            # Embedder-version fence: entries verified under another
            # version are dead on arrival — a rollout cutover cold-starts
            # the cache with no coordination (ISSUE 11's stamp).
            if embedder_version is not None:
                stale = [t for t in st.tracks
                         if t.embedder_version is not None
                         and t.embedder_version != embedder_version]
                for t in stale:
                    self._flush(st, t, FLUSH_VERSION)
                if stale:
                    self._set_gauges()
                    return None
            # A tentative track pending confirmation needs full frames to
            # mature (and may be a brand-new entrant the cached faces
            # would omit): no caching until the stream is all-confirmed.
            if any(not t.confirmed for t in st.tracks):
                return None
            interval = max(1, int(round(self.config.reverify_frames
                                        * max(1.0, reverify_stretch))))
            due = False
            sigs = []
            for t in st.tracks:
                if t.pending_verify or t.frames_since_verify + 1 >= interval:
                    if not t.pending_verify:
                        t.pending_verify = True
                        self._incr(mn.TRACK_REVERIFIES)
                    due = True
            if due:
                return None
            for t in st.tracks:
                sig = self._signature(frame, t.box)
                # Median cell delta, not mean: sub-cell box motion moves
                # only the EDGE cells (strongly — a half-cell shift is
                # half the fill/background contrast), so the median over
                # all cells stays ~0, while an in-place content change
                # (identity swap, vacated box) moves EVERY cell by the
                # full delta and the median reports it undiluted.
                if float(np.median(np.abs(sig - t.signature))) \
                        > self.config.drift_threshold:  # ocvf-lint: boundary-block=host-sync -- both signatures are host float32 pools from _signature; median over 64 host cells, no device value in reach
                    # Appearance moved under a live track: force the full
                    # verify NOW — an in-place identity swap never
                    # survives to the window edge.
                    t.pending_verify = True
                    self._incr(mn.TRACK_REVERIFIES)
                    due = True
                sigs.append(sig)
            if due:
                return None
            faces = []
            for t, sig in zip(st.tracks, sigs):
                t.frames_since_verify += 1
                # Rolling signature: smooth motion/appearance change is
                # followed (each hop is below the drift threshold); an
                # abrupt change still trips on its first frame.
                t.signature = sig
                y0, x0, y1, x1 = (float(v) for v in t.box)  # ocvf-lint: boundary=host-sync -- t.box is a host float32 array seeded from publish-path face dicts
                faces.append({
                    "box": [x0, y0, x1, y1],  # x-first, like _publish
                    "detection_score": t.detection_score,
                    "label": t.label,
                    "name": t.name,
                    "similarity": t.similarity,
                    "track_id": t.track_id,
                })
            self._hits += 1
            st.hits += 1
            self._incr(mn.TRACK_CACHE_HITS)
            self._set_gauges()
            return {"faces": faces,
                    "track_id": st.tracks[0].track_id,
                    "embedder_version": embedder_version}

    def update(self, stream_key: Any, faces: List[Dict[str, Any]],
               frame: np.ndarray,
               embedder_version: Optional[int] = None) -> None:
        """Fold one FULL published result into the stream's tracks:
        greedy-IoU (+ centroid fallback) association, identity
        cross-check (mismatch flushes, the fresh result already
        published), confirmation bookkeeping, miss aging, and the
        pairwise ambiguity sweep. ``faces`` are publish-path dicts
        (x-first ``box``, ``label`` -1 when unknown)."""
        cfg = self.config
        with self._lock:
            st = self._streams.setdefault(stream_key, _Stream())
            boxes = []
            for f in faces:
                x0, y0, x1, y1 = (float(v) for v in f["box"])
                boxes.append(np.asarray([y0, x0, y1, x1], np.float32))
            # Greedy best-IoU association, then a centroid pass for
            # leftovers (fast small faces whose boxes slipped past the
            # IoU floor between verifies).
            pairs = []
            for fi, b in enumerate(boxes):
                for ti, t in enumerate(st.tracks):
                    iou = _iou(b, t.box)
                    if iou >= cfg.iou_min:
                        pairs.append((iou, fi, ti))
            pairs.sort(key=lambda p: -p[0])
            face_used: set = set()
            track_used: set = set()
            matches = []
            for iou, fi, ti in pairs:
                if fi in face_used or ti in track_used:
                    continue
                face_used.add(fi)
                track_used.add(ti)
                matches.append((fi, ti))
            radius = cfg.centroid_frac * float(max(frame.shape[:2]))
            for ti, t in enumerate(st.tracks):
                if ti in track_used:
                    continue
                tc = _centroid(t.box)
                best = None
                for fi, b in enumerate(boxes):
                    if fi in face_used:
                        continue
                    fc = _centroid(b)
                    dist = ((tc[0] - fc[0]) ** 2
                            + (tc[1] - fc[1]) ** 2) ** 0.5
                    if dist <= radius and (best is None or dist < best[0]):
                        best = (dist, fi)
                if best is not None:
                    face_used.add(best[1])
                    track_used.add(ti)
                    matches.append((best[1], ti))
            # Association verdicts are collected first and applied after:
            # a mid-loop flush would shift the indices the match list
            # speaks in. ``matched`` is by object identity.
            flush: List[tuple] = []
            matched: set = set()
            for fi, ti in matches:
                t = st.tracks[ti]
                f = faces[fi]
                label = int(f.get("label", -1))
                known = label >= 0
                matched.add(t)
                if (known and label != t.label) or (t.confirmed and not known):
                    # Verify mismatch: the identity under this box is not
                    # the cached one (swap) or no longer known (occlusion
                    # / collapsed similarity). The track dies; the fresh
                    # result — already published by the caller — is the
                    # only thing ever served. A known new identity seeds
                    # a fresh tentative track below.
                    flush.append((t, FLUSH_IDENTITY))
                    if known:
                        face_used.discard(fi)
                    continue
                t.box = boxes[fi]
                t.signature = self._signature(frame, t.box)
                t.misses = 0
                t.frames_since_verify = 0
                t.pending_verify = False
                t.detection_score = float(f.get("detection_score", 0.0))
                t.embedder_version = embedder_version
                if known:
                    t.similarity = float(f.get("similarity", 0.0))
                    t.name = str(f.get("name", t.name))
                    t.hits += 1
                    if not t.confirmed and t.hits >= cfg.confirm_hits:
                        t.confirmed = True
                        self._incr(mn.TRACKS_CONFIRMED)
            # Identity re-acquisition (teleport/scene-cut recovery): a
            # KNOWN face that box-associated with nothing, when exactly
            # one live unmatched track carries its label, IS that track
            # seen again somewhere else — the full pipeline verified the
            # identity at the new box on THIS frame, so re-seeding keeps
            # the track's confirmed state without ever serving anything
            # unverified (the next cached serve still needs a fresh
            # association against the new box). Any ambiguity — two
            # candidate tracks, or two unmatched faces with the label —
            # falls through to fresh-track seeding instead.
            flushing = {t for t, _r in flush}
            by_label: Dict[int, List[int]] = {}
            for fi, f in enumerate(faces):
                label = int(f.get("label", -1))
                if fi not in face_used and label >= 0:
                    by_label.setdefault(label, []).append(fi)
            live_unmatched = [t for t in st.tracks
                              if t not in matched and t not in flushing]
            for label, fis in by_label.items():
                cands = [t for t in live_unmatched if t.label == label]
                if len(fis) != 1 or len(cands) != 1:
                    continue
                fi, t = fis[0], cands[0]
                f = faces[fi]
                face_used.add(fi)
                matched.add(t)
                t.box = boxes[fi]
                t.signature = self._signature(frame, t.box)
                t.misses = 0
                t.frames_since_verify = 0
                t.pending_verify = False
                t.detection_score = float(f.get("detection_score", 0.0))
                t.embedder_version = embedder_version
                t.similarity = float(f.get("similarity", 0.0))
                t.name = str(f.get("name", t.name))
                t.hits += 1
                if not t.confirmed and t.hits >= cfg.confirm_hits:
                    t.confirmed = True
                    self._incr(mn.TRACKS_CONFIRMED)
            # Unmatched tracks age: a track the full detector stopped
            # seeing must never serve again past its miss budget.
            for t in st.tracks:
                if t in matched:
                    continue
                t.misses += 1
                t.pending_verify = False
                t.frames_since_verify = 0
                if t.misses > cfg.miss_ttl:
                    flush.append((t, FLUSH_LOST))
            for t, reason in flush:
                self._flush(st, t, reason)
            # Unmatched KNOWN faces seed tentative tracks; unknown faces
            # never enter the cache (they would serve "unknown" blindly).
            for fi, f in enumerate(faces):
                if fi in face_used:
                    continue
                label = int(f.get("label", -1))
                if label < 0:
                    continue
                self._next_id += 1
                st.tracks.append(_Track(
                    track_id=self._next_id,
                    box=boxes[fi],
                    label=label,
                    name=str(f.get("name", str(label))),
                    similarity=float(f.get("similarity", 0.0)),
                    detection_score=float(f.get("detection_score", 0.0)),
                    signature=self._signature(frame, boxes[fi]),
                    embedder_version=embedder_version))
                self._incr(mn.TRACKS_CREATED)
            # Ambiguity ceiling: two live tracks overlapping this hard
            # could swap each other's association next frame — flush
            # BOTH immediately, so poisoning can never cross tracks.
            amb: set = set()
            for i in range(len(st.tracks)):
                for j in range(i + 1, len(st.tracks)):
                    if _iou(st.tracks[i].box,
                            st.tracks[j].box) >= cfg.iou_ambiguity:
                        amb.add(st.tracks[i])
                        amb.add(st.tracks[j])
            for t in amb:
                self._flush(st, t, FLUSH_AMBIGUITY)
            # Registry bound: oldest (front of list) flushes first.
            while len(st.tracks) > cfg.max_tracks_per_stream:
                self._flush(st, st.tracks[0], FLUSH_LOST)
            self._set_gauges()

    def note_miss(self, stream_key: Any) -> None:
        """A full pass saw this stream with NO faces (cascade early exit
        or an empty detection): every live track takes a miss; past the
        TTL it flushes ``lost`` — a vanished subject stops being served
        within ``miss_ttl`` full frames."""
        cfg = self.config
        with self._lock:
            st = self._streams.get(stream_key)
            if st is None:
                return
            for t in list(st.tracks):
                t.misses += 1
                # A missed track must re-associate on a full frame before
                # it may serve again — the flag parks it out of the cache
                # without burning a flush it may not deserve (occlusion).
                t.pending_verify = True
                if t.misses > cfg.miss_ttl:
                    self._flush(st, t, FLUSH_LOST)
            self._set_gauges()

    def flush_all(self, reason: str = FLUSH_RESET) -> int:
        """Cold start (gallery reload / explicit reset): every live track
        flushes under ``reason``. Returns the count flushed."""
        with self._lock:
            n = 0
            for st in self._streams.values():
                n += len(st.tracks)
                for _ in range(len(st.tracks)):
                    self._incr(mn.TRACK_FLUSHES_PREFIX + reason)
                st.tracks.clear()
            self._streams.clear()
            self._set_gauges()
            return n

    # ---- observability ----

    def registry(self) -> List[Dict[str, Any]]:
        """Read-only live-track snapshot for ``GET /tracks``."""
        with self._lock:
            out = []
            for key, st in self._streams.items():
                for t in st.tracks:
                    y0, x0, y1, x1 = (float(v) for v in t.box)  # ocvf-lint: boundary=host-sync -- host float32 track box; expo snapshot path
                    out.append({
                        "stream": key,
                        "track_id": t.track_id,
                        "box": [x0, y0, x1, y1],
                        "label": t.label,
                        "name": t.name,
                        "similarity": t.similarity,
                        "confirmed": t.confirmed,
                        "hits": t.hits,
                        "misses": t.misses,
                        "frames_since_verify": t.frames_since_verify,
                        "embedder_version": t.embedder_version,
                    })
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "streams": len(self._streams),
                "tracks_live": sum(len(s.tracks)
                                   for s in self._streams.values()),
                "lookups": self._lookups,
                "hits": self._hits,
                "hit_rate": self._hits / max(1, self._lookups),
                "reverify_frames": self.config.reverify_frames,
            }
