"""Fake serving backends for deterministic perf tests and smoke benches.

``InstantPipeline`` stands in for ``RecognitionPipeline`` in front of
``RecognizerService``: dispatch returns immediately with a packed result
array whose "device" behavior is scripted — optionally a simulated compute
delay before readiness, and optionally a **sync-poll cost** charged on
every ``is_ready`` call (the tunneled backend's ~100 ms readback floor,
reproduced on CPU). That makes the serving loop's host-side overheads —
batching delay, poll sleeps vs event-driven readback, publish — measurable
in isolation, fast, and deterministic: the tier-1 perf smoke asserts the
overlapped readback worker keeps ``ready_wait`` off the poll floor without
needing real hardware (see ``bench_serving.run_smoke`` and
``tests/test_serving_perf.py``).

No recognition happens: every frame comes back with zero detected faces,
which is exactly what the loop-perf surfaces need (results still publish
per frame, so end-to-end latency is real).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np


class FakePacked:
    """A packed-result device array stand-in with scripted readiness.

    ``is_ready`` reports completion of the simulated compute (charging
    ``poll_cost_s`` per call — the sync-poll floor); ``block_until_ready``
    sleeps exactly the remaining compute time (the event-driven wait);
    ``__array__`` materializes after blocking.
    """

    def __init__(self, arr: np.ndarray, ready_at: float,
                 poll_cost_s: float = 0.0):
        self._arr = arr
        self._ready_at = ready_at
        self._poll_cost_s = float(poll_cost_s)

    def is_ready(self) -> bool:
        if self._poll_cost_s > 0.0:
            time.sleep(self._poll_cost_s)
        return time.monotonic() >= self._ready_at

    def block_until_ready(self) -> "FakePacked":
        delay = self._ready_at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        return self

    def copy_to_host_async(self) -> None:
        pass

    def __array__(self, dtype=None):
        self.block_until_ready()
        return self._arr if dtype is None else self._arr.astype(dtype)


class _GalleryStub:
    size = 0
    grow_count = 0


class InstantPipeline:
    """Drop-in pipeline for RecognizerService with scripted device timing.

    ``compute_s`` — seconds after dispatch until the batch's readback is
    ready (simulated device compute + D2H). ``sync_poll_floor_s`` — cost
    charged on EVERY ``is_ready`` call, emulating the tunneled backend's
    sync-poll readback floor: the legacy inline-drain path pays it on the
    serving thread per check, while the readback worker's event-driven
    ``block_until_ready`` never does.
    """

    def __init__(self, frame_shape: Tuple[int, int], top_k: int = 1,
                 max_faces: int = 2, compute_s: float = 0.0,
                 sync_poll_floor_s: float = 0.0):
        self.frame_shape = tuple(frame_shape)
        self.top_k = int(top_k)
        self.max_faces = int(max_faces)
        self.compute_s = float(compute_s)
        self.sync_poll_floor_s = float(sync_poll_floor_s)
        self.face_size = (8, 8)
        self.gallery = _GalleryStub()
        self.fault_injector = None
        self.dispatches = 0
        #: batch dimension of every dispatch, in order — lets tests assert
        #: the service's bucket ladder sliced partial batches as designed.
        self.batch_sizes_seen: list = []

    def recognize_batch_packed(self, frames) -> FakePacked:
        if self.fault_injector is not None:
            self.fault_injector.on_dispatch()
        self.dispatches += 1
        b = int(np.asarray(frames).shape[0])
        self.batch_sizes_seen.append(b)
        # pack_result layout: boxes(4) | det_score | valid | labels(k) |
        # sims(k); valid=0 everywhere -> zero faces per frame.
        packed = np.zeros((b, self.max_faces, 6 + 2 * self.top_k), np.float32)
        return FakePacked(packed, time.monotonic() + self.compute_s,
                          poll_cost_s=self.sync_poll_floor_s)
