"""Fake serving backends for deterministic perf tests and smoke benches.

``InstantPipeline`` stands in for ``RecognitionPipeline`` in front of
``RecognizerService``: dispatch returns immediately with a packed result
array whose "device" behavior is scripted — optionally a simulated compute
delay before readiness, and optionally a **sync-poll cost** charged on
every ``is_ready`` call (the tunneled backend's ~100 ms readback floor,
reproduced on CPU). That makes the serving loop's host-side overheads —
batching delay, poll sleeps vs event-driven readback, publish — measurable
in isolation, fast, and deterministic: the tier-1 perf smoke asserts the
overlapped readback worker keeps ``ready_wait`` off the poll floor without
needing real hardware (see ``bench_serving.run_smoke`` and
``tests/test_serving_perf.py``).

No recognition happens: every frame comes back with zero detected faces,
which is exactly what the loop-perf surfaces need (results still publish
per frame, so end-to-end latency is real).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

import numpy as np


class FakePacked:
    """A packed-result device array stand-in with scripted readiness.

    ``is_ready`` reports completion of the simulated compute (charging
    ``poll_cost_s`` per call — the sync-poll floor); ``block_until_ready``
    sleeps exactly the remaining compute time (the event-driven wait);
    ``__array__`` materializes after blocking.
    """

    def __init__(self, arr: np.ndarray, ready_at: float,
                 poll_cost_s: float = 0.0):
        self._arr = arr
        self._ready_at = ready_at
        self._poll_cost_s = float(poll_cost_s)

    def is_ready(self) -> bool:
        if self._poll_cost_s > 0.0:
            time.sleep(self._poll_cost_s)
        return time.monotonic() >= self._ready_at

    def block_until_ready(self) -> "FakePacked":
        delay = self._ready_at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        return self

    def copy_to_host_async(self) -> None:
        pass

    def __array__(self, dtype=None):
        self.block_until_ready()
        return self._arr if dtype is None else self._arr.astype(dtype)


class _GalleryStub:
    size = 0
    grow_count = 0

    # Enough of the ShardedGallery surface that a ServiceSupervisor can
    # checkpoint/restore over a fake pipeline (the overload soak wraps
    # the service in one): nothing to snapshot, nothing to restore.
    def snapshot(self):
        return ()

    def load_snapshot(self, *parts, embedder_version=None) -> None:
        pass


class InstantPipeline:
    """Drop-in pipeline for RecognizerService with scripted device timing.

    ``compute_s`` — seconds after dispatch until the batch's readback is
    ready (simulated device compute + D2H). ``sync_poll_floor_s`` — cost
    charged on EVERY ``is_ready`` call, emulating the tunneled backend's
    sync-poll readback floor: the legacy inline-drain path pays it on the
    serving thread per check, while the readback worker's event-driven
    ``block_until_ready`` never does.
    """

    def __init__(self, frame_shape: Tuple[int, int], top_k: int = 1,
                 max_faces: int = 2, compute_s: float = 0.0,
                 sync_poll_floor_s: float = 0.0, dispatch_s: float = 0.0,
                 faces_per_frame: int = 0,
                 h2d_gb_s: Optional[float] = None,
                 dispatch_per_frame_s: float = 0.0,
                 cascade_stub: bool = False,
                 cascade_score_s: float = 0.0,
                 video_oracle: bool = False,
                 oracle_sim: float = 0.9):
        self.frame_shape = tuple(frame_shape)
        self.top_k = int(top_k)
        self.max_faces = int(max_faces)
        self.compute_s = float(compute_s)
        self.sync_poll_floor_s = float(sync_poll_floor_s)
        #: host-side seconds charged PER FRAME inside each dispatch call,
        #: on top of ``dispatch_s`` — models the per-frame device cost
        #: BENCH_DETAIL attributes to detect (dominant at every bucket),
        #: so the cascade's survivor compaction actually buys capacity
        #: against this fake's wall the way it does on the chip: a
        #: smaller dispatched bucket costs proportionally less.
        self.dispatch_per_frame_s = float(dispatch_per_frame_s)
        #: stage-1 cascade stand-in (the serving gate duck-types
        #: ``pipeline.cascade`` + ``cascade_scores``): scores each frame
        #: by peak brightness — the synthetic face blobs are stamped at
        #: 200 on a <=90 background (``_stamp_faces``), so a brightness
        #: threshold is a deterministic, training-free oracle for the
        #: perf smokes. ``cascade_score_s`` is the scripted cost of one
        #: stage-1 pass (charged per call, whole-batch).
        self.cascade = "brightness-stub" if cascade_stub else None
        self.cascade_score_s = float(cascade_score_s)
        self.cascade_calls = 0
        #: (batch, dtype) stage-1 signatures already "compiled" — the
        #: cascade mirror of ``compiled_batch_sizes``, feeding
        #: ``last_cascade_info`` for the recompile watchdog.
        self.compiled_cascade_sigs: set = set()
        self.last_cascade_info: dict = {}
        #: simulated H2D bandwidth (GB/s): each dispatch additionally
        #: sleeps frames.nbytes / bandwidth, making the fake backend
        #: TRANSFER-bound the way BENCH_DETAIL says the real one is — a
        #: uint8 batch (4x fewer bytes) then completes ~4x more frames
        #: against the same wall, which is what the ingest smoke's
        #: uplift arm measures. None = no transfer cost (the historical
        #: behavior; dispatch_s alone is the wall).
        self.h2d_gb_s = None if h2d_gb_s is None else float(h2d_gb_s)
        #: scripted detections: the first N face slots of every frame come
        #: back valid (fixed box, det_score 1, label 0, sim 1) instead of
        #: the default zero-face result — what the rollout parity hook and
        #: the enrolment-collection paths need to fire without a real
        #: detector. 0 keeps the historical zero-face behavior.
        self.faces_per_frame = min(int(faces_per_frame), int(max_faces))
        #: host-side seconds charged INSIDE each dispatch call (the serve
        #: thread sleeps it out). ``compute_s`` is pure latency — batches
        #: overlap through the in-flight queue and never limit throughput;
        #: ``dispatch_s`` models a saturated dispatch pipe, giving the fake
        #: backend a hard capacity of ``batch_size / dispatch_s`` frames/s
        #: — the deterministic overload wall the admission/brownout tests
        #: and the overload soak push against.
        self.dispatch_s = float(dispatch_s)
        self.face_size = (8, 8)
        self.gallery = _GalleryStub()
        self.fault_injector = None
        self.dispatches = 0
        #: batch dimension of every dispatch, in order — lets tests assert
        #: the service's bucket ladder sliced partial batches as designed.
        self.batch_sizes_seen: list = []
        #: (batch, dtype) signatures already "compiled" (first dispatch of
        #: a signature is a cache miss, like the real packed-step cache,
        #: whose ``_step_key`` includes the input dtype — a uint8 ingest
        #: dispatch against an f32-only prewarm MUST read as a recompile)
        #: — drives the ``last_dispatch_info`` provenance the recompile
        #: watchdog reads, so the watchdog is testable without hardware.
        #: Tests clear this to inject a post-warmup compile.
        self.compiled_batch_sizes: set = set()
        self.last_dispatch_info: dict = {}
        #: video oracle (ISSUE 17): derive detections host-side from the
        #: frame pixels instead of scripting them — each identity in a
        #: ``synthetic_video_stream`` frame is a blob filled with the
        #: distinct value ``160 + 24*i`` (all >= the brightness-stub's
        #: 150 floor), so the oracle recovers box AND label exactly:
        #: label ``i`` at the mask's bounding box, fixed ``oracle_sim``
        #: similarity. This is what lets the tracker bench/chaos runs
        #: assert identity-correctness end-to-end without a trained
        #: embedder: the pipeline "recognizes" whoever is actually in
        #: the frame, and an in-place fill swap IS an identity change.
        self.video_oracle = bool(video_oracle)
        self.oracle_sim = float(oracle_sim)

    @staticmethod
    def _sig(batch, dtype) -> tuple:
        return (int(batch), str(np.dtype(dtype)))

    def prewarm_batch_shapes(self, ladder, frame_shape,
                             dtype=np.float32) -> None:
        """Mirror ``RecognitionPipeline.prewarm_batch_shapes``: mark every
        (ladder bucket, transfer dtype) signature compiled — BOTH stages
        when the cascade stub is armed, like the real pipeline — so
        post-warmup serving dispatches are cache hits: the recompile
        watchdog's armed-and-silent baseline."""
        for bucket in ladder:
            self.compiled_batch_sizes.add(self._sig(bucket, dtype))
            if self.cascade is not None:
                self.compiled_cascade_sigs.add(self._sig(bucket, dtype))

    def cascade_scores(self, frames) -> np.ndarray:
        """Scripted stage-1 pass: [B, H, W] -> [B] scores (1.0 for frames
        carrying a bright face blob, 0.0 otherwise — see ``cascade`` in
        ``__init__``). Charges ``cascade_score_s`` per call and records
        compile provenance like the packed path."""
        host = np.asarray(frames)
        if self.cascade_score_s > 0.0:
            time.sleep(self.cascade_score_s)
        self.cascade_calls += 1
        sig = self._sig(host.shape[0], host.dtype)
        self.last_cascade_info = {
            "cache_hit": sig in self.compiled_cascade_sigs}
        self.compiled_cascade_sigs.add(sig)
        return (host.reshape(host.shape[0], -1).max(axis=1)
                >= 150).astype(np.float32)

    def recognize_batch_packed(self, frames) -> FakePacked:
        if self.fault_injector is not None:
            self.fault_injector.on_dispatch()
        host = np.asarray(frames)
        if self.dispatch_s > 0.0:
            time.sleep(self.dispatch_s)  # capacity wall (see __init__)
        if self.dispatch_per_frame_s > 0.0:
            # Per-frame device-cost wall: a compacted/bucketed batch pays
            # for the frames it actually carries (see __init__).
            time.sleep(host.shape[0] * self.dispatch_per_frame_s)
        if self.h2d_gb_s:
            # Transfer wall: the scripted PCIe/tunnel cost of shipping
            # this batch's actual bytes (so uint8 staging pays 1/4 the
            # f32 price, like the real link).
            time.sleep(host.nbytes / (self.h2d_gb_s * 1e9))
        self.dispatches += 1
        b = int(host.shape[0])
        self.batch_sizes_seen.append(b)
        sig = self._sig(b, host.dtype)
        self.last_dispatch_info = {"cache_hit": sig in self.compiled_batch_sizes,
                                   "mode": "fake"}
        self.compiled_batch_sizes.add(sig)
        # pack_result layout: boxes(4) | det_score | valid | labels(k) |
        # sims(k); valid=0 everywhere -> zero faces per frame (unless
        # faces_per_frame scripts some detections in).
        packed = np.zeros((b, self.max_faces, 6 + 2 * self.top_k), np.float32)
        if self.video_oracle:
            # Pixel-derived detections (see __init__): one face per
            # distinct identity fill value present in the frame.
            for fi in range(b):
                slot = 0
                for v in np.unique(host[fi]):
                    fv = float(v)
                    if fv < 160.0 or fv > 232.0 or (fv - 160.0) % 24.0:
                        continue
                    if slot >= self.max_faces:
                        break
                    ys, xs = np.nonzero(host[fi] == v)
                    packed[fi, slot, 0:4] = (float(ys.min()), float(xs.min()),
                                             float(ys.max()) + 1.0,
                                             float(xs.max()) + 1.0)
                    packed[fi, slot, 4] = 1.0   # det_score
                    packed[fi, slot, 5] = 1.0   # valid
                    packed[fi, slot, 6] = (fv - 160.0) / 24.0  # label
                    packed[fi, slot, 6 + self.top_k] = self.oracle_sim
                    slot += 1
            return FakePacked(packed, time.monotonic() + self.compute_s,
                              poll_cost_s=self.sync_poll_floor_s)
        if self.faces_per_frame:
            h, w = self.frame_shape
            for j in range(self.faces_per_frame):
                packed[:, j, 0:4] = (2.0, 2.0, max(6.0, h - 2.0),
                                     max(6.0, w - 2.0))  # y0 x0 y1 x1
                packed[:, j, 4] = 1.0   # det_score
                packed[:, j, 5] = 1.0   # valid
                packed[:, j, 6] = 0.0   # top-1 label
                packed[:, j, 6 + self.top_k] = 1.0  # top-1 similarity
        return FakePacked(packed, time.monotonic() + self.compute_s,
                          poll_cost_s=self.sync_poll_floor_s)


def _stamp_faces(rng, frame: np.ndarray, n_faces: int) -> None:
    """Stamp ``n_faces`` bright face-ish blobs (a light square with
    darker eye dots) onto ``frame`` in place at seeded positions. The
    blob peak (200) sits far above the 20-90 background, so both the
    ``InstantPipeline`` brightness-stub cascade and a trained
    ``FaceGate`` separate stamped from face-free frames cleanly."""
    h, w = frame.shape
    for _face in range(int(n_faces)):
        side = int(rng.integers(max(6, h // 8), max(8, h // 3)))
        y0 = int(rng.integers(0, max(1, h - side)))
        x0 = int(rng.integers(0, max(1, w - side)))
        frame[y0:y0 + side, x0:x0 + side] = 200
        ey = y0 + side // 3
        for ex in (x0 + side // 4, x0 + 3 * side // 4):
            frame[max(0, ey - 1):ey + 1, max(0, ex - 1):ex + 1] = 60


def synthetic_jpeg_frames(n: int, frame_hw: Tuple[int, int] = (64, 64),
                          seed: int = 0, quality: int = 85,
                          faces_per_frame: int = 0):
    """Seeded synthetic camera payloads as REAL JPEG bytes: ``n`` pairs of
    ``(jpeg_bytes, source_frame)`` (uint8 grayscale). Deterministic per
    seed — the same seed always produces byte-identical payloads, so the
    ingest tests and the smoke bench replay exactly.

    ``faces_per_frame`` stamps that many bright face-ish blobs
    (``_stamp_faces``) onto each frame at seeded positions — the knob the
    face-density traffic mix (``synthetic_frame_stream``) composes with.
    """
    from opencv_facerecognizer_tpu.runtime.ingest import encode_jpeg

    rng = np.random.default_rng(seed)
    h, w = int(frame_hw[0]), int(frame_hw[1])
    out = []
    for _ in range(int(n)):
        frame = rng.integers(20, 90, size=(h, w)).astype(np.uint8)
        _stamp_faces(rng, frame, faces_per_frame)
        out.append((encode_jpeg(frame, quality=quality), frame))
    return out


def synthetic_frame_stream(n: int, frame_hw: Tuple[int, int] = (64, 64),
                           face_density: float = 0.3, seed: int = 0,
                           faces_per_frame: int = 1, jpeg: bool = False,
                           quality: int = 85):
    """Seeded face-density traffic mix (ISSUE 13; reusable by the video
    workload of ROADMAP item #3): ``n`` frames of which EXACTLY
    ``round(n * face_density)`` carry ``faces_per_frame`` stamped face
    blobs, the rest pure background — the deterministic mixed stream the
    cascade uplift bench sweeps density over. Which positions carry
    faces is a seeded permutation, so the mix is interleaved, not a
    prefix, and byte-identical per seed.

    Returns ``[(frame, n_faces)]`` (uint8 grayscale), or with
    ``jpeg=True`` ``[(jpeg_bytes, frame, n_faces)]`` — composing with
    the PR 12 compressed-intake path the way ``synthetic_jpeg_frames``
    payloads do."""
    n = int(n)
    rng = np.random.default_rng(seed)
    h, w = int(frame_hw[0]), int(frame_hw[1])
    n_faced = int(round(n * float(face_density)))
    faced = np.zeros(n, dtype=bool)
    faced[rng.permutation(n)[:n_faced]] = True
    out = []
    for i in range(n):
        frame = rng.integers(20, 90, size=(h, w)).astype(np.uint8)
        k = int(faces_per_frame) if faced[i] else 0
        _stamp_faces(rng, frame, k)
        if jpeg:
            from opencv_facerecognizer_tpu.runtime.ingest import encode_jpeg

            out.append((encode_jpeg(frame, quality=quality), frame, k))
        else:
            out.append((frame, k))
    return out


def synthetic_video_stream(n: int, frame_hw: Tuple[int, int] = (64, 64),
                           streams: int = 1, tracks_per_stream: int = 1,
                           coherence: float = 0.9, face_density: float = 1.0,
                           seed: int = 0, step_px: int = 1,
                           identity_swap_at: Optional[int] = None,
                           track_churn: float = 0.0, jpeg: bool = False,
                           quality: int = 85):
    """Seeded multi-stream video traffic (ISSUE 17): ``n`` frames
    round-robined across ``streams`` camera keys, each carrying
    ``tracks_per_stream`` persistent identity blobs whose motion is
    temporally coherent — the workload the temporal identity cache is
    built to exploit, and the one its chaos arms attack.

    Identity encoding: blob ``i`` is filled with the constant value
    ``160 + 24*(identity % 4)``, which ``InstantPipeline(video_oracle=
    True)`` decodes back into (box, label) exactly — so recognition
    results track frame CONTENT, and the knobs below change what the
    pipeline reports, not just the pixels:

    - ``coherence``: per-frame probability a blob takes a small
      ``±step_px`` walk instead of teleporting to a random position.
      0.9 ~ video, 0.0 ~ shuffled stills (every frame a jump, so box
      association — and with it the cache — finds nothing to reuse).
    - ``track_churn``: per-frame probability a blob is replaced
      outright (new position AND next identity) — scene-cut churn.
    - ``identity_swap_at``: per-stream frame index at which track 0
      changes identity IN PLACE (same box, new fill) — the cache-
      poisoning probe: a tracker that trusts box association alone
      would keep publishing the old name.
    - ``face_density``: probability a frame carries its blobs at all;
      blob-free frames are pure background (the cascade rejects them).

    Returns ``[(frame, stream_key, n_faces)]`` (uint8), or with
    ``jpeg=True`` ``[(jpeg_bytes, frame, stream_key, n_faces)]`` —
    composing with the PR 12 compressed-intake path like
    ``synthetic_frame_stream``. (JPEG is lossy: feed the oracle the
    raw ``frame``, not the decode, when identity exactness matters.)"""
    n = int(n)
    streams = max(1, int(streams))
    rng = np.random.default_rng(seed)
    h, w = int(frame_hw[0]), int(frame_hw[1])
    side = max(8, h // 4)
    step = max(1, int(step_px))

    def _spawn(ident):
        return {"ident": int(ident) % 4,
                "y": int(rng.integers(0, max(1, h - side))),
                "x": int(rng.integers(0, max(1, w - side)))}

    state = []
    for _s in range(streams):
        tracks = [_spawn(i) for i in range(int(tracks_per_stream))]
        state.append({"tracks": tracks, "frame_idx": 0,
                      "next_ident": int(tracks_per_stream)})

    out = []
    for i in range(n):
        s = i % streams
        st = state[s]
        for ti, t in enumerate(st["tracks"]):
            if track_churn and rng.random() < float(track_churn):
                st["tracks"][ti] = _spawn(st["next_ident"])
                st["next_ident"] += 1
                continue
            if rng.random() < float(coherence):
                t["y"] = int(np.clip(t["y"] + rng.integers(-step, step + 1),
                                     0, max(0, h - side)))
                t["x"] = int(np.clip(t["x"] + rng.integers(-step, step + 1),
                                     0, max(0, w - side)))
            else:
                t["y"] = int(rng.integers(0, max(1, h - side)))
                t["x"] = int(rng.integers(0, max(1, w - side)))
        if (identity_swap_at is not None
                and st["frame_idx"] == int(identity_swap_at)
                and st["tracks"]):
            t0 = st["tracks"][0]
            t0["ident"] = (t0["ident"] + 1) % 4
        frame = rng.integers(20, 90, size=(h, w)).astype(np.uint8)
        faced = rng.random() < float(face_density)
        k = 0
        if faced:
            for t in st["tracks"]:
                fill = 160 + 24 * (t["ident"] % 4)
                frame[t["y"]:t["y"] + side, t["x"]:t["x"] + side] = fill
                k += 1
        st["frame_idx"] += 1
        key = "cam%d" % s
        if jpeg:
            from opencv_facerecognizer_tpu.runtime.ingest import encode_jpeg

            out.append((encode_jpeg(frame, quality=quality), frame, key, k))
        else:
            out.append((frame, key, k))
    return out


def build_overload_stack(frame_shape=(32, 32), batch_size: int = 8,
                         dispatch_s: float = 0.04,
                         max_inflight_frames: int = 24,
                         brownout_queue_wait_s: float = 0.05,
                         brownout_dwell_s: float = 0.3,
                         stale_after_s: float = 0.25,
                         fault_injector=None, journal=None, tracer=None,
                         slo_monitor=None, metrics=None):
    """The canonical deterministic overload harness: an
    ``InstantPipeline`` with a hard ``batch_size / dispatch_s`` frames/s
    capacity wall behind a ``RecognizerService`` with the full protection
    stack armed (admission bound with interactive reserve, brownout with
    hysteresis, stale shedding, halved bucket ladder). Single-sourced so
    ``scripts/chaos_soak.run_overload`` and
    ``bench_serving.run_overload_sweep`` exercise — and their notes/pass
    criteria describe — the exact same configuration. Returns
    ``(pipeline, service, connector)``."""
    from opencv_facerecognizer_tpu.runtime.admission import AdmissionController
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService
    from opencv_facerecognizer_tpu.runtime.resilience import (
        BrownoutPolicy,
        ResiliencePolicy,
    )

    pipeline = InstantPipeline(frame_shape, dispatch_s=dispatch_s)
    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=batch_size, frame_shape=frame_shape,
        flush_timeout=0.03, inflight_depth=2, similarity_threshold=0.0,
        metrics=metrics,
        resilience=ResiliencePolicy(readback_deadline_s=2.0),
        fault_injector=fault_injector,
        admission=AdmissionController(max_inflight_frames=max_inflight_frames),
        brownout=BrownoutPolicy(queue_wait_s=brownout_queue_wait_s,
                                dwell_s=brownout_dwell_s),
        dead_letter_journal=journal,
        shed_stale_after_s=stale_after_s,
        bucket_sizes=(max(1, batch_size // 2), batch_size),
        tracer=tracer,
        slo_monitor=slo_monitor,
    )
    return pipeline, service, connector


def build_replica_fleet(n_replicas: int, frame_shape=(32, 32),
                        batch_size: int = 8, dispatch_s: float = 0.04,
                        health_interval_s: float = 0.1,
                        budget_fps=None, router_metrics=None,
                        tracer=None, replica_fault_injectors=None,
                        router_fault_injector=None,
                        link_deadline_s=None, hedge_deadline_s=None,
                        dedup_window: int = 4096):
    """N in-process serving replicas behind one ``TopicRouter`` — the
    deterministic scale-out harness: each replica is the canonical
    overload stack (``build_overload_stack``: a hard ``batch_size /
    dispatch_s`` frames/s capacity wall with admission/brownout armed)
    with its OWN ``Metrics``, and the router spreads camera topics across
    them with rendezvous hashing + in-process health probes. Shared by
    ``bench_serving.run_replica_scaleout`` and the replication chaos
    scenario, so the bench ladder and the soak's failover assertions
    exercise one configuration. Returns ``(router, stacks)`` where each
    stack is ``(pipeline, service, connector, metrics)``.

    Partition-chaos knobs (ISSUE 16): ``replica_fault_injectors`` (list
    or per-index dict) arms each replica's OWN fault boundary;
    ``router_fault_injector`` arms the router's transport crossings;
    ``link_deadline_s``/``hedge_deadline_s``/``dedup_window`` pass
    straight through to ``TopicRouter`` — all default off/inert so the
    scale-out bench keeps its exact pre-16 configuration."""
    from opencv_facerecognizer_tpu.runtime.replication import (
        ReplicaHandle, TopicRouter, service_health_probe,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    stacks = []
    handles = []
    for i in range(n_replicas):
        metrics = Metrics()
        if isinstance(replica_fault_injectors, dict):
            faults = replica_fault_injectors.get(i)
        elif replica_fault_injectors is not None:
            faults = replica_fault_injectors[i]
        else:
            faults = None
        pipeline, service, connector = build_overload_stack(
            frame_shape=frame_shape, batch_size=batch_size,
            dispatch_s=dispatch_s, metrics=metrics,
            fault_injector=faults)
        stacks.append((pipeline, service, connector, metrics))
        handles.append(ReplicaHandle(
            f"replica-{i}", connector,
            health_fn=service_health_probe(service),
            budget_fps=budget_fps))
    router = TopicRouter(handles, metrics=router_metrics, tracer=tracer,
                         health_interval_s=health_interval_s,
                         fault_injector=router_fault_injector,
                         link_deadline_s=link_deadline_s,
                         hedge_deadline_s=hedge_deadline_s,
                         dedup_window=dedup_window)
    return router, stacks


class TrafficRecorder:
    """Seq-tagged send/receive recorder for driving a service under
    offered load: stamps each frame at offer time, collects its result
    publish time, and reduces to completion counts and latency
    percentiles. Shared by ``scripts/chaos_soak.run_overload`` and
    ``bench_serving.run_overload_sweep`` so the soak's pass criteria and
    the bench's rows measure traffic identically."""

    def __init__(self, connector):
        from opencv_facerecognizer_tpu.runtime.recognizer import RESULT_TOPIC

        self.send_t: dict = {}
        self.done_t: dict = {}
        self._lock = threading.Lock()
        connector.subscribe(RESULT_TOPIC, self._on_result)

    def _on_result(self, topic, message) -> None:
        seq = (message.get("meta") or {}).get("seq")
        if seq is not None:
            with self._lock:
                self.done_t.setdefault(seq, time.monotonic())

    def offer(self, connector, payload: dict, seq, priority: str,
              meta_extra: Optional[dict] = None) -> None:
        """Stamp + inject one frame message (``payload`` carries the frame
        encoding; priority rides both the admission field and the meta).
        ``meta_extra`` merges additional meta keys — the video bench
        stamps ``stream`` so the tracker can scope its cache."""
        from opencv_facerecognizer_tpu.runtime.recognizer import FRAME_TOPIC

        self.send_t[seq] = time.monotonic()
        meta = {"seq": seq, "pri": priority}
        if meta_extra:
            meta.update(meta_extra)
        connector.inject(FRAME_TOPIC, {**payload, "priority": priority,
                                       "meta": meta})

    def completed(self, seqs) -> int:
        with self._lock:
            return sum(1 for s in seqs if s in self.done_t)

    def latencies(self, seqs):
        with self._lock:
            return [self.done_t[s] - self.send_t[s]
                    for s in seqs if s in self.done_t]

    def percentile_ms(self, seqs, q: float) -> float:
        """Latency percentile in ms over the completed subset of ``seqs``
        — NaN when nothing completed (callers must treat that as its own
        verdict, never compare it)."""
        lat = self.latencies(seqs)
        if not lat:
            return float("nan")
        return float(np.percentile(lat, q)) * 1e3
