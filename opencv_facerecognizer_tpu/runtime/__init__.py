"""Serving runtime (SURVEY.md §1 L7-L8, §7.8): frame batcher, middleware
connectors, trainer, and the recognizer service.

The device-collective layer (``parallel``) and this host-transport layer are
deliberately separate (SURVEY.md §5.8): collectives ride ICI inside jitted
graphs; frames and results ride a pluggable ``MiddlewareConnector``.
"""

from opencv_facerecognizer_tpu.runtime.admission import (
    PRIORITY_BULK,
    PRIORITY_INTERACTIVE,
    AdmissionController,
    TokenBucket,
    parse_priority,
)
from opencv_facerecognizer_tpu.runtime.batcher import FrameBatcher
from opencv_facerecognizer_tpu.runtime.connector import (
    FakeConnector,
    JSONLConnector,
    MiddlewareConnector,
)
from opencv_facerecognizer_tpu.runtime.expo import ExpoServer
from opencv_facerecognizer_tpu.runtime.faults import FaultInjector
from opencv_facerecognizer_tpu.runtime.ingest import (
    DecodeWorkerPool,
    IngestConfig,
    IngestPipeline,
    StagingRing,
    resolve_ingest_mode,
)
from opencv_facerecognizer_tpu.runtime.journal import DeadLetterJournal
from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService
from opencv_facerecognizer_tpu.runtime.replication import (
    ReadReplica,
    ReplicaHandle,
    TopicRouter,
    WALTailer,
    WriterLease,
    WriterLeaseHeldError,
)
from opencv_facerecognizer_tpu.runtime.registry import (
    DetectionParity,
    ModelRegistry,
    RegistryStateError,
    RegistrySwapCoordinator,
    registry_params_path,
)
from opencv_facerecognizer_tpu.runtime.rollout import (
    DualScoreParity,
    ReEmbedStage,
    RolloutCoordinator,
    RolloutGateError,
    RolloutStateError,
)
from opencv_facerecognizer_tpu.runtime.resilience import (
    BrownoutPolicy,
    DurabilityDegradedError,
    DurabilityMonitor,
    ResiliencePolicy,
    ServiceSupervisor,
)
from opencv_facerecognizer_tpu.runtime.slo import (
    SLO,
    SLOMonitor,
    default_objectives,
    disk_free_objective,
    link_health_objective,
    loop_liveness_objective,
    registry_parity_objective,
    replication_lag_objective,
    rollout_parity_objective,
)
from opencv_facerecognizer_tpu.runtime.state_store import (
    CheckpointStore,
    EmbedderVersionMismatchError,
    EnrollmentWAL,
    StateLifecycle,
    graceful_shutdown,
)
from opencv_facerecognizer_tpu.runtime.trainer import TheTrainer

__all__ = [
    "AdmissionController",
    "BrownoutPolicy",
    "CheckpointStore",
    "DeadLetterJournal",
    "DecodeWorkerPool",
    "DetectionParity",
    "DualScoreParity",
    "DurabilityDegradedError",
    "DurabilityMonitor",
    "EmbedderVersionMismatchError",
    "EnrollmentWAL",
    "ExpoServer",
    "FakeConnector",
    "FaultInjector",
    "FrameBatcher",
    "IngestConfig",
    "IngestPipeline",
    "JSONLConnector",
    "MiddlewareConnector",
    "ModelRegistry",
    "PRIORITY_BULK",
    "PRIORITY_INTERACTIVE",
    "ReadReplica",
    "ReEmbedStage",
    "RecognizerService",
    "RegistryStateError",
    "RegistrySwapCoordinator",
    "ReplicaHandle",
    "ResiliencePolicy",
    "RolloutCoordinator",
    "RolloutGateError",
    "RolloutStateError",
    "TopicRouter",
    "WALTailer",
    "WriterLease",
    "WriterLeaseHeldError",
    "SLO",
    "SLOMonitor",
    "ServiceSupervisor",
    "StagingRing",
    "resolve_ingest_mode",
    "default_objectives",
    "disk_free_objective",
    "link_health_objective",
    "loop_liveness_objective",
    "registry_params_path",
    "registry_parity_objective",
    "replication_lag_objective",
    "rollout_parity_objective",
    "StateLifecycle",
    "TheTrainer",
    "TokenBucket",
    "graceful_shutdown",
    "parse_priority",
]
