"""Admission control at the connector-receive boundary (overload layer §1).

PR 1 made the serving loop survive its *backend*; this module protects it
from its *clients*. The batcher's only native defense against a traffic
flood is silently dropping the oldest frames — no backpressure signal, no
priority, no per-reason ledger. ``AdmissionController`` sits in front of
the batcher (``RecognizerService._on_frame`` consults it before decoding a
frame) and rejects EXPLICITLY, cheaply, and before any work is spent:

- **token-bucket rate limit** per topic (``rate_limit_fps``; burst =
  ``burst_factor`` seconds of rate): a producer exceeding its rate gets a
  ``rejected`` status with reason ``rate_limit`` instead of a silent drop;
- **bounded intake** (``max_inflight_frames``): when the number of frames
  inside the system (admitted − completed − dropped, read from the
  service's admission ledger) reaches the bound, new low-priority frames
  are rejected with reason ``overload``; interactive frames get a small
  headroom slice (``interactive_reserve``) so bulk traffic cannot starve
  them out of the front door.

Frames carry an optional ``priority`` field — ``"interactive"`` (the
default: a user is waiting on this frame) or ``"bulk"`` (enroll/backfill
traffic that tolerates shedding). ``parse_priority`` maps the wire forms
onto the numeric scale used everywhere downstream: smaller = more
important, ``PRIORITY_INTERACTIVE`` (0) < ``PRIORITY_BULK`` (1).

Rejections are counted per reason (``frames_rejected_<reason>``) on the
shared Metrics surface; they happen BEFORE admission, so they live outside
the admission ledger (``admitted == completed + Σ drops_by_reason``) by
design — a rejected frame never entered the system.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Union

#: numeric priority scale: smaller = more important. The wire forms are
#: the strings below; ints pass through (clamped non-negative).
PRIORITY_INTERACTIVE = 0
PRIORITY_BULK = 1

_PRIORITY_NAMES = {
    "interactive": PRIORITY_INTERACTIVE,
    "bulk": PRIORITY_BULK,
    "enroll": PRIORITY_BULK,
}


def parse_priority(value) -> int:
    """Wire ``priority`` field -> numeric priority. Unknown/missing values
    default to interactive (rejecting a frame because its producer spelled
    the priority wrong would be worse than serving it eagerly)."""
    if value is None:
        return PRIORITY_INTERACTIVE
    if isinstance(value, str):
        return _PRIORITY_NAMES.get(value.lower(), PRIORITY_INTERACTIVE)
    try:
        return max(0, int(value))
    except (TypeError, ValueError):
        return PRIORITY_INTERACTIVE


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second, capacity ``burst``.

    Thread-safe; ``try_acquire`` never blocks (admission must stay cheap —
    it runs on the connector's dispatch thread for every offered frame).
    """

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        now = time.monotonic()
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class AdmissionController:
    """Per-topic rate limits + a bounded intake, consulted per frame.

    ``admit(topic, priority)`` returns ``None`` to admit or a rejection
    reason string (``"rate_limit"`` / ``"overload"`` / ``"staging"`` —
    the last when a wired ingest staging ring has zero free buffers).
    The caller counts and announces the rejection; this object only
    decides.

    ``rate_limit_fps`` is a scalar (applied to every topic seen) or a
    ``{topic: fps}`` dict; ``0``/``None`` disables the rate limit for that
    topic. ``max_inflight_frames`` bounds admitted-but-unfinished frames,
    read through ``inflight_fn`` (the service wires its admission-ledger
    ``frames_in_system``); ``0``/``None`` disables the bound.

    Priority-aware headroom: bulk frames are rejected once in-flight
    reaches ``max_inflight_frames * (1 - interactive_reserve)`` — the
    reserved slice keeps the front door open for interactive frames while
    a bulk flood is being shed.
    """

    def __init__(
        self,
        max_inflight_frames: Optional[int] = None,
        rate_limit_fps: Union[None, float, Dict[str, float]] = None,
        burst_seconds: float = 1.0,
        interactive_reserve: float = 0.25,
        inflight_fn: Optional[Callable[[], float]] = None,
        # Ingest staging backpressure (runtime.ingest.StagingRing
        # .free_slots): when wired and reading 0 free staging buffers,
        # new frames are rejected with reason ``staging`` — the ring is
        # bounded BY DESIGN (exhaustion must shed at the front door,
        # never allocate), so this is the explicit form of that bound.
        staging_free_fn: Optional[Callable[[], int]] = None,
    ):
        self.max_inflight_frames = (None if not max_inflight_frames
                                    else int(max_inflight_frames))
        if rate_limit_fps is None or isinstance(rate_limit_fps, dict):
            self._rate_cfg: Optional[Dict[str, float]] = rate_limit_fps
            self._default_rate: Optional[float] = None
        else:
            self._rate_cfg = None
            self._default_rate = float(rate_limit_fps) or None
        self.burst_seconds = float(burst_seconds)
        self.interactive_reserve = min(0.9, max(0.0, float(interactive_reserve)))
        self.inflight_fn = inflight_fn
        self.staging_free_fn = staging_free_fn
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        # Immutable after __init__: lets the per-frame admit path skip the
        # bucket lock entirely in the common bound-only configuration.
        self._any_rate = bool(self._rate_cfg) or self._default_rate is not None

    def _bucket_for(self, topic: str) -> Optional[TokenBucket]:
        if not self._any_rate:
            return None  # no rate configured anywhere: stay lock-free
        with self._lock:
            bucket = self._buckets.get(topic)
            if bucket is None:
                if self._rate_cfg is not None:
                    rate = self._rate_cfg.get(topic)
                else:
                    rate = self._default_rate
                if not rate or rate <= 0:
                    return None
                bucket = TokenBucket(rate, burst=rate * self.burst_seconds)
                self._buckets[topic] = bucket
            return bucket

    def admit(self, topic: str, priority: int = PRIORITY_INTERACTIVE
              ) -> Optional[str]:
        """None = admitted; otherwise the rejection reason."""
        bucket = self._bucket_for(topic)
        if bucket is not None and not bucket.try_acquire():
            return "rate_limit"
        if self.max_inflight_frames and self.inflight_fn is not None:
            bound = self.max_inflight_frames
            if priority > PRIORITY_INTERACTIVE:
                bound = bound * (1.0 - self.interactive_reserve)
            if self.inflight_fn() >= bound:
                return "overload"
        if self.staging_free_fn is not None and self.staging_free_fn() <= 0:
            return "staging"
        return None
