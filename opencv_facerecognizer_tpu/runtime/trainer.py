"""TheTrainer: end-to-end enrolment (SURVEY.md §2.1 "Trainer", §3.1).

The reference walked a dataset dir, resized to ~70x70, built
Fisherfaces + NearestNeighbor(Euclidean, k=1), k-fold validated, and
pickled the model. This rebuild keeps that flow and adds the CNN backend:

- ``model="fisherfaces" | "eigenfaces" | "lbph"`` — the classic plugins
  (BASELINE.json:7-9 configs), trained and validated exactly like the
  reference but batched on device.
- ``model="lbp_fisherfaces"`` — the round-5 robustness winner (raw LBP
  spatial histograms -> Fisherfaces -> cosine NN; measured rationale at
  the `_build_model` branch and in BASELINE.md).
- ``model="cnn"`` — ArcFace-trained CNN embedder; ``build_gallery()`` then
  yields the ShardedGallery + nets for the serving pipeline.

Checkpoints go through utils.serialization (msgpack, pickle-free).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from opencv_facerecognizer_tpu.models import (
    ChainOperator,
    ExtendedPredictableModel,
    Fisherfaces,
    KernelSVM,
    NearestNeighbor,
    PCA,
    SVM,
    SpatialHistogram,
    TanTriggsPreprocessing,
)
from opencv_facerecognizer_tpu.models.embedder import CNNEmbedding
from opencv_facerecognizer_tpu.ops import lbp as lbp_ops
from opencv_facerecognizer_tpu.ops.distance import (
    ChiSquareDistance,
    CosineDistance,
    EuclideanDistance,
)
from opencv_facerecognizer_tpu.utils import dataset as dataset_utils
from opencv_facerecognizer_tpu.utils import serialization
from opencv_facerecognizer_tpu.utils.validation import KFoldCrossValidation


@dataclass
class TrainerConfig:
    """Flat config (SURVEY.md §5.6): one dataclass, no magic."""

    model: str = "fisherfaces"  # fisherfaces | eigenfaces | lbph | lbp_fisherfaces | cnn
    image_size: Tuple[int, int] = (70, 70)
    kfold: int = 3
    num_components: int = 0  # subspace dims (0 = auto)
    knn_k: int = 1
    tan_triggs: bool = True
    # classifier stage: nn (default, per model family) | svm | kernel_svm —
    # the reference's facerec lineage let any classifier pair with any
    # feature (SURVEY.md §2.1 "Classifiers": NearestNeighbor and SVM).
    classifier: str = "nn"
    svm_kernel: str = "rbf"  # kernel_svm only: rbf | poly | linear
    # cnn backend knobs
    embed_dim: int = 128
    train_steps: int = 200
    cnn_kwargs: Dict[str, Any] = field(default_factory=dict)


class TheTrainer:
    """Train + validate + checkpoint a recognition model from a dataset."""

    def __init__(self, config: Optional[TrainerConfig] = None, **overrides):
        self.config = config or TrainerConfig()
        for key, value in overrides.items():
            if not hasattr(self.config, key):
                raise TypeError(f"unknown TrainerConfig field {key!r}")
            setattr(self.config, key, value)
        self.model: Optional[ExtendedPredictableModel] = None
        self.validation: Optional[KFoldCrossValidation] = None
        #: previous model checkpoints retained on save (rotated to
        #: ``<model_path>.1..N``); 0 = overwrite only (still atomic).
        self.keep_checkpoints = 0

    # ---- model zoo ----

    def _build_model(self, subject_names: List[str]) -> ExtendedPredictableModel:
        cfg = self.config
        if cfg.model == "fisherfaces":
            feature = Fisherfaces(cfg.num_components)
            if cfg.tan_triggs:
                # sigma0=2, sigma1=4 (vs the paper's 1/2): the wider DoG
                # band removes more of the smooth illumination gradient —
                # 10-fold on the Yale-B analog: 0.8117 -> 0.9717
                # (BASELINE.md measured row).
                feature = ChainOperator(
                    TanTriggsPreprocessing(sigma0=2.0, sigma1=4.0), feature
                )
            classifier = NearestNeighbor(EuclideanDistance(), k=cfg.knn_k)
        elif cfg.model == "eigenfaces":
            feature = PCA(cfg.num_components)
            classifier = NearestNeighbor(EuclideanDistance(), k=cfg.knn_k)
        elif cfg.model == "lbph":
            # radius=2: measured k-fold accuracy on the noisy LFW-analog
            # jumps 0.76 -> 0.99 vs the radius=1 default (and stays equal
            # or better on clean data) — the wider ring's bilinear sampling
            # is effectively denoising the codes.
            feature = SpatialHistogram(
                lbp_ops.ExtendedLBP(radius=2, neighbors=8), sz=(8, 8)
            )
            classifier = NearestNeighbor(ChiSquareDistance(), k=cfg.knn_k)
        elif cfg.model == "lbp_fisherfaces":
            # The measured robustness winner on the hard Yale-B analog
            # (scripts/explore_fisherfaces.py, round 5): RAW ExtendedLBP
            # spatial histograms -> Fisherfaces -> cosine NN. Measured
            # surprises driving the design: (a) NO TanTriggs — LBP codes
            # are illumination-invariant already, and the DoG band-pass
            # destroys the micro-texture they encode (with TT: 0.8067;
            # raw: 0.93+); (b) a COARSE 6x6 grid beats 8x8/10x10 — fewer,
            # bigger cells give the LDA a denser histogram basis;
            # (c) radius 3 > 2 > 1. Hard-protocol k-fold: 0.9817 vs
            # 0.8283 for classic Fisherfaces (seed 2), and on UNSEEN
            # generator seeds {22, 42}: 0.9817/0.9950 vs 0.55/0.585 — the
            # classic's 0.83 was a lucky seed; this config's robustness
            # replicates (+0.15 over the pixel-space linear oracle
            # ceiling, BASELINE.md).
            feature = ChainOperator(
                SpatialHistogram(
                    lbp_ops.ExtendedLBP(radius=3, neighbors=8), sz=(6, 6)
                ),
                Fisherfaces(cfg.num_components),
            )
            classifier = NearestNeighbor(CosineDistance(), k=cfg.knn_k)
        elif cfg.model == "cnn":
            serialization.register(CNNEmbedding)
            feature = CNNEmbedding(
                embed_dim=cfg.embed_dim,
                input_size=cfg.image_size,
                train_steps=cfg.train_steps,
                **cfg.cnn_kwargs,
            )
            classifier = NearestNeighbor(CosineDistance(), k=cfg.knn_k)
        else:
            raise ValueError(f"unknown model type {self.config.model!r}")
        if cfg.classifier == "svm":
            classifier = SVM()
        elif cfg.classifier == "kernel_svm":
            classifier = KernelSVM(kernel=cfg.svm_kernel)
        elif cfg.classifier != "nn":
            raise ValueError(
                f"unknown classifier {cfg.classifier!r}; pick nn | svm | kernel_svm"
            )
        return ExtendedPredictableModel(
            feature, classifier, image_size=cfg.image_size, subject_names=subject_names
        )

    # ---- training flows ----

    def train_from_dir(self, dataset_path: str, model_path: Optional[str] = None):
        images, labels, names = dataset_utils.read_images(
            dataset_path, image_size=self.config.image_size
        )
        return self.train(images, labels, names, model_path)

    def train(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        subject_names: List[str],
        model_path: Optional[str] = None,
        validate: bool = True,
    ) -> ExtendedPredictableModel:
        from opencv_facerecognizer_tpu.ops import image as image_ops

        images = np.asarray(images, np.float32)
        if images.shape[1:] != tuple(self.config.image_size):
            images = np.asarray(image_ops.resize(images, self.config.image_size))
        labels = np.asarray(labels, np.int32)
        model = self._build_model(subject_names)
        if validate and self.config.kfold > 1:
            # Validation refits per fold on a scratch model so the final fit
            # below sees the full dataset.
            scratch = self._build_model(subject_names)
            self.validation = KFoldCrossValidation(k=self.config.kfold)
            self.validation.validate(scratch, images, labels)
        model.compute(images, labels)
        self.model = model
        if model_path:
            # Atomic write (tmp+fsync+rename) — a crash mid-save keeps the
            # previous checkpoint; keep_checkpoints>0 also rotates it to
            # model.ckpt.1..N so retrains retain history.
            serialization.save_model(model_path, model,
                                     keep_previous=self.keep_checkpoints)
        return model

    @property
    def mean_accuracy(self) -> float:
        return self.validation.mean_accuracy if self.validation else float("nan")

    # ---- model selection ----

    #: k-fold selection order for ``select_model``: cheap classics first,
    #: the CNN backend last (it trains longest). The round-5 measured
    #: default winner (lbp_fisherfaces) sits where its train cost does.
    SELECT_CANDIDATES = ("eigenfaces", "fisherfaces", "lbph",
                         "lbp_fisherfaces", "cnn")

    def validate_only(self, images: np.ndarray, labels: np.ndarray,
                      subject_names: List[str]) -> float:
        """K-fold this config on a scratch model WITHOUT the full-dataset
        fit (``train`` = this + fit; ``select_model`` scores candidates
        with this so losers never pay the fit — for the CNN backend that
        fit is the whole training run again). Returns the mean accuracy;
        ``self.validation`` holds the folds."""
        from opencv_facerecognizer_tpu.ops import image as image_ops

        images = np.asarray(images, np.float32)
        if images.shape[1:] != tuple(self.config.image_size):
            images = np.asarray(image_ops.resize(images, self.config.image_size))
        labels = np.asarray(labels, np.int32)
        scratch = self._build_model(subject_names)
        self.validation = KFoldCrossValidation(
            k=max(self.config.kfold, 2)).validate(scratch, images, labels)
        return self.mean_accuracy

    # ---- serving handoff (cnn backend) ----

    def build_gallery(self, images: np.ndarray, labels: np.ndarray, mesh,
                      capacity: int = 0, store_dtype=np.float32):
        """Embed the enrolled set with the trained CNN and install it into a
        ShardedGallery for the serving pipeline. A ``store_dtype`` that
        differs from the serving gallery's is fine for the
        ``Recognizer.reload_gallery`` handoff — ``swap_from`` casts the
        staged snapshot to the serving width at install (the default f32
        here lands in the bf16 ocvf-recognize default without the caller
        knowing serving's dtype; round-5 advisor). Pass ``jnp.bfloat16``
        to skip that one extra cast+upload when you do know it."""
        from opencv_facerecognizer_tpu.parallel.gallery import ShardedGallery

        if self.model is None or not isinstance(self.model.feature, CNNEmbedding):
            raise RuntimeError("build_gallery requires a trained cnn model")
        emb = np.array(self.model.feature.extract(np.asarray(images, np.float32)))
        capacity = capacity or max(2 * len(emb), 64)
        gallery = ShardedGallery(capacity=capacity, dim=emb.shape[1], mesh=mesh,
                                 store_dtype=store_dtype)
        gallery.add(emb, np.asarray(labels, np.int32))  # ocvf-lint: boundary=wal-before-mutate -- offline gallery BUILD from training data: the result is persisted wholesale via a checkpoint, not row-by-row enrollment; no WAL exists yet
        return gallery

    # ---- embedder evolution (the live-rollout recipe) ----

    def finetune_embedder(self, images: np.ndarray, labels: np.ndarray, *,
                          steps: int = 100, identities_per_batch: int = 8,
                          samples_per_identity: int = 4,
                          learning_rate: float = 1e-4, margin: float = 0.5,
                          scale: float = 32.0, seed: int = 0):
        """Multibatch metric-learning fine-tune (arxiv 1605.07270) of the
        trained CNN embedder on accumulated enrollments — the model half
        of a live rollout (``runtime.rollout`` owns the serving half).

        The multibatch recipe: every SGD batch samples ``k`` identities x
        ``m`` crops each, so all ``(km)² - km`` ordered pairs inside the
        batch contribute signal per step instead of the uniform sampler's
        mostly-negative pairs — the paper's variance-reduction argument,
        and the reason a few hundred steps over a small accumulated
        enrollment set moves a frozen embedder at all. Training starts
        FROM the serving model's params (a fine-tune, not a re-train) on
        a COPY: ``self.model`` — the embedder still serving the fleet —
        is never touched. Returns the fine-tuned ``CNNEmbedding``; hand
        it to ``make_reembed_fn`` + a ``RolloutCoordinator`` to roll it
        out, and roll BACK by pointing the same machinery at the old
        feature."""
        import jax
        import jax.numpy as jnp
        import optax

        from opencv_facerecognizer_tpu.models.embedder import (
            make_train_step, normalize_faces,
        )

        if self.model is None or not isinstance(self.model.feature,
                                                CNNEmbedding):
            raise RuntimeError("finetune_embedder requires a trained cnn "
                               "model (TheTrainer(model='cnn').train first)")
        old = self.model.feature
        x = np.asarray(normalize_faces(
            np.asarray(images, np.float32), old.input_size))
        y_raw = np.asarray(labels, np.int32)
        classes, y = np.unique(y_raw, return_inverse=True)
        y = y.astype(np.int32)
        # Clone the architecture; seed params from the SERVING model (a
        # deep copy — gradients must not alias the live embedder's trees).
        new_feature = CNNEmbedding(
            embed_dim=old.embed_dim, input_size=old.input_size,
            stem_features=old.stem_features,
            stage_features=old.stage_features,
            stage_blocks=old.stage_blocks, block=old.block,
            space_to_depth=old.space_to_depth, norm=old.norm,
            train_steps=0, seed=old.seed, tta=old.tta)
        params = jax.tree_util.tree_map(
            lambda a: jnp.array(np.asarray(a)), dict(old._params))
        num_classes = max(1, len(classes))
        if params["head"].shape[0] != num_classes:
            params = dict(params, head=jax.random.normal(
                jax.random.PRNGKey(seed + 1),
                (num_classes, old.embed_dim), dtype=jnp.float32))
        optimizer = optax.adam(float(learning_rate))
        opt_state = optimizer.init(params)
        step = make_train_step(old.net, optimizer, float(margin),
                               float(scale), augment=False)
        by_class = [np.flatnonzero(y == c) for c in range(num_classes)]
        k = min(int(identities_per_batch), num_classes)
        m = max(1, int(samples_per_identity))
        rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(seed)
        for i in range(int(steps)):
            # One multibatch: k identities x m samples (with replacement
            # inside an identity when it has fewer crops — small enrolled
            # subjects still contribute full positive-pair counts).
            ids = rng.choice(num_classes, size=k, replace=False)
            idx = np.concatenate([
                rng.choice(by_class[c], size=m,
                           replace=len(by_class[c]) < m) for c in ids])
            key, sub = jax.random.split(key)
            params, opt_state, _loss = step(
                params, opt_state, jnp.asarray(x[idx]), jnp.asarray(y[idx]),
                sub, jnp.float32(min(1.0, i / max(1, int(0.1 * steps)))))
        new_feature.load_params(params)
        return new_feature

    @staticmethod
    def make_reembed_fn(feature, source_images: np.ndarray):
        """The ``RolloutCoordinator.reembed_fn`` for a real fine-tuned
        embedder: re-EXTRACTS each gallery row's stored source crop with
        the new model (an embedding in one space cannot be mapped into
        another without its source — production keeps the enrollment
        crops exactly for this). ``source_images[i]`` must be row ``i``'s
        source crop, in gallery row order (append-only, like the rows).
        Deterministic over its inputs, as the stage's resume contract
        requires."""
        def reembed(rows: np.ndarray, start: int) -> np.ndarray:
            end = start + int(np.asarray(rows).shape[0])
            crops = np.asarray(source_images[start:end], np.float32)
            return np.asarray(feature.extract(crops), np.float32)

        return reembed


def select_model(
    images: np.ndarray,
    labels: np.ndarray,
    subject_names: List[str],
    candidates: Optional[Tuple[str, ...]] = None,
    model_path: Optional[str] = None,
    **config_overrides,
) -> Tuple[TheTrainer, Dict[str, float]]:
    """K-fold every candidate model kind on the SAME data and keep the
    winner: the reference workflow's 'which classic do I use?' question as
    a one-call measured answer (the round-5 LBP-Fisherfaces result showed
    the answer is dataset-dependent and guessing costs double-digit
    accuracy points).

    Each candidate scores through ``TheTrainer.validate_only`` with the
    shared ``config_overrides`` (kfold, image_size, classifier, ...); only
    the winner pays the full-dataset fit. Returns ``(winning trainer —
    trained on the full set and checkpointed to ``model_path`` if given,
    {kind: mean k-fold accuracy})``. Ties break toward the earlier
    candidate (cheaper family).
    """
    from opencv_facerecognizer_tpu.ops import image as image_ops

    candidates = tuple(candidates or TheTrainer.SELECT_CANDIDATES)
    trainers = {kind: TheTrainer(TrainerConfig(model=kind), **config_overrides)
                for kind in candidates}
    # image_size is shared (same overrides) — resize ONCE here; each
    # validate_only's internal resize then no-ops on matching shapes.
    shared_size = tuple(trainers[candidates[0]].config.image_size)
    images = np.asarray(images, np.float32)
    if images.shape[1:] != shared_size:
        images = np.asarray(image_ops.resize(images, shared_size))
    scores: Dict[str, float] = {}
    for kind in candidates:
        scores[kind] = float(trainers[kind].validate_only(
            images, labels, subject_names))
    best = max(candidates, key=lambda k: scores[k])
    winner = trainers[best]
    winner.train(images, labels, subject_names, model_path, validate=False)
    return winner, scores
