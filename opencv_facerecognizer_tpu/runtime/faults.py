"""Fault injection at the serving boundaries (chaos layer).

The serving stack has four places where the outside world can hurt it, and
each one has a distinct observed failure mode on this box (see
``utils/backend_probe.py`` for the round-4 outage evidence):

- **connector receive** — a camera/transport glitch delivers a corrupt
  payload, drops a message, delivers it twice, or **floods** (one delivery
  amplified ``flood_factor``-fold — the runaway-producer shape the
  admission-control layer exists for);
- **batcher put** — a malformed frame (wrong shape, NaN garbage) reaches the
  batch queue and must not poison the whole batch;
- **device dispatch** — the backend fast-fails (``UNAVAILABLE`` at call
  time: the tunnel's mode-1 outage);
- **async readback** — a dispatched batch's device->host transfer never
  completes (``stuck``: ``is_ready`` stays False forever, the tunnel's
  mode-2 hang) or completes late (``slow``: ready only after
  ``slow_readback_s`` — the congested-but-alive shape the overlapped
  readback worker must pipeline behind, not stall on).

``FaultInjector`` installs at all four. Faults are either **scripted**
(``script("dispatch", "unavailable", "unavailable")`` — consumed in order,
exactly once each: the deterministic form chaos tests assert exact counts
against) or **randomized** (``rates={"receive": {"corrupt": 0.01}}`` —
drawn from a seeded ``random.Random`` so a soak run is reproducible from
its logged seed). ``injected`` counts every fault actually fired, keyed
``"boundary:fault"``, so a test can demand metrics match injections exactly.

The injector is a pure test/chaos tool: with no scripted faults and zero
rates every hook is a cheap no-op passthrough, and production code paths
never require one to be installed.
"""

from __future__ import annotations

import errno
import random
import time
from collections import Counter, deque
from typing import Any, Dict, List, Optional

import numpy as np

#: boundary name -> fault kinds it understands.
BOUNDARIES: Dict[str, tuple] = {
    "receive": ("drop", "duplicate", "corrupt", "flood"),
    "put": ("corrupt",),
    "dispatch": ("unavailable",),
    "readback": ("stuck", "slow"),
    # Stage-1 cascade gate (runtime.recognizer._cascade_gate): a
    # pathological first stage that scores EVERY frame face-free — the
    # worst-case operating point (a corrupted gate checkpoint, a camera
    # whose exposure collapsed). The service must degrade to publishing
    # empty results with exact ``completed_empty`` ledger settlement —
    # zero matches, zero wedges, zero leaked frames.
    "cascade": ("reject_all",),
    # Compressed-frame intake (runtime.ingest.DecodeWorkerPool): "slow" =
    # a congested decoder (the worker sleeps slow_decode_s before
    # decoding — the pool must absorb it off the hot thread); "corrupt" =
    # the payload is replaced with bytes no JPEG decoder accepts, so the
    # frame must dead-letter with reason decode_error and exact ledger
    # settlement.
    "decode": ("slow", "corrupt"),
    # Durability boundaries (state lifecycle layer — runtime.state_store):
    # "torn" = the process dies mid-write leaving a partial record/file on
    # disk; "crash" = it dies before the write becomes visible (before the
    # WAL bytes land / before the checkpoint tmp renames); checkpoint
    # "late" = the checkpoint file lands but the process dies before the
    # WAL truncation that follows — the window where replay must dedup
    # against the checkpoint's recorded WAL sequence.
    "wal": ("torn", "crash"),
    "checkpoint": ("torn", "crash", "late"),
    # Embedder-rollout boundaries (runtime.rollout): "stage" faults hit
    # the background re-embed's progress append ("torn" = a partial chunk
    # line lands then the process dies — resume must re-stage that chunk;
    # "crash" = death before any byte); "cutover" faults hit the atomic
    # swap ("crash_before_record" = the stage delta is durable but the
    # fence record never landed — recovery stays on the old version;
    # "crash_after_record" = the fence is durable but the in-memory swap
    # and its checkpoint never ran — recovery must COMPLETE the cutover
    # from the staged shard set).
    "stage": ("torn", "crash"),
    "cutover": ("crash_before_record", "crash_after_record"),
    # Storage-fault boundary (ISSUE 15) — the disk STAYS broken, unlike
    # the wal/checkpoint kill-point faults above which simulate process
    # death. One boundary covers every durable path (WAL append/fsync,
    # checkpoint tmp+rename+directory fsync, dead-letter/span journals,
    # rollout stage appends, replica tailer reads, flight dumps):
    # "enospc" = the write raises OSError(ENOSPC) — a full disk;
    # "eio" = the write raises OSError(EIO) — dying media;
    # "slow_fsync" = the operation completes but only after
    # ``slow_fsync_s`` (a congested/remounting device — callers must
    # bound what serves behind it, not wedge);
    # "read_error" = a READ crossing raises OSError(EIO) (tailer polls,
    # checkpoint loads). Write crossings draw only the three write
    # kinds and read crossings only "read_error", so one scripted queue
    # can interleave both without a read consuming a write fault.
    "storage": ("enospc", "eio", "slow_fsync", "read_error"),
    # Transport boundary (ISSUE 16) — the network the PR 10/11 fleet lives
    # on.  Two families share the boundary:
    # STATEFUL link conditions, toggled per-(peer, direction) via
    # ``set_partition`` / ``set_half_open`` / ``set_slow_link`` and
    # cleared by the ``heal_*`` siblings — they apply to EVERY crossing
    # of that link while set:
    #   "partition" = the link is cut; messages vanish (the caller sees
    #     the same nothing a real partition delivers);
    #   "half_open" = the peer's TCP stack still ACKs but the application
    #     never sees the bytes — indistinguishable from partition at the
    #     message level, detectable only by heartbeat deadline;
    #   "slow"      = every crossing sleeps latency + uniform jitter (a
    #     congested or long-haul link — blocking senders feel it).
    # PER-CROSSING faults, scripted/rate-drawn like every other boundary:
    #   "drop" = this one message vanishes; "duplicate" = delivered
    #   twice; "reorder" = held back and delivered AFTER the next
    #   message that crosses the same link (out-of-order delivery the
    #   idempotent-routing layer must absorb).
    "transport": ("partition", "half_open", "slow",
                  "drop", "duplicate", "reorder"),
}

#: storage-boundary fault kinds applicable per crossing direction (the
#: filtered draw ``on_storage``/``on_storage_read`` use).
STORAGE_WRITE_KINDS = ("enospc", "eio", "slow_fsync")
STORAGE_READ_KINDS = ("read_error",)

#: transport-boundary kinds eligible for the per-crossing scripted/rate
#: draw (the stateful link conditions are toggled, never drawn — a
#: scripted "partition" would be a one-message blackhole masquerading as
#: a link cut, so ``script`` refuses the stateful kinds for transport).
TRANSPORT_DRAW_KINDS = ("drop", "duplicate", "reorder")

#: valid directions of a transport crossing, from the injecting side's
#: point of view: "send" = toward the peer, "recv" = from the peer.
TRANSPORT_DIRECTIONS = ("send", "recv")


class InjectedCrashError(RuntimeError):
    """Simulated process death at a durability boundary (``wal`` /
    ``checkpoint`` faults). The recovery chaos scenario raises this where
    a real kill -9 would land, then "restarts" by rebuilding the state
    lifecycle from disk — the caller must treat it as fatal, never catch
    and continue (a real SIGKILL offers no such choice)."""

    def __init__(self, msg: str = "injected crash at a durability boundary"):
        super().__init__(msg)


class InjectedUnavailableError(RuntimeError):
    """Simulates the backend's fast-fail outage mode. The message carries
    the literal ``UNAVAILABLE`` token so ``resilience.is_transient_error``
    classifies it exactly like the real PJRT error string."""

    def __init__(self, msg: str = "UNAVAILABLE: injected dispatch fault"):
        super().__init__(msg)


class StuckReadback:
    """Wraps a dispatched device array whose transfer "never" completes —
    the hang-mode outage at the readback boundary. ``is_ready()`` is False
    forever; materializing it raises instead of blocking, so an accounting
    bug that tries to read a stuck batch fails loudly in tests rather than
    wedging the suite."""

    def __init__(self, wrapped: Any):
        self._wrapped = wrapped

    def is_ready(self) -> bool:
        return False

    def copy_to_host_async(self) -> None:
        pass

    def block_until_ready(self):
        raise RuntimeError("blocked forever on an injected stuck readback")

    def __array__(self, dtype=None):
        raise RuntimeError("materialized an injected stuck readback — the "
                           "drain loop must dead-letter it at the deadline")


class SlowReadback:
    """Wraps a dispatched device array whose transfer completes only after
    ``delay_s`` — the degraded-but-alive readback shape (a congested
    tunnel, not an outage). ``is_ready`` turns True at the deadline;
    ``block_until_ready`` sleeps out the remainder (so the event-driven
    readback worker waits exactly the injected delay); materializing
    blocks the same way. Lets tests pin pipelining behavior — batches
    dispatched behind a slow head must still overlap — with deterministic
    timing and no real device. (``runtime.fakes.FakePacked`` is the
    sibling shape for whole-pipeline fakes; this one wraps a REAL
    dispatched array, so the chaos layer stays free of test-fake
    imports.)"""

    def __init__(self, wrapped: Any, delay_s: float):
        self._wrapped = wrapped
        self._ready_at = time.monotonic() + float(delay_s)

    def is_ready(self) -> bool:
        return time.monotonic() >= self._ready_at

    def copy_to_host_async(self) -> None:
        pass

    def block_until_ready(self):
        delay = self._ready_at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        return self._wrapped

    def __array__(self, dtype=None):
        self.block_until_ready()
        return np.asarray(self._wrapped, dtype=dtype)


class FaultInjector:
    """Deterministic, seedable fault injection for the serving loop.

    ``script(boundary, *faults)`` queues faults consumed one per boundary
    crossing (exact-count chaos tests); ``rates`` injects probabilistically
    from the seeded RNG (soak tests). ``disarm()`` turns every hook into a
    passthrough — the soak harness uses it to prove liveness with clean
    traffic after the chaos window.
    """

    def __init__(self, seed: int = 0,
                 rates: Optional[Dict[str, Dict[str, float]]] = None,
                 slow_readback_s: float = 0.05,
                 flood_factor: int = 8,
                 slow_decode_s: float = 0.05,
                 slow_fsync_s: float = 0.05):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        #: injected transfer latency of a ``readback: slow`` fault.
        self.slow_readback_s = float(slow_readback_s)
        #: injected decoder stall of a ``decode: slow`` fault (the worker
        #: sleeps this long before decoding the payload).
        self.slow_decode_s = float(slow_decode_s)
        #: injected stall of a ``storage: slow_fsync`` fault — the durable
        #: operation completes, but only after this long (the 2-second
        #: fsync shape: a congested or error-retrying block device).
        self.slow_fsync_s = float(slow_fsync_s)
        #: amplification of a ``receive: flood`` fault — one delivery
        #: becomes this many (a runaway producer / retry storm in
        #: miniature; the admission layer must shed the excess with
        #: explicit reasons instead of wedging).
        self.flood_factor = max(2, int(flood_factor))
        self.rates = rates or {}
        for boundary, fault_rates in self.rates.items():
            valid = (TRANSPORT_DRAW_KINDS if boundary == "transport"
                     else BOUNDARIES.get(boundary, ()))
            unknown = set(fault_rates) - set(valid)
            if boundary not in BOUNDARIES or unknown:
                raise ValueError(f"unknown fault(s) for {boundary!r}: "
                                 f"{sorted(unknown) or boundary}")
        self._scripted: Dict[str, deque] = {b: deque() for b in BOUNDARIES}
        self.injected: Counter = Counter()
        self.enabled = True
        # ---- transport link state (ISSUE 16) ----
        # Keys are (peer, direction) with direction in
        # TRANSPORT_DIRECTIONS; ``set_*(peer, direction="both")`` expands
        # to both keys.  ``_slow_links`` maps the key to
        # (latency_s, jitter_s); ``_holdback`` parks a reordered message
        # until the next crossing of the same link flushes it behind the
        # newer delivery.
        self._partitioned: set = set()
        self._half_open: set = set()
        self._slow_links: Dict[tuple, tuple] = {}
        self._holdback: Dict[tuple, list] = {}

    def script(self, boundary: str, *faults: str) -> None:
        """Queue deterministic faults at ``boundary``, consumed in order —
        one per crossing, exactly once each."""
        kinds = BOUNDARIES.get(boundary)
        if kinds is None:
            raise ValueError(f"unknown boundary {boundary!r}")
        if boundary == "transport":
            kinds = TRANSPORT_DRAW_KINDS  # stateful kinds are toggled
        for fault in faults:
            if fault not in kinds:
                raise ValueError(f"boundary {boundary!r} has no fault "
                                 f"{fault!r} (valid: {kinds})")
            self._scripted[boundary].append(fault)

    def disarm(self) -> None:
        """Every hook becomes a passthrough (scripted queues included)."""
        self.enabled = False

    def arm(self) -> None:
        self.enabled = True

    def _draw(self, boundary: str) -> Optional[str]:
        """Next fault to fire at this crossing, or None. Scripted faults
        take priority (and are consumed even when a rate is also set).
        The unfiltered form: every kind the boundary knows is eligible
        (``script`` already validated them), so this is exactly
        ``_draw_filtered`` over the boundary's full kind tuple — one
        implementation, never two to drift apart."""
        return self._draw_filtered(boundary, BOUNDARIES[boundary])

    def _draw_filtered(self, boundary: str, allowed: tuple) -> Optional[str]:
        """Like ``_draw`` but the crossing accepts only ``allowed`` kinds:
        a scripted fault at the queue head is consumed only when it
        matches (a scripted ``read_error`` waits for the next READ
        crossing instead of being burned by a write), and rate draws skip
        non-matching kinds."""
        if not self.enabled:
            return None
        queue = self._scripted[boundary]
        fault = None
        if queue and queue[0] in allowed:
            fault = queue.popleft()
        elif not queue:
            for kind, rate in self.rates.get(boundary, {}).items():
                if kind in allowed and rate > 0 and self._rng.random() < rate:
                    fault = kind
                    break
        if fault is not None:
            self.injected[f"{boundary}:{fault}"] += 1
        return fault

    # ---- boundary hooks ----

    def on_receive(self, message: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Connector-receive boundary: returns the message list to actually
        deliver — ``[]`` (dropped), ``[m, m]`` (duplicated), or a corrupted
        payload whose frame can no longer decode."""
        fault = self._draw("receive")
        if fault is None:
            return [message]
        if fault == "drop":
            return []
        if fault == "duplicate":
            return [message, message]
        if fault == "flood":
            return [message] * self.flood_factor
        # corrupt: force the decode_frame path onto a payload whose byte
        # count cannot match its declared dtype (5 bytes into float32) —
        # the service must count it malformed and keep serving.
        corrupted = dict(message)
        corrupted["__frame__"] = "corrupt!"
        corrupted.setdefault("shape", [1])
        corrupted.setdefault("dtype", "float32")
        return [corrupted]

    def on_put(self, frame: np.ndarray) -> np.ndarray:
        """Batcher-put boundary: a poisoned frame (wrong shape, NaN fill)
        that shape/dtype validation must drop before it joins a batch."""
        if self._draw("put") is None:
            return frame
        return np.full((1, 1), np.nan, np.float32)

    def on_dispatch(self) -> None:
        """Device-dispatch boundary: raises the fast-fail outage."""
        if self._draw("dispatch") is not None:
            raise InjectedUnavailableError()

    def on_readback(self, device_array: Any) -> Any:
        """Async-readback boundary: wraps the dispatched output in a
        never-ready proxy (``stuck`` — the hang-mode outage) or a
        delayed-ready one (``slow`` — ``slow_readback_s`` of injected
        transfer latency)."""
        fault = self._draw("readback")
        if fault is None:
            return device_array
        if fault == "slow":
            return SlowReadback(device_array, self.slow_readback_s)
        return StuckReadback(device_array)

    def on_cascade(self, keep: np.ndarray) -> np.ndarray:
        """Stage-1 cascade boundary: ``reject_all`` replaces the gate's
        keep mask with all-False — every frame in the batch scores
        face-free, so the whole batch must exit early as
        ``completed_empty`` with exact ledger settlement."""
        if self._draw("cascade") is None:
            return keep
        return np.zeros_like(keep, dtype=bool)

    def on_decode(self, payload: bytes) -> bytes:
        """Compressed-intake decode boundary (runs on a decode worker,
        never the hot thread): ``slow`` sleeps out the injected decoder
        stall then passes the payload through; ``corrupt`` returns a
        truncated pseudo-JPEG no decoder accepts (SOI marker then
        garbage), so the downstream decode raises exactly like real
        corrupt camera bytes."""
        fault = self._draw("decode")
        if fault is None:
            return payload
        if fault == "slow":
            time.sleep(self.slow_decode_s)
            return payload
        return b"\xff\xd8\xff" + b"\x00" * 5  # corrupt: truncated garbage

    def on_wal_append(self) -> Optional[str]:
        """Enrollment-WAL append boundary: returns the fault kind the
        writer must enact (``"torn"``: persist a partial line then die;
        ``"crash"``: die before any byte lands) or None. The WRITER
        performs the torn write and raises ``InjectedCrashError`` — the
        injector only draws, so the torn bytes are exactly the writer's
        real encoding, not a fake."""
        return self._draw("wal")

    def on_checkpoint(self) -> Optional[str]:
        """Checkpoint-save boundary: ``"torn"`` (die mid-tmp-write),
        ``"crash"`` (die after the tmp is complete but before the rename
        installs it), ``"late"`` (the checkpoint lands; die before the WAL
        truncation that follows), or None."""
        return self._draw("checkpoint")

    def on_stage(self) -> Optional[str]:
        """Rollout stage-append boundary (the background re-embed's
        progress journal): the WRITER enacts the fault — ``"torn"``
        persists a partial chunk line then raises, ``"crash"`` raises
        before any byte lands — so the torn bytes are its real encoding."""
        return self._draw("stage")

    def on_cutover(self) -> Optional[str]:
        """Atomic-cutover boundary (``StateLifecycle.perform_cutover``):
        returns which side of the fence record the simulated kill lands
        on, or None."""
        return self._draw("cutover")

    def on_storage(self, op: str = "write") -> None:
        """Durable-WRITE storage boundary (ISSUE 15): called by every
        durable writer (WAL/journal appends, checkpoint installs, rollout
        stage appends, flight dumps) immediately before the real syscall,
        INSIDE the caller's existing OSError handling — the injected
        errno therefore exercises the exact production error path.
        ``enospc``/``eio`` raise the corresponding ``OSError``;
        ``slow_fsync`` sleeps ``slow_fsync_s`` then lets the write
        proceed (the disk is slow, not broken). ``op`` only labels the
        raised error for forensics; the draw is op-agnostic."""
        fault = self._draw_filtered("storage", STORAGE_WRITE_KINDS)
        if fault is None:
            return
        if fault == "slow_fsync":
            time.sleep(self.slow_fsync_s)
            return
        code = errno.ENOSPC if fault == "enospc" else errno.EIO
        raise OSError(code, f"injected storage fault ({fault}) at {op}")

    def on_storage_read(self, op: str = "read") -> None:
        """Durable-READ storage boundary: replica tailer polls, checkpoint
        recovery reads. ``read_error`` raises ``OSError(EIO)`` — a read
        failure proves nothing about the bytes, and every consumer must
        already treat it as transient (retry/fall back), never as
        corruption."""
        if self._draw_filtered("storage", STORAGE_READ_KINDS) is not None:
            raise OSError(errno.EIO,
                          f"injected storage fault (read_error) at {op}")

    # ---- transport boundary (ISSUE 16) ----

    @staticmethod
    def _link_keys(peer: str, direction: str) -> List[tuple]:
        if direction == "both":
            return [(peer, d) for d in TRANSPORT_DIRECTIONS]
        if direction not in TRANSPORT_DIRECTIONS:
            raise ValueError(f"unknown transport direction {direction!r} "
                             f"(valid: {TRANSPORT_DIRECTIONS + ('both',)})")
        return [(peer, direction)]

    def set_partition(self, peer: str, direction: str = "both") -> None:
        """Cut the link to ``peer``: every crossing in ``direction``
        vanishes until ``heal_partition``."""
        self._partitioned.update(self._link_keys(peer, direction))

    def heal_partition(self, peer: str, direction: str = "both") -> None:
        self._partitioned.difference_update(self._link_keys(peer, direction))

    def set_half_open(self, peer: str, direction: str = "send") -> None:
        """Half-open link: crossings in ``direction`` are silently
        blackholed — no error, no EOF, exactly the shape a dead peer
        behind a still-ACKing TCP stack presents.  Only a heartbeat
        deadline can detect it."""
        self._half_open.update(self._link_keys(peer, direction))

    def heal_half_open(self, peer: str, direction: str = "both") -> None:
        self._half_open.difference_update(self._link_keys(peer, direction))

    def set_slow_link(self, peer: str, latency_s: float,
                      jitter_s: float = 0.0,
                      direction: str = "both") -> None:
        """Every crossing of the link sleeps ``latency_s`` plus a uniform
        draw from ``[0, jitter_s]`` before delivering."""
        for key in self._link_keys(peer, direction):
            self._slow_links[key] = (float(latency_s), float(jitter_s))

    def heal_slow_link(self, peer: str, direction: str = "both") -> None:
        for key in self._link_keys(peer, direction):
            self._slow_links.pop(key, None)

    def heal_all_links(self) -> None:
        """Clear every stateful link condition (scripted/rate transport
        faults are untouched — use ``disarm`` for a full passthrough).
        Held-back reordered messages stay parked until traffic flushes
        them; a drained link's remnant is dropped by ``flush_holdback``."""
        self._partitioned.clear()
        self._half_open.clear()
        self._slow_links.clear()

    def flush_holdback(self, peer: str,
                       direction: str = "both") -> List[Dict[str, Any]]:
        """Return (and forget) any reorder-held messages for the link —
        callers that tear a link down use this so an accounting test can
        settle exactly."""
        flushed: List[Dict[str, Any]] = []
        for key in self._link_keys(peer, direction):
            flushed.extend(self._holdback.pop(key, ()))
        return flushed

    def on_transport(self, peer: str, direction: str,
                     message: Dict[str, Any],
                     sink=None) -> List[Dict[str, Any]]:
        """Transport boundary: one send/recv crossing of the link to
        ``peer``.  Returns the messages to actually deliver, in order —
        ``[]`` (partitioned / half-open / dropped / held for reorder),
        ``[m, m]`` (duplicated), or the newer message followed by a
        previously held one (the reorder materializing).  ``sink``, when
        given, is called with each fault kind enacted — the caller's
        bridge to its own ``transport_fault_<kind>`` counters."""
        if not self.enabled:
            return [message]
        key = (peer, direction)

        def fire(kind: str) -> None:
            self.injected[f"transport:{kind}"] += 1
            if sink is not None:
                sink(kind)

        # Stateful link conditions first: a cut or half-open link eats
        # the message before any per-crossing draw (and leaves holdback
        # parked — nothing crosses a dead link, not even stragglers).
        if key in self._partitioned:
            fire("partition")
            return []
        if key in self._half_open:
            fire("half_open")
            return []
        slow = self._slow_links.get(key)
        if slow is not None:
            latency_s, jitter_s = slow
            delay = latency_s + (self._rng.random() * jitter_s
                                 if jitter_s > 0 else 0.0)
            if delay > 0:
                time.sleep(delay)
            fire("slow")
        fault = self._draw_filtered("transport", TRANSPORT_DRAW_KINDS)
        if fault is not None and sink is not None:
            sink(fault)  # _draw_filtered already counted into .injected
        if fault == "drop":
            return []
        if fault == "reorder":
            self._holdback.setdefault(key, []).append(message)
            return []
        held = self._holdback.pop(key, None)
        if fault == "duplicate":
            out = [message, message]
        else:
            out = [message]
        if held:
            out.extend(held)  # newer-first: the held message lands late
        return out

    def summary(self) -> Dict[str, int]:
        return dict(self.injected)
