"""Versioned model registry: every serving-model swap rides the rollout
fence (ISSUE 18; ROADMAP item 1).

PR 11 made EMBEDDER evolution crash-safe, but the detector and the
cascade stage-1 gate were promoted to their measured-fast configs by
editing constructor defaults — no version fence, no parity window, no
atomic cutover, no rollback. Cascade-style detectors are exactly the
models that get retrained and re-tuned in production (PAPERS.md
1508.01292, 1803.10103), so an unfenced detector swap is the most likely
way the fleet silently changes behavior. This module generalizes
``runtime.rollout`` from "embedder version" to a registry of every
served model role:

- **ModelRegistry** — a durable, checksummed manifest
  (``state_dir/registry.json``, atomic tmp+rename+dirsync with an
  embedded sha256 over the canonical manifest bytes) naming the served
  ``(role, version, config, params_path, params_sha256)`` for each of
  ``MODEL_ROLES``. Versions are monotonic per role (a rollback is a NEW
  version whose params equal a prior one's — numbers are never reused,
  so every WAL fence stays unambiguous). The embedder's entry mirrors
  the gallery's ``embedder_version`` (the gallery stays that role's
  source of truth; ``StateLifecycle.perform_cutover`` keeps the mirror
  current).
- **WAL fence + atomic cutover** — a detector/cascade swap goes through
  ``StateLifecycle.perform_registry_cutover``: under the enroll lock,
  candidate params already durable, a strict-fsync ``registry_cutover``
  WAL fence record lands (write-ahead, stamped with the full post-swap
  registry), then the manifest installs atomically and the in-memory
  params publish in one epoch-fenced step (model params are jit
  ARGUMENTS in ``parallel.pipeline`` — a same-architecture swap needs
  ZERO recompiles). No re-embed: gallery rows are untouched, which is
  why these swaps are cheap enough to gate purely on live parity.
- **DetectionParity** — the detector-role parity window: old and new
  detector run side by side on live sampled frames (off the publish
  path, scored on demand); agreement = box-overlap VERDICT match (both
  say face / both say no-face, and when both fire the best boxes
  overlap at IoU >= ``iou_threshold``). Same sliding-window contract as
  ``rollout.DualScoreParity`` (threshold + min samples; no data is not
  a breach), exported as ``registry_parity_*`` gauges with
  ``runtime.slo.registry_parity_objective`` feeding /health.
- **FaceGate retrain rides the swap** — ``evaluate_gate`` scores
  stage-1 recall against THE DETECTOR'S OWN verdicts, so a detector
  swap invalidates the gate's operating point. ``RegistrySwapCoordinator``
  runs ``gate_retrain_fn`` (trained against the CANDIDATE detector's
  verdicts) before the fence, and the (detector, gate) pair cuts over
  atomically — the fleet never serves a new detector under an old
  gate's operating point.
- **Recovery completes or cleanly abandons** — a ``registry_cutover``
  fence past the recovered checkpoint with the manifest still at the
  old version is the crash window between fence and manifest install.
  When the staged candidate params verify (sha256), recovery COMPLETES
  the swap (manifest -> to_version, counted
  ``registry_swaps_completed_recovery``); damaged/missing params
  ABANDON it cleanly (a ``registry_abort`` tombstone marks the fence
  dead, the role stays at from_version, counted loudly) — in every
  interleaving the fleet serves exactly one fenced version per role,
  never a mix.
- **Caches key on the full registry stamp** — the PR 17 tracker stamps
  cache entries with the registry stamp (any role's cutover changes it
  -> lazy flush), and the swap coordinator flushes eagerly
  (``flush_fn``) so no cached identity or cascade verdict from the old
  model outlives its cutover. The jit compile caches are keyed by
  SHAPE with params as call arguments, so a same-architecture swap
  keeps them warm — the bench's zero-recompile-watchdog-trips
  invariant.
- **Auto-rollback with a flight dump** — after cutover the parity
  window keeps scoring (phase ``watch``); a regression below the gate
  inside the watch window rolls back automatically at the next
  monotonic version, forcing a flight-recorder dump
  (``registry_auto_rollback``) with the full swap status attached.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from opencv_facerecognizer_tpu.runtime.rollout import RolloutGateError
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.serialization import (
    atomic_write_bytes,
)
from opencv_facerecognizer_tpu.utils.tracing import LIFECYCLE_TOPIC

__all__ = [
    "DetectionParity",
    "MODEL_ROLES",
    "ModelRegistry",
    "RegistryStateError",
    "RegistrySwapCoordinator",
    "box_iou",
    "registry_params_path",
]

logger = logging.getLogger(__name__)

#: every model role the registry fences. The embedder entry mirrors the
#: gallery's ``embedder_version`` (PR 11's machinery stays that role's
#: swap path — it needs the staged re-embed); detector and cascade swap
#: through ``RegistrySwapCoordinator`` (no re-embed needed).
MODEL_ROLES = ("embedder", "detector", "cascade")

#: manifest filename inside ``state_dir``.
MANIFEST_NAME = "registry.json"

#: state-dir subdirectory holding staged candidate params.
PARAMS_DIR = "registry"

#: registry swap phase gauge codes (``registry_phase`` on /prom).
PHASE_CODES = {"idle": 0, "parity": 1, "ready": 2, "cutover": 3,
               "watch": 4, "done": 5, "rolled_back": 6}


class RegistryStateError(RuntimeError):
    """Durable registry state (the manifest or staged candidate params)
    is torn, unreadable, or inconsistent where correctness requires it.
    Fails CLOSED: serving an unfenced or ambiguous model version is the
    outcome this subsystem exists to prevent."""


def registry_params_path(state_dir: str, role: str, version: int) -> str:
    """The conventional durable location for a candidate's params blob:
    ``state_dir/registry/<role>-v<version>.params`` (msgpack for the real
    models — ``FaceGate.save``/``CNNFaceDetector.save`` write here)."""
    return os.path.join(str(state_dir), PARAMS_DIR,
                        f"{role}-v{int(version)}.params")


def _file_sha256(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            digest.update(block)
    return digest.hexdigest()


def _canonical(roles: Dict[str, Any]) -> bytes:
    return json.dumps(roles, sort_keys=True).encode("utf-8")


class ModelRegistry:
    """The durable, checksummed manifest of served model versions.

    File shape (``state_dir/registry.json``)::

        {"format_version": 1,
         "roles": {"embedder": {"version": 1, "config": {...},
                                "params_path": null, "params_sha256": null},
                   "detector": {...}, "cascade": {...}},
         "updated_ts": ..., "checksum": sha256(canonical roles json)}

    Written atomically (tmp + fsync + rename + dirsync); the embedded
    checksum makes a torn or bit-flipped manifest DETECTABLE — the
    offline verifier reports it rc 3 (unreadable) / rc 2 (corrupt), and
    a writer refuses to start over one rather than guess versions.
    ``readonly=True`` (read replicas, the verifier) never writes."""

    def __init__(self, state_dir: str, metrics=None, readonly: bool = False):
        self.state_dir = str(state_dir)
        self.path = os.path.join(self.state_dir, MANIFEST_NAME)
        self.metrics = metrics
        self.readonly = bool(readonly)
        self._lock = threading.Lock()
        self._roles: Dict[str, Dict[str, Any]] = {
            role: {"version": 1, "config": None, "params_path": None,
                   "params_sha256": None}
            for role in MODEL_ROLES
        }
        if os.path.exists(self.path):
            self._roles = self.read_manifest(self.path)["roles"]
        elif not self.readonly:
            os.makedirs(self.state_dir, exist_ok=True)
            self._save_locked()
        self._publish_gauges()

    # ---- durable manifest plumbing ----

    @staticmethod
    def read_manifest(path: str) -> Dict[str, Any]:
        """Parse + validate one manifest file. Raises
        ``RegistryStateError`` with ``.reason`` = ``"unreadable"`` (the
        read/parse itself failed — proves nothing about intent, rc 3 in
        the verifier) or ``"corrupt"`` (checksum/shape mismatch — the
        bytes are damaged, rc 2)."""
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.loads(fh.read())
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as exc:
            err = RegistryStateError(
                f"registry manifest {path} unreadable: {exc!r}")
            err.reason = "unreadable"
            raise err from exc
        try:
            roles = doc["roles"]
            checksum = doc["checksum"]
            if not isinstance(roles, dict):
                raise TypeError("roles is not an object")
        except (KeyError, TypeError) as exc:
            err = RegistryStateError(
                f"registry manifest {path} malformed: {exc!r}")
            err.reason = "corrupt"
            raise err from exc
        if hashlib.sha256(_canonical(roles)).hexdigest() != checksum:
            err = RegistryStateError(
                f"registry manifest {path} checksum mismatch (torn or "
                f"bit-flipped write)")
            err.reason = "corrupt"
            raise err
        out: Dict[str, Dict[str, Any]] = {}
        for role in MODEL_ROLES:
            entry = roles.get(role)
            if not isinstance(entry, dict) or "version" not in entry:
                err = RegistryStateError(
                    f"registry manifest {path} missing role {role!r}")
                err.reason = "corrupt"
                raise err
            out[role] = {
                "version": int(entry["version"]),
                "config": entry.get("config"),
                "params_path": entry.get("params_path"),
                "params_sha256": entry.get("params_sha256"),
            }
            if "retired" in entry:
                out[role]["retired"] = int(entry["retired"])
        return {"roles": out, "doc": doc}

    def _save_locked(self) -> None:
        if self.readonly:
            raise RegistryStateError(
                "read-only ModelRegistry cannot write the manifest")
        doc = {
            "format_version": 1,
            "roles": self._roles,
            "updated_ts": time.time(),
            "checksum": hashlib.sha256(_canonical(self._roles)).hexdigest(),
        }
        atomic_write_bytes(self.path,
                           json.dumps(doc, sort_keys=True).encode("utf-8"))

    def reload(self) -> None:
        """Re-read the manifest from disk (read replicas re-anchor their
        registry view through this after a fence)."""
        roles = self.read_manifest(self.path)["roles"]
        with self._lock:
            self._roles = roles
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        if self.metrics is None:
            return
        for role, entry in self._roles.items():
            self.metrics.set_gauge(mn.MODEL_VERSION_PREFIX + role,
                                   int(entry["version"]))

    # ---- reads ----

    def version(self, role: str) -> int:
        with self._lock:
            return int(self._roles[role]["version"])

    def describe(self, role: str) -> Dict[str, Any]:
        with self._lock:
            return dict(self._roles[role])

    def stamp(self) -> Dict[str, int]:
        """``{role: version}`` for every role — the full registry stamp
        checkpoint headers, WAL rows, published results and the tracker's
        cache entries carry."""
        with self._lock:
            return {role: int(entry["version"])
                    for role, entry in self._roles.items()}

    def stamp_key(self) -> Tuple[Tuple[str, int], ...]:
        """Hashable form of ``stamp()`` (cache keys compare by opaque
        equality)."""
        return tuple(sorted(self.stamp().items()))

    def status(self) -> Dict[str, Any]:
        """JSON-able snapshot for ``GET /registry``."""
        with self._lock:
            return {"manifest": self.path,
                    "roles": {r: dict(e) for r, e in self._roles.items()}}

    # ---- writes ----

    def install(self, role: str, version: int, config: Any = None,
                params_path: Optional[str] = None,
                params_sha256: Optional[str] = None) -> None:
        """Durably advance one role to ``version`` (atomic manifest
        rewrite). Monotonic per role: versions never move backward or
        repeat — a rollback is a NEW version (the WAL fence stays
        unambiguous)."""
        with self._lock:
            entry = self._roles[role]
            floor = max(int(entry["version"]),
                        int(entry.get("retired", 0)))
            if int(version) <= floor:
                raise ValueError(
                    f"registry versions are monotonic: {role} is at "
                    f"v{entry['version']} (retired through "
                    f"v{entry.get('retired', 0)}), refusing install of "
                    f"v{version} (a rollback is a NEW version whose "
                    f"params equal a prior one's; abandoned numbers are "
                    f"never reused)")
            new_entry = {
                "version": int(version), "config": config,
                "params_path": params_path, "params_sha256": params_sha256,
            }
            if "retired" in entry:
                new_entry["retired"] = int(entry["retired"])
            self._roles[role] = new_entry
            self._save_locked()
        self._publish_gauges()

    def retire(self, role: str, version: int) -> None:
        """Mark ``version`` as burned for ``role`` WITHOUT serving it —
        the recovery path for an ABANDONED fenced swap. The served
        version stays put; future installs must exceed the retired
        number, so a WAL fence sequence never becomes ambiguous."""
        with self._lock:
            entry = self._roles[role]
            if int(version) <= int(entry.get("retired", 0)):
                return
            entry["retired"] = int(version)
            if not self.readonly:
                self._save_locked()

    def mirror_embedder(self, version: int) -> None:
        """Keep the embedder entry in step with the gallery's version
        (the gallery is that role's source of truth; PR 11's cutover
        calls this after the epoch-fenced install). Idempotent; never
        moves backward."""
        with self._lock:
            if int(version) <= int(self._roles["embedder"]["version"]):
                return
            self._roles["embedder"]["version"] = int(version)
            if not self.readonly:
                self._save_locked()
        self._publish_gauges()


def box_iou(a, b) -> float:
    """IoU of two yxyx (or xyxy — symmetric) pixel boxes."""
    ay0, ax0, ay1, ax1 = (float(v) for v in a)
    by0, bx0, by1, bx1 = (float(v) for v in b)
    iy0, ix0 = max(ay0, by0), max(ax0, bx0)
    iy1, ix1 = min(ay1, by1), min(ax1, bx1)
    inter = max(0.0, iy1 - iy0) * max(0.0, ix1 - ix0)
    if inter <= 0.0:
        return 0.0
    area_a = max(0.0, ay1 - ay0) * max(0.0, ax1 - ax0)
    area_b = max(0.0, by1 - by0) * max(0.0, bx1 - bx0)
    union = area_a + area_b - inter
    return inter / union if union > 0.0 else 0.0


class DetectionParity:
    """Old-vs-new DETECTOR agreement over a sliding window of live
    frames: the registry's parity definition for the detector role
    (module docstring). One sample per frame; agreement = verdict match
    (both fire / both pass) AND, when both fire, the best box pair
    overlaps at IoU >= ``iou_threshold``. Pure host math — it runs on
    demand off the publish path, never the hot loop. The window/sample
    contract mirrors ``rollout.DualScoreParity`` exactly (the SLO gauge
    reads ``disagreement``; below the sample floor no data is not a
    breach)."""

    def __init__(self, old_detect_fn: Callable[[np.ndarray], List],
                 new_detect_fn: Callable[[np.ndarray], List],
                 threshold: float = 0.98, min_samples: int = 16,
                 window: int = 256, iou_threshold: float = 0.5,
                 metrics=None):
        self.old_detect_fn = old_detect_fn
        self.new_detect_fn = new_detect_fn
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.iou_threshold = float(iou_threshold)
        self.metrics = metrics
        self._agreements: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()

    @staticmethod
    def _boxes(verdict) -> List:
        """Normalize a detect fn's output to a list of boxes: accepts a
        plain box list, or the ``detect_batch``-shaped ``(boxes, scores,
        valid)`` triple for one frame."""
        if verdict is None:
            return []
        if isinstance(verdict, tuple) and len(verdict) == 3:
            boxes, _scores, valid = verdict
            boxes = np.asarray(boxes)
            valid = np.asarray(valid, bool)
            return [boxes[i] for i in range(boxes.shape[0]) if valid[i]]
        return list(verdict)

    def _frame_agreement(self, old_boxes: List, new_boxes: List) -> float:
        if bool(old_boxes) != bool(new_boxes):
            return 0.0  # verdict mismatch: one fired, the other passed
        if not old_boxes:
            return 1.0  # both say no-face
        best = max(box_iou(a, b) for a in old_boxes for b in new_boxes)
        return 1.0 if best >= self.iou_threshold else 0.0

    def score(self, frames, old_boxes_list: Optional[List[List]] = None
              ) -> int:
        """Score frames through both detectors (or reuse the serving
        detector's live verdicts via ``old_boxes_list`` — the publish
        path already paid for them); returns samples recorded."""
        recorded = 0
        for i, frame in enumerate(frames):
            frame = np.asarray(frame)
            if old_boxes_list is not None:
                old_boxes = list(old_boxes_list[i])
            else:
                old_boxes = self._boxes(self.old_detect_fn(frame))
            new_boxes = self._boxes(self.new_detect_fn(frame))
            value = self._frame_agreement(old_boxes, new_boxes)
            with self._lock:
                self._agreements.append(value)
            recorded += 1
        if self.metrics is not None:
            with self._lock:
                n = len(self._agreements)
                agreement = (sum(self._agreements) / n) if n else 0.0
            self.metrics.set_gauge(mn.REGISTRY_PARITY_SAMPLES, n)
            self.metrics.set_gauge(mn.REGISTRY_PARITY_AGREEMENT,
                                   round(agreement, 4))
        return recorded

    def reset(self) -> None:
        """Clear the window (the post-cutover watch must not inherit the
        pre-cutover samples — a regression has to show on NEW traffic)."""
        with self._lock:
            self._agreements.clear()

    @property
    def samples(self) -> int:
        with self._lock:
            return len(self._agreements)

    @property
    def agreement(self) -> float:
        with self._lock:
            if not self._agreements:
                return 0.0
            return sum(self._agreements) / len(self._agreements)

    @property
    def disagreement(self) -> float:
        """1 - agreement once the window has data; 0.0 below the sample
        floor (no data is not a breach — the SLO gauge contract)."""
        with self._lock:
            n = len(self._agreements)
            if n < self.min_samples:
                return 0.0
            return 1.0 - sum(self._agreements) / n

    def ok(self) -> bool:
        with self._lock:
            n = len(self._agreements)
            return (n >= self.min_samples
                    and sum(self._agreements) / n >= self.threshold)


class RegistrySwapCoordinator:
    """Drives one detector/cascade registry swap end to end (module
    docstring): the live detection-parity window, the FaceGate retrain
    against the candidate detector, the gated atomic cutover through
    ``StateLifecycle.perform_registry_cutover``, and the post-cutover
    watch with auto-rollback.

    ``old_detect_fn``/``new_detect_fn`` produce per-frame verdicts (box
    lists, or ``detect_batch``-shaped triples) for the parity window —
    both optional, but without them the gate never opens and cutover
    needs ``force=True``. ``install_fn()`` performs the in-memory
    epoch-fenced install (pipeline param publish — it runs INSIDE the
    enroll-locked cutover, so keep it to attribute publishes);
    ``flush_fn(stamp)`` flushes the tracker/cascade caches right after
    the swap; ``gate_retrain_fn()`` returns the retrained stage-1 gate
    artifacts for a detector swap (run BEFORE the fence — the pair cuts
    over atomically). ``rollback_install_fn()`` restores the previous
    params in memory when a watch regression auto-rolls-back."""

    def __init__(self, state, registry: ModelRegistry, role: str,
                 to_version: int, *,
                 old_detect_fn: Optional[Callable] = None,
                 new_detect_fn: Optional[Callable] = None,
                 config: Any = None,
                 params_path: Optional[str] = None,
                 install_fn: Optional[Callable[[], None]] = None,
                 rollback_install_fn: Optional[Callable[[], None]] = None,
                 flush_fn: Optional[Callable[[Dict[str, int]], None]] = None,
                 gate_retrain_fn: Optional[Callable[[], Any]] = None,
                 parity_threshold: float = 0.98,
                 parity_min_samples: int = 16,
                 parity_window: int = 256,
                 parity_iou: float = 0.5,
                 watch_min_samples: int = 16,
                 live_sample_interval_s: float = 0.05,
                 metrics=None, tracer=None):
        if role not in MODEL_ROLES or role == "embedder":
            raise ValueError(
                f"RegistrySwapCoordinator handles detector/cascade swaps; "
                f"role {role!r} is not one (the embedder rolls out through "
                f"runtime.rollout — it needs the staged re-embed)")
        self.state = state
        self.registry = registry
        self.role = str(role)
        self.to_version = int(to_version)
        self.from_version = registry.version(role)
        if self.to_version <= self.from_version:
            raise ValueError(
                f"to_version {to_version} must exceed the served "
                f"{role} version {self.from_version} (versions are "
                f"monotonic; a rollback is a NEW version)")
        self.config = config
        self.params_path = params_path
        self.params_sha256 = (_file_sha256(params_path)
                              if params_path is not None
                              and os.path.exists(params_path) else None)
        self.install_fn = install_fn
        self.rollback_install_fn = rollback_install_fn
        self.flush_fn = flush_fn
        self.gate_retrain_fn = gate_retrain_fn
        self.gate_retrained: Any = None
        self.metrics = metrics
        self.tracer = tracer
        self.watch_min_samples = int(watch_min_samples)
        self.parity = (DetectionParity(old_detect_fn, new_detect_fn,
                                       threshold=parity_threshold,
                                       min_samples=parity_min_samples,
                                       window=parity_window,
                                       iou_threshold=parity_iou,
                                       metrics=metrics)
                       if old_detect_fn is not None
                       and new_detect_fn is not None else None)
        self._phase = "idle"
        self._live_q: deque = deque(maxlen=64)
        self._live_lock = threading.Lock()
        self._live_interval_s = float(live_sample_interval_s)
        self._last_live_t = 0.0
        self.cutover_seq: Optional[int] = None
        self.rollback_seq: Optional[int] = None
        self._set_phase("idle" if self.parity is None else "parity")

    # ---- phase bookkeeping ----

    def _set_phase(self, phase: str) -> None:
        self._phase = phase
        if self.metrics is not None:
            self.metrics.set_gauge(mn.REGISTRY_PHASE, PHASE_CODES[phase])
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "registry_phase",
                             topic=LIFECYCLE_TOPIC, phase=phase,
                             role=self.role, to_version=self.to_version)

    @property
    def phase(self) -> str:
        return self._phase

    # ---- live parity sampling ----

    def offer_live(self, frame: np.ndarray,
                   faces: Optional[List[Dict[str, Any]]] = None) -> None:
        """Publish-path hook (``RecognizerService._publish``): sample the
        frame, rate-limited, COPIED (the frame lives in a recycled
        staging buffer), with the serving detector's verdict boxes when
        the caller has them. Cheap and non-blocking by contract — the
        hot path pays one clock read in the common (not-due) case."""
        if self.parity is None or self._phase in ("done", "rolled_back"):
            return
        now = time.monotonic()
        if now - self._last_live_t < self._live_interval_s:
            return
        self._last_live_t = now
        boxes = None
        if faces is not None:
            boxes = [np.asarray(f["box"], np.float32) for f in faces
                     if "box" in f]
        with self._live_lock:
            self._live_q.append((np.asarray(frame).copy(), boxes))  # ocvf-lint: boundary=host-sync -- the publish path hands us the batch's HOST input frame (staging-ring numpy, never a device array); the copy exists precisely because that buffer is recycled

    def drain_live(self) -> int:
        """Score every queued live sample (the swap driver's thread, or
        tests calling it synchronously); returns samples scored. After
        cutover this feeds the WATCH window and a regression triggers
        the auto-rollback."""
        with self._live_lock:
            samples = list(self._live_q)
            self._live_q.clear()
        scored = 0
        for frame, boxes in samples:
            scored += self.score_parity(
                [frame], old_boxes_list=None if boxes is None else [boxes])
        return scored

    def score_parity(self, frames,
                     old_boxes_list: Optional[List[List]] = None) -> int:
        """Score frames through both detectors (tests and the chaos
        harness call this directly with synthetic traffic). In phase
        ``watch`` a completed window below the gate auto-rolls-back."""
        if self.parity is None:
            return 0
        n = self.parity.score(frames, old_boxes_list=old_boxes_list)
        if (self._phase == "parity" and self.parity.ok()):
            self._set_phase("ready")
        elif self._phase == "watch":
            self.check_watch()
        return n

    def parity_ok(self) -> bool:
        return self.parity is not None and self.parity.ok()

    # ---- the gated atomic cutover ----

    def cutover(self, force: bool = False) -> int:
        """Gate -> FaceGate retrain (detector swaps) -> WAL fence ->
        manifest install + epoch-fenced in-memory publish -> cache flush
        -> forced checkpoint -> watch. Returns the fence record's WAL
        sequence. Raises ``RolloutGateError`` (the same refusal type the
        embedder rollout gates with) when the parity window has not
        cleared its threshold (``force`` overrides — and is required
        when no parity detectors were wired)."""
        if not force:
            reasons = []
            if self.parity is None:
                reasons.append("no parity window wired (old/new detect fns)")
            elif not self.parity.ok():
                reasons.append(
                    f"parity gate not met: agreement "
                    f"{self.parity.agreement:.4f} over "
                    f"{self.parity.samples} samples (need >= "
                    f"{self.parity.threshold:g} over >= "
                    f"{self.parity.min_samples})")
            if reasons:
                if self.metrics is not None:
                    self.metrics.incr(mn.REGISTRY_SWAPS_BLOCKED)
                raise RolloutGateError(
                    f"{self.role} swap refused: " + "; ".join(reasons))
        if self.gate_retrain_fn is not None and self.gate_retrained is None:
            # The stage-1 gate's operating point is defined AGAINST the
            # detector's verdicts — retrain it against the CANDIDATE
            # before the fence so the pair cuts over atomically.
            self.gate_retrained = self.gate_retrain_fn()
            if self.metrics is not None:
                self.metrics.incr(mn.REGISTRY_GATE_RETRAINS)
        self._set_phase("cutover")
        seq = self.state.perform_registry_cutover(
            self.role, self.to_version, config=self.config,
            params_path=self.params_path,
            params_sha256=self.params_sha256,
            install_fn=self.install_fn)
        self.cutover_seq = seq
        if self.flush_fn is not None:
            # Eager cache flush: no cached identity or cascade verdict
            # computed under the OLD model outlives its cutover (the
            # tracker's stamp keying catches stragglers lazily).
            self.flush_fn(self.registry.stamp())
        # Forced checkpoint: the swap is fence-durable already (a crash
        # here recovers INTO the new version from the manifest/fence);
        # the checkpoint stamps the new registry and lets replicas
        # re-anchor past the fence.
        if not self.state.checkpoint_now(wait=True):
            self.state.maybe_checkpoint(force=True)
            logger.warning(
                "post-swap checkpoint did not land; the forced-checkpoint "
                "latch will retry (recovery completes the swap meanwhile)")
        if self.parity is not None:
            self.parity.reset()
            self._set_phase("watch")
        else:
            self._set_phase("done")
        return seq

    # ---- the post-cutover watch + auto-rollback ----

    def check_watch(self) -> bool:
        """Evaluate the post-cutover parity window; True when the swap
        regressed and was auto-rolled-back. A completed watch window at
        or above the gate settles the swap (phase ``done``)."""
        if self._phase != "watch" or self.parity is None:
            return False
        n = self.parity.samples
        if n < self.watch_min_samples:
            return False
        if self.parity.agreement >= self.parity.threshold:
            self._set_phase("done")
            return False
        self.auto_rollback()
        return True

    def auto_rollback(self) -> int:
        """Parity regressed inside the watch window: roll the role back
        at the NEXT monotonic version (numbers never reuse — the fence
        stays unambiguous), restore the previous params in memory, and
        force a flight-recorder dump with the full swap status — the
        forensic artifact the chaos scenario parses."""
        status = self.status()
        if self.metrics is not None:
            self.metrics.incr(mn.REGISTRY_AUTO_ROLLBACKS)
        if self.tracer is not None:
            self.tracer.dump("registry_auto_rollback",
                             extra={"registry_swap": status}, force=True)
        logger.warning(
            "registry %s swap v%d -> v%d auto-rolling-back: watch parity "
            "%.4f over %d samples below gate %.4g", self.role,
            self.from_version, self.to_version,
            self.parity.agreement if self.parity is not None else 0.0,
            self.parity.samples if self.parity is not None else 0,
            self.parity.threshold if self.parity is not None else 0.0)
        seq = self.state.perform_registry_cutover(
            self.role, self.to_version + 1, config=None,
            params_path=None, params_sha256=None,
            install_fn=self.rollback_install_fn)
        self.rollback_seq = seq
        if self.flush_fn is not None:
            self.flush_fn(self.registry.stamp())
        if not self.state.checkpoint_now(wait=True):
            self.state.maybe_checkpoint(force=True)
        self._set_phase("rolled_back")
        return seq

    def rollback(self) -> int:
        """Operator-driven rollback: the same mechanism as the automatic
        one, at the next monotonic version."""
        return self.auto_rollback()

    # ---- observability ----

    def status(self) -> Dict[str, Any]:
        """JSON-able snapshot for ``GET /registry`` and the chaos
        report."""
        out = {
            "role": self.role,
            "phase": self._phase,
            "from_version": self.from_version,
            "to_version": self.to_version,
            "cutover_seq": self.cutover_seq,
            "rollback_seq": self.rollback_seq,
            "gate_retrained": self.gate_retrained is not None,
            "params_path": self.params_path,
            "parity": None,
        }
        if self.parity is not None:
            out["parity"] = {
                "samples": self.parity.samples,
                "agreement": round(self.parity.agreement, 4),
                "threshold": self.parity.threshold,
                "min_samples": self.parity.min_samples,
                "iou_threshold": self.parity.iou_threshold,
                "ok": self.parity.ok(),
            }
        return out
