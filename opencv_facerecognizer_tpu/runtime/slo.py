"""SLO burn-rate monitor: declarative objectives, multi-window burn
rates, and an ok -> warn -> critical health state machine (signals layer,
beside ``runtime.expo``).

The tracing layer (PR 8) answers "what happened to frame X"; the metrics
layer answers "what are the counters right now".  Neither answers the
operator/orchestrator question: **is this replica healthy, and how fast is
it eating its error budget?**  This module does, with the standard
multi-window burn-rate construction:

- An **objective** declares what "good" means (a latency histogram window
  staying under a threshold for a target fraction of events, a counter
  ratio staying above a target, a gauge staying under a bound) plus two
  evaluation horizons — a short window that reacts fast and a long window
  that filters blips.
- The **burn rate** is ``observed error rate / error budget`` (budget =
  ``1 - target``): burn 1.0 means "exactly spending the budget", burn 6
  means "six times too fast".  An objective's severity requires the burn
  to exceed the rate on **both** windows — the short window alone flaps
  on every scheduler hiccup, the long window alone reacts too late; the
  pairing is what makes the signal actionable (the SRE multi-window
  multi-burn-rate alert, evaluated in-process).
- Latency objectives read ``Metrics.fraction_above`` over the rolling
  histograms (``utils.histogram``), so the short/long horizons are true
  wall-clock slices of one ring — no second bookkeeping.  Ratio
  objectives (the admission ledger's completion ratio) diff counter
  snapshots the monitor itself records per evaluation.  Gauge objectives
  (durability lag = WAL rows not yet covered by a checkpoint) read an
  injected callable; their burn is ``value / bound`` on both windows.
- **Watchdog events** (``note_event``): out-of-band warn signals — the
  recompile watchdog reports every post-warmup jit compile here — hold
  the health state at warn while any event is inside the short window,
  and are counted per reason (``slo_events_<reason>``).

The **health state machine** takes the worst objective severity each
evaluation.  Escalation is immediate; de-escalation requires
``recovery_evals`` consecutive cleaner evaluations (hysteresis — a state
that flaps is worse than no state at all).  Every transition emits a
lifecycle span; a transition INTO critical additionally fires a
flight-recorder dump (``slo_critical``) — the rings at the moment the
budget blew are exactly what the post-mortem needs.  ``health_state``
(0/1/2) and per-objective ``slo_burn_<name>`` gauges land on the shared
Metrics surface, so ``/metrics``, ``/prom`` and the JSONL sink all carry
them; ``/health`` serves the full verdict.

Consumers: the serving loop ticks the monitor (one time-check per batch;
evaluation every ``interval_s``); the brownout controller treats a
critical verdict as one extra level of intake pressure; the supervisor
publishes health transitions on the status topic.  The serving loop is
the primary evaluator with the expo refresh thread as a liveness
backstop for wedged loops — concurrent ticks are serialized by a
NON-BLOCKING claim (the loser skips; nobody ever waits), so the state
machine can never run twice over one instant and transition side
effects (spans, the critical flight dump) fire exactly once.  Readers
(``/health``, supervisor) read the last verdict dict by reference (an
atomic swap in CPython), and ``note_event`` appends to a thread-safe
deque.  The evaluation claim's only outgoing lock edge is into Metrics
(a leaf), so the lock-order graph stays acyclic.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from opencv_facerecognizer_tpu.utils import metric_names as mn

#: health states, in escalation order; index = the ``health_state`` gauge.
STATE_OK, STATE_WARN, STATE_CRITICAL = 0, 1, 2
STATE_NAMES = ("ok", "warn", "critical")


@dataclass
class SLO:
    """One objective. ``kind`` selects which fields apply:

    - ``"latency"``: ``window`` (a Metrics histogram window name) must
      stay under ``threshold_s`` for ``target`` of events;
    - ``"ratio"``: the ``bad_counters`` share of ``total_counters``
      growth must stay under ``1 - target`` (e.g. ledger drops vs
      admitted);
    - ``"gauge"``: ``value_fn()`` must stay under ``bound`` (burn =
      value / bound, both windows).
    """

    name: str
    kind: str  # "latency" | "ratio" | "gauge"
    # latency
    window: Optional[str] = None
    threshold_s: float = 0.0
    # latency + ratio: target fraction of good events (budget = 1-target)
    target: float = 0.99
    # ratio
    bad_counters: Tuple[str, ...] = ()
    total_counters: Tuple[str, ...] = ()
    # gauge
    value_fn: Optional[Callable[[], float]] = None
    bound: float = 0.0
    # evaluation windows (seconds) and burn-rate severity thresholds
    short_s: float = 60.0
    long_s: float = 600.0
    warn_burn: float = 1.0
    critical_burn: float = 6.0
    #: volume floor: latency/ratio severity is claimed only when BOTH
    #: windows hold at least this many events. One dropped frame on an
    #: idle replica is a 500x burn against a 0.001 budget — without a
    #: floor it would 503 /health, fire the critical dump, and add a
    #: brownout level all by itself. Gauge objectives are point-in-time
    #: reads and exempt. The burn is still computed and reported
    #: (``low_volume`` marks the verdict) so /health shows the signal
    #: without acting on it.
    min_events: int = 10

    def __post_init__(self) -> None:
        if self.kind not in ("latency", "ratio", "gauge"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.target < 1.0 and self.kind != "gauge":
            raise ValueError("target must be in (0, 1)")
        if self.kind == "latency" and not self.window:
            raise ValueError("latency SLO needs a metrics window name")
        if self.kind == "gauge" and self.value_fn is None:
            raise ValueError("gauge SLO needs a value_fn")
        if self.kind == "gauge" and not self.bound > 0:
            # bound<=0 would make burn read 0.0 forever — a permanently
            # green objective is worse than a loud constructor.
            raise ValueError("gauge SLO needs a positive bound")
        if self.short_s > self.long_s:
            # A swapped pair is symmetric for burn severity so it would
            # never surface as an error — but the reported
            # burn_short/burn_long horizons invert and the watchdog-event
            # hold window (derived from min short_s) inflates 10x.
            raise ValueError(
                f"SLO {self.name!r}: short_s {self.short_s:g} > long_s "
                f"{self.long_s:g} — pass windows short-first")


def default_objectives(drop_counters: Sequence[str] = (),
                       state=None,
                       e2e_p99_s: float = 0.5,
                       queue_wait_p99_s: float = 0.25,
                       completion_target: float = 0.999,
                       durability_rows: int = 1024,
                       short_s: float = 60.0,
                       long_s: float = 600.0) -> List[SLO]:
    """The four stock objectives from the signals-layer design: interactive
    e2e latency, completion ratio over the admission ledger's drop
    counters, durability lag against the state lifecycle (only when one is
    wired), and queue-wait. Callers pass the service's
    ``LEDGER_DROP_COUNTERS`` — this module deliberately does not import
    the recognizer (the service imports us)."""
    objectives = [
        SLO(name="interactive_p99", kind="latency",
            window=mn.E2E_LATENCY_INTERACTIVE, threshold_s=e2e_p99_s,
            target=0.99, short_s=short_s, long_s=long_s),
        SLO(name="queue_wait_p99", kind="latency",
            window=mn.QUEUE_WAIT, threshold_s=queue_wait_p99_s,
            target=0.99, short_s=short_s, long_s=long_s),
    ]
    if drop_counters:
        objectives.append(SLO(
            name="completion", kind="ratio", target=completion_target,
            bad_counters=tuple(drop_counters),
            total_counters=(mn.FRAMES_ADMITTED,),
            short_s=short_s, long_s=long_s))
    if state is not None:
        objectives.append(SLO(
            name="durability_lag", kind="gauge",
            value_fn=lambda: float(state.rows_since_checkpoint),
            bound=float(durability_rows),
            short_s=short_s, long_s=long_s))
    return objectives


def loop_liveness_objective(service, stale_s: float = 30.0,
                            short_s: float = 60.0,
                            long_s: float = 600.0) -> SLO:
    """Gauge objective over ``RecognizerService.loop_staleness_s``: warn
    once the serving loop has not completed an iteration for ``stale_s``,
    critical at 6x that. This closes the wedged-loop blind spot the
    latency/ratio objectives share — a loop that stops moving stops
    producing events, empty windows read as burn 0, and /health would
    report ok indefinitely. The gauge is evaluated by whichever ticker
    still runs (the expo refresh backstop when the loop itself is the
    casualty). Built via ``SLOMonitor.add_objective`` because the service
    is constructed WITH the monitor — this objective can only close over
    it afterwards."""
    return SLO(name="loop_liveness", kind="gauge",
               value_fn=lambda: float(service.loop_staleness_s),
               bound=float(stale_s), short_s=short_s, long_s=long_s)


def replication_lag_objective(replica, rows_bound: float = 1024.0,
                              short_s: float = 60.0,
                              long_s: float = 600.0) -> SLO:
    """Gauge objective over a read replica's ``lag_rows``
    (``runtime.replication.ReadReplica``): WAL rows visible but not yet
    applied locally. Warn once the backlog crosses ``rows_bound``,
    critical at 6x — and because the brownout controller already consumes
    a critical health verdict as one extra level of intake pressure, a
    stale replica **browns itself out**: it sheds bulk serving load until
    the tail catches up, composing with the existing controller instead
    of adding a second one. Takes any object with a ``lag_rows``
    attribute — the slo layer deliberately does not import replication
    (replication imports the state store, which sits beside us)."""
    return SLO(name="replication_lag", kind="gauge",
               value_fn=lambda: float(replica.lag_rows),
               bound=float(rows_bound), short_s=short_s, long_s=long_s)


def disk_free_objective(free_bytes_fn: Callable[[], float],
                        low_watermark_bytes: float,
                        short_s: float = 60.0,
                        long_s: float = 600.0) -> SLO:
    """Gauge objective over the state volume's free bytes (ISSUE 15):
    burn = ``low_watermark / free`` — exactly 1.0 (warn) at the low
    watermark, 6.0 (critical) at one sixth of it, the same critical
    point where ``DurabilityMonitor`` pre-empts the degraded flip before
    ENOSPC ever lands. Takes any free-bytes callable — the stock wiring
    passes ``DurabilityMonitor.free_bytes`` so /health and the
    watermark actions read ONE statvfs sample, and this module
    deliberately imports neither the monitor nor ``os.statvfs``. An
    empty/failed probe reads burn 0 through the standard gauge-probe
    contract (no data is not a breach)."""
    watermark = float(low_watermark_bytes)
    if not watermark > 0:
        raise ValueError("disk_free_objective needs a positive low "
                         "watermark (bytes)")

    def value() -> float:
        free = float(free_bytes_fn())
        if not math.isfinite(free):
            return 0.0  # no sample yet: no data is not a breach
        return watermark / max(1.0, free)

    return SLO(name="disk_free", kind="gauge", value_fn=value, bound=1.0,
               short_s=short_s, long_s=long_s)


def link_health_objective(down_fraction_fn: Callable[[], float],
                          max_down_fraction: float = 0.5,
                          short_s: float = 30.0,
                          long_s: float = 300.0) -> SLO:
    """Gauge objective over the router's failed-link fraction (ISSUE 16):
    burn = ``down_fraction / max_down_fraction`` — exactly 1.0 (warn)
    once the allowed fraction of supervised links is down, 6.0
    (critical) when the fleet is effectively partitioned away. One dead
    replica out of four is failover's job and stays under the bound; a
    majority dark is a NETWORK event no per-replica failover can route
    around, and /health should say so before the queue does. Takes any
    down-fraction callable — the stock wiring passes
    ``TopicRouter.down_link_fraction``; this module deliberately does
    not import replication (which imports the state store beside us).
    Short windows by default: link verdicts already debounce behind the
    pong deadline, so the objective's job is to REPORT fast.

    The critical threshold is lowered from the stock 6x wherever 6x is
    unreachable: a fraction tops out at 1.0, so against the default 0.5
    bound a fully-dark fleet would burn 2.0 forever and the standard
    6x critical could NEVER fire — critical lands at
    ``min(6 x bound, every supervised link down)`` instead."""
    bound = float(max_down_fraction)
    if not bound > 0:
        raise ValueError("link_health_objective needs a positive "
                         "max_down_fraction")

    def value() -> float:
        return float(down_fraction_fn()) / bound

    return SLO(name="link_health", kind="gauge", value_fn=value, bound=1.0,
               short_s=short_s, long_s=long_s,
               critical_burn=min(6.0, 1.0 / bound))


def rollout_parity_objective(coordinator, min_agreement: float = 0.98,
                             short_s: float = 60.0,
                             long_s: float = 600.0) -> SLO:
    """Gauge objective over a rollout's dual-score DISAGREEMENT fraction
    (``runtime.rollout.RolloutCoordinator`` — old vs new embedder top-1
    identity agreement on live traffic): warn once disagreement crosses
    the budget ``1 - min_agreement``, critical at 6x. Below the parity
    window's sample floor the gauge reads 0 (no data is not a breach —
    the same contract every gauge objective keeps), so an idle rollout
    never alarms; a rollout whose new embedder actually disagrees on
    live identities alarms BEFORE anyone forces the cutover. Takes any
    object with a ``parity`` attribute exposing ``disagreement`` — this
    module deliberately does not import the rollout (which imports the
    state store beside us)."""
    budget = 1.0 - float(min_agreement)
    if not budget > 0:
        raise ValueError("min_agreement must be < 1.0 (a zero "
                         "disagreement budget can never be scored)")

    def value() -> float:
        parity = getattr(coordinator, "parity", None)
        return float(parity.disagreement) if parity is not None else 0.0

    return SLO(name="rollout_parity", kind="gauge", value_fn=value,
               bound=budget, short_s=short_s, long_s=long_s)


def registry_parity_objective(coordinator, min_agreement: float = 0.98,
                              short_s: float = 60.0,
                              long_s: float = 600.0) -> SLO:
    """Gauge objective over a registry swap's detection DISAGREEMENT
    fraction (``runtime.registry.RegistrySwapCoordinator`` — old vs
    candidate detector box-overlap verdict agreement on live frames):
    warn once disagreement crosses ``1 - min_agreement``, critical at
    6x. Same contract as ``rollout_parity_objective`` — below the
    window's sample floor the gauge reads 0 (an idle registry never
    alarms), and it takes any object with a ``parity`` attribute
    exposing ``disagreement`` so this module never imports the registry
    (which imports the state store beside us). Rides /health for the
    whole swap INCLUDING the post-cutover watch, so a candidate that
    regresses on live traffic alarms while the coordinator's
    auto-rollback fires."""
    budget = 1.0 - float(min_agreement)
    if not budget > 0:
        raise ValueError("min_agreement must be < 1.0 (a zero "
                         "disagreement budget can never be scored)")

    def value() -> float:
        parity = getattr(coordinator, "parity", None)
        return float(parity.disagreement) if parity is not None else 0.0

    return SLO(name="registry_parity", kind="gauge", value_fn=value,
               bound=budget, short_s=short_s, long_s=long_s)


class SLOMonitor:
    """Evaluate a set of ``SLO`` objectives on a fixed interval and run
    the health state machine over them (module docstring)."""

    def __init__(self, metrics, objectives: Sequence[SLO],
                 tracer=None, interval_s: float = 5.0,
                 recovery_evals: int = 2,
                 event_window_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.metrics = metrics
        self.objectives = list(objectives)
        self.tracer = tracer
        self.interval_s = float(interval_s)
        for obj in self.objectives:
            self._validate_objective(obj)
        self.recovery_evals = max(1, int(recovery_evals))
        #: how long a watchdog event keeps the state at >= warn; defaults
        #: to the shortest objective short-window (or the interval).
        self._event_window_explicit = bool(event_window_s)
        self.event_window_s = float(event_window_s) if event_window_s else 0.0
        self._clock = clock
        self._state = STATE_OK
        self._calm_evals = 0
        self._last_eval_t: Optional[float] = None
        #: (monotonic t, reason) of recent warn-level watchdog events;
        #: appends are thread-safe, expiry happens at evaluation.
        self._events: deque = deque(maxlen=1024)
        #: ring of (t, counter snapshot) for ratio-objective deltas; sized
        #: by ``_resize_for_objectives`` to cover the longest long window
        #: at the evaluation cadence (plus slack for early/late ticks) —
        #: each entry is a full counter-dict copy, so an oversized ring is
        #: real memory and a longer ``_snapshot_at`` scan on every ratio
        #: evaluation.
        self._counter_ring: deque = deque(maxlen=8)
        self._resize_for_objectives()
        #: one evaluation at a time: the serving loop is the primary
        #: ticker but the expo refresh thread backstops it, so the
        #: interval gate alone is check-then-act. The claim is
        #: non-blocking — a contending ticker skips (the winner's verdict
        #: stands) — so neither hot path ever waits here.
        self._eval_lock = threading.Lock()
        self._verdict: Dict[str, Any] = {
            "state": STATE_NAMES[STATE_OK], "state_code": STATE_OK,
            "objectives": {}, "events": {}, "evaluations": 0, "ts": None,
        }

    def _validate_objective(self, obj: SLO) -> None:
        """Refuse an objective whose windows the metrics ring cannot
        honestly answer. A latency horizon longer than the rolling window
        would SILENTLY read only window_s of data; one below a ring slice
        would aggregate a full slice anyway — either way the configured
        reaction/filtering guarantee is quietly weaker than asked. Same
        philosophy as the gauge bound check: loud constructor over a
        quietly-wrong objective."""
        if obj.kind != "latency":
            return
        window_s = getattr(self.metrics, "window_s", None)
        slice_s = getattr(self.metrics, "window_slice_s", None)
        if window_s is not None and max(obj.short_s, obj.long_s) > window_s:
            raise ValueError(
                f"SLO {obj.name!r} window "
                f"{max(obj.short_s, obj.long_s):g}s exceeds the "
                f"metrics rolling horizon {window_s:g}s — construct "
                f"Metrics(window_s=...) to cover the longest "
                f"objective window")
        if slice_s is not None and min(obj.short_s, obj.long_s) < slice_s:
            raise ValueError(
                f"SLO {obj.name!r} window "
                f"{min(obj.short_s, obj.long_s):g}s is below the "
                f"metrics ring resolution {slice_s:g}s/slice — raise "
                f"the window or construct Metrics with more "
                f"window_slices")

    def _default_event_window(self) -> float:
        return max(self.interval_s,
                   min((o.short_s for o in self.objectives),
                       default=self.interval_s))

    def _resize_for_objectives(self) -> None:
        """Re-derive the objective-dependent sizes — the default event
        window (shortest short_s) and the counter-ring depth (longest
        long_s at the eval cadence, +2 slack, clamped to [8, 4096]) —
        from the CURRENT objective list. The single sizing rule for both
        the constructor and ``add_objective``; existing ring entries are
        preserved on a resize."""
        if not self._event_window_explicit:
            self.event_window_s = self._default_event_window()
        longest_s = max((o.long_s for o in self.objectives),
                        default=self.interval_s)
        depth = int(math.ceil(longest_s / self.interval_s)) + 2
        maxlen = max(8, min(4096, depth))
        if maxlen != self._counter_ring.maxlen:
            self._counter_ring = deque(self._counter_ring, maxlen=maxlen)

    def add_objective(self, obj: SLO) -> None:
        """Register one more objective after construction — for consumers
        that only exist once the monitor does (the serving loop's
        staleness gauge closes over the service, which is constructed WITH
        the monitor). Runs the same window validation and re-derives the
        event window and counter-ring depth exactly as the constructor
        would have."""
        self._validate_objective(obj)
        self.objectives.append(obj)
        self._resize_for_objectives()

    # ---- readers (any thread) ----

    @property
    def state_code(self) -> int:
        return self._state

    @property
    def state(self) -> str:
        return STATE_NAMES[self._state]

    def verdict(self) -> Dict[str, Any]:
        """The last evaluation's full verdict (per-objective burn rates,
        window counts, active events). Reference read — cheap and safe
        from any thread; the dict is never mutated after the swap."""
        return self._verdict

    # ---- watchdog events (any thread) ----

    def note_event(self, reason: str) -> None:
        """Record one warn-level out-of-band event (e.g. the recompile
        watchdog's post-warmup compile). Counted immediately
        (``slo_events_<reason>``); holds health at >= warn while inside
        ``event_window_s``."""
        self._events.append((self._clock(), str(reason)))
        if self.metrics is not None:
            self.metrics.incr(mn.SLO_EVENTS_PREFIX + reason)

    # ---- evaluation (serving-loop thread) ----

    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Evaluate iff ``interval_s`` has elapsed — the serving loop
        calls this once per batch/idle iteration; the non-due path is one
        clock read and one comparison."""
        now = self._clock() if now is None else now
        if (self._last_eval_t is not None
                and now - self._last_eval_t < self.interval_s):
            return None
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One full evaluation. Returns None (without evaluating) when
        another thread is mid-evaluation — the serving loop and the expo
        backstop can tick concurrently, and the winner's verdict stands;
        the state machine must never run twice over one instant."""
        if not self._eval_lock.acquire(blocking=False):
            return None
        try:
            now = self._clock() if now is None else now
            self._last_eval_t = now
            counters = (self.metrics.counters()
                        if self.metrics is not None else {})
            self._counter_ring.append((now, counters))
            per_objective: Dict[str, Dict[str, Any]] = {}
            worst = STATE_OK
            for obj in self.objectives:
                result = self._evaluate_one(obj, now, counters)
                per_objective[obj.name] = result
                worst = max(worst, result["state_code"])
                if self.metrics is not None:
                    self.metrics.set_gauge(mn.SLO_BURN_PREFIX + obj.name,
                                           result["burn"])
            active_events = self._active_events(now)
            if active_events and worst < STATE_WARN:
                worst = STATE_WARN
            prev = self._state
            state = self._advance_state(worst)
            verdict = {
                "state": STATE_NAMES[state],
                "state_code": state,
                "raw_state": STATE_NAMES[worst],
                "objectives": per_objective,
                "events": active_events,
                "evaluations": self._verdict["evaluations"] + 1,
                "ts": time.time(),
            }
            self._verdict = verdict
            # The health_state gauge and evaluation counter are written
            # INSIDE the claim: written after release, an evaluator
            # descheduled at the release point could overwrite a newer
            # evaluation's gauge with its stale state — /prom would then
            # disagree with /health until the next tick. Metrics is a leaf
            # lock, so the edge stays clean in the lock-order graph.
            if self.metrics is not None:
                self.metrics.incr(mn.SLO_EVALUATIONS)
                self.metrics.set_gauge(mn.HEALTH_STATE, state)
        finally:
            self._eval_lock.release()
        # Transition side effects (span, flight dump — file I/O) run
        # OUTSIDE the lock: the non-blocking claim above already
        # guarantees at most one thread reaches a given transition.
        if state != prev:
            self._note_transition(prev, state, verdict)
        return verdict

    # ---- internals ----

    def _active_events(self, now: float) -> Dict[str, int]:
        lo = now - self.event_window_s
        active: Dict[str, int] = {}
        # Snapshot before iterating: note_event appends from other threads
        # (the serving loop's recompile watchdog, any future watchdog) and
        # a deque append during iteration raises RuntimeError; tuple() of
        # a deque completes in C without a bytecode boundary, so the copy
        # itself cannot interleave with an append.
        for t, reason in tuple(self._events):
            if t >= lo:
                active[reason] = active.get(reason, 0) + 1
        return active

    def _evaluate_one(self, obj: SLO, now: float,
                      counters: Dict[str, float]) -> Dict[str, Any]:
        if obj.kind == "latency":
            burns = self._latency_burns(obj)
        elif obj.kind == "ratio":
            burns = self._ratio_burns(obj, now, counters)
        else:
            burns = self._gauge_burns(obj)
        (burn_short, n_short), (burn_long, n_long) = burns
        state = STATE_OK
        # Severity needs BOTH windows burning past its rate AND enough
        # volume to make the rate meaningful (the min_events floor —
        # docstrings here and on the field).
        enough = (obj.kind == "gauge"
                  or min(n_short, n_long) >= obj.min_events)
        if enough:
            if (burn_short >= obj.critical_burn
                    and burn_long >= obj.critical_burn):
                state = STATE_CRITICAL
            elif burn_short >= obj.warn_burn and burn_long >= obj.warn_burn:
                state = STATE_WARN
        result = {
            "kind": obj.kind,
            "burn_short": round(burn_short, 4),
            "burn_long": round(burn_long, 4),
            "burn": round(max(burn_short, burn_long), 4),
            "events_short": n_short,
            "events_long": n_long,
            "state": STATE_NAMES[state],
            "state_code": state,
        }
        if not enough:
            result["low_volume"] = True
        return result

    def _latency_burns(self, obj: SLO):
        budget = 1.0 - obj.target
        if getattr(self.metrics, "window_count", None) is None:
            # No histogram surface wired (metrics=None constructs by
            # documented contract): zero events in both windows. The
            # min_events floor then keeps the objective at ok/low_volume
            # instead of the serving loop crash-looping on an
            # AttributeError every tick.
            return [(0.0, 0), (0.0, 0)]
        out = []
        for horizon in (obj.short_s, obj.long_s):
            count = self.metrics.window_count(obj.window, horizon_s=horizon)
            frac = (self.metrics.fraction_above(obj.window, obj.threshold_s,
                                                horizon_s=horizon)
                    if count else 0.0)
            out.append((frac / budget, count))
        return out

    def _ratio_burns(self, obj: SLO, now: float, counters: Dict[str, float]):
        budget = 1.0 - obj.target
        out = []
        for horizon in (obj.short_s, obj.long_s):
            base = self._snapshot_at(now - horizon)
            bad = sum(counters.get(k, 0.0) - base.get(k, 0.0)
                      for k in obj.bad_counters)
            total = sum(counters.get(k, 0.0) - base.get(k, 0.0)
                        for k in obj.total_counters)
            frac = (bad / total) if total > 0 else 0.0
            out.append((max(0.0, frac) / budget, int(max(0.0, total))))
        return out

    def _snapshot_at(self, t: float) -> Dict[str, float]:
        """The newest recorded counter snapshot taken at or before ``t``
        (so the delta covers AT LEAST the horizon); the empty dict —
        i.e. since-process-start deltas — when the ring does not reach
        back that far yet."""
        best: Dict[str, float] = {}
        for ts, snap in self._counter_ring:
            if ts <= t:
                best = snap
            else:
                break
        return best

    def _gauge_burns(self, obj: SLO):
        try:
            value = float(obj.value_fn())
        except Exception:  # noqa: BLE001 — a probe failure is not a breach
            # Counted, not raised: a dead gauge probe must read as burn 0
            # (no data is not a breach), but it must not be silent either.
            if self.metrics is not None:
                self.metrics.incr(mn.SLO_PROBE_FAILURES)
            value = 0.0
        burn = (value / obj.bound) if obj.bound > 0 else 0.0
        return [(burn, 1), (burn, 1)]

    def _advance_state(self, worst: int) -> int:
        """Hysteresis: escalate immediately, de-escalate one level per
        ``recovery_evals`` consecutive evaluations whose raw severity sat
        below the current state."""
        prev = self._state
        if worst >= prev:
            self._calm_evals = 0
            self._state = worst
        else:
            self._calm_evals += 1
            if self._calm_evals >= self.recovery_evals:
                self._calm_evals = 0
                self._state = prev - 1
        return self._state

    def _note_transition(self, prev: int, new: int,
                         verdict: Dict[str, Any]) -> None:
        if self.metrics is not None:
            self.metrics.incr(mn.SLO_TRANSITIONS)
        tracer = self.tracer
        if tracer is not None:
            from opencv_facerecognizer_tpu.utils import tracing

            # Instant lifecycle span: health transitions are the signals
            # layer's causal markers, same shape as the brownout spans.
            tracer.emit(tracer.new_trace(), "health",
                        topic=tracing.LIFECYCLE_TOPIC,
                        from_state=STATE_NAMES[prev],
                        to_state=STATE_NAMES[new])
            if new == STATE_CRITICAL:
                # The budget just blew: capture what was in flight. Rate
                # limited like every recorder trigger; the per-objective
                # burns ride the dump so the post-mortem starts with the
                # verdict, not just the spans.
                tracer.dump("slo_critical",
                            extra={"verdict": {
                                k: verdict[k] for k in
                                ("state", "objectives", "events")}})
