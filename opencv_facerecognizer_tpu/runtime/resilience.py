"""Resilience policy + supervised serving (SURVEY.md §5.3 extended to
steady state).

``utils/backend_probe.py`` hardened *startup* against the two observed
backend outage modes (fast-fail ``UNAVAILABLE`` and silent hang); this
module extends that posture to the serving loop itself:

- ``ResiliencePolicy`` — the retry/deadline/degraded knobs threaded through
  ``RecognizerService``: a dispatch failure retries with exponential
  backoff, a readback that outlives its deadline is dead-lettered (the loop
  keeps serving), and N consecutive dispatch failures flip the service into
  **degraded mode** (status published on ``STATUS_TOPIC``, optional bounded
  backend probe, optional CPU-fallback hook) instead of wedging.
- ``BrownoutPolicy`` — the overload-degradation knobs (queue-wait EWMA
  threshold, hysteresis, per-level shedding) the recognizer's brownout
  controller runs on; the *client-side* sibling of ``ResiliencePolicy``'s
  backend-side knobs (see ``runtime.admission`` for the front door).
- ``is_transient_error`` — classifies an exception as retryable
  (backend/transport outage shaped) vs permanent (a poisoned batch: retrying
  a shape error burns the retry budget for nothing).
- ``ServiceSupervisor`` — restarts a crashed serving loop with the
  last-known-good gallery snapshot, reusing the existing double-buffered
  ``reload_gallery`` swap. Restart count is bounded; giving up publishes a
  terminal status rather than flapping forever.
- ``DurabilityMonitor`` — the degraded-DURABILITY state machine
  (ISSUE 15): the backend-outage machinery above assumes the *disk* is
  fine; this class owns the case where it is not (ENOSPC, EIO, a
  2-second fsync).  Sustained WAL append failure (or a critical disk
  watermark) flips the writer to ``durability_degraded``: enrollments
  are refused closed with an explicit status (the ack never lies),
  serving/read traffic continues, and non-critical sinks (dead-letter
  journal, span JSONL, flight dumps) shed with exact per-sink
  accounting. A background probe (tmp-file write + fsync in the state
  dir) detects recovery and re-arms with a lifecycle span and a status
  announcement — the same degrade/announce/recover shape as the
  dispatch-side degraded mode. Disk-pressure watermarks ride the same
  tick: below the low watermark the monitor preemptively compacts the
  WAL (forced checkpoint) and shrinks checkpoint/flight/journal
  retention; below ``watermark / critical_divisor`` it pre-empts the
  degraded flip BEFORE ENOSPC ever lands.

Every transition is counted in the service's ``Metrics`` (``dispatch_
retries``, ``batches_dead_lettered``, ``degraded_transitions``,
``supervisor_restarts``), so chaos tests can assert fault handling exactly
(see ``tests/test_chaos.py`` and ``scripts/chaos_soak.py``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple
from opencv_facerecognizer_tpu.utils import metric_names as mn

#: substrings (lowercased) that mark an exception as outage-shaped and
#: therefore worth retrying. "unavailable" covers both the real PJRT
#: fast-fail string and faults.InjectedUnavailableError; the rest are the
#: transport/tunnel shapes seen in the round-4 outage logs.
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline exceeded",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "resource exhausted",
    "internal: failed to",
)


def is_transient_error(exc: BaseException) -> bool:
    """True when ``exc`` looks like a backend/transport outage (retry it),
    False for permanent errors like a shape mismatch from a poisoned batch
    (retrying those can never succeed — abandon the batch instead)."""
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(marker in text for marker in _TRANSIENT_MARKERS)


@dataclass
class ResiliencePolicy:
    """Steady-state failure-handling knobs for ``RecognizerService``.

    Defaults are serving-shaped (seconds-scale deadlines, a few retries);
    chaos tests shrink them to keep wall time short.
    """

    #: retry attempts per batch after the first dispatch failure; the
    #: batch is abandoned (``batches_failed``) once exhausted.
    dispatch_retries: int = 3
    #: exponential backoff between dispatch retries: base * mult^attempt,
    #: capped at ``backoff_max_s``. The wait keeps draining readbacks.
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    backoff_multiplier: float = 2.0
    #: a dispatched batch whose readback is not ready this long after
    #: dispatch is dead-lettered (``batches_dead_lettered``) and the loop
    #: moves on — the hang-mode outage must cost one deadline, not wedge
    #: the service. Sized for a tunneled backend (~100 ms readback floor
    #: plus multi-second H2D contention behind gallery uploads).
    readback_deadline_s: float = 30.0
    #: consecutive failed dispatch *attempts* (across batches) that flip
    #: the service into degraded mode.
    degraded_after: int = 3
    #: on entering degraded mode, run the bounded subprocess backend probe
    #: (``utils.backend_probe``) and attach its verdict to the status
    #: message; a dead backend then triggers ``cpu_fallback`` when wired.
    probe_backend_on_degraded: bool = False
    #: deadline for that probe. None (default) defers to
    #: ``backend_probe.probe_for_recovery``'s resolution — the
    #: ``OCVF_RECOVERY_PROBE_TIMEOUT_S`` env var, else 15 s (shorter than
    #: startup's 60 s: the serving loop is already failing, so a quick
    #: verdict beats a precise one). Set explicitly to override both.
    probe_timeout_s: Optional[float] = None

    def backoff(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based)."""
        return min(self.backoff_max_s,
                   self.backoff_base_s * self.backoff_multiplier ** attempt)


@dataclass
class BrownoutPolicy:
    """Load-shedding degradation knobs for ``RecognizerService`` (the
    overload layer's §2 — see the recognizer docstring's "Overload
    protection" block).

    The controller watches a queue-wait EWMA (frame enqueue -> batch pop:
    the term that balloons first when offered load exceeds capacity).
    Crossing ``queue_wait_s`` raises the brownout level (1, then 2 at the
    next dwell); dropping below ``exit_ratio * queue_wait_s`` lowers it.
    The asymmetric thresholds plus the ``dwell_s`` minimum between
    transitions are the hysteresis — a load hovering at the threshold must
    not flap the service in and out of brownout every batch.

    Degradation per level:

    - level 1: bulk-priority frames are skip-``bulk_skip`` shed at intake
      (keep one of every ``bulk_skip``), reason ``brownout``;
    - level 2 (``max_level``): ALL bulk frames shed at intake, and the
      dispatch bucket ladder is capped at its smallest rung — an
      oversized partial batch is trimmed to one small fast device call
      (the trimmed frames shed with reason ``brownout``), keeping
      per-batch latency low for the interactive traffic that remains.

    Interactive frames are never shed by the INTAKE skip (levels 1-2 drop
    only bulk there). The level-2 ladder trim, however, is class-blind: a
    popped batch carries no per-frame priority, so when interactive
    traffic alone still overfills the smallest bucket (bulk is already
    gone at intake by then), the trimmed excess is interactive — counted
    and journaled under the same explicit ``brownout`` reason so
    producers can retry. Keeping interactive loss at zero is the
    admission bound's job (``max_inflight_frames`` with its interactive
    reserve), not the brownout's.
    """

    #: queue-wait EWMA (seconds) above which the brownout level rises.
    queue_wait_s: float = 0.25
    #: the level drops once the EWMA falls below ``exit_ratio *
    #: queue_wait_s`` (hysteresis band).
    exit_ratio: float = 0.5
    #: minimum seconds between level changes (both directions).
    dwell_s: float = 0.5
    #: highest level (2 = shed-all-bulk + capped bucket ladder).
    max_level: int = 2
    #: level 1 keeps one of every ``bulk_skip`` bulk frames.
    bulk_skip: int = 2
    #: EWMA smoothing for the queue-wait signal.
    ewma_alpha: float = 0.3


class DurabilityDegradedError(RuntimeError):
    """An enrollment was refused CLOSED because durability is degraded
    (sustained WAL/storage failure or a critical disk watermark). The
    caller must surface an explicit refusal status — never acknowledge,
    never queue for later: the acknowledged == fsync-durable promise is
    exactly what degraded mode exists to protect."""


#: disk-pressure severity codes (the ``disk_pressure_state`` gauge).
DISK_OK, DISK_WARN, DISK_CRITICAL = 0, 1, 2


class DurabilityMonitor:
    """Degraded-durability state machine + disk-pressure watermarks for
    one writer's state dir (module docstring; README "Degraded-durability
    runbook").

    Construction attaches to the ``StateLifecycle``: ``state.durability``
    becomes this monitor, so ``append_enrollment`` refuses closed while
    degraded and feeds WAL append outcomes back in (from outside the
    enroll lock — the flip publishes a status and emits a span, I/O that
    must never run under durability locks).

    Two independent triggers flip ``armed -> durability_degraded``:

    - ``degraded_after`` CONSECUTIVE strict-WAL-append ``OSError``s
      (ENOSPC/EIO — each one already refused its enrollment; the flip
      stops new appends from even being attempted);
    - the disk falling below ``low_watermark_bytes / critical_divisor``
      free (the preemptive flip: refuse BEFORE ENOSPC tears a line).

    While degraded: serving and read traffic continue untouched;
    enrollments are refused closed (``enrollments_refused_degraded``,
    status reason ``durability_degraded``); sinks wired via
    ``attach_sinks`` shed with exact per-sink ``*_shed`` counters.

    Recovery is PROBED, never assumed: every ``probe_interval_s`` the
    monitor durably writes + fsyncs + unlinks a tmp file in the state
    dir (through the same fault injector as every durable path, so chaos
    controls it). A probe success while the disk is above the critical
    watermark re-arms durability — lifecycle span, ``durability_rearms``,
    and a ``durability_restored`` status announcement.

    Disk pressure rides the same tick: a ``statvfs`` free-bytes gauge
    (``disk_free_bytes``) and the ``disk_pressure_state`` 0/1/2 gauge.
    Crossing into warn fires ONE preemptive WAL compaction (forced
    checkpoint — its success truncates the WAL) and one retention shrink
    (checkpoint keep / flight-dump keep / journal backups to their
    floor) per pressure episode; recovery above the watermark restores
    the original retention. The ``slo.disk_free_objective`` gauge SLO
    reads the same free-bytes probe, so /health and /prom carry the
    pressure verdict without a second statvfs.
    """

    PROBE_NAME = ".durability_probe"

    def __init__(self, state, metrics=None, tracer=None,
                 degraded_after: int = 3,
                 probe_interval_s: float = 5.0,
                 low_watermark_bytes: int = 0,
                 critical_divisor: float = 6.0,
                 publish: Optional[Callable[[dict], None]] = None,
                 fault_injector=None,
                 statvfs_fn=None):
        self.state = state
        self.metrics = metrics
        self.tracer = tracer
        self.degraded_after = max(1, int(degraded_after))
        self.probe_interval_s = float(probe_interval_s)
        self.low_watermark_bytes = max(0, int(low_watermark_bytes))
        self.critical_divisor = max(1.0, float(critical_divisor))
        #: status-announcement hook ({"status": ...} dicts). The service
        #: wires its ``_publish_status`` here at construction; bare
        #: lifecycles (chaos scenarios) may leave it None or capture it.
        self.publish = publish
        self._faults = fault_injector
        self._statvfs = statvfs_fn if statvfs_fn is not None else os.statvfs
        self._degraded = False
        self._degraded_reason: Optional[str] = None
        self._consecutive_wal_failures = 0
        self._consecutive_lease_failures = 0
        self._disk_state = DISK_OK
        self._retention_shrunk = False
        self._saved_retention: dict = {}
        #: sinks registered by attach_sinks, kept for retention shrink.
        self._journal = None
        self._tracer_sink = None
        self._lock = threading.Lock()
        #: one tick cycle at a time (non-blocking claim, like the SLO
        #: monitor's evaluation lock): the serving loop and the background
        #: thread both tick, and the watermark transitions +
        #: shrink/restore bookkeeping are check-then-act — two threads
        #: crossing the warn watermark together would double-fire the
        #: compaction and save the already-shrunk retention values as
        #: "originals", pinning retention at the floor forever.
        self._tick_lock = threading.Lock()
        self._last_tick_t = 0.0
        self._free_bytes: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if state is not None:
            state.durability = self
        if self.metrics is not None:
            self.metrics.set_gauge(mn.DURABILITY_STATE, 0)

    # ---- readers (any thread) ----

    @property
    def degraded(self) -> bool:
        return self._degraded

    @property
    def degraded_reason(self) -> Optional[str]:
        return self._degraded_reason

    @property
    def disk_state(self) -> int:
        return self._disk_state

    def free_bytes(self) -> float:
        """Last observed free bytes on the state volume (refreshing once
        when never sampled) — the ``disk_free_objective`` probe, shared
        with the gauge so /health and /prom agree without a second
        statvfs per evaluation."""
        if self._free_bytes is None:
            self._sample_disk()
        return float(self._free_bytes if self._free_bytes is not None
                     else float("inf"))

    def status(self) -> dict:
        return {
            "degraded": self._degraded,
            "reason": self._degraded_reason,
            "consecutive_wal_failures": self._consecutive_wal_failures,
            "consecutive_lease_failures": self._consecutive_lease_failures,
            "disk_state": self._disk_state,
            "free_bytes": self._free_bytes,
            "low_watermark_bytes": self.low_watermark_bytes,
            "retention_shrunk": self._retention_shrunk,
        }

    # ---- sink wiring ----

    def attach_sinks(self, journal=None, span_sink=None, tracer=None) -> None:
        """Point the non-critical sinks' shed hooks at this monitor: while
        degraded they drop writes with exact per-sink accounting instead
        of one swallowed OSError per attempt. The WAL is deliberately NOT
        sheddable — its failures are the signal."""
        shed = lambda: self._degraded  # noqa: E731 — the one-line contract
        if journal is not None:
            journal.shed_fn = shed
            self._journal = journal
        if span_sink is not None:
            span_sink.shed_fn = shed
        if tracer is not None:
            tracer.shed_fn = shed
            self._tracer_sink = tracer

    # ---- WAL outcome feed (called by StateLifecycle, outside its locks) --

    def note_wal_failure(self, exc: BaseException) -> None:
        """One strict WAL append failed with a storage-shaped error. At
        ``degraded_after`` consecutive failures the writer flips."""
        with self._lock:
            self._consecutive_wal_failures += 1
            should_flip = (not self._degraded
                           and self._consecutive_wal_failures
                           >= self.degraded_after)
        if should_flip:
            self._flip_degraded(
                "wal_append_failures",
                error=repr(exc),
                consecutive=self._consecutive_wal_failures)

    def note_wal_success(self) -> None:
        with self._lock:
            self._consecutive_wal_failures = 0

    # ---- transitions ----

    def _flip_degraded(self, reason: str, **detail) -> None:
        with self._lock:
            if self._degraded:
                return
            self._degraded = True
            self._degraded_reason = reason
        if self.metrics is not None:
            self.metrics.incr(mn.DURABILITY_DEGRADED_TRANSITIONS)
            self.metrics.set_gauge(mn.DURABILITY_STATE, 1)
        logging.getLogger(__name__).error(
            "durability DEGRADED (%s): enrollments refused closed, "
            "serving continues, recovery probe armed (%s)", reason, detail)
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "durability",
                             topic=_lifecycle_topic(),
                             from_state="armed", to_state="degraded",
                             reason=reason, **detail)
        self._announce({"status": "durability_degraded", "reason": reason,
                        **detail})

    def _rearm(self) -> None:
        with self._lock:
            if not self._degraded:
                return
            self._degraded = False
            reason = self._degraded_reason
            self._degraded_reason = None
            self._consecutive_wal_failures = 0
        if self.metrics is not None:
            self.metrics.incr(mn.DURABILITY_REARMS)
            self.metrics.set_gauge(mn.DURABILITY_STATE, 0)
        logging.getLogger(__name__).warning(
            "durability RE-ARMED (probe write+fsync succeeded; was "
            "degraded: %s) — enrollments accepted again", reason)
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "durability",
                             topic=_lifecycle_topic(),
                             from_state="degraded", to_state="armed",
                             was=reason)
        self._announce({"status": "durability_restored", "was": reason})

    def _announce(self, status: dict) -> None:
        publish = self.publish
        if publish is None:
            return
        try:
            publish(status)
        except Exception:  # noqa: BLE001 — a dead transport never blocks a flip
            logging.getLogger(__name__).exception(
                "durability status publish failed")

    # ---- the recovery probe ----

    def probe_now(self) -> bool:
        """One durable tmp-file write + fsync + unlink in the state dir —
        proof the volume accepts durable writes again. Routed through the
        shared storage fault boundary so chaos owns the verdict. A
        success while the disk sits above the critical watermark re-arms
        degraded durability."""
        if self.metrics is not None:
            self.metrics.incr(mn.DURABILITY_PROBES)
        path = os.path.join(getattr(self.state, "state_dir", "."),
                            self.PROBE_NAME)
        try:
            if self._faults is not None:
                self._faults.on_storage("durability_probe")
            with open(path, "wb") as fh:  # ocvf-lint: disable=non-atomic-write -- the probe file IS the test: its only purpose is this write+fsync round trip, it is unlinked on the next line, and a torn remnant carries no state (readers never exist)
                fh.write(b"probe\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.unlink(path)
        except OSError:
            if self.metrics is not None:
                self.metrics.incr(mn.DURABILITY_PROBE_FAILURES)
            return False
        if self._degraded and self._disk_state < DISK_CRITICAL:
            self._rearm()
        return True

    # ---- split-brain lease guard (ISSUE 16) ----

    def _check_lease(self) -> None:
        """Writer split-brain safety: a writer whose state dir (home of
        ``writer.lease``) has become unreachable can no longer PROVE it
        still owns enrollment — a healed partition may find a second
        writer leased over the same volume. After ``degraded_after``
        consecutive reachability failures the writer flips
        durability-degraded, which fails enrollments closed (the same
        machinery as WAL failures) while recognition serving continues.
        Recovery rides the existing probe: a durable write+fsync in the
        state dir is strictly stronger proof than this stat."""
        state_dir = getattr(self.state, "state_dir", None)
        if state_dir is None:
            return
        try:
            if self._faults is not None:
                self._faults.on_storage_read("lease_check")
            os.stat(state_dir)
        except OSError:
            if self.metrics is not None:
                self.metrics.incr(mn.DURABILITY_LEASE_CHECK_FAILURES)
            with self._lock:
                self._consecutive_lease_failures += 1
                should_flip = (not self._degraded
                               and self._consecutive_lease_failures
                               >= self.degraded_after)
            if should_flip:
                self._flip_degraded(
                    "lease_unreachable",
                    consecutive=self._consecutive_lease_failures)
            return
        with self._lock:
            self._consecutive_lease_failures = 0

    # ---- disk-pressure watermarks ----

    def _sample_disk(self) -> None:
        state_dir = getattr(self.state, "state_dir", None)
        if state_dir is None:
            return
        try:
            st = self._statvfs(state_dir)
            self._free_bytes = float(st.f_bavail) * float(st.f_frsize)
        except OSError:
            return  # keep the last sample; the probe owns hard failures
        if self.metrics is not None:
            self.metrics.set_gauge(mn.DISK_FREE_BYTES, self._free_bytes)

    def _check_watermarks(self) -> None:
        if not self.low_watermark_bytes or self._free_bytes is None:
            return
        free = self._free_bytes
        critical_at = self.low_watermark_bytes / self.critical_divisor
        new_state = (DISK_CRITICAL if free < critical_at
                     else DISK_WARN if free < self.low_watermark_bytes
                     else DISK_OK)
        prev = self._disk_state
        self._disk_state = new_state
        if self.metrics is not None:
            self.metrics.set_gauge(mn.DISK_PRESSURE_STATE, new_state)
        if new_state >= DISK_WARN and prev < DISK_WARN:
            self._on_disk_warn(free)
        if new_state >= DISK_CRITICAL and not self._degraded:
            # Preempt ENOSPC: flip BEFORE a torn WAL line ever lands. The
            # probe still owns recovery — and refuses to re-arm while the
            # disk stays critical.
            self._flip_degraded("disk_critical", free_bytes=int(free),
                                low_watermark_bytes=self.low_watermark_bytes)
        if new_state == DISK_OK and prev > DISK_OK:
            self._restore_retention()

    def _on_disk_warn(self, free: float) -> None:
        """Entering warn: one preemptive WAL compaction (forced
        checkpoint — success truncates the WAL below its sequence) and
        one retention shrink per pressure episode."""
        logging.getLogger(__name__).warning(
            "disk pressure: %d bytes free < %d watermark — forcing a "
            "checkpoint (WAL compaction) and shrinking retention",
            int(free), self.low_watermark_bytes)
        if self.state is not None:
            try:
                self.state.maybe_checkpoint(force=True)
                if self.metrics is not None:
                    self.metrics.incr(mn.DISK_PRESSURE_COMPACTIONS)
            except Exception:  # noqa: BLE001 — pressure relief is best-effort
                logging.getLogger(__name__).exception(
                    "disk-pressure checkpoint trigger failed")
        self._shrink_retention()
        self._announce({"status": "disk_pressure", "state": "warn",
                        "free_bytes": int(free),
                        "low_watermark_bytes": self.low_watermark_bytes})

    def _shrink_retention(self) -> None:
        if self._retention_shrunk:
            return
        self._retention_shrunk = True
        store = getattr(self.state, "store", None)
        if store is not None:
            self._saved_retention["store_keep"] = store.keep
            store.keep = 1
        tracer = self._tracer_sink if self._tracer_sink is not None else self.tracer
        if tracer is not None and hasattr(tracer, "keep_dumps"):
            self._saved_retention["keep_dumps"] = tracer.keep_dumps
            tracer.keep_dumps = 1
        if self._journal is not None:
            self._saved_retention["journal_backups"] = self._journal.backups
            self._journal.backups = 0
        if self.metrics is not None:
            self.metrics.incr(mn.DISK_PRESSURE_RETENTION_SHRINKS)

    def _restore_retention(self) -> None:
        if not self._retention_shrunk:
            return
        self._retention_shrunk = False
        store = getattr(self.state, "store", None)
        if store is not None and "store_keep" in self._saved_retention:
            store.keep = self._saved_retention["store_keep"]
        tracer = self._tracer_sink if self._tracer_sink is not None else self.tracer
        if tracer is not None and "keep_dumps" in self._saved_retention:
            tracer.keep_dumps = self._saved_retention["keep_dumps"]
        if self._journal is not None and "journal_backups" in self._saved_retention:
            self._journal.backups = self._saved_retention["journal_backups"]
        self._saved_retention.clear()

    # ---- ticking ----

    def tick(self, force: bool = False, probe: bool = False) -> None:
        """Interval-gated cycle (the serving loop calls this beside
        ``state.tick()``; the non-due path is one clock read): refresh the
        disk gauges + watermark actions, and — only with ``probe`` and
        while degraded — run the recovery probe. The serving loop always
        calls with ``probe=False``: the probe is a blocking write+fsync
        against a disk already known broken, and a hung device would
        wedge the very serving this machine promises to keep running —
        probing belongs exclusively to the background thread
        (``start()``, which the service runs alongside the loop).
        Concurrent tickers are serialized by a NON-BLOCKING claim — the
        loser skips, nobody waits, and the watermark transitions fire
        exactly once."""
        now = time.monotonic()
        if not force and now - self._last_tick_t < self.probe_interval_s:
            return
        if not self._tick_lock.acquire(blocking=False):
            return  # another ticker owns this cycle
        try:
            self._last_tick_t = now
            self._sample_disk()
            self._check_watermarks()
            should_probe = probe and self._degraded
        finally:
            self._tick_lock.release()
        if probe:
            # Split-brain guard (ISSUE 16): like the recovery probe, real
            # I/O against a possibly-dead volume — background thread only,
            # outside the claim.
            self._check_lease()
        if should_probe:
            # Outside the claim: the probe is file I/O (possibly a slow
            # fsync) and must never hold the tick lock against the
            # serving loop's cheap watermark refresh.
            self.probe_now()

    def start(self) -> None:
        """Background ticker (daemon): keeps watermarks fresh and the
        recovery probe running even when the serving loop is busy riding
        out a slow_fsync. Idempotent."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="durability-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(timeout=max(0.05, self.probe_interval_s)):
            try:
                self.tick(force=True, probe=True)
            except Exception:  # noqa: BLE001 — the monitor thread must live
                logging.getLogger(__name__).exception(
                    "durability monitor tick failed")


def _lifecycle_topic() -> str:
    from opencv_facerecognizer_tpu.utils.tracing import LIFECYCLE_TOPIC

    return LIFECYCLE_TOPIC


def rebuild_pipeline_on_cpu(service) -> None:
    """The stock ``cpu_fallback`` hook: rebuild the service's recognition
    pipeline on host CPU devices when degraded mode finds the accelerator
    dead (``ocvf-recognize --probe-on-degraded`` wires this).

    Reuses the live nets/params as-is, copies the gallery through the
    host-mirror ``snapshot``/``load_snapshot`` path onto a fresh
    single-CPU-device mesh (no device readback — the dead accelerator may
    not answer one), and swaps ``service.pipeline`` between batches. The
    swap itself pays the ladder's XLA compiles (prewarm, below) so the
    recompile watchdog stays armed; after that the job is degraded
    (CPU-speed) but serving. Raises when no CPU backend exists — the
    caller treats a failed fallback as best-effort (``cpu_fallback:
    False`` in the degraded status)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from opencv_facerecognizer_tpu.parallel.gallery import ShardedGallery
    from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline

    old = service.pipeline
    cpu_device = jax.devices("cpu")[0]
    cpu_mesh = Mesh(np.asarray([cpu_device]).reshape(1, 1),
                    (DP_AXIS, TP_AXIS))
    # default_device(cpu) for the WHOLE rebuild: gallery init and snapshot
    # install run jnp ops whose placement would otherwise go through the
    # default (dead) accelerator backend — hanging or raising inside the
    # very hook that exists to escape it.
    with jax.default_device(cpu_device):
        gallery = ShardedGallery(capacity=old.gallery.capacity,
                                 dim=old.gallery.dim, mesh=cpu_mesh,
                                 store_dtype=old.gallery.store_dtype)
        gallery.load_snapshot(*old.gallery.snapshot())
    pipeline = RecognitionPipeline(old.detector, old.embed_net,
                                   old.embed_params, gallery,
                                   face_size=old.face_size, top_k=old.top_k)
    # The chaos boundary FOLLOWS the swap — moved, not copied: an armed
    # injector left on the abandoned pipeline would leak faults into the
    # next service built on it (production leaves both None).
    pipeline.fault_injector = getattr(old, "fault_injector", None)
    old.fault_injector = None
    service.pipeline = pipeline
    # Keep the recompile watchdog armed ACROSS the swap by prewarming the
    # fresh pipeline's ladder here: its jit cache starts empty, and those
    # by-design compiles are the documented cost of the fallback — paid up
    # front, not smeared over the first serving dispatches. Simply
    # disarming instead would silence the watchdog for the rest of the
    # process, losing exactly the mid-serving-compile coverage it exists
    # for. If the prewarm itself fails, disarm and keep serving — a CPU
    # fallback that serves with a quiet watchdog beats one that crashed
    # in its own escape hook.
    if service._warmed:
        try:
            with jax.default_device(cpu_device):
                pipeline.prewarm_batch_shapes(
                    service._bucket_ladder, service.batcher.frame_shape,
                    service.batcher.dtype)
        except Exception:  # noqa: BLE001 — fallback must finish
            logging.getLogger(__name__).exception(
                "CPU-fallback ladder prewarm failed; "
                "recompile watchdog disarmed")
            service._warmed = False
    # The enrolment embed graph must follow too: the service's jitted
    # chunk embedder would otherwise keep dispatching on the dead
    # accelerator (see RecognizerService._run_embed_chunk).
    service._embed_device = cpu_device
    # And the ingest uploader: its explicit per-dispatch device_put would
    # otherwise keep committing frames to the dead default device —
    # every batch failing against the very fallback built to survive it.
    if getattr(service, "ingest", None) is not None:
        service.ingest.upload_device = cpu_device


class ServiceSupervisor:
    """Restart a crashed serving loop with the last-known-good gallery.

    The service loop already survives per-batch failures; what it cannot
    survive is an exception escaping the loop body itself (a connector
    handler raising inside ``publish``, a batcher bug, ...) — the thread
    dies and frames pile up unserved. The supervisor watches for that
    crash flag and restarts the loop, first restoring the gallery from the
    snapshot taken at the last ``checkpoint()`` — start, every committed
    change (the supervisor subscribes to ``STATUS_TOPIC`` and checkpoints
    on ``enrolled``/``reloaded``), plus any point the operator/app calls
    it — via the existing ``reload_gallery``/``swap_from`` double-buffer
    path. A crash mid-enrolment cannot leave a half-written gallery
    serving, and a crash AFTER a committed enrolment rolls back only to
    that commit, not to startup.

    Restarts are bounded: after ``max_restarts`` the supervisor publishes
    ``{"status": "supervisor_gave_up"}`` and stops intervening (a crash
    loop almost always means a real bug, and flapping hides it).

    With a durable state lifecycle wired (``state=``,
    ``runtime.state_store``), the in-memory snapshot stays the primary
    in-process restore and the lifecycle's checkpoint+WAL recovery is the
    fallback when that snapshot is missing or fails to install — the same
    path a full process restart takes, so both rungs of the restart
    ladder land on consistent state.

    Honest limitation — the **call-time hang**: a backend that blocks
    forever *inside* the dispatch call itself (not the readback) cannot be
    preempted from within the process — the serving thread is stuck in
    native code, alive, so neither the readback deadline nor the crash
    watchdog fires. The supervisor's stall watchdog at least SURFACES that
    shape: frames pending with zero progress for ``stall_warn_s`` publishes
    ``{"status": "stalled"}`` (``supervisor_stalls``), the signal a
    deploy-level supervisor (systemd/k8s liveness) needs to restart the
    process. In-process, prevention stays with the bounded *startup* probe
    (``utils.backend_probe``).
    """

    def __init__(self, service, max_restarts: int = 5,
                 poll_interval_s: float = 0.2,
                 restart_backoff_s: float = 0.1,
                 commit_wait_s: float = 30.0,
                 state=None):
        self.service = service
        self.max_restarts = int(max_restarts)
        self.poll_interval_s = float(poll_interval_s)
        self.restart_backoff_s = float(restart_backoff_s)
        #: optional runtime.state_store.StateLifecycle — the DURABLE
        #: last-known-good. The in-memory snapshot stays the primary
        #: restore (cheap, no disk); the lifecycle is the fallback when
        #: that snapshot is missing or its install fails, and the source
        #: of process-restart recovery either way.
        self.state = state
        #: bounded wait for async-grow staged rows to land before a
        #: post-commit checkpoint (a snapshot taken mid-grow would MISS
        #: the rows the commit announced); on timeout the previous
        #: checkpoint is kept — never a partial one.
        self.commit_wait_s = float(commit_wait_s)
        #: frames pending with zero processing progress for this long
        #: publishes a one-shot ``stalled`` status (see class docstring:
        #: the call-time-hang shape can only be surfaced, not fixed,
        #: in-process).
        self.stall_warn_s = 60.0
        self.restarts = 0
        self.gave_up = False
        self._last_processed = -1.0
        self._last_progress_t = time.monotonic()
        self._stall_warned = False
        #: last SLO health state seen by the watchdog (edge detection for
        #: the health status publishes; -1 = not yet observed).
        self._last_health = -1
        self._snapshot: Optional[Tuple] = None
        self._snapshot_wal_seq: Optional[int] = None
        self._snapshot_version: Optional[int] = None
        self._subject_names: Optional[list] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False

    # ---- lifecycle ----

    def start(self, warmup: bool = True) -> None:
        """Start the service (if not already running) and the monitor."""
        if self._thread is not None:
            return
        self.service.start(warmup=warmup)
        self.checkpoint()
        # Every committed gallery change (a finished enrolment, a retrain
        # reload) advances last-known-good: a later crash must roll back
        # only half-done work, not every subject enrolled since startup.
        # Registered as a DIRECT service hook, not a STATUS_TOPIC
        # subscription: wire connectors publish outbound only and never
        # dispatch their own publishes locally, so a subscription would
        # silently never fire in production.
        self.service.commit_hooks.append(self._on_commit)
        self._running = True
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name="service-supervisor")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._on_commit in self.service.commit_hooks:
            self.service.commit_hooks.remove(self._on_commit)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.service.stop()

    def checkpoint(self) -> None:
        """Record the current gallery + subject names as last-known-good.
        Host-mirror copies only — no device readback (the axon backend's
        sync-poll trap, see runtime.recognizer). With a state lifecycle
        wired, the snapshot is STAMPED with the WAL sequence it covers —
        a restore then replays the acknowledged tail past the stamp, so
        rolling back to this snapshot can never desync the gallery from
        the WAL coverage the next durable checkpoint claims."""
        if self.state is not None:
            (self._snapshot_wal_seq, self._snapshot,
             self._subject_names,
             self._snapshot_version) = self.state.stamped_snapshot()
        else:
            self._snapshot_wal_seq = None
            self._snapshot = self.service.pipeline.gallery.snapshot()
            self._subject_names = list(self.service.subject_names)
            self._snapshot_version = getattr(
                self.service.pipeline.gallery, "embedder_version", None)
        self.service.metrics.incr(mn.SUPERVISOR_CHECKPOINTS)

    def _on_commit(self) -> None:
        """Advance last-known-good after a committed gallery change. Runs
        on whatever thread committed (enrolment worker, reload caller) —
        checkpoint() only copies host mirrors, so that is cheap there.
        Under ``async_grow`` the committing add() may have only STAGED its
        rows; wait (bounded) for the grow to land them, and on timeout
        keep the previous checkpoint rather than capture a snapshot that
        silently misses the rows this commit announced."""
        if not self._running:
            return
        gallery = self.service.pipeline.gallery
        wait_ready = getattr(gallery, "wait_ready", None)
        if wait_ready is not None and not wait_ready(timeout=self.commit_wait_s):
            logging.getLogger(__name__).warning(
                "post-commit checkpoint skipped: staged rows not landed "
                "within %.0fs; keeping previous snapshot", self.commit_wait_s)
            return
        self.checkpoint()

    # ---- the watchdog ----

    def _monitor(self) -> None:
        from opencv_facerecognizer_tpu.runtime.recognizer import STATUS_TOPIC

        service = self.service
        while self._running:
            time.sleep(self.poll_interval_s)
            self._check_stall(service, STATUS_TOPIC)
            self._check_health(service, STATUS_TOPIC)
            if not service.loop_crashed or not service._running:
                continue
            if not service.restart_pending():
                # Crash flagged but every serving-side thread (dispatch
                # loop AND readback worker) is still unwinding (e.g. a
                # slow 'crashed' status subscriber): restart_loop would
                # no-op on the alive threads, so acting now would burn a
                # phantom restart (and desync restarts vs loop_crashes,
                # which the soak treats as an unsupervised crash). Wait
                # for a thread to actually exit.
                continue
            if self.restarts >= self.max_restarts:
                if not self.gave_up:
                    self.gave_up = True
                    service.metrics.incr(mn.SUPERVISOR_GAVE_UP)
                    self._publish(STATUS_TOPIC, {
                        "status": "supervisor_gave_up",
                        "restarts": self.restarts,
                    })
                continue
            self.restarts += 1
            # Flight-recorder dump BEFORE the restore/restart mutate
            # anything: the rings hold exactly what was in flight when
            # the loop died — the evidence a post-mortem needs.
            tracer = getattr(service, "tracer", None)
            if tracer is not None:
                tracer.dump("supervisor_restart",
                            extra={"restarts": self.restarts,
                                   "ledger": service.ledger()})
            try:
                self._restore_gallery()
            except Exception:
                logging.getLogger(__name__).exception(
                    "gallery restore failed; trying durable state")
                if not self._restore_durable():
                    logging.getLogger(__name__).exception(
                        "durable restore unavailable; restarting with "
                        "current state")
            service.restart_loop()
            # Counter flips only once the restore + restart are done, so a
            # watcher seeing it can rely on the last-known-good gallery
            # already being live (the chaos test's synchronization point).
            service.metrics.incr(mn.SUPERVISOR_RESTARTS)
            self._publish(STATUS_TOPIC, {
                "status": "supervisor_restart",
                "restarts": self.restarts,
            })
            time.sleep(self.restart_backoff_s)

    def _check_stall(self, service, status_topic: str) -> None:
        """One-shot ``stalled`` announcement when frames are pending but
        the loop has made no progress for ``stall_warn_s`` — the
        call-time-hang signature a deploy-level liveness check keys on.
        Progress is ANY batch outcome, including abandons and dead-letters:
        a loop actively surviving a fast-fail outage (every batch retried
        then abandoned) is degraded, not stalled — flagging it would make
        the deploy layer kill a process that is degrading gracefully."""
        m = service.metrics
        processed = (m.counter("frames_processed")
                     + m.counter("batches_failed")
                     + m.counter("batches_dead_lettered"))
        now = time.monotonic()
        if processed != self._last_processed:
            self._last_processed = processed
            self._last_progress_t = now
            self._stall_warned = False
            return
        if (not self._stall_warned
                and service.batcher.pending > 0
                and now - self._last_progress_t > self.stall_warn_s):
            self._stall_warned = True
            service.metrics.incr(mn.SUPERVISOR_STALLS)
            # Wedge detection is a flight-recorder trigger: the dump is
            # the answer to "what was in flight when the soak wedged" —
            # the spans of every undrained frame/batch at stall time.
            tracer = getattr(service, "tracer", None)
            if tracer is not None:
                tracer.dump("wedge_stall", extra={
                    "pending_frames": service.batcher.pending,
                    "seconds_without_progress":
                        round(now - self._last_progress_t, 1),
                    "ledger": service.ledger(),
                })
            self._publish(status_topic, {
                "status": "stalled",
                "pending_frames": service.batcher.pending,
                "seconds_without_progress": round(now - self._last_progress_t, 1),
            })

    def _check_health(self, service, status_topic: str) -> None:
        """Publish the SLO monitor's health transitions on the status
        topic — the supervisor is the component a deploy layer already
        listens to, so the health verdict rides the same channel as
        ``stalled``/``supervisor_restart``. Edge-triggered: one status per
        state change, carrying the per-objective burn rates, so an
        orchestrator can act (drain this replica, route around it)
        without polling ``/health``. The monitor itself owns evaluation,
        spans, gauges, and the critical flight dump; the supervisor only
        ANNOUNCES."""
        monitor = getattr(service, "slo", None)
        if monitor is None:
            return
        # Backstop tick before reading: the serving loop is the primary
        # ticker, but a wedged loop stops ticking — and a wedged loop is
        # exactly what the loop_liveness gauge exists to escalate. The
        # expo refresh thread also backstops, but expo is optional; the
        # supervisor's poll loop is the always-on ticker when supervised.
        # tick() is interval-gated and its evaluation claim is
        # non-blocking, so this is cheap and never double-evaluates.
        try:
            monitor.tick()
        except Exception:  # noqa: BLE001 — the watchdog thread must live
            logging.getLogger(__name__).exception(
                "supervisor slo backstop tick failed")
            service.metrics.incr(mn.SLO_TICK_ERRORS)
        state = monitor.state_code
        if state == self._last_health:
            return
        first = self._last_health < 0
        self._last_health = state
        if first and state == 0:
            return  # don't announce the boring initial "ok"
        verdict = monitor.verdict()
        self._publish(status_topic, {
            "status": "health",
            "state": monitor.state,
            "objectives": {
                name: obj.get("burn")
                for name, obj in verdict.get("objectives", {}).items()},
            "events": verdict.get("events", {}),
        })

    def _restore_gallery(self) -> None:
        if self._snapshot is None:
            # No in-memory last-known-good (possible when start() raced a
            # crash before its first checkpoint): fall back to the durable
            # lifecycle when one is wired.
            self._restore_durable()
            return
        service = self.service
        # Rows + embedder version re-install in ONE atomic publish: a
        # snapshot taken before a cutover restores the OLD version stamp
        # with the old-space rows (never old rows under the new stamp),
        # and replay_tail's version fence then keeps post-cutover records
        # from mixing in.
        service.pipeline.gallery.load_snapshot(
            *self._snapshot,
            embedder_version=getattr(self, "_snapshot_version", None))
        if self._subject_names is not None:
            # Same in-place trim/extend rule as the gallery restore: names
            # enrolled after the checkpoint have no committed rows anymore.
            service.subject_names[:] = self._subject_names
        if self.state is not None and self._snapshot_wal_seq is not None:
            # Enrollments ACKNOWLEDGED after this snapshot was stamped
            # (crash raced the commit hook) must come back: without the
            # tail replay they would vanish from serving and the next
            # durable checkpoint would truncate their WAL records.
            self.state.replay_tail(self._snapshot_wal_seq)
        # load_snapshot invalidated any attached IVF quantizer (derived
        # state): schedule the background retrain here, or a match-heavy
        # workload with no further enrolments (the other poke site) stays
        # pinned to the linear exact scan forever.
        poke = getattr(service.pipeline.gallery, "_poke_quantizer", None)
        if poke is not None:
            poke()

    def _restore_durable(self) -> bool:
        """Fallback restore from the durable state lifecycle (checkpoint +
        WAL replay) — the same path a process restart takes. Returns True
        when it ran."""
        if self.state is None:
            return False
        try:
            self.state.recover(self.service.pipeline.gallery,
                               self.service.subject_names)
            self.service.metrics.incr(mn.SUPERVISOR_DURABLE_RESTORES)
            return True
        except Exception:  # noqa: BLE001 — restore is best-effort here
            logging.getLogger(__name__).exception("durable restore failed")
            return False

    def _publish(self, topic: str, message: dict) -> None:
        try:
            self.service.connector.publish(topic, message)
        except Exception:  # a dead transport must not kill the watchdog
            logging.getLogger(__name__).exception("supervisor publish failed")
