"""Multi-replica serving: single-writer / N-reader replication over the
PR-4 state lifecycle, plus a health-routed topic router (ROADMAP #4 —
"the refactor that unlocks millions of users").

Everything before this module is one process. The durable state layer
(``runtime.state_store``) already made the gallery *shared state in
waiting*: enrollment is an always-fsync write-ahead log (ack == durable)
and checkpoints are atomic, checksummed, and stamped with the WAL
sequence they cover. This module adds the replication protocol that lets
N recognizer replicas serve that one logical gallery:

- **WriterLease** — an fcntl ``flock`` lockfile (``<state-dir>/
  writer.lease``) serializing enrollment ownership. Exactly one process
  may hold it; a second writer **fails closed** at startup
  (``WriterLeaseHeldError``) instead of silently interleaving WAL
  appends — flock conflicts across processes AND across file
  descriptors within one process, and the kernel releases it on any
  death, so a crashed writer never needs a lease-breaking tool. The
  file's JSON contents (pid/host/ts) are diagnostics only; the flock is
  the truth.
- **WALTailer** — a strictly read-only incremental reader of the
  enrollment WAL. It advances only past complete (newline-terminated)
  lines, so a writer's in-progress append is never half-read; an
  unparseable line (a torn tail the writer later sealed) is skipped
  exactly as replay skips it. Compaction (``truncate_below`` atomically
  swaps in a rewritten file) is detected by inode change / size
  shrinkage on the **open fd** (stat-then-open would race the swap) and
  answered by re-reading from offset zero — row-level dedup is the
  consumer's job, keyed on the monotonic ``seq``.
- **ReadReplica** — the tailer composed with a live gallery: initial
  sync loads the newest readable checkpoint (read-only — corrupt files
  are skipped and counted, never quarantined: renames belong to the
  writer) and anchors ``applied_seq`` at its published ``wal_seq``, then
  every ``poll()`` applies new WAL rows through the same
  ``ShardedGallery.add`` route WAL replay uses (IVF incremental
  assignment and epoch fencing ride along unchanged). A WAL reopen
  whose newest checkpoint has advanced past ``applied_seq`` re-anchors
  via a full resync; an abort tombstone arriving *after* its enroll was
  applied (the writer rolled back a failed apply) also forces a resync —
  a replica must never serve rows the writer's gallery never kept.
  ``replication_lag_rows`` / ``replication_lag_s`` gauges feed the SLO
  monitor (``runtime.slo.replication_lag_objective``) so a stale
  replica's brownout composes with the existing controller.
- **TopicRouter** — a ``MiddlewareConnector`` that spreads camera
  topics across replicas with rendezvous (highest-random-weight)
  hashing: each topic hashes to a stable preference order over replica
  names, so adding/removing a replica only moves the topics that hashed
  to it. Per-replica admission budgets (token buckets) spill an
  over-budget topic to its next-preferred replica; health-based
  failover (each replica's PR-9 ``/health`` verdict, via an in-process
  probe or HTTP) excludes critical replicas from routing — rendezvous
  re-routes their topics automatically, the flight recorder fires, and
  recovery reinstates them. Results/status from every replica fan back
  in to the router's own subscribers.

Consistency contract: a read replica serves a *prefix* of the
acknowledged enrollment history — every row it holds was fsync-durable
on the writer before the replica applied it, and once its lag reaches
zero it holds exactly the acknowledged history (the replication chaos
scenario asserts bit-equal rows across writer death and replica death).
Staleness is bounded by the poll interval plus WAL append visibility;
it is surfaced, never hidden (the lag gauges + SLO objective).
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import socket
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from opencv_facerecognizer_tpu.runtime.admission import TokenBucket
from opencv_facerecognizer_tpu.runtime.connector import MiddlewareConnector
from opencv_facerecognizer_tpu.runtime.state_store import (
    CheckpointCorruptError,
    CheckpointVersionError,
    StateLifecycle,
    _decode_checkpoint,
    decode_enroll_record,
    read_checkpoint_header,
    scan_checkpoint_files,
)
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.tracing import LIFECYCLE_TOPIC

LEASE_NAME = "writer.lease"

logger = logging.getLogger(__name__)


class WriterLeaseHeldError(RuntimeError):
    """Another process holds the writer lease — the second writer MUST
    fail closed (split-brain WAL appends would interleave sequences and
    silently corrupt every replica's replay)."""


class WriterLease:
    """Exclusive enrollment-ownership lease over one ``--state-dir``
    (module docstring). ``acquire`` is non-blocking by design: a blocked
    writer waiting for a lease it may never get is indistinguishable
    from a hang — the operator should see the conflict immediately."""

    def __init__(self, state_dir: str, metrics=None):
        self.state_dir = str(state_dir)
        self.path = os.path.join(self.state_dir, LEASE_NAME)
        self.metrics = metrics
        self._fd: Optional[int] = None

    @property
    def held(self) -> bool:
        return self._fd is not None

    def acquire(self) -> "WriterLease":
        """Take the lease or raise ``WriterLeaseHeldError``. Idempotent
        while held. The holder info is written AFTER the flock wins —
        never clobber a live holder's diagnostics with a loser's."""
        if self._fd is not None:
            return self
        os.makedirs(self.state_dir, exist_ok=True)
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            holder = ""
            try:
                holder = os.read(fd, 4096).decode("utf-8", "replace").strip()
            except OSError:
                pass
            try:
                os.close(fd)
            except OSError:
                pass
            if self.metrics is not None:
                self.metrics.incr(mn.REPLICATION_LEASE_CONFLICTS)
            raise WriterLeaseHeldError(
                f"writer lease {self.path} is held"
                + (f" (holder: {holder})" if holder else "")
                + " — refusing to start a second writer (split-brain "
                "fails closed)")
        info = {"pid": os.getpid(), "host": socket.gethostname(),
                "acquired_ts": time.time()}
        try:
            os.ftruncate(fd, 0)
            os.write(fd, (json.dumps(info) + "\n").encode("utf-8"))
            os.fsync(fd)
        except OSError:
            # Diagnostics only — the flock (already won) is the guard.
            logger.exception("writer lease holder info write failed")
        self._fd = fd
        if self.metrics is not None:
            self.metrics.incr(mn.REPLICATION_LEASE_ACQUIRED)
        return self

    def release(self) -> None:
        """Drop the lease. The file stays behind (its contents are stale
        diagnostics) — the flock vanishes with the fd, which is also what
        happens automatically when the holding process dies."""
        fd, self._fd = self._fd, None
        if fd is None:
            return
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        except OSError:
            pass
        try:
            os.close(fd)
        except OSError:
            pass

    close = release

    def __enter__(self) -> "WriterLease":
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class WALTailer:
    """Strictly read-only incremental reader of one WAL file (module
    docstring). Single-consumer by design — ``poll`` runs on the owning
    replica's serving-loop thread (or the verifier's main thread), so it
    needs no lock and never holds one across file I/O."""

    def __init__(self, path: str, metrics=None, fault_injector=None):
        self.path = str(path)
        self.metrics = metrics
        #: chaos hook: the ``storage`` boundary's read side (read_error)
        #: fires at the top of every poll — an injected EIO lands on the
        #: exact counted poll-error path a dying shared disk produces.
        self._faults = fault_injector
        self._offset = 0
        self._inode: Optional[int] = None
        self.reopens = 0
        self.malformed_lines = 0

    def reset(self) -> None:
        """Forget the read position — the next ``poll`` re-reads the file
        from the beginning (resync path; dedup is the consumer's job)."""
        self._offset = 0
        self._inode = None

    def poll(self) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
        """Read every COMPLETE line appended since the last poll; returns
        ``(records, info)`` where records are the parsed JSON objects in
        file order and ``info`` flags ``reopened`` (compaction swapped a
        new file in — earlier rows may have been truncated away) and
        ``partial`` (an in-progress append is pending past the offset).
        Unparseable / non-object lines (torn remnants) are skipped and
        counted, exactly like replay."""
        info: Dict[str, Any] = {"reopened": False, "partial": False}
        try:
            if self._faults is not None:
                self._faults.on_storage_read("tailer_poll")
            fd = os.open(self.path, os.O_RDONLY)
        except FileNotFoundError:
            info["missing"] = True
            return [], info
        except OSError:
            if self.metrics is not None:
                self.metrics.incr(mn.REPLICATION_POLL_ERRORS)
            info["error"] = True
            return [], info
        try:
            st = os.fstat(fd)
            if (self._inode is not None
                    and (st.st_ino != self._inode
                         or st.st_size < self._offset)):
                # truncate_below installed a rewritten file (new inode),
                # or the file shrank under us: restart from zero — the
                # consumer dedups by seq.
                self._offset = 0
                self.reopens += 1
                info["reopened"] = True
                if self.metrics is not None:
                    self.metrics.incr(mn.REPLICATION_WAL_REOPENS)
            self._inode = st.st_ino
            if st.st_size <= self._offset:
                return [], info
            os.lseek(fd, self._offset, os.SEEK_SET)
            chunks = []
            while True:
                chunk = os.read(fd, 1 << 20)
                if not chunk:
                    break
                chunks.append(chunk)
            blob = b"".join(chunks)
        finally:
            os.close(fd)
        nl = blob.rfind(b"\n")
        if nl < 0:
            # A single in-progress append with no newline yet: the writer
            # is mid-write (or crashed torn — the seal at its next open
            # will terminate it). Never advance past incomplete bytes.
            info["partial"] = True
            return [], info
        self._offset += nl + 1
        if nl + 1 < len(blob):
            info["partial"] = True
        records: List[Dict[str, Any]] = []
        for line in blob[:nl].split(b"\n"):
            text = line.strip()
            if not text:
                continue
            try:
                record = json.loads(text.decode("utf-8", errors="replace"))
            except (json.JSONDecodeError, UnicodeDecodeError):
                record = None
            if not isinstance(record, dict):
                # Sealed torn remnant (an unacknowledged crash leftover),
                # skipped exactly as replay skips it.
                self.malformed_lines += 1
                continue
            records.append(record)
        return records, info


def load_latest_checkpoint_readonly(ckpt_dir: str, metrics=None):
    """Newest fully-verified checkpoint as ``(header, state_dict, path)``
    or None — the read replica's sibling of
    ``CheckpointStore.load_latest`` that NEVER mutates the directory:
    corrupt files are logged + counted and skipped (quarantine renames
    belong to the writer; a reader racing a writer's in-progress rename
    must not move files under it)."""
    from flax import serialization as flax_serialization

    for _seq, path in scan_checkpoint_files(ckpt_dir):
        try:
            with open(path, "rb") as fh:
                header, payload = _decode_checkpoint(fh.read(), path)
            state = flax_serialization.msgpack_restore(payload)
            emb = np.asarray(state["emb"], np.float32)
            lab = np.asarray(state["lab"], np.int32)
            val = np.asarray(state["val"], bool)
        except CheckpointVersionError as exc:
            logger.warning("replica: newer-format checkpoint skipped: %s",
                           exc)
            continue
        except (OSError, CheckpointCorruptError, KeyError, TypeError,
                ValueError) as exc:
            logger.warning("replica: unreadable checkpoint skipped "
                           "(read-only, not quarantined): %s: %r", path, exc)
            if metrics is not None:
                metrics.incr(mn.CHECKPOINTS_CORRUPT)
            continue
        return header, {"emb": emb, "lab": lab, "val": val}, path
    return None


def newest_checkpoint_info(ckpt_dir: str) -> Tuple[int, int]:
    """``(wal_seq, embedder_version)`` of the newest header-verified
    checkpoint (``(0, 0)`` when none): the re-anchor point a replica
    compares its ``applied_seq`` against after every WAL compaction, and
    — during a rollout — the signal that the writer's post-cutover
    checkpoint landed. Header-only reads — a few KB per file, never the
    payload."""
    for _seq, path in scan_checkpoint_files(ckpt_dir):
        try:
            header = read_checkpoint_header(path)
        except (OSError, CheckpointCorruptError, CheckpointVersionError):
            continue
        meta = header.get("meta", {})
        return (int(meta.get("wal_seq", 0)),
                int(meta.get("embedder_version", 1)))
    return 0, 0


def newest_checkpoint_wal_seq(ckpt_dir: str) -> int:
    """Back-compat form of ``newest_checkpoint_info`` (the verifier's
    ``--follow`` mode keys on the sequence alone)."""
    return newest_checkpoint_info(ckpt_dir)[0]


class ReadReplica:
    """One read replica's view of a shared ``--state-dir`` (module
    docstring): checkpoint anchor + WAL tail applied into a live gallery
    between batches. Single-threaded by contract — ``poll()`` runs on the
    owning serving loop (``RecognizerService(replica=...)`` ticks it), so
    gallery application interleaves with dispatch exactly like the
    writer's own enrolment applies do."""

    def __init__(self, state_dir: str, gallery, subject_names: Optional[list] = None,
                 metrics=None, tracer=None, poll_interval_s: float = 0.05,
                 name: str = "replica", fault_injector=None):
        self.state_dir = str(state_dir)
        self.wal_path = os.path.join(self.state_dir, "enroll.wal")
        self.ckpt_dir = os.path.join(self.state_dir, "checkpoints")
        self.gallery = gallery
        self.subject_names = subject_names if subject_names is not None else []
        self.metrics = metrics
        self.tracer = tracer
        self.poll_interval_s = float(poll_interval_s)
        self.name = str(name)
        self.tailer = WALTailer(self.wal_path, metrics=metrics,
                                fault_injector=fault_injector)
        #: highest WAL seq applied to (or covered by the checkpoint under)
        #: the local gallery.
        self.applied_seq = 0
        #: highest WAL seq OBSERVED in the file (applied or not) — the
        #: lag_rows numerator.
        self.seen_seq = 0
        self.anchor_checkpoint: Optional[str] = None
        self.lag_rows = 0
        self.lag_s = 0.0
        self._synced = False
        self._resync_needed = False
        self._last_poll_t = 0.0
        #: the wal_seq the last resync anchored at, and the abort seqs
        #: already accounted for: a compaction reopen re-reads the whole
        #: file, so surviving tombstones for rows this replica only ever
        #: BURNED (never applied) come around again — without these two
        #: filters every such re-read would force a needless full resync
        #: (checkpoint reload on the serving thread) and a false
        #: aborts_after_apply count.
        self._anchor_seq = 0
        self._aborted_seen: set = set()
        #: embedder version this replica's gallery currently serves
        #: (anchored from the checkpoint header; rollout fencing).
        self.embedder_version = int(getattr(gallery, "embedder_version", 1))
        #: a cutover fence was observed in the tail: ``{"to_version",
        #: "seq"}``. While set, NOTHING is applied — the replica keeps
        #: serving its pure old-version gallery and re-anchors only once
        #: the writer's NEW-version checkpoint lands (the PR-10 resync
        #: path pointed at the post-cutover state). Applying new-space
        #: rows to the old gallery, or half-resyncing onto a pre-cutover
        #: checkpoint, would both violate the no-mixing invariant.
        self._await_cutover: Optional[Dict[str, int]] = None
        #: optional drain hook, called ``on_resync("begin"|"end")`` around
        #: every full re-anchor — the fleet wiring points it at
        #: ``TopicRouter.set_cordon`` so this replica's topics route to
        #: peers while the checkpoint load runs on the serving thread and
        #: fleet-wide completed-frames never blanks through a cutover.
        self.on_resync: Optional[Callable[[str], None]] = None
        #: read-only model-registry view (``runtime.registry.ModelRegistry``
        #: with ``readonly=True``) + change hook. A ``registry_cutover``
        #: fence parks the tail exactly like an embedder cutover fence;
        #: resync re-reads the manifest after every re-anchor, so the
        #: post-swap model set becomes visible here only across that
        #: re-anchor — a replica never serves a mixed set. The hook gets
        #: the new stamp dict (fleet wiring points it at the service's
        #: ``flush_model_caches``).
        self.registry = None
        self.on_registry_change: Optional[
            Callable[[Dict[str, int]], None]] = None

    # ---- sync ----

    def resync(self) -> Dict[str, Any]:
        """Full re-anchor: newest readable checkpoint -> ``load_snapshot``
        (or an empty gallery when none exists yet), ``applied_seq`` = its
        published ``wal_seq``, then one full WAL read applying every
        surviving row past the anchor — abort tombstones are honored
        across the whole file here, exactly like writer-side replay."""
        report = {"checkpoint": None, "applied_records": 0,
                  "applied_rows": 0}
        if self.on_resync is not None:
            # Planned drain window: the router cordons this replica so
            # its topics route to peers while the checkpoint load runs
            # on the serving thread (completed-frames continuity through
            # a cutover re-anchor).
            try:
                self.on_resync("begin")
            except Exception:  # noqa: BLE001 — a drain hook bug must not block the resync itself
                logger.exception("replica %s on_resync(begin) failed",
                                 self.name)
        try:
            loaded = load_latest_checkpoint_readonly(self.ckpt_dir,
                                                     metrics=self.metrics)
            prior_version = self.embedder_version
            if loaded is not None:
                header, state, path = loaded
                meta = header.get("meta", {})
                dim = int(meta.get("dim", -1))
                if dim != self.gallery.dim:
                    raise ValueError(
                        f"replica {self.name}: state dir {self.state_dir!r} "
                        f"holds dim={dim} checkpoints but the gallery is "
                        f"dim={self.gallery.dim} — wrong --state-dir for this "
                        f"model?")
                size = int(meta.get("size", int(state["val"].sum())))
                ckpt_version = int(meta.get("embedder_version", 1))
                self.gallery.load_snapshot(state["emb"], state["lab"],
                                           state["val"], size,
                                           embedder_version=ckpt_version)
                self.subject_names[:] = [str(s) for s
                                         in meta.get("subject_names", [])]
                self.applied_seq = int(meta.get("wal_seq", 0))
                self.anchor_checkpoint = path
                self.embedder_version = ckpt_version
                report["checkpoint"] = path
                if ckpt_version != prior_version:
                    # The rollout re-anchor: this replica just crossed the
                    # version fence onto the writer's post-cutover state.
                    if self.metrics is not None:
                        self.metrics.incr(mn.ROLLOUT_REPLICA_REANCHORS)
                    logger.info("replica %s re-anchored onto embedder "
                                "v%d (was v%d)", self.name, ckpt_version,
                                prior_version)
            else:
                # No checkpoint yet (a brand-new writer): replay the whole
                # WAL onto an empty gallery.
                if self.gallery.size:
                    self.gallery.reset()
                self.subject_names[:] = []
                self.applied_seq = 0
                self.anchor_checkpoint = None
            if self.registry is not None:
                # Registry re-anchor: the manifest this replica serves
                # moves only here, never mid-tail — same no-mixing rule
                # as the gallery snapshot above.
                prior_stamp = self.registry.stamp()
                self.registry.reload()
                new_stamp = self.registry.stamp()
                if new_stamp != prior_stamp:
                    logger.info("replica %s re-anchored registry %s -> %s",
                                self.name, prior_stamp, new_stamp)
                    if self.metrics is not None:
                        self.metrics.incr(mn.ROLLOUT_REPLICA_REANCHORS)
                    if self.on_registry_change is not None:
                        try:
                            self.on_registry_change(dict(new_stamp))
                        except Exception:  # noqa: BLE001 — cache hook only
                            logger.exception(
                                "replica %s on_registry_change failed",
                                self.name)
            self.seen_seq = max(self.seen_seq, self.applied_seq)
            self._anchor_seq = self.applied_seq
            self._aborted_seen.clear()
            self._await_cutover = None
            if self.metrics is not None:
                self.metrics.set_gauge(mn.ROLLOUT_REPLICA_AWAITING, 0)
            self.tailer.reset()
            records, _info = self.tailer.poll()
            applied = self._apply_records(records)
            report["applied_records"] = applied["records"]
            report["applied_rows"] = applied["rows"]
            self._synced = True
            self._resync_needed = False
            self._update_lag()
        finally:
            if self.on_resync is not None:
                try:
                    self.on_resync("end")
                except Exception:  # noqa: BLE001 — see begin
                    logger.exception("replica %s on_resync(end) failed",
                                     self.name)
        if self.metrics is not None:
            self.metrics.incr(mn.REPLICATION_RESYNCS)
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "wal_tail",
                             topic=LIFECYCLE_TOPIC, replica=self.name,
                             resync=True, applied_seq=self.applied_seq,
                             rows=applied["rows"],
                             embedder_version=self.embedder_version,
                             checkpoint=report["checkpoint"])
        return report

    # ---- the tail loop ----

    def poll(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """Apply whatever the WAL grew since the last poll (interval-
        gated; ``force`` bypasses the gate). Called by the serving loop
        between batches; the non-due path is one clock read. Returns the
        poll summary, or None when not due."""
        now = time.monotonic()
        if not force and now - self._last_poll_t < self.poll_interval_s:
            return None
        self._last_poll_t = now
        if self.metrics is not None:
            self.metrics.incr(mn.REPLICATION_POLLS)
        if not self._synced or self._resync_needed:
            return self.resync()
        if self._await_cutover is not None:
            # Parked on a cutover fence: keep serving the pure old-version
            # gallery and watch (header-only, cheap) for the writer's
            # post-cutover checkpoint; re-anchor the moment one lands. The
            # unpark key is the SEQUENCE, not the awaited version: any
            # checkpoint whose wal_seq covers the fence was snapshotted
            # after the swap (version + wal_seq are read in one critical
            # section on the writer), so it necessarily carries the
            # post-cutover version — or a LATER one, when cutovers
            # stacked because the first post-cutover checkpoint failed;
            # waiting for the exact awaited version would strand the
            # replica on stale rows forever in that supported sequence.
            # The tail still advances ``seen_seq`` so the lag gauges stay
            # honest about the backlog building up behind the fence.
            anchor_seq, _anchor_version = newest_checkpoint_info(
                self.ckpt_dir)
            if anchor_seq >= self._await_cutover["seq"]:
                return self.resync()
            records, _info = self.tailer.poll()
            for record in records:
                seq = record.get("seq")
                if isinstance(seq, (int, float)):
                    self.seen_seq = max(self.seen_seq, int(seq))
            self._update_lag()
            return {"records": 0, "rows": 0, "awaiting_version":
                    self._await_cutover["to_version"]}
        records, info = self.tailer.poll()
        if info["reopened"]:
            # Compaction: rows <= the newest checkpoint's wal_seq were
            # truncated away. If that anchor has moved past what we
            # applied, the truncated rows are ones we never saw — only
            # the checkpoint still has them. Re-anchor fully.
            anchor = newest_checkpoint_wal_seq(self.ckpt_dir)
            if anchor > self.applied_seq:
                return self.resync()
        applied = self._apply_records(records)
        if self._resync_needed:
            # An abort tombstone arrived for a row we already applied:
            # the local gallery holds rows the writer rolled back. Rebuild
            # from the checkpoint rather than serve phantoms.
            return self.resync()
        self._update_lag()
        if applied["rows"] and self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "wal_tail",
                             topic=LIFECYCLE_TOPIC, replica=self.name,
                             resync=False, rows=applied["rows"],
                             records=applied["records"],
                             applied_seq=self.applied_seq,
                             lag_s=round(self.lag_s, 4))
        return applied

    def _apply_records(self, records: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Apply one poll batch in file order with batch-local abort
        filtering (the writer appends an abort right after a failed
        apply, so enroll+abort almost always land in one read). An abort
        whose enroll was applied in an EARLIER poll flags a resync."""
        applied_at_entry = self.applied_seq
        aborted = set()
        for record in records:
            seq = record.get("seq")
            if record.get("kind") == "abort" and isinstance(seq, (int, float)):
                seq = int(seq)
                aborted.add(seq)
                # "After apply" only when this tombstone is genuinely NEW
                # (not a compaction-reopen replay of one we already
                # burned/handled) and not covered by the resync anchor
                # (the checkpoint never held the aborted row). Only then
                # may the local gallery hold a row the writer rolled
                # back, and only then is a resync warranted.
                if (seq <= applied_at_entry and seq > self._anchor_seq
                        and seq not in self._aborted_seen):
                    logger.warning(
                        "replica %s: abort for already-applied seq %d — "
                        "scheduling resync", self.name, seq)
                    if self.metrics is not None:
                        self.metrics.incr(mn.REPLICATION_ABORTS_AFTER_APPLY)
                    self._resync_needed = True
                self._aborted_seen.add(seq)
                if len(self._aborted_seen) > 1 << 16:
                    # Pathological abort volume: resync rather than grow
                    # the dedup set unboundedly (the anchor advances, so
                    # the set restarts empty and covered tombstones stop
                    # mattering).
                    self._resync_needed = True
        out = {"records": 0, "rows": 0}
        oldest_applied_ts: Optional[float] = None
        for record in records:
            seq = record.get("seq")
            if isinstance(seq, (int, float)):
                self.seen_seq = max(self.seen_seq, int(seq))
            kind = record.get("kind")
            if kind == "cutover" and isinstance(seq, (int, float)):
                seq = int(seq)
                if seq <= self.applied_seq:
                    continue  # covered by the anchor checkpoint: burned
                to_version = int(record.get("to_version", 0))
                if to_version == int(getattr(self.gallery,
                                             "embedder_version",
                                             self.embedder_version)):
                    # Already on the target version (resync landed on the
                    # post-cutover checkpoint whose wal_seq trails the
                    # fence — cannot happen with the writer's ordering,
                    # but burn it rather than park forever).
                    self.applied_seq = seq
                    continue
                # Park on the fence: nothing past it is applicable until
                # the writer's new-version checkpoint lands (poll watches
                # for it). Everything already applied is pure old-version
                # — serving continues un-blanked.
                self._await_cutover = {"to_version": to_version, "seq": seq}
                if self.metrics is not None:
                    self.metrics.set_gauge(mn.ROLLOUT_REPLICA_AWAITING, 1)
                logger.info(
                    "replica %s: cutover fence seq %d -> embedder v%d "
                    "observed; holding at v%d until the new-version "
                    "checkpoint lands", self.name, seq, to_version,
                    self.embedder_version)
                break
            if kind == "registry_cutover" and isinstance(seq, (int, float)):
                seq = int(seq)
                if seq <= self.applied_seq:
                    continue  # covered by the anchor checkpoint: burned
                role = str(record.get("role", "?"))
                to_version = int(record.get("to_version", 0))
                if (self.registry is not None
                        and self.registry.version(role) >= to_version):
                    # The manifest visible here already covers this swap
                    # (resync landed past it): burn the fence.
                    self.applied_seq = seq
                    continue
                # Park exactly like an embedder cutover fence: the swap
                # becomes visible only across the re-anchor onto the
                # writer's post-swap checkpoint (or the post-recovery
                # one, when the swap was abandoned — either way the
                # checkpoint's wal_seq covers this fence).
                self._await_cutover = {"to_version": to_version, "seq": seq,
                                       "role": role}
                if self.metrics is not None:
                    self.metrics.set_gauge(mn.ROLLOUT_REPLICA_AWAITING, 1)
                logger.info(
                    "replica %s: registry fence seq %d -> %s v%d observed; "
                    "holding until a covering checkpoint lands",
                    self.name, seq, role, to_version)
                break
            if kind == "registry_abort" and isinstance(seq, (int, float)):
                # Abandoned-swap tombstone (recovery appended it; its seq
                # IS the voided fence's seq). Nothing to apply — the
                # fence it voids parks the tail until a covering
                # checkpoint lands, and the re-anchor reads the manifest
                # the abandon left at the old version.
                continue
            if kind != "enroll" or not isinstance(seq, (int, float)):
                continue
            seq = int(seq)
            if seq <= self.applied_seq:
                continue  # dedup: already applied or checkpoint-covered
            if seq in aborted:
                self.applied_seq = seq  # tombstoned: burn it, apply nothing
                continue
            if int(record.get("embedder_version", 1)) != int(
                    getattr(self.gallery, "embedder_version",
                            self.embedder_version)):
                # Version fence without a visible cutover record (e.g. a
                # late-start replica whose first tail read begins past a
                # compacted fence): never apply across it — park exactly
                # like the explicit fence and wait for the matching
                # checkpoint.
                self._await_cutover = {
                    "to_version": int(record.get("embedder_version", 1)),
                    "seq": seq}
                if self.metrics is not None:
                    self.metrics.set_gauge(mn.ROLLOUT_REPLICA_AWAITING, 1)
                logger.warning(
                    "replica %s: enroll seq %d carries embedder v%s but "
                    "the gallery serves v%d — holding for a matching "
                    "checkpoint (version fence)", self.name, seq,
                    record.get("embedder_version"), self.embedder_version)
                break
            row_stamp = record.get("registry")
            if (isinstance(row_stamp, dict) and self.registry is not None
                    and any(int(v) != self.registry.version(str(r))
                            for r, v in row_stamp.items())):
                # Registry fence without a visible registry_cutover
                # record (late-start tail past a compacted fence): park
                # rather than apply rows produced under a model set this
                # replica hasn't re-anchored onto.
                self._await_cutover = {"to_version": 0, "seq": seq,
                                       "registry": dict(row_stamp)}
                if self.metrics is not None:
                    self.metrics.set_gauge(mn.ROLLOUT_REPLICA_AWAITING, 1)
                logger.warning(
                    "replica %s: enroll seq %d carries registry stamp %s "
                    "but the manifest here serves %s — holding for a "
                    "covering checkpoint (registry fence)", self.name,
                    seq, row_stamp, self.registry.stamp())
                break
            decoded = decode_enroll_record(record)
            if decoded is None:
                # A parseable record failing crc/base64 was acknowledged
                # and is now unreadable — count it loudly; the row cannot
                # be applied (real loss is the verifier's verdict).
                if self.metrics is not None:
                    self.metrics.incr(mn.REPLICATION_CORRUPT_RECORDS)
                logger.error("replica %s: corrupt acked WAL record seq %d",
                             self.name, seq)
                self.applied_seq = seq
                continue
            self.gallery.add(decoded["embeddings"], decoded["labels_np"])
            StateLifecycle._grow_names(self.subject_names, decoded)
            self.applied_seq = seq
            out["records"] += 1
            out["rows"] += int(decoded["n"])
            ts = record.get("ts")
            if isinstance(ts, (int, float)) and oldest_applied_ts is None:
                oldest_applied_ts = float(ts)
        if out["rows"]:
            if self.metrics is not None:
                self.metrics.incr(mn.REPLICATION_RECORDS_APPLIED,
                                  out["records"])
                self.metrics.incr(mn.REPLICATION_ROWS_APPLIED, out["rows"])
            if oldest_applied_ts is not None:
                # Age of the oldest row at the moment it became visible
                # here: the honest staleness sample (0 once caught up).
                self.lag_s = max(0.0, time.time() - oldest_applied_ts)
        else:
            self.lag_s = 0.0
        return out

    def _update_lag(self) -> None:
        self.lag_rows = max(0, self.seen_seq - self.applied_seq)
        if self.metrics is not None:
            self.metrics.set_gauge(mn.REPLICATION_LAG_ROWS, self.lag_rows)
            self.metrics.set_gauge(mn.REPLICATION_LAG_S,
                                   round(self.lag_s, 4))

    def stats(self) -> Dict[str, Any]:
        return {"name": self.name, "applied_seq": self.applied_seq,
                "seen_seq": self.seen_seq, "lag_rows": self.lag_rows,
                "lag_s": round(self.lag_s, 4),
                "wal_reopens": self.tailer.reopens,
                "anchor_checkpoint": self.anchor_checkpoint,
                "embedder_version": self.embedder_version,
                "registry": (self.registry.stamp()
                             if self.registry is not None else None),
                "awaiting_cutover": (dict(self._await_cutover)
                                     if self._await_cutover else None),
                "gallery_size": int(self.gallery.size)}


# ---- health probes ---------------------------------------------------------


def service_health_probe(service) -> Callable[[], int]:
    """In-process health: critical when the service stopped or crashed,
    else the SLO monitor's state code (ok when no monitor is wired) —
    the same verdict ``/health`` serves, read without HTTP."""
    from opencv_facerecognizer_tpu.runtime.slo import STATE_CRITICAL, STATE_OK

    def probe() -> int:
        if service.loop_crashed or not service._running:
            return STATE_CRITICAL
        monitor = getattr(service, "slo", None)
        return monitor.state_code if monitor is not None else STATE_OK

    return probe


def http_health_probe(url: str, timeout_s: float = 2.0) -> Callable[[], int]:
    """Probe a replica's PR-9 ``GET /health`` endpoint: 503 reads as
    critical (the endpoint's contract — load balancers key on the status
    alone), 200 reads the JSON ``state_code`` (ok when absent). Any other
    failure raises — the router counts it and fails the replica closed."""
    import urllib.error
    import urllib.request

    def probe() -> int:
        from opencv_facerecognizer_tpu.runtime.slo import STATE_CRITICAL

        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                body = resp.read(1 << 16)
        except urllib.error.HTTPError as exc:
            if exc.code == 503:
                return STATE_CRITICAL
            raise
        try:
            return int(json.loads(body.decode("utf-8")).get("state_code", 0))
        except (json.JSONDecodeError, UnicodeDecodeError, TypeError,
                ValueError, AttributeError):
            return 0  # 200 with an unparseable body: reachable == serving

    return probe


# ---- the topic router ------------------------------------------------------


class ReplicaHandle:
    """One routable replica: a connector to reach it, an optional health
    probe (callable returning a ``runtime.slo`` state code; raising reads
    as down), and an optional per-replica admission budget (frames/s
    token bucket — over-budget topics spill to their next-preferred
    replica instead of overrunning this one)."""

    def __init__(self, name: str, connector: MiddlewareConnector,
                 health_fn: Optional[Callable[[], int]] = None,
                 budget_fps: Optional[float] = None,
                 budget_burst_s: float = 1.0, writer: bool = False):
        self.name = str(name)
        self.connector = connector
        self.health_fn = health_fn
        self.budget = (TokenBucket(float(budget_fps),
                                   float(budget_fps) * float(budget_burst_s))
                       if budget_fps else None)
        self.budget_fps = budget_fps
        #: enrollment owner: control-topic traffic routes here only.
        self.writer = bool(writer)
        self.healthy = True
        self.health_state = 0
        #: planned-drain flag (``TopicRouter.set_cordon``): excluded from
        #: rendezvous like an unhealthy replica, but deliberately — the
        #: rollout re-anchor drains a replica through its cutover without
        #: tripping failover machinery (no flight dump, no failover
        #: counter; the replica IS healthy, just busy re-anchoring).
        self.cordoned = False
        self.routed = 0
        self.last_probe_error: Optional[str] = None
        #: consecutive health-probe exceptions (capped; reset on the
        #: first clean probe) — the warn log fires only on the 0 -> 1
        #: transition, so a permanently-raising probe is one line, not
        #: one per cycle.
        self.probe_streak = 0
        #: link supervision (ISSUE 16): up = a heartbeat pong was seen
        #: within the router's ``link_deadline_s``. A down link excludes
        #: the replica from rendezvous exactly like bad health — the
        #: half-open-TCP case where the probe may still say "healthy".
        self.link_up = True
        self.last_pong_t: Optional[float] = None


class TopicRouter(MiddlewareConnector):
    """Rendezvous-hashing topic router over N replicas (module
    docstring). Producers ``publish(<camera topic>, frame_msg)`` into the
    router; each topic forwards to its chosen replica's ``FRAME_TOPIC``.
    Results and statuses from every replica fan back in to the router's
    own subscribers (status messages gain a ``replica`` field).

    Health checking runs on a dedicated daemon thread (probes may be
    HTTP — they must never block a producer's publish); the routing path
    only reads the per-replica ``healthy`` flags. A replica turning
    critical is a **failover**: counted, spanned (``failover``), flight-
    recorder dumped, and excluded from rendezvous until it recovers —
    nothing is queued in the router itself, so "drain + reroute" is
    simply the next frame hashing elsewhere while the replica's own
    supervisor/restart rung (unchanged) nurses it back.
    """

    def __init__(self, replicas: List[ReplicaHandle], metrics=None,
                 tracer=None, health_interval_s: float = 1.0,
                 fault_injector=None,
                 link_deadline_s: Optional[float] = None,
                 hedge_deadline_s: Optional[float] = None,
                 dedup_window: int = 4096):
        from opencv_facerecognizer_tpu.runtime.recognizer import (
            CONTROL_TOPIC, FRAME_TOPIC, LINK_PING_TOPIC, LINK_PONG_TOPIC,
            RESULT_TOPIC, STATUS_TOPIC,
        )

        self.metrics = metrics
        self.tracer = tracer
        self.health_interval_s = float(health_interval_s)
        #: transport fault boundary (ISSUE 16): when installed, every
        #: forward/heartbeat (send) and every fan-in/pong (recv) crosses
        #: ``on_transport(<replica name>, direction, ...)`` — the chaos
        #: layer cuts, slows, drops, duplicates and reorders the exact
        #: paths production messages travel.
        self._faults = fault_injector
        #: link supervision: None disables. When set, the health loop
        #: pings each replica every cycle and a replica whose last pong
        #: is older than the deadline is excluded from rendezvous until
        #: it pongs again — bounded-time detection of half-open links.
        self.link_deadline_s = (None if link_deadline_s is None
                                else float(link_deadline_s))
        #: interactive hedging: None disables. When set, an interactive
        #: frame with no result after the deadline is re-sent to its
        #: next rendezvous-preferred replica; first result wins, the
        #: loser is deduped at fan-in.
        self.hedge_deadline_s = (None if hedge_deadline_s is None
                                 else float(hedge_deadline_s))
        #: idempotent routing: size of the frame-id windows (stamped
        #: ``meta["_fid"]``, fan-in seen-set, hedge in-flight map).
        #: 0 disables stamping and result dedup entirely.
        self.dedup_window = max(0, int(dedup_window))
        self.frame_topic = FRAME_TOPIC
        self.control_topic = CONTROL_TOPIC
        self.status_topic = STATUS_TOPIC
        self.result_topic = RESULT_TOPIC
        self.link_ping_topic = LINK_PING_TOPIC
        self.link_pong_topic = LINK_PONG_TOPIC
        self._result_topics = (RESULT_TOPIC, STATUS_TOPIC)
        self._lock = threading.Lock()
        self._replicas: List[ReplicaHandle] = list(replicas)
        self._handlers: Dict[str, List] = {}
        #: topic -> (replica name, last routed monotonic t): the observed
        #: assignment map behind ``GET /replicas`` (bounded, best-effort).
        self._topic_map: Dict[str, Tuple[str, float]] = {}
        self._topic_map_max = 4096
        self._order_cache: Dict[str, List[ReplicaHandle]] = {}
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Hedge/dedup state, all under one lock separate from the
        # routing lock (fan-in runs on replica dispatch threads):
        # _inflight tracks un-answered interactive fids; _seen_results
        # is the first-result-wins window keyed by fid.
        self._hedge_lock = threading.Lock()
        self._fid_counter = 0
        self._inflight: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._seen_results: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._ping_counter = 0
        for handle in self._replicas:
            self._wire_replica(handle)
        self._set_replica_gauges()

    # ---- registry ----

    def _wire_replica(self, handle: ReplicaHandle) -> None:
        for topic in self._result_topics:
            handle.connector.subscribe(
                topic, self._make_fan_in(topic, handle.name))
        handle.connector.subscribe(self.link_pong_topic,
                                   self._make_pong(handle.name))

    def _transport_sink(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.incr(mn.TRANSPORT_FAULTS_PREFIX + kind)

    def _cross(self, name: str, direction: str,
               message: Dict[str, Any]) -> List[Dict[str, Any]]:
        """One transport-boundary crossing of the link to replica
        ``name`` — the identity list when no injector is installed."""
        if self._faults is None:
            return [message]
        return self._faults.on_transport(name, direction, message,
                                         sink=self._transport_sink)

    def _make_fan_in(self, topic: str, name: str):
        # Status messages are stamped with the originating replica (an
        # orchestrator needs to know WHICH replica went degraded); result
        # messages pass through untouched — keyed on the subscription
        # topic, never sniffed from the payload.  Both cross the
        # transport boundary (recv direction), and results additionally
        # pass the first-result-wins fid window — a duplicated delivery,
        # a failover re-send, or a hedge loser can never double-publish
        # upstream.
        stamp = topic == self.status_topic
        dedup = topic == self.result_topic

        def fan_in(_topic, message, _name=name, _up=topic, _stamp=stamp,
                   _dedup=dedup):
            for msg in self._cross(_name, "recv", message):
                if _stamp and isinstance(msg, dict):
                    msg = {**msg, "replica": _name}
                if _dedup and not self._admit_result(_name, msg):
                    continue
                self._dispatch_up(_up, msg)

        return fan_in

    def _make_pong(self, name: str):
        def on_pong(_topic, message, _name=name):
            if not self._cross(_name, "recv", message):
                return  # the pong died on the (injected) wire
            with self._lock:
                handle = next((r for r in self._replicas
                               if r.name == _name), None)
            if handle is None:
                return
            handle.last_pong_t = time.monotonic()
            if self.metrics is not None:
                self.metrics.incr(mn.LINK_HEARTBEATS_RECEIVED)

        return on_pong

    def replace_connector(self, name: str,
                          connector: MiddlewareConnector) -> None:
        """Point one replica at a fresh connector — the restarted-process
        case: the replica came back at a new address/connector, keeping
        its name (so rendezvous hands it exactly its old topics). Fan-in
        handlers are re-subscribed on the new connector; without that,
        results from the restarted replica would publish into a connector
        nobody listens to and silently vanish. The old connector's
        subscriptions are left behind on the dead object (harmless —
        nothing publishes into it again). Raises ``KeyError`` on an
        unknown name."""
        with self._lock:
            handle = next((r for r in self._replicas if r.name == name),
                          None)
        if handle is None:
            raise KeyError(f"no replica named {name!r}")
        handle.connector = connector
        self._wire_replica(handle)

    def _dispatch_up(self, topic: str, message: Dict[str, Any]) -> None:
        with self._lock:
            handlers = list(self._handlers.get(topic, ()))
        for handler in handlers:
            handler(topic, message)

    def subscribe(self, topic: str, handler) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def set_cordon(self, name: str, cordoned: bool) -> None:
        """Planned drain for one replica (the rollout re-anchor path):
        while cordoned, its topics rendezvous to their next-preferred
        replicas — serving never blanks through a checkpoint reload — and
        uncordoning hands exactly its own topics back (route-time
        filtering over the stable preference order, same property as
        health failover). Distinct from failover on purpose: no flight
        dump, no failover counter — this is choreography, not an
        incident. Raises ``KeyError`` on an unknown name."""
        with self._lock:
            handle = next((r for r in self._replicas if r.name == name),
                          None)
        if handle is None:
            raise KeyError(f"no replica named {name!r}")
        if cordoned and not handle.cordoned:
            if self.metrics is not None:
                self.metrics.incr(mn.ROUTER_CUTOVER_DRAINS)
            if self.tracer is not None:
                self.tracer.emit(self.tracer.new_trace(), "cutover_drain",
                                 topic=LIFECYCLE_TOPIC, replica=name)
        handle.cordoned = bool(cordoned)
        logger.info("router: replica %s %s", name,
                    "cordoned (draining topics to peers)" if cordoned
                    else "uncordoned (topics handed back)")

    def cordon_hook(self, name: str) -> Callable[[str], None]:
        """The ``ReadReplica.on_resync`` adapter: cordon on "begin",
        uncordon on "end" — one line of fleet wiring per replica."""
        def hook(phase: str, _name=name) -> None:
            self.set_cordon(_name, phase == "begin")

        return hook

    def replicas(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._replicas)

    def registry(self) -> List[Dict[str, Any]]:
        """Snapshot for ``GET /replicas``: per-replica health, routing
        stats and the recently-observed topic assignment."""
        from opencv_facerecognizer_tpu.runtime.slo import STATE_NAMES

        with self._lock:
            handles = list(self._replicas)
            topic_map = dict(self._topic_map)
        by_name: Dict[str, List[str]] = {}
        for topic, (name, _t) in topic_map.items():
            by_name.setdefault(name, []).append(topic)
        out = []
        for handle in handles:
            out.append({
                "name": handle.name,
                "writer": handle.writer,
                "healthy": handle.healthy,
                "cordoned": handle.cordoned,
                "health_state": STATE_NAMES[min(handle.health_state,
                                                len(STATE_NAMES) - 1)],
                "routed": handle.routed,
                "budget_fps": handle.budget_fps,
                "topics": sorted(by_name.get(handle.name, ())),
                "probe_error": handle.last_probe_error,
                "probe_streak": handle.probe_streak,
                "link_up": handle.link_up,
            })
        return out

    def _set_replica_gauges(self) -> None:
        if self.metrics is None:
            return
        with self._lock:
            handles = list(self._replicas)
            total = len(handles)
            healthy = sum(1 for r in handles if r.healthy)
        self.metrics.set_gauge(mn.ROUTER_REPLICAS, total)
        self.metrics.set_gauge(mn.ROUTER_HEALTHY_REPLICAS, healthy)
        if self.link_deadline_s is not None:
            down = sum(1 for r in handles if not r.link_up)
            self.metrics.set_gauge(mn.LINKS_DOWN, down)
            for handle in handles:
                self.metrics.set_gauge(mn.LINK_STATE_PREFIX + handle.name,
                                       1 if handle.link_up else 0)

    # ---- rendezvous routing ----

    @staticmethod
    def _weight(topic: str, name: str) -> int:
        import hashlib

        digest = hashlib.blake2b(f"{topic}\x00{name}".encode("utf-8"),
                                 digest_size=8).digest()
        return int.from_bytes(digest, "big")

    def _preference_order(self, topic: str) -> List[ReplicaHandle]:
        """Stable highest-random-weight order of ALL replicas for one
        topic (health filtering happens at route time, so a recovered
        replica reclaims exactly its own topics). Cached per topic,
        bounded; the replica set is fixed at construction, so cached
        orders never go stale."""
        with self._lock:
            order = self._order_cache.get(topic)
            if order is not None:
                return order
            order = sorted(self._replicas,
                           key=lambda r: self._weight(topic, r.name),
                           reverse=True)
            if len(self._order_cache) < self._topic_map_max:
                self._order_cache[topic] = order
            return order

    def route(self, topic: str) -> Optional[ReplicaHandle]:
        """The replica this topic forwards to right now: rendezvous
        order, filtered to healthy, spilled past exhausted budgets.
        Returns None (counted) when nothing can take it."""
        spilled = False
        for handle in self._preference_order(topic):
            if not handle.healthy or handle.cordoned or not handle.link_up:
                continue
            if handle.budget is not None and not handle.budget.try_acquire():
                spilled = True
                if self.metrics is not None:
                    self.metrics.incr(mn.ROUTER_BUDGET_SPILLS)
                continue
            return handle
        if self.metrics is not None:
            self.metrics.incr(mn.ROUTER_REJECTED_PREFIX
                              + ("budget" if spilled else "no_replica"))
        return None

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        if topic == self.control_topic:
            self._publish_control(message)
            return
        handle = self.route(topic)
        if handle is None:
            return
        message = self._stamp_fid(message)
        handle.routed += 1
        now = time.monotonic()
        with self._lock:
            if (topic in self._topic_map
                    or len(self._topic_map) < self._topic_map_max):
                self._topic_map[topic] = (handle.name, now)
        # Forward OUTSIDE the router lock: the replica connector may
        # dispatch handlers synchronously (FakeConnector) or write a
        # socket — neither belongs under a routing lock.
        forwarded = message
        if topic != self.frame_topic:
            forwarded = {**message, "_route_topic": topic}
        self._track_inflight(topic, forwarded, handle, now)
        self._forward(handle, forwarded)
        if self.metrics is not None:
            self.metrics.incr(mn.ROUTER_ROUTED)

    #: test/bench ergonomics, same as FakeConnector.
    inject = publish

    def _forward(self, handle: ReplicaHandle,
                 forwarded: Dict[str, Any]) -> None:
        for msg in self._cross(handle.name, "send", forwarded):
            handle.connector.publish(self.frame_topic, msg)

    # ---- idempotent routing: fid stamping + first-result-wins ----

    def _stamp_fid(self, message: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp a router-unique frame id into ``meta["_fid"]``.  The
        service round-trips ``meta`` into its results untouched, so the
        same id identifies the frame at replica intake (dedup window)
        and at result fan-in (first-result-wins) — retries, duplicated
        deliveries and hedge re-sends all carry the ORIGINAL id."""
        if self.dedup_window <= 0 or not isinstance(message, dict):
            return message
        meta = message.get("meta")
        if meta is not None and not isinstance(meta, dict):
            return message  # caller passthrough of a non-dict: hands off
        meta = dict(meta) if meta else {}
        if "_fid" in meta:
            return message  # a re-send keeps its identity
        with self._hedge_lock:
            self._fid_counter += 1
            meta["_fid"] = f"f{self._fid_counter}"
        return {**message, "meta": meta}

    def _track_inflight(self, topic: str, forwarded: Dict[str, Any],
                        handle: ReplicaHandle, now: float) -> None:
        """Record an interactive frame as hedge-eligible (no-op unless
        hedging is on): ``check_hedges`` re-sends it to the next
        preference if no result lands within the deadline."""
        if self.hedge_deadline_s is None or not isinstance(forwarded, dict):
            return
        if forwarded.get("priority") != "interactive":
            return
        meta = forwarded.get("meta")
        fid = meta.get("_fid") if isinstance(meta, dict) else None
        if fid is None:
            return
        with self._hedge_lock:
            self._inflight[fid] = {"topic": topic, "forwarded": forwarded,
                                   "t0": now, "replicas": [handle.name],
                                   "hedged": False}
            while len(self._inflight) > self.dedup_window:
                self._inflight.popitem(last=False)

    def _admit_result(self, name: str, message: Any) -> bool:
        """First-result-wins gate at fan-in: True admits the message
        upstream, False swallows it (counted).  Messages without a fid
        (dedup off, foreign producers) always pass."""
        if self.dedup_window <= 0 or not isinstance(message, dict):
            return True
        meta = message.get("meta")
        fid = meta.get("_fid") if isinstance(meta, dict) else None
        if fid is None:
            return True
        wasted = deduped = win = False
        with self._hedge_lock:
            seen = self._seen_results.get(fid)
            if seen is not None:
                deduped = True
                wasted = seen["hedged"]
            else:
                entry = self._inflight.pop(fid, None)
                hedged = bool(entry and entry["hedged"])
                self._seen_results[fid] = {"hedged": hedged,
                                           "winner": name}
                while len(self._seen_results) > self.dedup_window:
                    self._seen_results.popitem(last=False)
                win = hedged and bool(entry["replicas"]) \
                    and name != entry["replicas"][0]
        if self.metrics is not None:
            if deduped:
                self.metrics.incr(mn.ROUTER_RESULTS_DEDUPED)
                if wasted:
                    self.metrics.incr(mn.ROUTER_HEDGE_WASTED)
            elif win:
                self.metrics.incr(mn.ROUTER_HEDGE_WINS)
        return not deduped

    def check_hedges(self, now: Optional[float] = None) -> int:
        """Re-send past-deadline interactive frames to their next
        rendezvous-preferred replica (one hedge per frame).  Runs on the
        health thread; tests call it directly.  Returns hedges fired."""
        if self.hedge_deadline_s is None:
            return 0
        now = time.monotonic() if now is None else now
        to_send: List[Tuple[ReplicaHandle, Dict[str, Any]]] = []
        with self._hedge_lock:
            stale_after = max(30.0 * self.hedge_deadline_s, 30.0)
            for fid in list(self._inflight):
                entry = self._inflight[fid]
                age = now - entry["t0"]
                if age > stale_after:
                    del self._inflight[fid]  # both copies died; stop tracking
                    continue
                if entry["hedged"] or age < self.hedge_deadline_s:
                    continue
                target = self._hedge_target(entry)
                entry["hedged"] = True  # one hedge per frame, ever
                if target is not None:
                    entry["replicas"].append(target.name)
                    to_send.append((target, entry["forwarded"]))
        for target, forwarded in to_send:
            self._forward(target, forwarded)
            if self.metrics is not None:
                self.metrics.incr(mn.ROUTER_HEDGES)
        return len(to_send)

    def _hedge_target(self, entry: Dict[str, Any]) -> Optional[ReplicaHandle]:
        tried = set(entry["replicas"])
        for handle in self._preference_order(entry["topic"]):
            if handle.name in tried:
                continue
            if not handle.healthy or handle.cordoned or not handle.link_up:
                continue
            return handle
        return None

    def _publish_control(self, message: Dict[str, Any]) -> None:
        """Control traffic (enrollment) routes to the writer replica
        only — read replicas fail it closed themselves, but the router
        should not even offer it to them."""
        writer = next((r for r in self.replicas()
                       if r.writer and r.healthy), None)
        if writer is None:
            if self.metrics is not None:
                self.metrics.incr(mn.ROUTER_REJECTED_PREFIX + "no_writer")
            return
        writer.connector.publish(self.control_topic, message)

    # ---- health-based failover ----

    def check_health(self) -> None:
        """Probe every replica once and apply transitions. Runs on the
        health thread (probes may block on HTTP); tests call it directly
        for determinism."""
        from opencv_facerecognizer_tpu.runtime.slo import STATE_CRITICAL

        for handle in self.replicas():
            if handle.health_fn is None:
                continue
            try:
                state = int(handle.health_fn())
                if handle.probe_streak:
                    logger.info("router: health probe for %s recovered "
                                "after %d consecutive error(s)",
                                handle.name, handle.probe_streak)
                handle.probe_streak = 0
                handle.last_probe_error = None
            except Exception as exc:  # noqa: BLE001 — a dead probe fails the replica closed
                # Log only the INTO-erroring transition: a permanently
                # raising probe is one warn line per streak, never one
                # per cycle; the streak itself is capped and surfaced in
                # the registry so forensics still see "it has been
                # failing for a while".
                if handle.probe_streak == 0:
                    logger.warning("router: health probe for %s failed "
                                   "(suppressing repeats): %r",
                                   handle.name, exc)
                handle.probe_streak = min(handle.probe_streak + 1,
                                          self.PROBE_STREAK_CAP)
                if self.metrics is not None:
                    self.metrics.incr(mn.ROUTER_HEALTH_PROBE_FAILURES)
                    self.metrics.incr(mn.ROUTER_PROBE_ERRORS)
                handle.last_probe_error = repr(exc)
                state = STATE_CRITICAL
            handle.health_state = state
            healthy = state < STATE_CRITICAL
            if healthy != handle.healthy:
                self._transition(handle, healthy)
        self._set_replica_gauges()

    #: ceiling on the per-replica consecutive-probe-error streak (the
    #: monotonic ``router_probe_errors`` counter is unbounded; the streak
    #: is a diagnostic that must not grow without limit).
    PROBE_STREAK_CAP = 1000

    # ---- link supervision (application-level heartbeats) ----

    def check_links(self, now: Optional[float] = None) -> None:
        """One heartbeat cycle (no-op unless ``link_deadline_s`` is set):
        ping every replica through the transport boundary, then fail any
        link whose last pong is older than the deadline.  A half-open
        peer — TCP alive, application deaf — is detected here in bounded
        time, never by waiting on a socket.  Runs on the health thread;
        tests call it directly with a pinned ``now``."""
        if self.link_deadline_s is None:
            return
        now = time.monotonic() if now is None else now
        for handle in self.replicas():
            with self._lock:
                self._ping_counter += 1
                ping = {"ping": self._ping_counter,
                        "replica": handle.name}
            for msg in self._cross(handle.name, "send", ping):
                handle.connector.publish(self.link_ping_topic, msg)
            if self.metrics is not None:
                self.metrics.incr(mn.LINK_HEARTBEATS_SENT)
            if handle.last_pong_t is None:
                # Grace: the deadline clock starts at the first ping —
                # a replica is never failed for silence before it was
                # ever asked.
                handle.last_pong_t = now
                continue
            up = (now - handle.last_pong_t) <= self.link_deadline_s
            if up != handle.link_up:
                self._link_transition(handle, up)
        self._set_replica_gauges()

    def _link_transition(self, handle: ReplicaHandle, up: bool) -> None:
        handle.link_up = up
        if self.metrics is not None:
            self.metrics.incr(mn.LINK_RECOVERIES if up
                              else mn.LINK_FAILURES)
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "link",
                             topic=LIFECYCLE_TOPIC, replica=handle.name,
                             link_up=up)
            if not up:
                # A dead link IS a failover: the rings hold what was
                # routed when the link went dark.
                self.tracer.dump("failover",
                                 extra={"replica": handle.name,
                                        "link": "down",
                                        "registry": self.registry()})
        logger.warning("router: link to replica %s %s", handle.name,
                       "recovered (pong within deadline)" if up else
                       "down (pong deadline passed) — rerouting its "
                       "topics")

    def down_link_fraction(self) -> float:
        """Fraction of replica links currently down — the ``link_health``
        SLO objective's gauge value (``runtime.slo.link_health_objective``)."""
        handles = self.replicas()
        if not handles:
            return 0.0
        return sum(1 for h in handles if not h.link_up) / len(handles)

    def _transition(self, handle: ReplicaHandle, healthy: bool) -> None:
        handle.healthy = healthy
        if self.metrics is not None:
            self.metrics.incr(mn.ROUTER_RECOVERIES if healthy
                              else mn.ROUTER_FAILOVERS)
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "failover",
                             topic=LIFECYCLE_TOPIC, replica=handle.name,
                             healthy=healthy,
                             health_state=handle.health_state)
            if not healthy:
                # The flight recorder fires on failover: the rings hold
                # what was routed when the replica went dark.
                self.tracer.dump("failover",
                                 extra={"replica": handle.name,
                                        "registry": self.registry()})
        logger.warning("router: replica %s %s", handle.name,
                       "recovered" if healthy else
                       "critical — draining + rerouting its topics")

    def _health_loop(self) -> None:
        while not self._stop.wait(timeout=self.health_interval_s):
            try:
                self.check_health()
                self.check_links()
                self.check_hedges()
            except Exception:  # noqa: BLE001 — the health thread must live
                logger.exception("router health sweep failed")
                if self.metrics is not None:
                    self.metrics.incr(mn.ROUTER_HEALTH_PROBE_FAILURES)

    # ---- lifecycle ----

    def start(self) -> None:
        if self._health_thread is not None:
            return
        self._stop.clear()
        self.check_health()
        self.check_links()
        self._health_thread = threading.Thread(target=self._health_loop,
                                               daemon=True,
                                               name="ocvf-router-health")
        self._health_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=2.0)
            self._health_thread = None
